"""OSD daemon: boot, map handling, heartbeats, op dispatch, PG hosting.

Mirrors the src/osd/OSD.cc skeleton: boot to the monitor, subscribe to
OSDMap deltas, a ping mesh with failure reports past a grace period
(handle_osd_ping :5767, heartbeat_check :6138), fast dispatch of client
ops into per-PG execution (ms_fast_dispatch :7550 -> dequeue_op :9793),
and dmClock admission for client vs recovery work.
"""

from __future__ import annotations

import asyncio
import itertools
import time
import uuid as uuid_mod

from ..common import AdminSocket, ConfigProxy, PerfCountersCollection, \
    make_task_tracker
from ..mon.osdmap import OSDMap, Incremental
from ..msg import Message, Messenger
from ..os.store import MemStore, make_default_store
from .pg import PG, WRITE_OPS
from .scheduler import MClockScheduler, OpClass


class OSD:
    def __init__(self, uuid: str | None = None, whoami: int | None = None,
                 store=None, host: str = "host0",
                 secret: bytes | None = None,
                 config: dict | None = None,
                 admin_socket_path: str | None = None,
                 msgr_opts: dict | None = None,
                 cephx_key: str | None = None,
                 require_ticket: bool = False,
                 fault_injector=None) -> None:
        self.msgr_opts = msgr_opts
        # deterministic chaos (common/faults.py MessageFaultInjector):
        # threaded into the messenger at start(); its firings surface
        # in the "fault_inject" perf counter set.  None in production.
        self.faults = fault_injector
        # cephx: this OSD's entity key (hex).  When set, boot fetches
        # the rotating "osd" service keys (to VALIDATE tickets peers
        # present) and its own ticket (to PRESENT on osd->osd
        # connections); require_ticket makes the messenger NACK
        # ticketless peers (src/auth/cephx/CephxProtocol.h)
        self.cephx_key = cephx_key
        self.require_ticket = require_ticket
        self._rk_holder: dict | None = None
        self.host = host
        self.store = store or make_default_store()
        # identity lives in the store (OSD superblock analog,
        # OSD::read_superblock): a daemon restarted on a durable store
        # must reclaim its osd id (the mon resolves uuid->id), not
        # register as a fresh OSD and orphan its own data
        sb = self._read_superblock()
        self.uuid = uuid or sb.get("uuid") or uuid_mod.uuid4().hex
        if whoami is not None:
            self.whoami = whoami
        elif self.uuid == sb.get("uuid"):
            # the stored id belongs to the stored uuid: reclaiming it
            # under a DIFFERENT uuid would evict whatever daemon
            # legitimately owns that id in the map
            self.whoami = int(sb.get("whoami", -1))
        else:
            self.whoami = -1
        if not sb:
            self._write_superblock()
        self.config = {
            "osd_heartbeat_interval": 0.5,
            "osd_heartbeat_grace": 3.0,
            "osd_max_backfills": 2,
            **(config or {}),
        }
        # pre-override snapshot: central-config removals revert to this
        self._base_config = dict(self.config)
        self._pushed_config: set[str] = set()
        # in-flight client payload byte cap (Throttle backpressure,
        # osd_client_message_size_cap = 500 MiB in the reference)
        from ..common.throttle import Throttle
        self.client_throttle = Throttle(
            "osd_client_bytes",
            int(self.config.get("osd_client_message_size_cap",
                                500 << 20)))
        # typed registry over the same values: admin-socket `config set`
        # flows through the schema validation and back into the dict the
        # hot paths read (ConfigProxy observer pattern)
        from ..common.config import DEFAULT_SCHEMA
        known = {o.name for o in DEFAULT_SCHEMA}
        self.conf = ConfigProxy(values={
            k: v for k, v in self.config.items() if k in known})
        for name in known:
            self.conf.add_observer(
                name, lambda k, v: self.config.__setitem__(k, v))
        self.secret = secret
        self.msgr: Messenger | None = None
        self.mon_addr: tuple[str, int] | None = None
        self.monmap: list[list] = []
        self.osdmap = OSDMap()
        self.pgs: dict[str, PG] = {}
        # backfill reservation slots (AsyncReserver.h / osd_max_backfills):
        # local = backfills this OSD primaries, remote = backfills
        # targeting this OSD
        from ..common.reserver import AsyncReserver
        self.local_reserver = AsyncReserver(
            int(self.config["osd_max_backfills"]))
        self.remote_reserver = AsyncReserver(
            int(self.config["osd_max_backfills"]))
        # scrub slots (osd_max_scrubs; separate from backfill so a
        # recovering cluster can still scrub and vice versa)
        self.scrub_reserver = AsyncReserver(
            int(self.config.get("osd_max_scrubs", 1)))
        self._scrub_stamps: dict[str, float] = {}
        self._scrubbing: set[str] = set()
        self._sched_event = asyncio.Event()
        self._tid = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        self._hb_last: dict[int, float] = {}
        self._tasks: list[asyncio.Task] = []
        self._stopped = False
        self._track = make_task_tracker(self._tasks)
        self._rebooting = False
        # observability (src/common/perf_counters + TrackedOp analog)
        self.perf = PerfCountersCollection()
        self.perf_osd = self.perf.create("osd")
        # dmClock admission with its own perf set: per-class queue
        # depth gauges + dispatch counters, so a load harness can
        # report client-vs-recovery QoS behavior instead of inferring
        # it from latency alone
        self.sched = MClockScheduler(perf=self.perf.create("scheduler"))
        # the traffic harness's process-wide workload counters (ops and
        # bytes the client swarm pushed); adopting them means a plain
        # `perf dump` shows offered load next to what the daemon did
        from ..loadgen.stats import PERF as _workload_perf
        self.perf.adopt(_workload_perf)
        # the map owns the placement-cache counters (they live and die
        # with it); adopt them so `perf dump` includes the set.  A
        # full-map ingest re-adopts the fresh map's instance.
        self.perf.adopt(self.osdmap.placement_perf)
        # the integrity pipeline's counters are process-wide (every
        # CRC path -- codec batcher, scrub, blockstore, native scalar
        # fallback -- reports to one set); adopt so `perf dump` shows
        # batched vs scalar call mix
        from ..ops.crc32c_batch import PERF as _integrity_perf
        self.perf.adopt(_integrity_perf)
        # write-pipeline observability ("ec_pipeline" perf set): the
        # double-buffered batcher (staged_batches, overlap windows,
        # stage stalls), the deferred commit path (commit_overlap_ms)
        # and the per-peer sub-op coalescer (coalesced_subops,
        # flush_windows) all report here.  Pipeline knobs are SNAPSHOT
        # at construction -- the kill switch osd_pipeline_enabled=false
        # restores the serial chain end to end.
        self.perf_pipeline = self.perf.create("ec_pipeline")
        for key in ("staged_batches", "inflight_overlap_windows",
                    "stage_stalls", "overlapped_commits",
                    "commit_overlap_ms", "coalesced_subops",
                    "flush_windows"):
            self.perf_pipeline.inc(key, 0)    # visible even when idle
        self.pipeline_enabled = bool(
            self.config.get("osd_pipeline_enabled", True))
        self._pipeline_flush_window = float(
            self.config.get("osd_pipeline_flush_window", 0.002))
        self.subop_pipe = None       # built in start() (needs msgr)
        # cross-PG EC codec aggregation stage: every ECBackend on this
        # OSD funnels encode/decode work through ONE batcher so
        # concurrent ops share accelerator launches
        # (ceph_tpu/osd/codec_batcher.py)
        # every knob (batching AND the sharded-mesh data plane) is
        # snapshot here, once: the launch loop never reads config
        from .codec_batcher import CodecBatcher
        self.codec_batcher = CodecBatcher.from_config(
            self.config, perf=self.perf.create("ec_batch"),
            pipe_perf=self.perf_pipeline)
        # device-resident shard cache (os/device_cache.py): hot shard
        # buffers stay resident across encode -> commit -> read-verify
        # -> scrub -> decode instead of round-tripping the store.
        # Attached to the store UNCONDITIONALLY (None detaches): the
        # store boundary invalidates on every mutating txn, and a
        # revived OSD re-attaching a fresh (empty) cache is what makes
        # kill/revive incapable of serving stale resident bytes.
        from ..os.device_cache import DeviceShardCache
        from ..os.device_cache import PERF as _datapath_perf
        self.shard_cache = DeviceShardCache.from_config(self.config)
        self.store.attach_shard_cache(self.shard_cache)
        self.perf.adopt(_datapath_perf)
        # straggler-tolerant hedged gathers (osd/hedged_gather.py):
        # ONE engine + per-peer latency EWMA per daemon -- every
        # ECBackend, scrub collection and recovery pull on this OSD
        # shares the tracker (a peer's history is a daemon-level fact)
        # and the "ec_hedge" perf set.  All osd_ec_hedge_* knobs are
        # snapshot here, once.
        from .hedged_gather import HedgedGather, PeerLatencyEWMA
        self.peer_latency = PeerLatencyEWMA.from_config(self.config)
        self.hedger = HedgedGather.from_config(
            self, self.config, perf=self.perf.create("ec_hedge"),
            tracker=self.peer_latency)
        self._notify_serial = itertools.count(1)
        self._notify_waiters: dict[str, asyncio.Future] = {}
        # TrackedOp/OpTracker (src/common/TrackedOp.h): in-flight op
        # introspection + historic retention + slow-op complaints
        from ..common.optracker import OpTracker
        self.op_tracker = OpTracker(
            complaint_time=float(self.config.get(
                "osd_op_complaint_time", 30.0)))
        self.admin_socket: AdminSocket | None = None
        self._admin_socket_path = admin_socket_path

    # -- lifecycle ----------------------------------------------------------
    # -- superblock (identity persisted with the data) ----------------------
    _SB_COLL = "osd_superblock"
    _SB_OID = "superblock"

    def _read_superblock(self) -> dict:
        if not self.store.collection_exists(self._SB_COLL):
            return {}
        omap = self.store.omap_get(self._SB_COLL, self._SB_OID)
        return {k: v.decode() for k, v in omap.items()}

    def _write_superblock(self) -> None:
        from ..os.transaction import Transaction
        txn = Transaction()
        if not self.store.collection_exists(self._SB_COLL):
            txn.create_collection(self._SB_COLL)
            txn.touch(self._SB_COLL, self._SB_OID)
        txn.omap_setkeys(self._SB_COLL, self._SB_OID, {
            "uuid": self.uuid.encode(),
            "whoami": str(self.whoami).encode()})
        self.store.queue_transaction(txn)

    async def start(self, mon_addr: tuple[str, int],
                    host: str = "127.0.0.1", port: int = 0) -> int:
        self.mon_addr = tuple(mon_addr)
        self.store.mount()
        name = f"osd.{self.whoami}" if self.whoami >= 0 else \
            f"osd-boot-{self.uuid[:8]}"
        if self.faults is not None and self.faults.perf is None:
            self.faults.perf = self.perf.create("fault_inject")
        self.msgr = Messenger(name, secret=self.secret,
                              faults=self.faults,
                              **(self.msgr_opts or {}))
        self.msgr.add_dispatcher(self._dispatch)
        self.msgr.fast_dispatch = self.fast_dispatch
        if self.pipeline_enabled:
            # per-peer sub-op coalescing (msg/messenger.py SubOpPipe):
            # concurrent ops' sub-writes to one peer share a framed
            # flush per window instead of one send per shard
            from ..msg.messenger import SubOpPipe
            self.subop_pipe = SubOpPipe(
                self.msgr,
                flush_window=self._pipeline_flush_window,
                perf=self.perf_pipeline)
        addr = await self.msgr.bind(host, port)
        ack = await self._mon_request(
            "osd_boot", {"uuid": self.uuid, "host": self.host,
                         "addr": list(addr),
                         "osd_id": self.whoami if self.whoami >= 0
                         else None},
            reply_type="osd_boot_ack")
        self.whoami = ack["osd_id"]
        self._write_superblock()
        self.monmap = [list(a) for a in ack.get("monmap", [])] or \
            [list(self.mon_addr)]
        self.msgr.name = f"osd.{self.whoami}"
        if self.cephx_key:
            await self._cephx_boot()
        # subscribe to map deltas; mon replies with the full map
        full = await self._mon_request("sub_osdmap", {},
                                       reply_type="osdmap_full")
        self._apply_full_map(full["map"])
        # extend, never reassign: anything registered into _tasks before
        # this point would lose its only strong reference and get
        # garbage-collected mid-await
        self._tasks += [
            asyncio.ensure_future(self._heartbeat_loop()),
            asyncio.ensure_future(self._sched_loop()),
        ]
        if self._admin_socket_path:
            self.admin_socket = AdminSocket(self._admin_socket_path)
            self._register_admin_commands()
            await self.admin_socket.start()
        return self.whoami

    def _register_admin_commands(self) -> None:
        sock = self.admin_socket

        async def perf_dump(req):
            return self.perf.dump()

        async def scrub_cmd(req):
            pgid = req.get("pgid")
            if not pgid or pgid not in self.pgs:
                return {"err": f"no such pg {pgid!r}"}
            pg = self.pgs[pgid]
            if not pg.is_primary():
                return {"err": f"osd.{self.whoami} is not primary "
                               f"for {pgid}"}
            if pgid in self._scrubbing:
                return {"err": f"pg {pgid} already scrubbing"}
            # operator scrubs obey the same slot budget as scheduled
            # ones -- osd_max_scrubs must bound BOTH
            self._scrubbing.add(pgid)
            try:
                await self.scrub_reserver.request(pgid, timeout=30)
                # the slot wait suspended: the PG may have been
                # replaced or re-targeted by an epoch change -- scrub
                # the current object, not the pre-wait snapshot
                pg = self.pgs.get(pgid)
                if pg is None or not pg.is_primary():
                    return {"err": f"pg {pgid} moved while waiting "
                                   f"for a scrub slot"}
                from .scrub import scrub_pg
                res = await scrub_pg(pg,
                                     repair=bool(req.get("repair")))
                self._scrub_stamps[pgid] = time.monotonic()
                return res.to_dict()
            except asyncio.TimeoutError:
                return {"err": "scrub slots busy; try again"}
            finally:
                self.scrub_reserver.release(pgid)
                self._scrubbing.discard(pgid)

        async def status(req):
            return {"whoami": self.whoami, "epoch": self.osdmap.epoch,
                    "num_pgs": len(self.pgs),
                    "pg_states": {pgid: pg.state
                                  for pgid, pg in self.pgs.items()}}

        async def ops_in_flight(req):
            return self.op_tracker.dump_ops_in_flight()

        async def historic_ops(req):
            return self.op_tracker.dump_historic_ops()

        async def historic_ops_by_duration(req):
            return self.op_tracker.dump_historic_ops_by_duration()

        async def config_show(req):
            return self.conf.show()

        async def config_get(req):
            return self.conf.describe(req["name"])

        async def config_set(req):
            self.conf.set(req["name"], req["value"])
            return {req["name"]: self.conf.get(req["name"])}

        sock.register("perf dump", "dump perf counters", perf_dump)
        sock.register("status", "osd status", status)
        sock.register("dump_ops_in_flight", "in-flight client ops",
                      ops_in_flight)
        sock.register("dump_historic_ops", "recently completed ops",
                      historic_ops)
        sock.register("dump_historic_ops_by_duration",
                      "slowest completed ops",
                      historic_ops_by_duration)
        async def dump_tracing(req):
            from ..common.tracing import get_tracer
            return get_tracer(f"osd.{self.whoami}").dump(
                (req or {}).get("trace_id"))

        sock.register("dump_tracing",
                      "finished trace spans (optionally one trace_id)",
                      dump_tracing)
        sock.register("config show", "all config values", config_show)
        sock.register("scrub", "scrub a pg: {pgid, repair}", scrub_cmd)
        sock.register("config get", "describe one option", config_get)
        sock.register("config set", "set option (name=..., value=...)",
                      config_set)

    async def stop(self) -> None:
        self._stopped = True
        if self.codec_batcher is not None:
            self.codec_batcher.close()
        if self.subop_pipe is not None:
            # ship staged sub-ops before the messenger dies: a parked
            # flush would wedge every op awaiting its replies
            await self.subop_pipe.close()
            self.subop_pipe = None
        if self.admin_socket is not None:
            await self.admin_socket.stop()
        for t in list(self._tasks):
            t.cancel()
        for pg in self.pgs.values():
            if pg._recovery_task:
                pg._recovery_task.cancel()
            if pg._peering_task:
                pg._peering_task.cancel()
            if pg._snap_trim_task:
                pg._snap_trim_task.cancel()
        if self.msgr:
            await self.msgr.shutdown()
        self.store.umount()

    # -- public accessors (the in-process daemon boundary) ------------------
    # Harness/bench code must not reach into the OSD's private state
    # (cross-daemon-state rule): these expose the few facts the
    # kill/revive/wait helpers need as plain data.

    def is_stopped(self) -> bool:
        return self._stopped

    def revive_token(self) -> dict:
        """Everything a revive needs to rebuild this OSD in place.
        The store object rides along because an in-process revive
        re-mounts the same backend; a multiprocess revive would carry
        its path instead."""
        return {"uuid": self.uuid, "whoami": self.whoami,
                "store": self.store, "host": self.host,
                "config": dict(self._base_config)}

    def inflight_ops(self) -> int:
        """Client ops awaiting replies on this OSD right now."""
        return len(self._waiters)

    def has_pending_recovery(self) -> bool:
        """True while any primary PG here is degraded or still owes
        recovery work (the wait_clean predicate)."""
        for pg in self.pgs.values():
            if not pg.is_primary():
                continue
            if pg.state != "active" or pg._recovery_pending():
                return True
        return False

    def primary_pg_states(self) -> dict[str, int]:
        """State -> count over the PGs this OSD leads."""
        states: dict[str, int] = {}
        for pg in self.pgs.values():
            if pg.is_primary():
                states[pg.state] = states.get(pg.state, 0) + 1
        return states

    async def _mon_request(self, mtype: str, data: dict,
                           reply_type: str, timeout: float = 10) -> dict:
        """Mon RPC with monmap failover: a dead mon rotates the request
        to the next one (the MonClient hunting behavior).  Peons either
        answer (map reads) or forward to the leader."""
        q: asyncio.Queue = asyncio.Queue()

        async def d(conn, msg):
            if msg.type == reply_type:
                await q.put(msg.data)

        targets = self._mon_targets()
        per_try = max(2.0, timeout / max(1, len(targets)))
        self.msgr.add_dispatcher(d)
        try:
            last_err: Exception | None = None
            for addr, rank in targets:
                try:
                    await self.msgr.send(addr, f"mon.{rank}",
                                         Message(mtype, data))
                    reply = await asyncio.wait_for(q.get(), per_try)
                    self.mon_addr = addr        # stick with a live mon
                    return reply
                except (ConnectionError, OSError,
                        asyncio.TimeoutError) as e:
                    last_err = e
            raise last_err or asyncio.TimeoutError(mtype)
        finally:
            self.msgr.dispatchers.remove(d)

    def _mon_targets(self) -> list[tuple[tuple[str, int], int]]:
        """(addr, rank) hunting order: the current mon first, then the
        rest of the monmap."""
        mons = [tuple(a) for a in (self.monmap or [self.mon_addr])]
        if tuple(self.mon_addr) in mons:
            i0 = mons.index(tuple(self.mon_addr))
            mons = mons[i0:] + mons[:i0]
        return [(addr,
                 self.monmap.index(list(addr))
                 if self.monmap and list(addr) in self.monmap else 0)
                for addr in mons]

    async def _mon_send_failover(self, msg: Message) -> None:
        """Fire-and-forget to the mon cluster: a dead mon rotates the
        send to the next monmap entry (and re-homes mon_addr)."""
        for addr, rank in self._mon_targets():
            try:
                await asyncio.wait_for(
                    self.msgr.send(addr, f"mon.{rank}", msg), 2.0)
                self.mon_addr = addr
                return
            except (ConnectionError, OSError, asyncio.TimeoutError):
                continue

    # -- map handling -------------------------------------------------------
    def _apply_full_map(self, map_dict: dict) -> None:
        # steady-state dedupe: epochs are monotonic per change, so a
        # full map at an epoch we already hold is byte-for-byte the
        # map we have -- re-ingesting it would rebuild the placement
        # cache and sweep every PG for nothing.  The heartbeat's
        # map-freshness probe refetches the full map every few quiet
        # seconds per OSD; before this guard that re-ingest was the
        # single largest steady-state CPU line in the cluster bench
        # (the op loop starved under its own liveness probes).
        if int(map_dict.get("epoch", 0)) <= self.osdmap.epoch \
                and self.osdmap.epoch > 0:
            self._last_map_time = time.monotonic()
            return
        # capture the outgoing table: delta() against it lets the new
        # map touch only the PGs that actually moved
        prev = self.osdmap.peek_placement_cache()
        old_perf = self.osdmap._placement_perf
        self.osdmap = OSDMap.from_dict(map_dict)
        if old_perf is not None:
            # counters are per-daemon, not per-map-object: a full-map
            # ingest must not zero the recompute/delta history
            self.osdmap._placement_perf = old_perf
        self.perf.adopt(self.osdmap.placement_perf)
        self._last_map_time = time.monotonic()
        # full-map ingest rebuilds EVERY PoolSpec object, so hosted
        # PGs must rebind their pool regardless of placement deltas
        self._on_map_change(prev_cache=prev, rebuilt_pools=None)

    def _apply_incremental(self, inc_dict: dict) -> None:
        inc = Incremental.from_dict(inc_dict)
        self._last_map_time = time.monotonic()
        if inc.epoch <= self.osdmap.epoch:
            return          # duplicate delivery (multi-mon subscriptions)
        if inc.epoch != self.osdmap.epoch + 1:
            self._track(asyncio.ensure_future(self._catch_up_maps()))
            return
        prev = self.osdmap.peek_placement_cache()
        self.osdmap.apply_incremental(inc)
        self._on_map_change(prev_cache=prev,
                            rebuilt_pools=set(inc.new_pools))

    async def _catch_up_maps(self) -> None:
        try:
            full = await self._mon_request("sub_osdmap", {},
                                           reply_type="osdmap_full")
            self._apply_full_map(full["map"])
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass

    def _on_map_change(self, prev_cache=None,
                       rebuilt_pools: set[int] | None = None) -> None:
        """Instantiate/retarget PGs after an epoch change.

        With the previous epoch's placement table in hand the sweep is
        delta-driven: only PGs whose up/acting actually moved are
        visited (PGMapping.delta), so an epoch that merely bumps
        up_thru or fences a client touches nothing.  Without one (boot,
        gap catch-up) it walks the whole cached table once.

        ``rebuilt_pools`` names pools whose PoolSpec objects were
        REPLACED by this map (inc.new_pools); None means all of them
        (full-map ingest) -- hosted PGs rebind to the live object so
        snap state et al keep flowing (the old full-sweep did this as
        a side effect of visiting every PG)."""
        t0 = time.monotonic()
        epoch = self.osdmap.epoch
        cache = self.osdmap.placement_cache()
        if rebuilt_pools is None or rebuilt_pools:
            for pgid, pg in self.pgs.items():
                pool_id = int(pgid.split(".")[0])
                if rebuilt_pools is not None \
                        and pool_id not in rebuilt_pools:
                    continue
                live = self.osdmap.pools.get(pool_id)
                if live is not None:
                    pg.pool = live
        if prev_cache is not None:
            todo = cache.delta(prev_cache,
                               perf=self.osdmap.placement_perf)
        else:
            todo = [(pool_id, pg_no) for pool_id, pg_no, _, _
                    in cache.iter_all()]
        profiles: dict[int, dict | None] = {}
        for pool_id, pg_no in todo:
            pool = self.osdmap.pools.get(pool_id)
            if pool is None or pg_no >= pool.pg_num:
                continue        # deleted pool / shrunk range: dropped below
            if pool_id not in profiles:
                profiles[pool_id] = (self.osdmap.ec_profiles.get(
                    pool.erasure_code_profile)
                    if pool.is_erasure() else None)
            up, acting = cache.lookup(pool_id, pg_no)
            pgid = f"{pool_id}.{pg_no:x}"
            involved = self.whoami in up or self.whoami in acting
            pg = self.pgs.get(pgid)
            if pg is None:
                if not involved:
                    continue
                pg = PG(self, pgid, pool, profiles[pool_id])
                self.pgs[pgid] = pg
            # a full-map catch-up builds NEW PoolSpec objects: the
            # pg must track the live one (removed_snaps et al)
            pg.pool = pool
            changed = pg.update_mapping(up, acting, epoch)
            if changed and pg.is_primary():
                pg.kick_peering()
        # drop PGs for deleted pools
        live_pools = set(self.osdmap.pools)
        for pgid in list(self.pgs):
            pool_id = int(pgid.split(".")[0])
            if pool_id not in live_pools:
                self.pgs.pop(pgid)
        # restart the failure-detection clock for peers currently down
        # so a re-booted OSD is not instantly re-reported from a stale
        # last-heard timestamp
        for osd, info in self.osdmap.osds.items():
            if not info.up:
                self._hb_last.pop(osd, None)
        # a long synchronous map change stalls OUR event loop; peers
        # were not silent, we were deaf — credit the stall to the
        # failure-detection clocks
        stall = time.monotonic() - t0
        if stall > 0.05:
            for osd in self._hb_last:
                self._hb_last[osd] += stall
        # falsely marked down (we are clearly alive): re-assert with a
        # fresh boot, as the reference OSD does on seeing itself down
        # in a new map
        me = self.osdmap.osds.get(self.whoami)
        if (me is not None and not me.up and not self._stopped
                and not self._rebooting):
            self._rebooting = True
            self._track(asyncio.ensure_future(self._reboot()))

    async def _reboot(self) -> None:
        try:
            await asyncio.sleep(0.2)     # let the down epoch settle
            await self._mon_request(
                "osd_boot", {"uuid": self.uuid, "host": self.host,
                             "addr": list(self.msgr.addr),
                             "osd_id": self.whoami},
                reply_type="osd_boot_ack")
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            self._rebooting = False

    def _get_pg(self, pgid: str) -> PG | None:
        pg = self.pgs.get(pgid)
        if pg is not None:
            return pg
        # a peer knows about a PG we have not instantiated yet (e.g. a
        # query raced our map delivery): create it if the pool exists
        try:
            pool_id = int(pgid.split(".")[0])
        except ValueError:
            return None
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            return None
        profile = self.osdmap.ec_profiles.get(
            pool.erasure_code_profile) if pool.is_erasure() else None
        pg = PG(self, pgid, pool, profile)
        ps = int(pgid.split(".")[1], 16)
        up, acting = self.osdmap.pg_to_up_acting(pool_id, ps)
        pg.update_mapping(up, acting, self.osdmap.epoch)
        self.pgs[pgid] = pg
        return pg

    def osd_is_up(self, osd: int) -> bool:
        return osd == self.whoami or self.osdmap.is_up(osd)

    async def ensure_up_thru(self, min_epoch: int,
                             timeout: float = 30.0) -> bool:
        """Block until the osdmap records our up_thru >= min_epoch
        (PeeringState WaitUpThru: the primary may not activate a new
        interval before the map proves the interval went live, or a
        later peering could prune it as never-active and lose writes).

        All waiting PGs share ONE MOSDAlive sender (the reference
        sends one alive per map epoch per OSD, not per PG): the task
        asks for the max wanted epoch and every waiter just watches
        the subscribed map."""
        self._alive_want = max(getattr(self, "_alive_want", 0),
                               min_epoch)
        if (getattr(self, "_alive_task", None) is None
                or self._alive_task.done()):
            self._alive_task = asyncio.ensure_future(self._alive_loop())
            self._track(self._alive_task)
        deadline = asyncio.get_event_loop().time() + timeout
        while self.osdmap.get_up_thru(self.whoami) < min_epoch:
            if asyncio.get_event_loop().time() > deadline:
                return False
            await asyncio.sleep(0.05)
        return True

    async def _alive_loop(self) -> None:
        """Single in-flight MOSDAlive per OSD, re-sent every 2s until
        the map catches up to the largest wanted epoch."""
        while self.osdmap.get_up_thru(self.whoami) < self._alive_want:
            try:
                await self._mon_request(
                    "osd_alive",
                    {"osd_id": self.whoami,
                     "want_up_thru": self._alive_want},
                    reply_type="osd_alive_reply", timeout=5)
                # the reply races the map incremental; fetch once
                await self._catch_up_maps()
            except (asyncio.TimeoutError, ConnectionError, OSError):
                pass
            if self.osdmap.get_up_thru(self.whoami) >= self._alive_want:
                return
            await asyncio.sleep(2.0)

    def request_pg_temp(self, pgid: str, osds: list[int]) -> None:
        """Fire-and-forget MOSDPGTemp to the mon (an empty list clears
        the override); the map change comes back as an incremental."""
        async def _send():
            try:
                await self._mon_request(
                    "osd_pg_temp", {"pgid": pgid, "osds": osds},
                    reply_type="osd_pg_temp_reply", timeout=10)
            except (asyncio.TimeoutError, ConnectionError, OSError):
                pass                 # re-requested on the next peering
        self._track(asyncio.ensure_future(_send()))

    # -- peer RPC -----------------------------------------------------------
    def _peer_addr(self, osd: int) -> tuple[str, int]:
        info = self.osdmap.osds.get(osd)
        if info is None or info.addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        return tuple(info.addr)

    def start_request(self, osd: int, mtype: str, data: dict,
                      segments=()) -> tuple[int, asyncio.Task]:
        """Issue ONE peer request; the returned task resolves to the
        reply Message (matched by tid, like fanout_and_wait) or raises
        ConnectionError on a send failure.

        The caller OWNS the task: awaiting, cancelling and reaping it
        are its job (HedgedGather is the owning engine on the read
        spine).  Cancellation pops the tid waiter in the task's
        finally, so a straggler's late reply is dropped at the
        dispatch layer instead of crosstalking into a later op that
        happens to reuse the wire."""
        tid = next(self._tid)
        fut = asyncio.get_event_loop().create_future()
        self._waiters[tid] = fut
        d = dict(data)
        d["tid"] = tid

        async def _issue():
            try:
                try:
                    await self.msgr.send(
                        self._peer_addr(osd), f"osd.{osd}",
                        Message(mtype, d, segments=list(segments)))
                except (ConnectionError, OSError) as e:
                    if not fut.done():
                        fut.set_exception(ConnectionError(str(e)))
                return await fut
            finally:
                self._waiters.pop(tid, None)
                # a cancel landing between the send failure and the
                # await leaves the failure un-consumed: mark it
                # retrieved (or park the waiter) so nothing warns at GC
                if fut.done() and not fut.cancelled():
                    fut.exception()
                else:
                    fut.cancel()

        return tid, asyncio.ensure_future(_issue())

    async def fanout_and_wait(self, requests, collect: bool = False,
                              timeout: float = 10):
        """Send (osd, type, data, segments) requests; await all replies.

        Replies are matched by tid (every handler echoes it).  Raises
        TimeoutError if any peer fails to respond — callers treat that
        as a failed sub-op (the op layer above re-peers on map change).
        """
        futs = []
        for osd, mtype, data, segments in requests:
            tid = next(self._tid)
            fut = asyncio.get_event_loop().create_future()
            self._waiters[tid] = fut
            futs.append((tid, fut))
            d = dict(data)
            d["tid"] = tid
            try:
                await self.msgr.send(
                    self._peer_addr(osd), f"osd.{osd}",
                    Message(mtype, d, segments=list(segments)))
            except (ConnectionError, OSError) as e:
                if not fut.done():
                    fut.set_exception(ConnectionError(str(e)))
        return await self.await_staged(futs, collect=collect,
                                       timeout=timeout)

    def fanout_staged(self, requests) -> list:
        """Stage (osd, type, data, segments) sub-op sends through the
        per-peer coalescing pipe and return the (tid, future) reply
        waiters for ``await_staged``.

        Staging is SYNCHRONOUS (no await between requests): staging
        order is the per-peer wire order, which is what keeps replica
        logs applied in version order when commits overlap.  The
        caller owns the reply futures -- a bare call orphans them
        (the dropped-task lint roots this entry point)."""
        pipe = self.subop_pipe
        futs = []
        for osd, mtype, data, segments in requests:
            tid = next(self._tid)
            fut = asyncio.get_event_loop().create_future()
            self._waiters[tid] = fut
            futs.append((tid, fut))
            d = dict(data)
            d["tid"] = tid

            def on_error(e, fut=fut):
                if not fut.done():
                    fut.set_exception(ConnectionError(str(e)))

            try:
                pipe.stage(self._peer_addr(osd), f"osd.{osd}",
                           Message(mtype, d, segments=list(segments)),
                           on_error=on_error)
            except (ConnectionError, OSError) as e:
                on_error(e)
        return futs

    async def await_staged(self, futs, collect: bool = False,
                           timeout: float = 10):
        """Await the (tid, future) reply waiters of a staged fan-out
        (shared wait tail of fanout_and_wait)."""
        try:
            if futs:
                done, pending = await asyncio.wait(
                    [f for _, f in futs], timeout=timeout)
            else:
                done, pending = set(), set()
        finally:
            for tid, _ in futs:
                self._waiters.pop(tid, None)
        replies, errors = [], []
        for f in done:
            if f.exception() is not None:
                errors.append(f.exception())
            else:
                replies.append(f.result())
        for f in pending:
            f.cancel()
        if collect:
            return replies      # partial results are fine (down peers)
        if errors:
            raise errors[0]
        if pending:
            raise asyncio.TimeoutError(
                f"{len(pending)} sub-op replies outstanding")
        return replies

    def _resolve_tid(self, msg: Message) -> None:
        fut = self._waiters.pop(msg.data.get("tid"), None)
        if fut is not None and not fut.done():
            fut.set_result(msg)

    # reply types whose whole handler is the synchronous tid
    # resolution above: they take the messenger's fast-dispatch path
    # (no task per message) -- the bulk of sub-op traffic on the
    # pipelined write spine is exactly these
    _FAST_REPLIES = frozenset((
        "rep_op_reply", "ec_subop_write_reply", "ec_subop_read_reply",
        "pg_pull_reply", "pg_push_reply", "scrub_release_ack"))

    def fast_dispatch(self, conn, msg: Message) -> bool:
        """Synchronous fast path consulted by the messenger before
        spawning a dispatch task; True = consumed."""
        t = msg.type
        if t in self._FAST_REPLIES:
            self._resolve_tid(msg)
            return True
        if t == "osd_ping_reply":
            self._hb_last[msg.data["from_osd"]] = time.monotonic()
            return True
        return False

    # -- dmclock admission --------------------------------------------------
    async def admit(self, op_class: OpClass):
        fut = asyncio.get_event_loop().create_future()
        self.sched.enqueue(op_class, fut)
        self._sched_event.set()
        await fut

    async def _sched_loop(self) -> None:
        try:
            while True:
                await self._sched_event.wait()
                item = self.sched.dequeue()
                if item is None:
                    self._sched_event.clear()
                    continue
                _, fut = item
                if not fut.done():
                    fut.set_result(None)
                # yield so the admitted op actually starts
                await asyncio.sleep(0)
        except asyncio.CancelledError:
            pass

    # -- heartbeats ---------------------------------------------------------
    async def _heartbeat_loop(self) -> None:
        try:
            while True:
                interval = self.config["osd_heartbeat_interval"]
                t0 = time.monotonic()
                await asyncio.sleep(interval)
                # scheduling-lag credit: if OUR sleep woke late, the
                # event loop was starved -- and peers sharing it were
                # equally starved, not silent.  Crediting the clocks
                # keeps loop congestion (peering bursts, recovery
                # storms) from reading as peer death; false failure
                # reports during a real failure are how one kill
                # cascades into a cluster-wide peering storm (the
                # degraded-phase collapse the bench caught).
                # the lag credit must use the SAME interval the
                # sleep ran with; a config change applies next tick
                # lint: disable=await-invalidates-snapshot -- per-tick snapshot
                late = time.monotonic() - t0 - interval
                if late > 0.2:
                    for osd in self._hb_last:
                        self._hb_last[osd] += late
                await self._heartbeat_once()
        except asyncio.CancelledError:
            pass

    # -- cephx ---------------------------------------------------------------
    async def _cephx_boot(self) -> None:
        """Fetch rotating validation keys + our own service ticket
        over the (PSK-authenticated) mon session, install the
        messenger validator (src/auth/RotatingKeyRing.h role)."""
        from ..common.cephx import (fetch_rotating, fetch_ticket,
                                    install_validator)
        entity = f"osd.{self.whoami}"
        rk = await fetch_rotating(self.msgr, self.mon_addr, entity,
                                  self.cephx_key, "osd")
        self._rk_holder = {"rk": rk}
        install_validator(self.msgr, self._rk_holder)
        self.msgr.require_ticket = self.require_ticket
        await fetch_ticket(self.msgr, self.mon_addr, entity,
                           self.cephx_key, "osd")
        self._cephx_next_refresh = time.monotonic() + 60.0

    async def _cephx_refresh(self) -> None:
        """Keep validation keys current across rotations and our own
        ticket live past its expiry; runs on the heartbeat cadence."""
        if not self.cephx_key or self._rk_holder is None:
            return
        now = time.monotonic()
        if now < getattr(self, "_cephx_next_refresh", 0):
            return
        self._cephx_next_refresh = now + 60.0
        from ..common.cephx import fetch_rotating, fetch_ticket
        entity = f"osd.{self.whoami}"
        try:
            t = self.msgr.tickets.get("osd")
            if t is None or t["expires"] - time.time() < 120.0:
                await fetch_ticket(self.msgr, self.mon_addr, entity,
                                   self.cephx_key, "osd")
            self._rk_holder["rk"] = await fetch_rotating(
                self.msgr, self.mon_addr, entity,
                self.cephx_key, "osd")
        except Exception:
            pass            # mon hunt/retry next cycle

    async def _ping_one(self, osd: int, now: float) -> None:
        """One bounded ping send — a dead peer's connect/reconnect stall
        must never block the heartbeat cycle (the reference runs a
        dedicated hb messenger for the same reason)."""
        try:
            await asyncio.wait_for(
                self.msgr.send(
                    self._peer_addr(osd), f"osd.{osd}",
                    Message("osd_ping", {"from_osd": self.whoami,
                                         "stamp": now})), 1.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass

    def _heartbeat_peers(self) -> list[int]:
        """Up peers this OSD pings, capped at osd_heartbeat_max_peers.

        A full mesh is O(N^2) messages per interval — fine at 3 OSDs,
        ruinous at the 64–1000 the cluster harness brings up.  The
        reference picks heartbeat peers from hosted PGs plus map-order
        neighbors (OSD::maybe_update_heartbeat_peers); we do the same:
        PG peers (whose liveness gates OUR peering/recovery) first,
        then ring neighbors by osd id so the detection graph stays
        connected and every OSD is somebody's neighbor.
        """
        ups = sorted(o for o, info in self.osdmap.osds.items()
                     if o != self.whoami and info.up)
        cap = int(self.config.get("osd_heartbeat_max_peers", 10))
        if cap <= 0 or len(ups) <= cap:
            return ups
        cap = max(cap, 4)
        # ring neighbors FIRST: every up OSD is the +-1 neighbor of two
        # others, so even a PG-less daemon has someone watching it
        import bisect
        i = bisect.bisect_left(ups, self.whoami)
        n = len(ups)
        peers: list[int] = []
        seen: set[int] = set()

        def add(o: int) -> None:
            if o != self.whoami and o not in seen:
                seen.add(o)
                peers.append(o)

        add(ups[i % n])              # i points past self (not in ups)
        add(ups[(i - 1) % n])
        add(ups[(i + 1) % n])
        for pg in self.pgs.values():
            if len(peers) >= cap:
                break
            for o in pg.up:
                if o in self.osdmap.osds and self.osdmap.osds[o].up:
                    add(o)
        for step in range(2, n):
            if len(peers) >= cap:
                break
            add(ups[(i + step) % n])
            add(ups[(i - step) % n])
        return peers[:cap]

    async def _heartbeat_once(self) -> None:
        now = time.monotonic()
        grace = self.config["osd_heartbeat_grace"]
        # map-feed freshness: our subscribed mon may have died -- a
        # quiet feed re-subscribes through the failover path (MonClient
        # re-hunts on session loss the same way)
        if now - getattr(self, "_last_map_time", now) > 5.0:
            self._last_map_time = now          # one probe per window
            self._track(asyncio.ensure_future(self._catch_up_maps()))
        await self._cephx_refresh()
        # mgr perf reporting rides the same cadence (MgrClient reports)
        if now - getattr(self, "_last_mgr_report", 0.0) > 2.0:
            self._last_mgr_report = now
            self._track(asyncio.ensure_future(self._report_to_mgr()))
        # slow-op complaints (OSD::get_health_metrics): ops in flight
        # past osd_op_complaint_time surface in the mon's health and,
        # once per op, in the cluster log
        # re-read the threshold each tick: central config may have
        # changed osd_op_complaint_time at runtime
        self.op_tracker.complaint_time = float(
            self.config.get("osd_op_complaint_time", 30.0))
        slow = self.op_tracker.slow_ops()
        if slow or getattr(self, "_had_slow_ops", False):
            self._had_slow_ops = bool(slow)
            fresh = [o for o in slow
                     if o.opid not in self.op_tracker.complained]
            for o in fresh:
                self.op_tracker.complained.add(o.opid)
                self.perf_osd.inc("slow_ops")
            self._track(asyncio.ensure_future(
                self._mon_send_failover(Message(
                    "osd_slow_ops",
                    {"osd_id": self.whoami, "count": len(slow),
                     "oldest_age": max((o.age for o in slow),
                                       default=0.0),
                     "log": bool(fresh)}))))
        # opportunistic re-kicks: a recovery push/pull that raced a peer
        # reboot backs off (the tick restarts it); a peering task that
        # died leaves the PG stranded (the tick re-runs it)
        for pg in self.pgs.values():
            if not pg.is_primary():
                continue
            if pg.state == "active" and pg._recovery_pending():
                pg.kick_recovery()
            elif pg.state in ("peering", "incomplete", "wait_up_thru",
                              "wait_acting_change"):
                # incomplete re-probes each tick (a revived peer with
                # complete history un-wedges it -- the reference reacts
                # to MNotifyRec; the tick is our notify cadence), and a
                # wait-state whose task DIED (e.g. up_thru timeout with
                # the epoch moved, so peer() exited) restarts here;
                # kick_peering is a no-op while the task still runs
                pg.kick_peering()
            if pg.state == "active" and pg.pool.removed_snaps:
                pg.kick_snap_trim(pg.pool.removed_snaps)
        self._maybe_schedule_scrubs(now)
        peers = self._heartbeat_peers()
        await asyncio.gather(*(self._ping_one(o, now) for o in peers),
                             return_exceptions=True)
        for osd in peers:
            last = self._hb_last.get(osd)
            if last is None:
                self._hb_last[osd] = now     # start the clock
            # one sweep judges every peer against ONE grace;
            # re-reading mid-sweep grades peers on different clocks
            # lint: disable=await-invalidates-snapshot -- per-sweep snapshot
            elif now - last > grace:
                # yield once so queued ping/reply handlers run, then
                # re-check: distinguishes "peer silent" from "our loop
                # was busy and the replies are still in the queue"
                await asyncio.sleep(0)
                last = self._hb_last.get(osd, now)
                if now - last <= grace:
                    continue
                await self._mon_send_failover(
                    Message("osd_failure", {"target": osd}))

    # -- dispatch -----------------------------------------------------------
    async def _dispatch(self, conn, msg: Message) -> None:
        handler = getattr(self, f"_h_{msg.type}", None)
        if handler is not None:
            await handler(conn, msg)

    async def _h_config_update(self, conn, msg) -> None:
        """Central config push (ConfigMonitor -> MConfig): values flow
        through the ConfigProxy so observers fire on change.  The
        message carries the FULL effective config: keys previously
        pushed but now absent revert to their local values (config rm
        must actually undo the override)."""
        cfg = msg.data.get("config", {})
        pushed = getattr(self, "_pushed_config", set())
        for name in pushed - set(cfg):
            if name in self._base_config:
                self.config[name] = self._base_config[name]
                try:
                    self.conf.set(name, self._base_config[name])
                except (KeyError, ValueError):
                    pass
            else:
                self.config.pop(name, None)
        applied = set()
        for name, value in cfg.items():
            try:
                self.conf.set(name, value)
                applied.add(name)
            except ValueError:
                # KNOWN option, invalid value: reject the NEW value --
                # but keep tracking the key if an earlier push set it,
                # or a later `config rm` could never revert it
                if name in pushed:
                    applied.add(name)
                continue
            except KeyError:
                # unschema'd option: best-effort numeric cast so hot
                # paths comparing against numbers keep working
                for cast in (int, float):
                    try:
                        value = cast(value)
                        break
                    except (TypeError, ValueError):
                        continue
                self.config[name] = value
                applied.add(name)
        self._pushed_config = applied

    async def _h_osdmap_inc(self, conn, msg) -> None:
        self._apply_incremental(msg.data["inc"])

    async def _h_osdmap_full(self, conn, msg) -> None:
        self._apply_full_map(msg.data["map"])

    async def _h_osd_ping(self, conn, msg) -> None:
        self._hb_last[msg.data["from_osd"]] = time.monotonic()
        await conn.send(Message("osd_ping_reply",
                                {"from_osd": self.whoami,
                                 "stamp": msg.data["stamp"]}))

    async def _h_mgr_map(self, conn, msg) -> None:
        self._mgr_addr = tuple(msg.data["addr"])
        self._mgr_name = msg.data.get("name", "0")

    async def _report_to_mgr(self) -> None:
        """Push a perf summary to the active mgr (the MgrClient report
        protocol the DaemonServer aggregates)."""
        addr = getattr(self, "_mgr_addr", None)
        if addr is None:
            return
        summary = {}
        try:
            dump = self.perf.dump().get("osd", {})
            for key in ("op", "op_w", "op_r", "op_in_bytes",
                        "op_out_bytes", "subop_w", "recovery_ops"):
                if key in dump:
                    v = dump[key]
                    summary[key] = v.get("value", v) \
                        if isinstance(v, dict) else v
            summary["num_pgs"] = len(self.pgs)
            # recovery/backfill state for the mgr progress module
            # (pg stats feeding progress events in the reference)
            states: dict[str, int] = {}
            missing = 0
            backfills = 0
            for pg in self.pgs.values():
                states[pg.state] = states.get(pg.state, 0) + 1
                if pg.is_primary():
                    missing += len(pg.missing) + sum(
                        len(ms) for ms in pg.peer_missing.values())
                    backfills += len(pg.backfill_targets)
            summary["pg_states"] = states
            summary["slow_ops"] = len(self.op_tracker.slow_ops())
            summary["missing_objects"] = missing
            summary["backfills"] = backfills
        except Exception:
            return
        try:
            await asyncio.wait_for(self.msgr.send(
                addr, f"mgr.{getattr(self, '_mgr_name', '0')}",
                Message("mgr_report",
                        {"daemon": f"osd.{self.whoami}",
                         "summary": summary})), 2.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            # keep the address: a transient stall must not silence
            # reporting forever (the mon only re-publishes mgr_map on
            # CHANGE); the next cadence simply retries
            pass

    async def _h_mgr_report_ack(self, conn, msg) -> None:
        pass

    async def _h_watch_notify_ack(self, conn, msg) -> None:
        fut = self._notify_waiters.pop(msg.data.get("notify_id"), None)
        if fut is not None and not fut.done():
            fut.set_result(msg.data)

    async def _h_osd_ping_reply(self, conn, msg) -> None:
        self._hb_last[msg.data["from_osd"]] = time.monotonic()

    # client I/O
    async def _h_osd_op(self, conn, msg) -> None:
        await self.admit(OpClass.CLIENT)
        # byte throttle on in-flight client payloads
        # (osd_client_message_size_cap backpressure); the limit re-reads
        # config so runtime `config set` takes effect
        self.client_throttle.limit = int(
            self.config.get("osd_client_message_size_cap", 500 << 20))
        nbytes = sum(len(s) for s in msg.segments)
        await self.client_throttle.get(nbytes)
        try:
            await self._do_osd_op(conn, msg)
        finally:
            self.client_throttle.put(nbytes)

    async def _do_osd_op(self, conn, msg) -> None:
        # blocklist fence (OSD.cc session blocklist check): a fenced
        # instance's delayed/in-flight writes must NOT land -- this is
        # what makes cap revocation and rbd lock steal safe against a
        # wedged-but-alive client
        reqid = msg.data.get("reqid") or [None]
        iid = reqid[0]
        # an entry may name a full instance ("client.x:inc") or a bare
        # entity ("client.x" -- rbd lock break fences every instance)
        if iid is not None and (
                self.osdmap.is_blocklisted(str(iid))
                or self.osdmap.is_blocklisted(
                    str(iid).split(":", 1)[0])):
            await conn.send(Message(
                "osd_op_reply", {"tid": msg.data.get("tid"),
                                 "err": "EBLOCKLISTED"}))
            return
        pg = self._get_pg(msg.data["pgid"])
        if pg is None:
            await conn.send(Message(
                "osd_op_reply", {"tid": msg.data.get("tid"),
                                 "err": "ENXIO no such pg"}))
            return
        from ..common.tracing import get_tracer
        span = get_tracer(f"osd.{self.whoami}").start(
            "osd.do_op", parent=msg.data.get("trace"),
            pgid=msg.data["pgid"], oid=msg.data["oid"]).activate()
        try:
            await self._do_osd_op_traced(conn, msg, pg)
        finally:
            span.finish()

    async def _do_osd_op_traced(self, conn, msg, pg) -> None:
        op_names = [o.get("op") for o in msg.data.get("ops", [])]
        top = self.op_tracker.create(
            oid=msg.data["oid"], pgid=msg.data["pgid"],
            type="+".join(op_names),
            client=str(msg.from_name))
        try:
            with self.perf_osd.time("op_latency"):
                data, segments = await pg.do_op(msg, conn, top=top)
        finally:
            top.finish()
        if "err" not in data:          # rejected ops aren't throughput
            self.perf_osd.inc("op")
            if any(n in WRITE_OPS for n in op_names):
                self.perf_osd.inc("op_w")
                self.perf_osd.inc("op_in_bytes",
                                  sum(len(s) for s in msg.segments))
            else:
                self.perf_osd.inc("op_r")
                self.perf_osd.inc("op_out_bytes",
                                  sum(len(s) for s in segments))
        data["tid"] = msg.data.get("tid")
        data["epoch"] = self.osdmap.epoch
        await conn.send(Message("osd_op_reply", data, segments=segments))

    # replication / EC sub-ops
    async def _h_rep_op(self, conn, msg) -> None:
        from ..common.tracing import get_tracer
        span = get_tracer(f"osd.{self.whoami}").start(
            "osd.rep_op", parent=msg.data.get("trace"),
            pgid=msg.data["pgid"]).activate()
        try:
            await self._h_rep_op_traced(conn, msg)
        finally:
            span.finish()

    async def _h_rep_op_traced(self, conn, msg) -> None:
        from .types import LogEntry
        from .backend import unpack_mutations
        pg = self._get_pg(msg.data["pgid"])
        if pg is not None:
            entry = LogEntry.from_dict(msg.data["entry"])
            muts = unpack_mutations(msg.data["muts"], msg.segments)
            pg.backend.apply_rep_op(entry, muts,
                                    log_only=bool(
                                        msg.data.get("log_only")))
            self.perf_osd.inc("subop_w")
        await conn.send(Message("rep_op_reply",
                                {"tid": msg.data.get("tid"),
                                 "from_osd": self.whoami}))

    async def _h_rep_op_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_ec_subop_write(self, conn, msg) -> None:
        from .types import LogEntry
        from .backend import unpack_mutations
        pg = self._get_pg(msg.data["pgid"])
        if pg is not None:
            entry = LogEntry.from_dict(msg.data["entry"])
            w = msg.data["w"]
            if w.get("writes") is not None:      # ranged RMW sub-write
                n_data_segs = len(w["writes"])
            elif w.get("remove") or w.get("touch") or w.get("log_only"):
                n_data_segs = 0
            else:
                n_data_segs = 1
            attr_muts = unpack_mutations(msg.data.get("attr_muts", []),
                                         msg.segments[n_data_segs:])
            pg.backend.apply_sub_write(
                entry, w, msg.segments[:n_data_segs], attr_muts,
                shard=msg.data.get("shard"))
            self.perf_osd.inc("subop_w")
        await conn.send(Message("ec_subop_write_reply",
                                {"tid": msg.data.get("tid"),
                                 "from_osd": self.whoami}))

    async def _h_ec_subop_write_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    # -- scrub scheduling (osd_scrub_sched.cc in miniature) -----------------
    def _maybe_schedule_scrubs(self, now: float) -> None:
        interval = float(self.config.get("osd_scrub_interval", 0))
        if interval <= 0:       # scheduling off unless configured
            return
        import random
        due = []
        for pgid, pg in self.pgs.items():
            if (not pg.is_primary() or pg.state != "active"
                    or pgid in self._scrubbing
                    or pg._recovery_pending()):
                continue
            last = self._scrub_stamps.get(pgid, 0.0)
            if now - last < interval:
                continue
            due.append(pgid)
        if not due:
            return
        # ONE scrub kick per tick, randomly chosen: launching every due
        # PG at once makes all primaries collide on the replicas'
        # single scrub slots in lockstep, tick after tick
        pgid = random.choice(due)
        self._scrubbing.add(pgid)
        self._track(asyncio.ensure_future(
            self._run_scheduled_scrub(pgid)))

    async def _run_scheduled_scrub(self, pgid: str) -> None:
        """One reserved scrub: local slot + a slot on every acting
        replica, then the scrub itself (repair on by default, the
        osd_scrub_auto_repair discipline)."""
        pg = self.pgs.get(pgid)
        granted_remote: list[int] = []
        got_local = False
        try:
            if pg is None or not pg.is_primary():
                return
            await self.scrub_reserver.request(pgid, timeout=30)
            got_local = True
            # the slot wait suspended: re-read the PG, an epoch
            # change may have replaced or deposed it meanwhile
            pg = self.pgs.get(pgid)
            if pg is None or not pg.is_primary():
                return
            peers = [o for o in pg.acting_peers() if self.osd_is_up(o)]
            for o in peers:
                replies = await self.fanout_and_wait(
                    [(o, "scrub_reserve", {"pgid": pgid}, [])],
                    collect=True, timeout=10)
                if not replies or not replies[0].data.get("granted"):
                    return          # replica busy; retried next tick
                granted_remote.append(o)
            from .scrub import scrub_pg
            # the replica handshakes suspended too
            pg = self.pgs.get(pgid)
            if pg is None or not pg.is_primary():
                return
            res = await scrub_pg(pg, repair=bool(
                self.config.get("osd_scrub_auto_repair", True)))
            self._scrub_stamps[pgid] = time.monotonic()
            self.perf_osd.inc("scrubs")
            if not res.clean:
                self.perf_osd.inc("scrub_repairs", len(res.repaired))
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass                    # retried next tick
        finally:
            if got_local:
                self.scrub_reserver.release(pgid)
            for o in granted_remote:
                try:
                    await self.fanout_and_wait(
                        [(o, "scrub_release", {"pgid": pgid}, [])],
                        collect=True, timeout=5)
                except (ConnectionError, OSError,
                        asyncio.TimeoutError):
                    pass
            self._scrubbing.discard(pgid)

    async def _h_pg_scrub_map_req(self, conn, msg) -> None:
        """Replica side of a scrub round: digest every local object
        (scrub_backend.cc building the replica scrub map)."""
        from .scrub import build_scrub_map
        pg = self._get_pg(msg.data["pgid"])
        smap = await build_scrub_map(self.store, pg.coll) if pg else {}
        await conn.send(Message("pg_scrub_map", {
            "pgid": msg.data["pgid"], "map": smap,
            "from_osd": self.whoami, "tid": msg.data.get("tid")}))

    async def _h_pg_scrub_map(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_scrub_reserve(self, conn, msg) -> None:
        """Remote scrub slot (the scrubber's replica reservations --
        a replica scrubs for at most osd_max_scrubs PGs at once)."""
        granted = self.scrub_reserver.get_or_fail(
            msg.data["pgid"], lease=120.0)
        await conn.send(Message("scrub_reserve_reply", {
            "pgid": msg.data["pgid"], "granted": granted,
            "from_osd": self.whoami, "tid": msg.data.get("tid")}))

    async def _h_scrub_reserve_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_scrub_release(self, conn, msg) -> None:
        self.scrub_reserver.release(msg.data["pgid"])
        await conn.send(Message("scrub_release_ack", {
            "pgid": msg.data["pgid"], "from_osd": self.whoami,
            "tid": msg.data.get("tid")}))

    async def _h_scrub_release_ack(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_ec_subop_read(self, conn, msg) -> None:
        pg = self._get_pg(msg.data["pgid"])
        data, buf = {"tid": msg.data.get("tid")}, b""
        if msg.data.get("shard") is not None:
            # echo what the requester ASKED for, so it can match the
            # reply to its plan independently of what we report below
            data["req_shard"] = int(msg.data["shard"])
        if pg is not None and msg.data.get("frag_for") is not None:
            # regenerating-code repair fragment: combine MY stored
            # chunk by the codec's fragment row for the lost shard and
            # ship beta-sized bytes instead of the whole chunk.  The
            # fragment carries its own CRC plus this shard's write-time
            # label/version so the aggregator can verify before mixing.
            oid = msg.data["oid"]
            backend = pg.backend
            frag = backend.fragment_of(oid, int(msg.data["frag_for"])) \
                if hasattr(backend, "fragment_of") else None
            if frag is None:
                data["frag_err"] = "ENOFRAG"
            else:
                fbuf, size, ver, label = frag
                buf = fbuf
                data["size"] = size
                data["ver"] = list(ver)
                data["frag_for"] = int(msg.data["frag_for"])
                if label is not None:
                    data["shard"] = int(label)
                from .backend import shard_crc
                data["crc"] = shard_crc(fbuf)
            await conn.send(Message("ec_subop_read_reply", data,
                                    segments=[buf]))
            return
        if pg is not None:
            oid = msg.data["oid"]
            off = int(msg.data.get("off", 0))
            length = msg.data.get("len")     # None = whole shard
            # serve from the device-resident shard cache when the
            # bytes are resident: the reply (identity xattrs included)
            # never touches the store -- the wire segment is the one
            # unavoidable materialization of a remote read
            entry = self.shard_cache.get(pg.coll, oid) \
                if self.shard_cache is not None else None
            if entry is not None:
                arr = entry.buf if length is None \
                    else entry.buf[off:off + length]
                buf = arr.tobytes()
                data["size"] = entry.size
                data["ver"] = list(entry.ver)
                if entry.shard is not None:
                    data["shard"] = entry.shard
                if entry.crc is not None:
                    data["crc"] = entry.crc
                await conn.send(Message("ec_subop_read_reply", data,
                                        segments=[buf]))
                return
            try:
                buf = self.store.read(pg.coll, oid, off, length)
            except FileNotFoundError:
                buf = b""
            from .backend import (CRC_XATTR, SIZE_XATTR, VER_XATTR,
                                  ver_decode)
            sx = self.store.getattr(pg.coll, oid, SIZE_XATTR)
            data["size"] = int(sx) if sx else 0
            data["ver"] = list(ver_decode(
                self.store.getattr(pg.coll, oid, VER_XATTR)))
            # report the WRITE-TIME identity of the stored bytes (per-
            # object pin, PG pin fallback), NOT the current acting-set
            # index: after a re-peer the index is a claim about where
            # shards SHOULD live; the label is what these bytes ARE.
            # The reader rejects a mismatch instead of decoding garbage.
            label = pg.backend.shard_label(oid) \
                if hasattr(pg.backend, "shard_label") else None
            if label is not None:
                data["shard"] = int(label)
            crc = self.store.getattr(pg.coll, oid, CRC_XATTR)
            if crc is not None:
                data["crc"] = int(crc)
            if self.shard_cache is not None:
                self.shard_cache.note_host_read(len(buf))
                if length is None and off == 0 and (buf or data["size"]):
                    # read-through fill: repeat remote reads of a hot
                    # shard stop re-materializing it from the store
                    self.shard_cache.put(
                        pg.coll, oid, buf, size=data["size"],
                        ver=tuple(data["ver"]), shard=label,
                        crc=data.get("crc"))
        await conn.send(Message("ec_subop_read_reply", data,
                                segments=[buf]))

    async def _h_ec_subop_read_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    # peering
    async def _h_pg_query(self, conn, msg) -> None:
        pg = self._get_pg(msg.data["pgid"])
        if pg is not None:
            data = pg.on_query()
        else:
            from .types import PGInfo
            data = {"pgid": msg.data["pgid"],
                    "info": PGInfo(pgid=msg.data["pgid"]).to_dict(),
                    "entries": [], "from_osd": self.whoami}
        data["tid"] = msg.data.get("tid")
        await conn.send(Message("pg_notify", data))

    async def _h_pg_notify(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_pg_activate(self, conn, msg) -> None:
        pg = self._get_pg(msg.data["pgid"])
        if pg is None:
            await conn.send(Message("pg_activate_ack",
                                    {"tid": msg.data.get("tid"),
                                     "err": "ENXIO", "missing": {},
                                     "from_osd": self.whoami}))
            return
        data = await pg.on_activate(msg)
        data["tid"] = msg.data.get("tid")
        await conn.send(Message("pg_activate_ack", data))

    async def _h_pg_activate_ack(self, conn, msg) -> None:
        self._resolve_tid(msg)

    # recovery
    async def _h_pg_pull(self, conn, msg) -> None:
        pg = self._get_pg(msg.data["pgid"])
        if pg is None:
            await conn.send(Message("pg_pull_reply",
                                    {"tid": msg.data.get("tid"),
                                     "err": "ENXIO"}))
            return
        data, segments = await pg.on_pull(msg)
        data["tid"] = msg.data.get("tid")
        await conn.send(Message("pg_pull_reply", data, segments=segments))

    async def _h_pg_pull_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_pg_push(self, conn, msg) -> None:
        pg = self._get_pg(msg.data["pgid"])
        if pg is None:
            await conn.send(Message("pg_push_reply",
                                    {"tid": msg.data.get("tid"),
                                     "err": "ENXIO"}))
            return
        data = await pg.on_push(msg)
        self.perf_osd.inc("recovery_ops")
        data["tid"] = msg.data.get("tid")
        await conn.send(Message("pg_push_reply", data))

    async def _h_pg_push_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    # backfill (scan diff + completion + reservations)
    async def _h_pg_scan(self, conn, msg) -> None:
        pg = self._get_pg(msg.data["pgid"])
        data = {"tid": msg.data.get("tid"), "from_osd": self.whoami}
        if pg is None:
            data["err"] = "ENXIO"
        else:
            objs, exhausted = pg.scan_range(
                msg.data.get("begin", ""),
                int(msg.data.get("limit", 0)) or 10 ** 9)
            data["objects"] = {o: list(v) for o, v in objs.items()}
            data["exhausted"] = exhausted
        await conn.send(Message("pg_scan_reply", data))

    async def _h_pg_backfill_progress(self, conn, msg) -> None:
        pg = self._get_pg(msg.data["pgid"])
        if pg is None:
            data = {"err": "ENXIO", "from_osd": self.whoami}
        else:
            data = pg.on_backfill_progress(msg.data["cursor"])
        data["tid"] = msg.data.get("tid")
        await conn.send(Message("pg_backfill_progress_reply", data))

    async def _h_pg_backfill_progress_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_pg_scan_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_pg_backfill_done(self, conn, msg) -> None:
        pg = self._get_pg(msg.data["pgid"])
        if pg is None:
            data = {"err": "ENXIO", "from_osd": self.whoami}
        else:
            data = pg.on_backfill_done()
        data["tid"] = msg.data.get("tid")
        await conn.send(Message("pg_backfill_done_reply", data))

    async def _h_pg_backfill_done_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_backfill_reserve(self, conn, msg) -> None:
        """Grant-or-busy: the primary polls again next recovery round
        rather than queueing forever on a busy target."""
        token = msg.data["pgid"]
        try:
            await self.remote_reserver.request(token, timeout=5)
            granted = True
        except asyncio.TimeoutError:
            granted = False
        await conn.send(Message("backfill_reserve_reply",
                                {"tid": msg.data.get("tid"),
                                 "granted": granted,
                                 "from_osd": self.whoami}))

    async def _h_backfill_reserve_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)

    async def _h_backfill_release(self, conn, msg) -> None:
        self.remote_reserver.release(msg.data["pgid"])
        await conn.send(Message("backfill_release_reply",
                                {"tid": msg.data.get("tid"),
                                 "from_osd": self.whoami}))

    async def _h_backfill_release_reply(self, conn, msg) -> None:
        self._resolve_tid(msg)
