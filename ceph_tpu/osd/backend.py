"""PGBackend: replication fan-out vs erasure-coded shard I/O.

The SPI mirrors src/osd/PGBackend.cc:570 build_pg_backend — the pool
type selects ReplicatedBackend (primary-copy fan-out, MOSDRepOp) or
ECBackend (encode + per-shard sub-writes, MOSDECSubOpWrite; reads
gather minimum_to_decode shards and reconstruct, ECCommon.cc:597).

Mutations are resolved to concrete, offset-explicit ops at the primary
(append/writefull become plain writes) so replicas and shards apply
them deterministically — the same discipline as
PrimaryLogPG ops -> ObjectStore::Transaction translation.
"""

from __future__ import annotations

import asyncio

import numpy as np

from ..os.transaction import Transaction
from .ec_util import StripeInfo
from .types import LogEntry, MissingSet, ZERO

META_OID = "_pgmeta_"
SIZE_XATTR = "_size"
VER_XATTR = "_ver"     # per-object version stamp, "epoch,v" (object_info_t
                       # analog: lets readers reject stale shards and lets
                       # backfill diff object versions without log overlap)
SHARD_XATTR = "_shard"  # WRITE-TIME-PINNED shard id of the stored bytes
                        # (shard_id_t in the reference's ghobject): reads
                        # and recovery verify this label instead of
                        # trusting the OSD's CURRENT acting-set position,
                        # which changes across re-peering
CRC_XATTR = "_crc"      # CRC32C of the stored shard bytes (the per-shard
                        # hashinfo digest): rejects payloads/replies whose
                        # bytes don't match their claimed identity
HIDDEN_XATTRS = frozenset({SIZE_XATTR, VER_XATTR, SHARD_XATTR,
                           CRC_XATTR})               # never client-visible


def shard_crc(data) -> int:
    """CRC32C of shard bytes -- ONE polynomial everywhere (the same
    kernel the codec batcher, scrub and blockstore ride).  Pre-
    unification tags were zlib.crc32 (a different polynomial);
    shard_crc_matches() keeps those readable."""
    from ..ops.crc32c_batch import crc32c_batch
    return int(crc32c_batch([bytes(data)])[0])


def shard_crc_matches(data, tag, precomputed: int | None = None) -> bool:
    """Does a stored/reported ``_crc`` tag vouch for ``data``?

    Matches the unified CRC32C first (``precomputed`` lets batched
    verify paths pass a value they already hold).  On mismatch, ONE
    compat re-check against the pre-unification zlib.crc32 polynomial
    accepts tags stamped before the integrity pipeline unified -- a
    genuinely corrupt buffer pays the second hash only on the failure
    path, and the legacy acceptance is counted so it can be watched
    going to zero.
    """
    if tag is None:
        return True
    tag = int(tag)
    crc = shard_crc(data) if precomputed is None else int(precomputed)
    if crc == tag:
        return True
    import zlib
    if zlib.crc32(bytes(data)) & 0xFFFFFFFF == tag:
        from ..ops.crc32c_batch import PERF
        PERF.inc("legacy_crc_tags")
        return True
    return False


def ver_encode(version) -> bytes:
    return f"{version.epoch},{version.version}".encode()


def ver_decode(raw: bytes | None) -> tuple[int, int]:
    if not raw:
        return (0, 0)
    a, b = raw.decode().split(",")
    return (int(a), int(b))


# -- wire packing: JSON meta + binary segments ------------------------------

def pack_mutations(muts: list[dict]) -> tuple[list[dict], list[bytes]]:
    meta, segments = [], []
    for m in muts:
        m2 = dict(m)
        for key in ("data", "value"):
            if key in m2 and isinstance(m2[key], (bytes, bytearray,
                                                  np.ndarray)):
                buf = bytes(m2[key]) if not isinstance(
                    m2[key], np.ndarray) else m2[key].tobytes()
                m2[key] = {"seg": len(segments), "len": len(buf)}
                segments.append(buf)
        if "kv" in m2:
            kv = m2["kv"]
            buf = b"".join(
                len(k.encode()).to_bytes(4, "big") + k.encode()
                + len(v).to_bytes(4, "big") + bytes(v)
                for k, v in kv.items())
            m2["kv"] = {"seg": len(segments), "n": len(kv)}
            segments.append(buf)
        meta.append(m2)
    return meta, segments


def unpack_mutations(meta: list[dict],
                     segments: list[bytes]) -> list[dict]:
    out = []
    for m in meta:
        m2 = dict(m)
        for key in ("data", "value"):
            if isinstance(m2.get(key), dict):
                m2[key] = segments[m2[key]["seg"]]
        if isinstance(m2.get("kv"), dict):
            buf = segments[m2["kv"]["seg"]]
            kv, pos = {}, 0
            for _ in range(m2["kv"]["n"]):
                klen = int.from_bytes(buf[pos:pos + 4], "big"); pos += 4
                k = buf[pos:pos + klen].decode(); pos += klen
                vlen = int.from_bytes(buf[pos:pos + 4], "big"); pos += 4
                kv[k] = buf[pos:pos + vlen]; pos += vlen
            m2["kv"] = kv
        out.append(m2)
    return out


def apply_mutations(txn: Transaction, coll: str, oid: str,
                    muts: list[dict]) -> None:
    """Translate resolved logical mutations into Transaction ops."""
    for m in muts:
        op = m["op"]
        if op == "create":
            txn.touch(coll, oid)
        elif op == "write":
            txn.write(coll, oid, m["off"], m["data"])
        elif op == "truncate":
            txn.truncate(coll, oid, m["size"])
        elif op == "zero":
            txn.zero(coll, oid, m["off"], m["len"])
        elif op == "remove":
            txn.remove(coll, oid)
        elif op == "setxattr":
            txn.setattr(coll, oid, m["name"], m["value"])
        elif op == "rmxattr":
            txn.rmattr(coll, oid, m["name"])
        elif op == "omap_set":
            txn.omap_setkeys(coll, oid, m["kv"])
        elif op == "omap_rm":
            txn.omap_rmkeys(coll, oid, m["keys"])
        elif op == "omap_clear":
            txn.omap_clear(coll, oid)
        # -- snapshot machinery (ceph_tpu/osd/snaps.py): these ride in
        # the same entry as the data op so replicas stay in lockstep
        elif op == "clone_from":
            # clone-on-write: oid here is the CLONE object; src is the
            # head whose current state it freezes
            from .snaps import SNAPMAPPER_OID, snapmapper_key
            txn.clone(coll, m["src"], oid)
            txn.omap_setkeys(coll, SNAPMAPPER_OID,
                             {snapmapper_key(s, m["src"]): b""
                              for s in m.get("snaps", [])})
        elif op == "snapset_set":
            from .snaps import SNAPSETS_OID
            txn.touch(coll, SNAPSETS_OID)
            value = m["value"]
            if isinstance(value, str):
                value = value.encode()
            txn.omap_setkeys(coll, SNAPSETS_OID, {m["head"]: value})
        elif op == "snapmap_rm":
            from .snaps import SNAPMAPPER_OID
            txn.omap_rmkeys(coll, SNAPMAPPER_OID, m["keys"])
        else:
            raise ValueError(f"unknown mutation op {op}")


class PGBackend:
    """SPI both backends implement; `pg` provides log/info/persistence
    and `osd` provides peer RPC + the local store."""

    def __init__(self, pg) -> None:
        self.pg = pg
        self.osd = pg.osd
        # pipelined write spine (PR 12): when on, submit_transaction
        # stages its sub-op sends through the per-peer coalescing pipe
        # and RETURNS the commit wait instead of awaiting it -- the PG
        # releases its lock before awaiting, so the next op's
        # gather/encode/store phases overlap this op's peer round
        # trip.  Snapshot at construction (hot-path-config-read).
        self._pipeline = self._cfg("osd_pipeline_enabled", True)

    def _cfg(self, name: str, default):
        cfg = getattr(self.osd, "config", None)
        if not isinstance(cfg, dict):
            return default
        return type(default)(cfg.get(name, default))

    @property
    def store(self):
        return self.osd.store

    @property
    def coll(self) -> str:
        return self.pg.coll

    def _queue_txn_traced(self, txn: Transaction, oid: str) -> None:
        """Commit the txn with a store.txn span when an op trace is
        active on this task (the client->OSD->store hop chain)."""
        from ..common.tracing import current_span, get_tracer
        cur = current_span.get()
        if cur is None:
            self.store.queue_transaction(txn)
            return
        sp = get_tracer(cur._tracer.daemon).start("store.txn", oid=oid)
        try:
            self.store.queue_transaction(txn)
        finally:
            sp.finish()

    async def submit_transaction(self, entry: LogEntry,
                                 muts: list[dict]) -> None:
        raise NotImplementedError

    async def object_read(self, oid: str, off: int,
                          length: int | None) -> bytes:
        raise NotImplementedError

    async def object_size(self, oid: str) -> int:
        raise NotImplementedError

    # recovery: full-object state transfer units
    async def read_recovery_payload(self, oid: str, shard: int) -> dict:
        raise NotImplementedError

    def invalidate_extents(self, oid: str | None = None) -> None:
        """Shard content changed outside the write path (recovery push,
        backfill, peering reset): drop any cached extents.  No-op for
        backends without a cache."""

    async def _fanout_commits(self, awaiting, entry: LogEntry) -> None:
        """All-commit fan-out with laggard healing.

        A peer that fails to ack inside the timeout has NOT applied the
        write but stays acting (nobody died, no re-peer).  Leaving it be
        is a time bomb: the object's data there is stale, and a later
        write that only stamps versions (the ranged RMW path) would make
        the staleness invisible.  The reference wedges the op until the
        laggard commits or is marked down (all_commit); this framework
        heals forward instead -- the laggard is recorded missing that
        object and recovery re-pushes the full object.  The op only
        ACKS when commits (local + acked peers) still reach the pool's
        min_size; below that the durability story is too thin and the
        error surfaces to the client."""
        if not awaiting:
            return
        replies = await self.osd.fanout_and_wait(awaiting, collect=True)
        self._heal_laggards(awaiting, replies, entry)

    def _heal_laggards(self, awaiting, replies, entry: LogEntry) -> None:
        """The all-commit accounting tail shared by the serial and
        pipelined fan-outs: record laggards missing, kick recovery,
        error below min_size."""
        acked = {r.data.get("from_osd") for r in replies}
        laggards = [t[0] for t in awaiting if t[0] not in acked]
        if not laggards:
            return
        for osd_id in laggards:
            ms = self.pg.peer_missing.setdefault(osd_id, MissingSet())
            ms.add(entry.oid, need=entry.version, have=ZERO)
        self.pg.kick_recovery()
        n_committed = 1 + len(acked)         # local shard + repliers
        if n_committed < self.pg.pool.min_size:
            raise TimeoutError(
                f"{entry.oid}: only {n_committed} commits < min_size "
                f"{self.pg.pool.min_size} (laggards {laggards})")

    def _start_commits(self, awaiting, entry: LogEntry):
        """Deferred all-commit fan-out, the pipelined half of
        ``_fanout_commits``: stage every sub-op send NOW -- staging is
        synchronous, so the per-peer wire order is the submit order
        (replica logs apply in version order) -- and return a Task
        that resolves when the commits land, with the same laggard
        healing and min_size semantics.  None when the pipeline is
        off (kill switch) or the coalescing pipe is not up."""
        pipe = getattr(self.osd, "subop_pipe", None)
        if not self._pipeline or pipe is None or pipe.closed:
            return None
        futs = self.osd.fanout_staged(awaiting)

        async def _commit():
            replies = await self.osd.await_staged(futs, collect=True)
            self._heal_laggards(awaiting, replies, entry)

        # a bare coroutine, not a task: PG._chain_commit wraps it in
        # the ONE per-write ordering task (two tasks per write is
        # measurable overhead on a saturated loop)
        return _commit()

    async def _commit_or_defer(self, awaiting, entry: LogEntry):
        """Serial chain (await the fan-out under the caller) or
        pipelined chain (return the commit wait for the PG to await
        OUTSIDE its lock).  The two paths share the send payloads and
        the healing tail; only WHERE the await happens differs.

        The staged sends deliberately ship from the pipe's per-peer
        workers, NOT inline here: an inline send runs under the PG
        lock, and a dead peer's reconnect backoff would hold the lock
        across it -- measured at 64 OSDs as the degraded phase
        collapsing into wedged ops (the serial chain's exact failure
        mode, reintroduced).  The one scheduling pass a worker costs
        is the price of keeping peer death out of the lock."""
        if not awaiting:
            return None
        commit = self._start_commits(awaiting, entry)
        if commit is None:
            await self._fanout_commits(awaiting, entry)
        return commit


def build_pg_backend(pg):
    """PGBackend.cc:570 — pool type picks the backend."""
    if pg.pool.is_erasure():
        return ECBackend(pg)
    return ReplicatedBackend(pg)


class ReplicatedBackend(PGBackend):
    async def submit_transaction(self, entry, muts) -> None:
        txn = Transaction()
        apply_mutations(txn, self.coll, entry.oid, muts)
        if not entry.is_delete():
            txn.setattr(self.coll, entry.oid, VER_XATTR,
                        ver_encode(entry.version))
        self.pg.append_log_and_meta(txn, entry)
        self._queue_txn_traced(txn, entry.oid)
        # fan out to every other acting replica and wait for all commits
        # (ReplicatedBackend.cc: all_commit before client reply).
        # Backfill targets beyond their last_backfill watermark get the
        # LOG ENTRY only (empty transaction): their data for that object
        # arrives when the backfill scan reaches it, but their log/
        # last_update must stay in step with the acting set.
        meta, segs = pack_mutations(muts)
        from ..common.tracing import current_span
        cur = current_span.get()
        tr = {"trace": cur.ctx()} if cur is not None else {}
        targets = []
        for o in self.pg.acting:
            if o < 0 or o == self.osd.whoami:
                continue
            if self.pg.should_send_to(o, entry.oid):
                targets.append((o, "rep_op",
                                {"pgid": self.pg.pgid,
                                 "entry": entry.to_dict(),
                                 "muts": meta, **tr}, segs))
            else:
                targets.append((o, "rep_op",
                                {"pgid": self.pg.pgid,
                                 "entry": entry.to_dict(),
                                 "muts": [], "log_only": True,
                                 **tr}, []))
        return await self._commit_or_defer(targets, entry)

    def apply_rep_op(self, entry: LogEntry, muts: list[dict],
                     log_only: bool = False) -> None:
        """Replica side: apply the primary's resolved mutations."""
        txn = Transaction()
        if not log_only:
            apply_mutations(txn, self.coll, entry.oid, muts)
            if not entry.is_delete():
                txn.setattr(self.coll, entry.oid, VER_XATTR,
                            ver_encode(entry.version))
        self.pg.append_log_and_meta(txn, entry)
        self._queue_txn_traced(txn, entry.oid)

    async def object_read(self, oid, off, length) -> bytes:
        return self.store.read(self.coll, oid, off, length)

    async def object_size(self, oid) -> int:
        st = self.store.stat(self.coll, oid)
        return 0 if st is None else st["size"]

    async def read_recovery_payload(self, oid, shard) -> dict:
        try:
            data = self.store.read(self.coll, oid, 0, None)
        except FileNotFoundError:
            return {"data": b"", "xattrs": {}, "omap": {},
                    "absent": True}
        return {"data": data,
                "xattrs": self.store.getattrs(self.coll, oid),
                "omap": self.store.omap_get(self.coll, oid)}


class ECBackend(PGBackend):
    """Erasure-coded object I/O over acting-set shards.

    Shard i of every object lives on acting[i] (shard id = position in
    the acting set, ErasureCodeInterface.h:39-78).  Writes that cover
    whole objects (fresh objects, truncate/remove chains, rewrites of
    every stripe) run full-object RMW: reconstruct current logical
    bytes, apply the mutation, re-encode, distribute per-shard
    sub-writes.  Partial overwrites of existing objects take the
    RMW pipeline (ECCommon.cc:704 start_rmw analog, _plan_rmw /
    _submit_partial below): only the touched stripes are read (the
    ExtentCache feeds repeats), merged, re-encoded and shipped as
    RANGED per-shard sub-writes — write amplification is
    O(touched stripes), not O(object)
    (tests/test_ec_rmw.py pins both the byte movement and this
    docstring's claim).

    Codec launches go through the per-OSD CodecBatcher
    (osd.codec_batcher): all stripes of an op share one
    encode_batch/decode_batch launch, and concurrent ops across PGs
    coalesce into common launches.  The batcher in turn launches
    coalesced batches through the sharded device mesh
    (parallel/mesh_codec.MeshCodec) when one is configured, so
    full-stripe writes, degraded-read decodes and recovery
    reconstructions all ride the multichip data plane transparently
    -- on a single device that is a 1-device mesh, same code path.
    """

    def __init__(self, pg) -> None:
        super().__init__(pg)
        profile = dict(pg.ec_profile)
        plugin = profile.pop("plugin", "tpu")
        from ..ec import registry
        from .ec_util import parse_stripe_unit
        from .extent_cache import ExtentCache
        self.codec = registry().factory(plugin, profile)
        self.sinfo = StripeInfo.for_codec(
            self.codec, stripe_unit=parse_stripe_unit(
                self.codec, profile.get("stripe_unit", 4096)))
        self.cache = ExtentCache()
        # degraded-path observability (perf counter set "ec_degraded"):
        # reconstructions actually run, mislabeled/corrupt shards
        # rejected, gather retry rounds (None on bare-backend tests)
        perf = getattr(self.osd, "perf", None)
        self.perf_degraded = perf.create("ec_degraded") \
            if perf is not None else None
        # repair-I/O observability (perf counter set "ec_recovery"):
        # the bytes recovery actually gathers vs ships is the whole
        # point of the recovery-bandwidth-optimal codes -- chaos and
        # bench.py --recovery pin the per-code ratios on these instead
        # of trusting the repair-math claim
        self.perf_recovery = perf.create("ec_recovery") \
            if perf is not None else None
        # hot-path config SNAPSHOT (the ROADMAP config-reads-on-hot-
        # paths item): _gather_shards runs per degraded read; looking
        # these up per call put a dict probe chain on the read path
        self._read_retries = self._cfg("osd_ec_read_retries", 3)
        self._read_timeout = self._cfg("osd_ec_read_timeout", 5.0)
        self._read_backoff = self._cfg("osd_ec_read_backoff", 0.25)
        # device-resident shard cache (os/device_cache.py): full-shard
        # reads, ranged RMW slices, scrub verifies and the write-path
        # identity stamp all serve from residency instead of
        # round-tripping the store.  None in bare tests / when disabled.
        self.dcache = getattr(self.osd, "shard_cache", None)
        # partial-stripe writes delta-update parity in place
        # (MeshCodec.rmw / CodecBatcher.rmw) instead of re-encoding
        # whole stripes; snapshot, never read per write
        self._rmw_delta = self._cfg("osd_ec_rmw_delta_enabled", True)
        # straggler-tolerant gathers: the OSD-wide HedgedGather engine
        # (osd/hedged_gather.py) + per-peer latency EWMA.  None on bare
        # test backends -- every hedged path degrades to the legacy
        # fixed fanout.
        self.hedger = getattr(self.osd, "hedger", None)
        # regenerating-code repair fragments (the pmsr plugin): helpers
        # ship beta-sized COMPUTED sub-chunks instead of full chunks;
        # snapshot the gate and the stripe geometry the fragment
        # algebra reshapes at (hot-path-config-read discipline)
        self._frag_repair = self._cfg("osd_ec_repair_fragments_enabled",
                                      True)
        if hasattr(self.codec, "set_fragment_chunk_size"):
            self.codec.set_fragment_chunk_size(self.sinfo.chunk_size)

    def _count(self, key: str, by: int = 1) -> None:
        if self.perf_degraded is not None:
            self.perf_degraded.inc(key, by)

    def _rcount(self, key: str, by: int = 1) -> None:
        if self.perf_recovery is not None:
            self.perf_recovery.inc(key, by)

    @property
    def batcher(self):
        """The OSD-wide codec aggregation stage (None in bare tests)."""
        return getattr(self.osd, "codec_batcher", None)

    def _log_only_subop(self, osd: int, shard: int, entry: LogEntry):
        """ec_subop_write carrying only the log entry (backfill target
        beyond its watermark)."""
        return (osd, "ec_subop_write",
                {"pgid": self.pg.pgid, "oid": entry.oid, "shard": shard,
                 "entry": entry.to_dict(), "w": {"log_only": True},
                 "attr_muts": []}, [])

    @property
    def k(self) -> int:
        return self.sinfo.k

    def my_shard(self) -> int:
        """This OSD's shard position in the CURRENT acting set.  The
        PG-pinned shard_id (write-time identity) normally agrees; when
        they diverge the PG has been remapped and pg._check_shard_identity
        already queued the local objects for re-recovery."""
        return self.pg.acting.index(self.osd.whoami)

    def shard_label(self, oid: str) -> int | None:
        """The WRITE-TIME shard id of the locally stored bytes: the
        per-object pin first, the PG-level pin as fallback for objects
        predating per-object stamps, else the current acting position."""
        raw = self.store.getattr(self.coll, oid, SHARD_XATTR)
        if raw is not None:
            try:
                return int(raw)
            except ValueError:
                pass
        if self.pg.shard_id is not None:
            return self.pg.shard_id
        try:
            return self.my_shard()
        except ValueError:
            return None

    def invalidate_extents(self, oid: str | None = None) -> None:
        if oid is None:
            self.cache.clear()
        else:
            self.cache.invalidate(oid)

    # -- logical object reconstruction --------------------------------------
    def _local_entry(self, oid: str,
                     rng: tuple[int, int] | None = None):
        """(buf, size, ver, label, crc, cached) for my shard; absent
        -> (b'', 0, (0,0), ..., False).

        ``rng`` = (chunk_off, chunk_len) reads only that slice of the
        shard (the partial-stripe RMW read phase).  The device-resident
        cache serves full reads AND ranged slices without touching the
        store; misses read through the store's checksum-on-read path
        and (full reads) populate the cache so scrub re-verifies and
        repeat degraded reads hit.  ``cached`` marks content that was
        verified at fill/write time and needs no CRC re-hash."""
        cache = self.dcache
        if cache is not None:
            e = cache.get(self.coll, oid)
            if e is not None:
                buf = e.buf if rng is None \
                    else e.buf[rng[0]:rng[0] + rng[1]]
                return buf, e.size, e.ver, e.shard, e.crc, True
        off, length = rng if rng else (0, None)
        try:
            raw = self.store.read(self.coll, oid, off, length)
        except FileNotFoundError:
            raw = b""
        sx = self.store.getattr(self.coll, oid, SIZE_XATTR)
        size = int(sx) if sx else 0
        ver = ver_decode(self.store.getattr(self.coll, oid, VER_XATTR))
        label = self.shard_label(oid)
        crc_raw = self.store.getattr(self.coll, oid, CRC_XATTR)
        crc = int(crc_raw) if crc_raw is not None else None
        buf = np.frombuffer(raw, np.uint8)
        if cache is not None:
            cache.note_host_read(len(raw))
            if rng is None and (raw or size):
                # read-through fill: content just came through the
                # store's verified read path, with its identity xattrs
                cache.put(self.coll, oid, buf, size=size, ver=ver,
                          shard=label, crc=crc)
        return buf, size, ver, label, crc, False

    def _label_ok(self, shard: int, label, buf, ver) -> bool:
        """Is a stored/reported shard label consistent with serving
        position ``shard``?  Absent objects (no version, no bytes) are
        consistent everywhere; an explicit mismatched label means the
        bytes were written AS a different shard -- decoding them under
        this position is the mislabeling corruption, so the source is
        rejected instead."""
        if tuple(ver) == (0, 0) and not len(buf):
            return True
        return label is None or int(label) == shard

    def _entry_from_reply(self, rep, default_shard: int | None = None
                          ) -> tuple:
        """An ec_subop_read reply as a gather entry: (shard, label,
        crc, buf, size, ver, trusted)."""
        s = rep.data.get("req_shard", rep.data.get("shard",
                                                   default_shard))
        buf = np.frombuffer(
            rep.segments[0] if rep.segments else b"", np.uint8)
        return (s, rep.data.get("shard"), rep.data.get("crc"), buf,
                rep.data.get("size", 0),
                tuple(rep.data.get("ver", (0, 0))), False)

    def _admit_entries(self, entries: list[tuple],
                       rng: tuple[int, int] | None,
                       out: dict, failed: set,
                       relabeled: dict) -> set[int]:
        """Verify one batch of gathered entries into the caller's
        (out, failed, relabeled) state; returns the accepted shards.

        Whole-shard fetches verify their CRC tags in ONE batched pass
        over the batch (the hot read path used to re-hash each reply
        with its own scalar host call); cache-resident buffers were
        verified when they became resident and skip the re-hash
        entirely -- deep scrub re-checks them on its cadence."""
        have: dict[int, int] = {}
        if rng is None:
            idx = [i for i, e in enumerate(entries) if not e[6]]
            if idx:
                from ..ops.crc32c_batch import crc32c_batch
                crcs = crc32c_batch([entries[i][3] for i in idx])
                have = {i: int(c) for i, c in zip(idx, crcs)}
        accepted: set[int] = set()
        for i, (s, label, crc, buf, size, ver,
                trusted) in enumerate(entries):
            hv = have.get(i)
            if not self._label_ok(s, label, buf, ver):
                self._count("shard_mismatch")
                failed.add(s)
                # CRC-verified bytes under their OWN label are salvage,
                # not garbage (ranged reads can't re-check the whole-
                # shard crc; the label xattr alone vouches there)
                if label is not None and int(label) >= 0 and \
                        (rng is not None or crc is None or trusted
                         or shard_crc_matches(buf, crc,
                                              precomputed=hv)):
                    relabeled.setdefault(int(label), (buf, size, ver))
                continue
            if rng is None and crc is not None and not trusted \
                    and not shard_crc_matches(buf, crc,
                                              precomputed=hv):
                self._count("crc_mismatch")
                failed.add(s)
                continue
            out[s] = (buf, size, ver)
            accepted.add(s)
        return accepted

    async def _fetch_shards(self, oid: str, shards: list[int],
                            avail: dict[int, int],
                            rng: tuple[int, int] | None = None,
                            timeout: float = 10.0, *,
                            want: set[int] | None = None,
                            have: frozenset = frozenset(),
                            rejected: frozenset = frozenset()
                            ) -> tuple[dict, set[int], dict]:
        """Fetch several shards' (buf, size, ver) in ONE parallel pass
        (the hot read path: serial round trips would multiply latency
        by k).

        With ``want`` given (and the OSD's HedgedGather enabled), the
        remote sub-reads are HEDGED: issued individually, a hedge
        timer armed off the per-peer latency EWMA's adaptive quantile,
        extra shards requested on fire, and the gather completed on
        the FIRST verified sufficient set -- a straggling source is
        decoded around instead of awaited.  Without ``want`` (ranged
        RMW parity fetches, bare-test backends) the legacy fixed
        fanout runs.

        Returns (fetched, failed, relabeled): a shard lands in
        ``failed`` when its source did not answer inside ``timeout``
        (and the gather still needed it), reported a mismatched
        write-time shard label, or returned bytes that fail the CRC
        tag -- the caller excludes those sources and re-plans, so a
        dead or mislabeled source can never wedge or corrupt a read.
        A sub-read cancelled because the gather already held a
        sufficient set is NOT failed: its source is merely slow.  A
        mismatched source whose bytes verify under their OWN label
        goes into ``relabeled`` keyed by that label: a remapped OSD's
        old-shard bytes are still perfectly good data for the shard
        they WERE, and using them is what lets reads and recovery
        converge while relocation is in flight."""
        out: dict[int, tuple] = {}
        failed: set[int] = set()
        relabeled: dict[int, tuple] = {}
        # (shard, label, crc, buf, size, ver, trusted); trusted marks
        # cache-resident content verified at fill/write time
        entries: list[tuple] = []
        remote = []
        for s in shards:
            if avail[s] == self.osd.whoami:
                buf, size, ver, label, crc, cached = \
                    self._local_entry(oid, rng)
                entries.append((s, label, crc, buf, size, ver, cached))
            else:
                remote.append(s)
        self._admit_entries(entries, rng, out, failed, relabeled)
        if not remote:
            return out, failed, relabeled
        hedger = self.hedger
        if want is not None and hedger is not None and hedger.enabled:
            await self._fetch_remote_hedged(
                oid, remote, avail, rng, timeout, set(want),
                set(have), set(rejected), out, failed, relabeled)
        else:
            await self._fetch_remote_fanout(
                oid, remote, avail, rng, timeout, out, failed,
                relabeled)
        return out, failed, relabeled

    async def _fetch_remote_fanout(self, oid, remote, avail, rng,
                                   timeout, out, failed,
                                   relabeled) -> None:
        """Legacy fixed fan-out: one parallel wait for every reply."""
        payload = {"pgid": self.pg.pgid, "oid": oid}
        if rng is not None:
            payload["off"], payload["len"] = rng
        replies = await self.osd.fanout_and_wait(
            [(avail[s], "ec_subop_read", {**payload, "shard": s}, [])
             for s in remote],
            collect=True, timeout=timeout)
        # same sub-read accounting as the hedged path, so a hedged-vs-
        # unhedged comparison (bench.py --straggler's extra-bytes gate)
        # reads one counter set either way
        if self.hedger is not None:
            self.hedger.note("subreads", len(remote))
            self.hedger.note("subread_bytes",
                             sum(len(seg) for rep in replies
                                 for seg in rep.segments))
        entries = []
        for rep in replies:
            e = self._entry_from_reply(rep)
            if e[0] is None or e[0] not in remote:
                continue
            entries.append(e)
        self._admit_entries(entries, rng, out, failed, relabeled)
        failed |= {s for s in remote
                   if s not in out and s not in failed}

    async def _fetch_remote_hedged(self, oid, remote, avail, rng,
                                   timeout, want, have, rejected,
                                   out, failed, relabeled) -> None:
        """First-k-of-(k+h) remote gather through the OSD's
        HedgedGather engine.

        Sufficiency re-plans ``minimum_to_decode`` over everything
        verified so far (prior rounds + this one + relabeled salvage),
        so a late-set switch -- the hedged parity shard arriving
        before a straggling data shard -- completes the gather with a
        DIFFERENT set than originally planned; the decode-repair-
        matrix cache makes that switch cheap downstream.  Hedge extras
        are chosen by ``minimum_to_decode_with_cost`` with per-peer
        EWMA costs (in-hand shards cost zero, outstanding stragglers
        carry a lateness penalty), which preserves the LRC plugin's
        locality preference."""
        hedger = self.hedger
        tracker = hedger.tracker
        payload = {"pgid": self.pg.pgid, "oid": oid}
        if rng is not None:
            payload["off"], payload["len"] = rng

        def sub(s):
            return (avail[s], "ec_subop_read", {**payload, "shard": s})

        plan = {s: sub(s) for s in remote}
        pool = {s: sub(s) for s in avail
                if s not in remote and s not in have
                and s not in rejected
                and avail[s] != self.osd.whoami}
        pending_entries: list[tuple] = []

        def on_reply(s, msg):
            if msg is None:                  # send failure: dead peer
                failed.add(s)
                return
            pending_entries.append(
                self._entry_from_reply(msg, default_shard=s))

        def flush():
            if pending_entries:
                self._admit_entries(pending_entries, rng, out, failed,
                                    relabeled)
                pending_entries.clear()

        def sufficient():
            flush()
            usable = have | set(out) | set(relabeled)
            try:
                plan2 = set(self.codec.minimum_to_decode(want, usable))
            except Exception:
                return False
            return plan2 if plan2 <= usable else False

        default_s = hedger.delay_max
        late_penalty = int(1e6 * hedger.delay_max) + 1

        def choose_extras(h):
            flush()
            in_hand = have | set(out) | set(relabeled)
            costs = {s: 0 for s in in_hand}
            for s in plan:
                if s not in costs and s not in failed:
                    # outstanding and already late relative to the
                    # cohort quantile: costlier than any fresh source
                    costs[s] = tracker.cost_us(avail[s], default_s) \
                        + late_penalty
            for s in pool:
                if s not in costs:
                    costs[s] = max(
                        1, tracker.cost_us(avail[s], default_s))
            try:
                cheap = set(self.codec.minimum_to_decode_with_cost(
                    set(want), costs))
            except Exception:
                return {}
            picks = sorted(s for s in cheap if s in pool)[:h]
            return {s: pool[s] for s in picks}

        outcome = await hedger.gather_shards(
            plan, on_reply=on_reply, sufficient=sufficient,
            hedge_pool=pool, choose_extras=choose_extras,
            timeout=timeout)
        flush()
        if not outcome.completed:
            # sources that never answered (and were still needed) are
            # failures for the caller's re-plan; cancelled sub-reads
            # of a COMPLETED gather never land here
            failed |= {s for s in outcome.timed_out if s not in out}
            failed |= {s for s in remote
                       if s not in out and s not in failed
                       and s not in outcome.cancelled}

    async def _gather_shards(self, oid: str,
                             need_shards: set[int] | None = None,
                             rng: tuple[int, int] | None = None,
                             exclude: set[int] | None = None
                             ) -> tuple[dict[int, np.ndarray], int]:
        """Read enough CONSISTENT shard buffers to decode.

        A shard OSD that missed the object (recovering peer, stale
        incarnation) must not contribute zero-fill as if it were data --
        decoding from it silently corrupts the reconstruction (the
        reference gates shard reads on peer_missing / object versions).
        Every shard write stamps VER_XATTR; here only shards carrying the
        newest version seen participate, and minimum_to_decode is re-run
        over the survivors when a shard is rejected.
        """
        acting = self.pg.acting
        avail: dict[int, int] = {}           # shard -> osd
        for shard, osd in enumerate(acting):
            if osd >= 0 and self.osd.osd_is_up(osd) \
                    and (exclude is None or shard not in exclude):
                avail[shard] = osd
        want = set(need_shards
                   or self.sinfo.data_positions(self.codec))
        if not want <= set(avail):
            self._count("degraded_reads")    # a decode must reconstruct
        retries = self._read_retries
        timeout = self._read_timeout
        backoff = self._read_backoff
        fetched: dict[int, tuple[np.ndarray, int, tuple]] = {}
        rejected: set[int] = set()
        # bounded: staleness can reject at most len(acting) shards and
        # transient fetch failures get `retries` extra rounds -- beyond
        # that the read ERRORS instead of wedging (the seed's unbounded
        # wait turned one dead source into a hung client read)
        for attempt in range(retries + len(acting) + 1):
            # what's already verified in hand (including relabeled
            # salvage from remapped holders) counts as available
            usable = (set(avail) | set(fetched)) - rejected
            try:
                plan = set(self.codec.minimum_to_decode(want, usable))
            except Exception as e:
                raise IOError(
                    f"EIO {oid}: cannot decode shards {sorted(want)} "
                    f"from {sorted(usable)}") from e
            to_fetch = sorted(s for s in plan - set(fetched)
                              if s in avail)
            got, failed, relabeled = await self._fetch_shards(
                oid, to_fetch, avail, rng, timeout, want=want,
                have=frozenset(fetched), rejected=frozenset(rejected))
            fetched.update(got)
            for label, item in relabeled.items():
                # direct position-keyed fetches take precedence over
                # salvage; salvage never overwrites either
                fetched.setdefault(label, item)
            rejected |= failed
            # decodable from what's in hand?  A hedged fetch may have
            # completed with a DIFFERENT sufficient set than the
            # pre-fetch plan (the late-set switch), so re-plan over the
            # fetched set instead of insisting on the original one.
            try:
                plan2 = set(self.codec.minimum_to_decode(
                    want, set(fetched)))
            except Exception:
                plan2 = None
            if plan2 is None or not plan2 <= set(fetched):
                # insufficient: THIS is the only path into the retry/
                # backoff ladder.  A gather already holding a
                # sufficient set can therefore never ALSO schedule a
                # retry round -- hedging does not multiply with
                # osd_ec_read_retries (the combined sub-read bound is
                # pinned in tests/test_hedged_reads.py).
                self._count("gather_retries")
                if backoff > 0 and attempt < retries:
                    await asyncio.sleep(min(backoff * (2 ** attempt),
                                            2.0))
                continue                     # re-plan around the losses
            vers = {s: fetched[s][2] for s in plan2}
            newest = max(vers.values())
            stale = {s for s, v in vers.items() if v < newest}
            if not stale:
                bufs = {s: fetched[s][0] for s in plan2}
                size = max((fetched[s][1] for s in plan2), default=0)
                # ranged reads must pad every shard to the full range so
                # decode sees aligned slices (a short read = the shard
                # file ends inside the range; logical zeros beyond)
                shard_len = (rng[1] if rng is not None else
                             max((len(b) for b in bufs.values()),
                                 default=0))
                for s, b in list(bufs.items()):
                    if len(b) < shard_len:
                        nb = np.zeros(shard_len, np.uint8)
                        nb[:len(b)] = b
                        bufs[s] = nb
                return bufs, size, newest
            rejected |= stale
            for s in stale:
                fetched.pop(s, None)
        self._count("gather_failures")
        raise IOError(
            f"EIO {oid}: no consistent shard set "
            f"(rejected {sorted(rejected)})")

    async def _read_logical(self, oid: str) -> bytes:
        bufs, size, _ = await self._gather_shards(oid)
        if not bufs or not any(len(b) for b in bufs.values()):
            return b""
        if not set(self.sinfo.data_positions(self.codec)) <= set(bufs):
            self._count("reconstructions")   # decode fills a data shard
        data = await self.sinfo.reconstruct_logical_async(
            self.codec, bufs, batcher=self.batcher)
        return data[:size]

    async def collect_shard_states(self, oid: str
                                   ) -> tuple[list[tuple], int]:
        """Every up acting shard's stored state for scrub: a list of
        (shard, buf, label, crc, ver, trusted) plus the count of up
        acting shards.

        One PARALLEL gather through the HedgedGather sub-read
        machinery (scrub used to round-trip each shard serially, so a
        deep scrub of a wide stripe paid k+m sequential RTTs); every
        reply feeds the same per-peer latency EWMA the hedge timer
        draws from.  No hedging applies -- scrub wants EVERY stored
        shard, not a sufficient subset -- but a straggler is bounded
        by the read deadline instead of stalling the whole scrub: a
        missing shard simply falls out to the reconstruct path."""
        pg = self.pg
        stored: list[tuple] = []
        remote: dict[int, int] = {}
        n_acting = 0
        for shard, osd_id in enumerate(pg.acting):
            if osd_id < 0 or not self.osd.osd_is_up(osd_id):
                continue
            n_acting += 1
            if osd_id == self.osd.whoami:
                buf, _, over, label, crc, cached = \
                    self._local_entry(oid)
                stored.append((shard, buf, label, crc, tuple(over),
                               cached))
            else:
                remote[shard] = osd_id
        if remote:
            payload = {"pgid": pg.pgid, "oid": oid}
            collected: dict[int, object] = {}
            if self.hedger is not None:
                def on_reply(s, msg):
                    if msg is not None:
                        collected[s] = msg
                await self.hedger.gather_shards(
                    {s: (o, "ec_subop_read",
                         {**payload, "shard": s})
                     for s, o in remote.items()},
                    on_reply=on_reply, timeout=self._read_timeout)
            else:
                replies = await self.osd.fanout_and_wait(
                    [(o, "ec_subop_read", {**payload, "shard": s}, [])
                     for s, o in remote.items()],
                    collect=True, timeout=self._read_timeout)
                for rep in replies:
                    s = rep.data.get("req_shard", rep.data.get("shard"))
                    if s in remote:
                        collected[s] = rep
            for s, rep in sorted(collected.items()):
                raw = rep.segments[0] if rep.segments else b""
                stored.append((s, raw, rep.data.get("shard"),
                               rep.data.get("crc"),
                               tuple(rep.data.get("ver", (0, 0))),
                               False))
        stored.sort(key=lambda e: e[0])
        return stored, n_acting

    # -- write path ---------------------------------------------------------
    async def submit_transaction(self, entry, muts) -> None:
        """Full-object RMW: new logical content -> k+m shard writes."""
        data_muts = [m for m in muts if m["op"] in
                     ("create", "write", "truncate", "zero", "remove")]
        attr_muts = [m for m in muts if m not in data_muts]
        content_muts = [m for m in data_muts if m["op"] != "create"]
        if not content_muts:
            # create-only (touch) or attr-only: existing shard content is
            # preserved -- re-encoding "empty" here would truncate a live
            # object to zero (the replicated path uses touch for the same
            # reason)
            attr_meta, attr_segs = pack_mutations(attr_muts)
            acting = self.pg.acting
            awaiting = []
            for shard, osd in enumerate(acting):
                if osd < 0:
                    continue
                if osd == self.osd.whoami:
                    self.apply_sub_write(entry, {"touch": True}, [],
                                         attr_muts, shard=shard)
                elif not self.pg.should_send_to(osd, entry.oid):
                    awaiting.append(
                        self._log_only_subop(osd, shard, entry))
                else:
                    payload = {"pgid": self.pg.pgid, "oid": entry.oid,
                               "shard": shard, "entry": entry.to_dict(),
                               "w": {"touch": True},
                               "attr_muts": attr_meta}
                    awaiting.append((osd, "ec_subop_write", payload,
                                     attr_segs))
            return await self._commit_or_defer(awaiting, entry)
        old_size = await self.object_size(entry.oid)
        plan = self._plan_rmw(content_muts, old_size)
        if plan is not None:
            return await self._submit_partial(entry, content_muts,
                                              attr_muts, old_size,
                                              *plan)
        logical = bytearray(await self._read_logical(entry.oid))
        remove = False          # tracks the FINAL state: a remove followed
        for m in content_muts:  # by a write recreates the object in-order
            if m["op"] == "write":
                end = m["off"] + len(m["data"])
                if len(logical) < end:
                    logical.extend(b"\0" * (end - len(logical)))
                logical[m["off"]:end] = m["data"]
                remove = False
            elif m["op"] == "truncate":
                if len(logical) < m["size"]:
                    logical.extend(b"\0" * (m["size"] - len(logical)))
                else:
                    del logical[m["size"]:]
                remove = False
            elif m["op"] == "zero":
                end = min(m["off"] + m["len"], len(logical))
                logical[m["off"]:end] = b"\0" * max(0, end - m["off"])
            elif m["op"] == "remove":
                logical = bytearray()
                remove = True

        acting = self.pg.acting
        if remove:
            self.cache.invalidate(entry.oid)
            per_shard = [{"remove": True} for _ in acting]
            segs_per_shard = [[] for _ in acting]
        else:
            size = len(logical)
            padded = bytes(logical) + b"\0" * (
                self.sinfo.logical_to_next_stripe_offset(size) - size)
            if padded:
                # the codec launch returns the shard CRCs along with
                # the parity: the identity stamp below consumes them
                # instead of re-hashing bytes the encoder just produced
                shards, shard_crcs = await self.sinfo.encode_async(
                    self.codec, padded, batcher=self.batcher,
                    with_crc=True)
            else:
                shards = {i: np.zeros(0, np.uint8)
                          for i in range(len(acting))}
                empty_crc = shard_crc(b"")
                shard_crcs = {i: empty_crc
                              for i in range(len(acting))}
            sw = self.sinfo.stripe_width
            self.cache.truncate_beyond(entry.oid, len(padded) // sw)
            if len(padded) <= self.cache.max_bytes // 4:
                for s in range(len(padded) // sw):
                    self.cache.put(entry.oid, s,
                                   padded[s * sw:(s + 1) * sw])
            else:
                # a huge rewrite would churn the whole LRU for entries
                # that mostly evict each other; drop stale ones instead
                self.cache.invalidate(entry.oid)
            per_shard, segs_per_shard = [], []
            for shard in range(len(acting)):
                buf = shards[shard].tobytes()
                per_shard.append({"size": size, "shard_len": len(buf),
                                  "attrs": None,
                                  "crc": int(shard_crcs[shard])})
                segs_per_shard.append([buf])
        # local shard applies in-line; remote shards via ec_subop_write
        awaiting = []
        for shard, osd in enumerate(acting):
            if osd < 0:
                continue
            if osd == self.osd.whoami:
                self.apply_sub_write(entry, per_shard[shard],
                                     segs_per_shard[shard], attr_muts,
                                     shard=shard)
            elif not self.pg.should_send_to(osd, entry.oid):
                awaiting.append(self._log_only_subop(osd, shard, entry))
            else:
                payload = {"pgid": self.pg.pgid, "oid": entry.oid,
                           "shard": shard, "entry": entry.to_dict(),
                           "w": per_shard[shard],
                           "attr_muts": pack_mutations(attr_muts)[0]}
                segs = (segs_per_shard[shard]
                        + pack_mutations(attr_muts)[1])
                awaiting.append((osd, "ec_subop_write", payload, segs))
        return await self._commit_or_defer(awaiting, entry)

    # -- partial-stripe RMW pipeline ----------------------------------------
    # The reference's RMWPipeline (ECCommon.cc:704 start_rmw ->
    # try_state_to_reads -> try_reads_to_commit): only the stripes a
    # write touches are read, merged, re-encoded and shipped as ranged
    # per-shard sub-writes, so a 4KiB overwrite of a huge object moves
    # O(stripe), not O(object).  The ExtentCache feeds the read phase
    # for stripes a recent write already materialized.

    def _plan_rmw(self, muts: list[dict],
                  old_size: int) -> tuple[int, list[int]] | None:
        """(new_size, touched stripe indices) for the partial path, or
        None when the full-object path is required (truncate/remove
        chains, fresh objects, or writes covering everything)."""
        if old_size == 0:
            return None
        sw = self.sinfo.stripe_width
        size = old_size
        touched: set[int] = set()
        for m in muts:          # content_muts: create is pre-filtered
            op = m["op"]
            if op == "write":
                data, off = m["data"], m["off"]
                # empty writes still extend to `off` (the full path's
                # bytearray-extend semantics); they just touch nothing
                if data:
                    end = off + len(data)
                    touched.update(range(off // sw, (end - 1) // sw + 1))
                size = max(size, off + len(data))
            elif op == "zero":
                # clamp to the RUNNING size: a zero may target a region
                # an earlier write in this op vector just extended
                end = min(m["off"] + m["len"], size)
                if end > m["off"]:
                    touched.update(range(m["off"] // sw,
                                         (end - 1) // sw + 1))
            else:               # truncate / remove: full path
                return None
        if not touched:
            return None
        n_stripes = (self.sinfo.logical_to_next_stripe_offset(size) // sw)
        if len(touched) >= n_stripes:
            return None         # rewriting everything anyway
        return size, sorted(touched)

    @staticmethod
    def _runs(stripes: list[int]) -> list[tuple[int, int]]:
        """Contiguous [lo, hi] inclusive runs of sorted stripe indices."""
        runs: list[tuple[int, int]] = []
        for s in stripes:
            if runs and s == runs[-1][1] + 1:
                runs[-1] = (runs[-1][0], s)
            else:
                runs.append((s, s))
        return runs

    async def _read_stripes(self, oid: str, stripes: list[int],
                            old_size: int) -> dict[int, bytearray]:
        """Old logical content of ``stripes``: ExtentCache first, then
        ranged shard gathers (degraded-safe: _gather_shards picks shards
        via minimum_to_decode and decodes when data shards are down)."""
        sw, cs = self.sinfo.stripe_width, self.sinfo.chunk_size
        n_old = self.sinfo.logical_to_next_stripe_offset(old_size) // sw
        dpos = self.sinfo.data_positions(self.codec)
        out: dict[int, bytearray] = {}
        misses: list[int] = []
        for s in stripes:
            if s >= n_old:
                out[s] = bytearray(sw)       # beyond old EOF: zeros
                continue
            c = self.cache.get(oid, s)
            if c is not None:
                out[s] = bytearray(c)
            else:
                misses.append(s)
        async def _fetch_run(lo: int, hi: int):
            rng = (lo * cs, (hi - lo + 1) * cs)
            bufs, _, _ = await self._gather_shards(oid, rng=rng)
            return lo, hi, await self.sinfo.decode_async(
                self.codec, bufs, want=set(dpos), batcher=self.batcher)

        # runs fetch+decode concurrently: their gathers overlap and
        # their decodes coalesce in the batcher
        for lo, hi, data_shards in await asyncio.gather(
                *(_fetch_run(lo, hi) for lo, hi in self._runs(misses))):
            for i, s in enumerate(range(lo, hi + 1)):
                # one concatenate+tobytes per stripe, not one
                # asarray+tobytes hop per data chunk
                out[s] = bytearray(np.concatenate(
                    [data_shards[p][i * cs:(i + 1) * cs]
                     for p in dpos]).tobytes())
        return out

    async def _submit_partial(self, entry, content_muts: list[dict],
                              attr_muts: list[dict], old_size: int,
                              new_size: int, stripes: list[int]) -> None:
        oid = entry.oid
        sw, cs = self.sinfo.stripe_width, self.sinfo.chunk_size
        stripe_data = await self._read_stripes(oid, stripes, old_size)
        # snapshot the OLD stripe bytes before merging: the delta-RMW
        # parity path encodes (new XOR old) and XORs it onto the stored
        # parity (GF linearity) instead of re-encoding whole stripes
        old_data = {s: bytes(d) for s, d in stripe_data.items()} \
            if self._rmw_delta else {}
        # merge the mutations into the touched stripes; `cur` tracks the
        # running logical size so a zero clamps against what earlier
        # writes in this vector extended, not the stale old_size
        cur = old_size
        for m in content_muts:
            if m["op"] == "write":
                off, data = m["off"], m["data"]
                end = off + len(data)
                cur = max(cur, end)
            elif m["op"] == "zero":
                off = m["off"]
                end = min(off + m["len"], cur)
                data = None
            else:
                continue
            for s in stripes:
                lo, hi = s * sw, (s + 1) * sw
                a, b = max(off, lo), min(end, hi)
                if a >= b:
                    continue
                if data is None:
                    stripe_data[s][a - lo:b - lo] = b"\0" * (b - a)
                else:
                    stripe_data[s][a - lo:b - lo] = data[a - off:b - off]
        # process each contiguous run in one driver call (runs submit
        # concurrently so the batcher coalesces them — and any other
        # op's stripes — into a single launch); collect ranged
        # per-shard writes.  Runs whose stripes already exist take the
        # DELTA path: parity' = parity XOR encode(new XOR old) -- one
        # rmw launch, and data shards whose chunks did not change ship
        # NO payload (their sub-write carries only the version stamp),
        # so the per-write byte movement drops from (k+m) chunks per
        # stripe to (changed data chunks + m parity chunks).  Runs past
        # old EOF (no stored parity) and delta-ineligible codecs keep
        # the full re-encode.
        acting = self.pg.acting
        shard_writes: list[list[tuple[int, bytes]]] = [
            [] for _ in acting]
        runs = self._runs(stripes)
        n_old = self.sinfo.logical_to_next_stripe_offset(old_size) // sw
        dpos = self.sinfo.data_positions(self.codec)
        ppos = [i for i in range(self.sinfo.k + self.sinfo.m)
                if i not in dpos]
        from .codec_batcher import CodecBatcher
        delta_ok = (self._rmw_delta and self.batcher is not None
                    and CodecBatcher.supports(self.codec)
                    and len(acting) == self.sinfo.k + self.sinfo.m)
        avail = {shard: osd for shard, osd in enumerate(acting)
                 if osd >= 0 and self.osd.osd_is_up(osd)}

        async def _full_run(lo: int, hi: int):
            """Re-encode the whole run: every shard gets its chunk."""
            blob = b"".join(bytes(stripe_data[s])
                            for s in range(lo, hi + 1))
            shards = await self.sinfo.encode_async(
                self.codec, blob, batcher=self.batcher)
            if self.batcher is not None:
                self.batcher.note_rmw(delta=False)
            return [(shard, lo * cs, shards[shard].tobytes())
                    for shard in range(len(acting))]

        async def _delta_run(lo: int, hi: int):
            """Delta-update parity in place; ship only changed data
            chunks + the m parity chunks."""
            n = hi - lo + 1
            rng = (lo * cs, n * cs)
            pbufs, pfailed, _ = await self._fetch_shards(
                oid, [p for p in ppos if p in avail], avail, rng,
                self._read_timeout)
            if pfailed or set(ppos) - set(pbufs) or any(
                    len(pbufs[p][0]) != n * cs for p in ppos):
                # a parity source is down/stale/short: the delta has
                # nothing sound to XOR onto -- re-encode instead
                return await _full_run(lo, hi)
            old_parity = np.stack(
                [np.asarray(pbufs[p][0], np.uint8).reshape(n, cs)
                 for p in ppos], axis=1)              # (n, m, cs)
            new_arr = np.frombuffer(
                b"".join(bytes(stripe_data[s])
                         for s in range(lo, hi + 1)),
                np.uint8).reshape(n, self.sinfo.k, cs)
            old_arr = np.frombuffer(
                b"".join(old_data[s] for s in range(lo, hi + 1)),
                np.uint8).reshape(n, self.sinfo.k, cs)
            delta = new_arr ^ old_arr
            new_parity = await self.batcher.rmw(self.codec,
                                                old_parity, delta)
            self.batcher.note_rmw(delta=True)
            out = []
            changed = delta.any(axis=2)               # (n, k)
            for j, p in enumerate(dpos):
                for i in range(n):
                    if changed[i, j]:
                        out.append((p, (lo + i) * cs,
                                    new_arr[i, j].tobytes()))
            for r, p in enumerate(ppos):
                out.append((p, lo * cs, np.ascontiguousarray(
                    new_parity[:, r]).reshape(-1).tobytes()))
            return out

        async def _run_one(lo: int, hi: int):
            if delta_ok and hi < n_old and all(
                    s in old_data for s in range(lo, hi + 1)):
                return await _delta_run(lo, hi)
            return await _full_run(lo, hi)

        for writes in await asyncio.gather(
                *(_run_one(lo, hi) for lo, hi in runs)):
            for shard, off, buf in writes:
                shard_writes[shard].append((off, buf))
        for s in stripes:
            self.cache.put(oid, s, bytes(stripe_data[s]))
        shard_len = self.sinfo.object_size_to_shard_size(new_size)
        attr_meta, attr_segs = pack_mutations(attr_muts)
        awaiting = []
        for shard, osd in enumerate(acting):
            if osd < 0:
                continue
            w = {"size": new_size, "shard_len": shard_len,
                 "writes": [[off, len(buf)]
                            for off, buf in shard_writes[shard]]}
            segs = [buf for _, buf in shard_writes[shard]]
            if osd == self.osd.whoami:
                self.apply_sub_write(entry, w, segs, attr_muts,
                                     shard=shard)
            elif not self.pg.should_send_to(osd, oid):
                awaiting.append(self._log_only_subop(osd, shard, entry))
            else:
                payload = {"pgid": self.pg.pgid, "oid": oid,
                           "shard": shard, "entry": entry.to_dict(),
                           "w": w, "attr_muts": attr_meta}
                awaiting.append((osd, "ec_subop_write", payload,
                                 segs + attr_segs))
        return await self._commit_or_defer(awaiting, entry)

    def apply_sub_write(self, entry: LogEntry, w: dict,
                        segs: list[bytes], attr_muts: list[dict],
                        shard: int | None = None) -> None:
        txn = Transaction()
        oid = entry.oid
        if w.get("log_only"):
            # backfill target beyond its watermark: log entry only
            self.pg.append_log_and_meta(txn, entry)
            self.store.queue_transaction(txn)
            return
        # write-time identity pin: remember which shard these bytes ARE
        # (per-object xattr) and which shard this PG instance serves
        # (PG meta, persisted by append_log_and_meta below) -- readers
        # and recovery verify against the pin, never the live index
        if shard is None:
            try:
                shard = self.my_shard()
            except ValueError:
                shard = self.pg.shard_id
        if shard is not None and self.pg.shard_id is None:
            self.pg.shard_id = shard
        # final shard content for the device-resident cache: full-shard
        # writes hand their payload straight through; ranged RMW writes
        # patch the PRE-txn resident copy (captured before the store's
        # coherence invalidation fires) so the identity stamp never
        # reads the shard back from the store
        content = size = vtuple = None
        if w.get("remove"):
            txn.remove(self.coll, oid)
        elif w.get("writes") is not None:
            # partial-stripe RMW: ranged chunk writes + final length
            pre = self.dcache.get(self.coll, oid) \
                if self.dcache is not None else None
            txn.touch(self.coll, oid)
            for i, (off, ln) in enumerate(w["writes"]):
                buf = segs[i] if i < len(segs) else b""
                assert len(buf) == ln, (len(buf), ln)
                txn.write(self.coll, oid, off, buf)
            txn.truncate(self.coll, oid, w["shard_len"])
            txn.setattr(self.coll, oid, SIZE_XATTR,
                        str(w["size"]).encode())
            txn.setattr(self.coll, oid, VER_XATTR,
                        ver_encode(entry.version))
            if pre is not None:
                arr = np.zeros(w["shard_len"], np.uint8)
                n = min(pre.buf.size, w["shard_len"])
                arr[:n] = pre.buf[:n]
                for (off, ln), buf in zip(w["writes"], segs):
                    arr[off:off + ln] = np.frombuffer(buf, np.uint8)
                content, size = arr, w["size"]
                vtuple = (entry.version.epoch, entry.version.version)
        elif w.get("touch"):
            # create-only / attr-only: never rewrite shard content
            txn.touch(self.coll, oid)
            if self.store.getattr(self.coll, oid, SIZE_XATTR) is None:
                txn.setattr(self.coll, oid, SIZE_XATTR, b"0")
            txn.setattr(self.coll, oid, VER_XATTR,
                        ver_encode(entry.version))
        else:
            buf = segs[0] if segs else b""
            txn.truncate(self.coll, oid, 0)
            txn.write(self.coll, oid, 0, buf)
            txn.truncate(self.coll, oid, w["shard_len"])
            txn.setattr(self.coll, oid, SIZE_XATTR,
                        str(w["size"]).encode())
            txn.setattr(self.coll, oid, VER_XATTR,
                        ver_encode(entry.version))
            if len(buf) == w["shard_len"]:
                content, size = buf, w["size"]
                vtuple = (entry.version.epoch, entry.version.version)
        apply_mutations(txn, self.coll, oid, attr_muts)
        self.pg.append_log_and_meta(txn, entry)
        self.store.queue_transaction(txn)
        if not w.get("remove"):
            self._stamp_identity(oid, shard, crc=w.get("crc"),
                                 content=content, size=size,
                                 ver=vtuple)

    def _stamp_identity(self, oid: str, shard: int | None,
                        crc: int | None = None, content=None,
                        size: int | None = None,
                        ver: tuple | None = None) -> None:
        """Post-commit identity tag: shard label + CRC of the FINAL
        shard content.  Full-shard writes pass the ``crc`` the codec
        launch already computed (no read-back, no re-hash); ranged RMW
        writes pass the patched resident ``content`` (no store
        read-back) or, with no resident copy, read back from the store
        after the txn applied (queue_transaction is synchronous, no
        interleaving await) -- still through the batched kernel.

        When the final content is in hand it becomes the cache entry
        for ``(coll, oid)`` -- the write's encoded bytes flow straight
        into residency, so the next read/scrub/decode never touches
        the store."""
        if crc is None:
            if content is None:
                try:
                    content = self.store.read(self.coll, oid, 0, None)
                except FileNotFoundError:
                    return
                if self.dcache is not None:
                    self.dcache.note_host_read(len(content))
            crc = shard_crc(content)
        txn = Transaction()
        if shard is not None:
            txn.setattr(self.coll, oid, SHARD_XATTR,
                        str(int(shard)).encode())
        txn.setattr(self.coll, oid, CRC_XATTR,
                    str(int(crc)).encode())
        self.store.queue_transaction(txn)
        if self.dcache is not None and content is not None \
                and size is not None and ver is not None:
            self.dcache.put(self.coll, oid, content, size=size,
                            ver=ver, shard=shard, crc=int(crc))

    # -- read path ----------------------------------------------------------
    async def object_read(self, oid, off, length) -> bytes:
        data = await self._read_logical(oid)
        if length is None:
            return data[off:]
        return data[off:off + length]

    async def object_size(self, oid) -> int:
        sx = self.store.getattr(self.coll, oid, SIZE_XATTR)
        if sx is not None:
            return int(sx)
        _, size, _ = await self._gather_shards(oid)
        return size

    async def read_recovery_payload(self, oid, shard) -> dict:
        """Reconstruct the target shard's buffer for a recovering peer.

        Regenerating codecs (pmsr) take the FRAGMENT path first: d
        helpers each ship one beta-sized computed sub-chunk instead of
        a full chunk, so rebuilding one shard moves d/alpha chunks of
        bytes instead of k (counted in ``ec_recovery``, asserted by
        chaos/bench, never assumed).  Any fragment-path failure --
        helper down, version skew, codec ineligible -- falls back to
        the full shard gather transparently."""
        self._rcount("repair_reads")
        frag = await self._fragment_recover(oid, shard)
        if frag is not None:
            buf, size, ver = frag
        else:
            # the target shard is being REBUILT: its holder's current
            # (empty or stale) bytes must never serve as the source of
            # itself -- a revived OSD answering the gather for its own
            # missing shard used to satisfy the plan with an absent
            # reply, and the "recovery" pushed a remove instead of a
            # reconstruction (the shard stayed lost forever)
            bufs, size, ver = await self._gather_shards(
                oid, need_shards={shard}, exclude={int(shard)})
            self._rcount("repair_bytes_read",
                         sum(len(b) for b in bufs.values()))
            if len(bufs) < self.sinfo.k:
                # a layered plan (the LRC local group) read fewer than
                # k chunks: the locality savings, counted
                self._rcount("repair_local_repairs")
            else:
                self._rcount("repair_global_decodes")
            if ver == (0, 0) and not any(len(b) for b in bufs.values()):
                # object exists on no shard: tell the peer to remove
                # its copy (backfill pushes extras as absent)
                return {"data": b"", "xattrs": {}, "omap": {},
                        "absent": True}
            if shard in bufs:
                buf = bufs[shard]
            else:
                # reconstruction decode rides the batcher: concurrent
                # recovery/backfill pushes for the same down-shard
                # pattern share one decode_batch launch
                self._count("reconstructions")
                decoded = await self.sinfo.decode_async(
                    self.codec, bufs, want={shard},
                    batcher=self.batcher)
                buf = decoded[shard]
        # the pushed shard must carry the version stamp (an unstamped
        # recovered shard would read as (0,0) and be rejected as stale
        # by _gather_shards forever after) AND its identity pin: the
        # shard label + CRC travel in the xattrs so the applied copy is
        # self-describing, and again at the payload top level so the
        # receiver can verify BEFORE applying anything
        raw = buf.tobytes()
        self._rcount("repair_bytes_shipped", len(raw))
        return {"data": raw,
                "xattrs": {SIZE_XATTR: str(size).encode(),
                           VER_XATTR: f"{ver[0]},{ver[1]}".encode(),
                           SHARD_XATTR: str(int(shard)).encode(),
                           CRC_XATTR: str(shard_crc(raw)).encode()},
                "omap": {},
                "shard": int(shard)}

    # -- regenerating-code repair fragments (pmsr) ---------------------------
    def fragment_of(self, oid: str, lost_shard: int
                    ) -> tuple[bytes, int, tuple, int | None] | None:
        """This OSD's beta-sized repair fragment for ``lost_shard``:
        the locally stored chunk combined by the codec's fragment row.
        Returns (fragment bytes, size, ver, my shard label), or None
        when the codec has no fragment algebra or nothing is stored."""
        if not hasattr(self.codec, "fragment_for"):
            return None
        buf, size, ver, label, _, _ = self._local_entry(oid)
        if not len(buf):
            return None
        frag = self.codec.fragment_for(lost_shard, buf)
        return frag.tobytes(), size, tuple(ver), label

    async def _fragment_recover(self, oid: str, shard: int
                                ) -> tuple | None:
        """Rebuild ``shard`` from beta-sized helper fragments, or None
        (fall back to the full-chunk gather).  Every fragment reply is
        identity-checked -- the helper's write-time shard label must
        match its serving position and all versions must agree -- so a
        remapped or stale helper degrades to the safe path instead of
        aggregating garbage."""
        codec = self.codec
        if not self._frag_repair \
                or not hasattr(codec, "minimum_to_repair"):
            return None
        acting = self.pg.acting
        avail = {s: osd for s, osd in enumerate(acting)
                 if osd >= 0 and self.osd.osd_is_up(osd)}
        plan = codec.minimum_to_repair(int(shard),
                                       set(avail) - {int(shard)})
        if not plan:
            return None
        sub = codec.get_sub_chunk_count()
        if all(sum(c for _, c in spec) >= sub
               for spec in plan.values()):
            return None           # no fragment saving: gather instead
        frags: dict[int, np.ndarray] = {}
        meta: dict[int, tuple] = {}
        remote = []
        for h in plan:
            if h not in avail:
                return None
            if avail[h] == self.osd.whoami:
                local = self.fragment_of(oid, int(shard))
                if local is None:
                    return None
                fbuf, size, ver, label = local
                if not self._label_ok(h, label, fbuf, ver):
                    return None
                frags[h] = np.frombuffer(fbuf, np.uint8)
                meta[h] = (size, ver)
            else:
                remote.append(h)
        if remote:
            payload = {"pgid": self.pg.pgid, "oid": oid,
                       "frag_for": int(shard)}
            try:
                replies = await self.osd.fanout_and_wait(
                    [(avail[h], "ec_subop_read",
                      {**payload, "shard": h}, []) for h in remote],
                    collect=True, timeout=self._read_timeout)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                self._rcount("repair_fragment_falls")
                return None
            for rep in replies:
                h = rep.data.get("req_shard")
                if h is None or h not in remote \
                        or rep.data.get("frag_err"):
                    continue
                fbuf = rep.segments[0] if rep.segments else b""
                crc = rep.data.get("crc")
                if crc is not None and not shard_crc_matches(fbuf, crc):
                    self._count("crc_mismatch")
                    continue
                label = rep.data.get("shard")
                ver = tuple(rep.data.get("ver", (0, 0)))
                if not self._label_ok(h, label,
                                      np.frombuffer(fbuf, np.uint8),
                                      ver):
                    self._count("shard_mismatch")
                    continue
                frags[h] = np.frombuffer(fbuf, np.uint8)
                meta[h] = (rep.data.get("size", 0), ver)
        if set(frags) != set(plan):
            self._rcount("repair_fragment_falls")
            return None
        vers = {v for _, v in meta.values()}
        lens = {len(f) for f in frags.values()}
        if len(vers) != 1 or len(lens) != 1 or not lens.pop():
            # version skew mid-recovery or ragged fragments: the
            # aggregate would mix stripes from different writes
            self._rcount("repair_fragment_falls")
            return None
        try:
            buf = codec.aggregate_fragments(int(shard), frags)
        except (IOError, OSError, ValueError):
            self._rcount("repair_fragment_falls")
            return None
        nbytes = sum(len(f) for f in frags.values())
        self._rcount("repair_fragment_pulls")
        self._rcount("repair_fragments", len(frags))
        self._rcount("repair_bytes_read", nbytes)
        size = max(s for s, _ in meta.values())
        return buf, size, vers.pop()          # uint8 ndarray from the
                                              # aggregate, shard-sized
