"""PGBackend: replication fan-out vs erasure-coded shard I/O.

The SPI mirrors src/osd/PGBackend.cc:570 build_pg_backend — the pool
type selects ReplicatedBackend (primary-copy fan-out, MOSDRepOp) or
ECBackend (encode + per-shard sub-writes, MOSDECSubOpWrite; reads
gather minimum_to_decode shards and reconstruct, ECCommon.cc:597).

Mutations are resolved to concrete, offset-explicit ops at the primary
(append/writefull become plain writes) so replicas and shards apply
them deterministically — the same discipline as
PrimaryLogPG ops -> ObjectStore::Transaction translation.
"""

from __future__ import annotations

import numpy as np

from ..os.transaction import Transaction
from .ec_util import StripeInfo
from .types import LogEntry

META_OID = "_pgmeta_"
SIZE_XATTR = "_size"


# -- wire packing: JSON meta + binary segments ------------------------------

def pack_mutations(muts: list[dict]) -> tuple[list[dict], list[bytes]]:
    meta, segments = [], []
    for m in muts:
        m2 = dict(m)
        for key in ("data", "value"):
            if key in m2 and isinstance(m2[key], (bytes, bytearray,
                                                  np.ndarray)):
                buf = bytes(m2[key]) if not isinstance(
                    m2[key], np.ndarray) else m2[key].tobytes()
                m2[key] = {"seg": len(segments), "len": len(buf)}
                segments.append(buf)
        if "kv" in m2:
            kv = m2["kv"]
            buf = b"".join(
                len(k.encode()).to_bytes(4, "big") + k.encode()
                + len(v).to_bytes(4, "big") + bytes(v)
                for k, v in kv.items())
            m2["kv"] = {"seg": len(segments), "n": len(kv)}
            segments.append(buf)
        meta.append(m2)
    return meta, segments


def unpack_mutations(meta: list[dict],
                     segments: list[bytes]) -> list[dict]:
    out = []
    for m in meta:
        m2 = dict(m)
        for key in ("data", "value"):
            if isinstance(m2.get(key), dict):
                m2[key] = segments[m2[key]["seg"]]
        if isinstance(m2.get("kv"), dict):
            buf = segments[m2["kv"]["seg"]]
            kv, pos = {}, 0
            for _ in range(m2["kv"]["n"]):
                klen = int.from_bytes(buf[pos:pos + 4], "big"); pos += 4
                k = buf[pos:pos + klen].decode(); pos += klen
                vlen = int.from_bytes(buf[pos:pos + 4], "big"); pos += 4
                kv[k] = buf[pos:pos + vlen]; pos += vlen
            m2["kv"] = kv
        out.append(m2)
    return out


def apply_mutations(txn: Transaction, coll: str, oid: str,
                    muts: list[dict]) -> None:
    """Translate resolved logical mutations into Transaction ops."""
    for m in muts:
        op = m["op"]
        if op == "create":
            txn.touch(coll, oid)
        elif op == "write":
            txn.write(coll, oid, m["off"], m["data"])
        elif op == "truncate":
            txn.truncate(coll, oid, m["size"])
        elif op == "zero":
            txn.zero(coll, oid, m["off"], m["len"])
        elif op == "remove":
            txn.remove(coll, oid)
        elif op == "setxattr":
            txn.setattr(coll, oid, m["name"], m["value"])
        elif op == "rmxattr":
            txn.rmattr(coll, oid, m["name"])
        elif op == "omap_set":
            txn.omap_setkeys(coll, oid, m["kv"])
        elif op == "omap_rm":
            txn.omap_rmkeys(coll, oid, m["keys"])
        elif op == "omap_clear":
            txn.omap_clear(coll, oid)
        else:
            raise ValueError(f"unknown mutation op {op}")


class PGBackend:
    """SPI both backends implement; `pg` provides log/info/persistence
    and `osd` provides peer RPC + the local store."""

    def __init__(self, pg) -> None:
        self.pg = pg
        self.osd = pg.osd

    @property
    def store(self):
        return self.osd.store

    @property
    def coll(self) -> str:
        return self.pg.coll

    async def submit_transaction(self, entry: LogEntry,
                                 muts: list[dict]) -> None:
        raise NotImplementedError

    async def object_read(self, oid: str, off: int,
                          length: int | None) -> bytes:
        raise NotImplementedError

    async def object_size(self, oid: str) -> int:
        raise NotImplementedError

    # recovery: full-object state transfer units
    async def read_recovery_payload(self, oid: str, shard: int) -> dict:
        raise NotImplementedError


def build_pg_backend(pg):
    """PGBackend.cc:570 — pool type picks the backend."""
    if pg.pool.is_erasure():
        return ECBackend(pg)
    return ReplicatedBackend(pg)


class ReplicatedBackend(PGBackend):
    async def submit_transaction(self, entry, muts) -> None:
        txn = Transaction()
        apply_mutations(txn, self.coll, entry.oid, muts)
        self.pg.append_log_and_meta(txn, entry)
        self.store.queue_transaction(txn)
        # fan out to every other acting replica and wait for all commits
        # (ReplicatedBackend.cc: all_commit before client reply)
        meta, segs = pack_mutations(muts)
        payload = {"pgid": self.pg.pgid, "entry": entry.to_dict(),
                   "muts": meta}
        await self.osd.fanout_and_wait(
            [(o, "rep_op", payload, segs) for o in self.pg.acting
             if o >= 0 and o != self.osd.whoami])

    def apply_rep_op(self, entry: LogEntry, muts: list[dict]) -> None:
        """Replica side: apply the primary's resolved mutations."""
        txn = Transaction()
        apply_mutations(txn, self.coll, entry.oid, muts)
        self.pg.append_log_and_meta(txn, entry)
        self.store.queue_transaction(txn)

    async def object_read(self, oid, off, length) -> bytes:
        return self.store.read(self.coll, oid, off, length)

    async def object_size(self, oid) -> int:
        st = self.store.stat(self.coll, oid)
        return 0 if st is None else st["size"]

    async def read_recovery_payload(self, oid, shard) -> dict:
        try:
            data = self.store.read(self.coll, oid, 0, None)
        except FileNotFoundError:
            return {"data": b"", "xattrs": {}, "omap": {},
                    "absent": True}
        return {"data": data,
                "xattrs": self.store.getattrs(self.coll, oid),
                "omap": self.store.omap_get(self.coll, oid)}


class ECBackend(PGBackend):
    """Erasure-coded object I/O over acting-set shards.

    Shard i of every object lives on acting[i] (shard id = position in
    the acting set, ErasureCodeInterface.h:39-78).  Writes run
    full-object RMW: reconstruct current logical bytes, apply the
    mutation, re-encode, distribute per-shard sub-writes
    (ECCommon.cc:704 start_rmw — partial-stripe overwrite support via
    an extent cache is future work; this always rewrites the stripe
    set, which is correct if pessimal for tiny overwrites).
    """

    def __init__(self, pg) -> None:
        super().__init__(pg)
        profile = dict(pg.ec_profile)
        plugin = profile.pop("plugin", "tpu")
        from ..ec import registry
        self.codec = registry().factory(plugin, profile)
        self.sinfo = StripeInfo.for_codec(
            self.codec, stripe_unit=int(profile.get("stripe_unit", 4096)))

    @property
    def k(self) -> int:
        return self.sinfo.k

    def my_shard(self) -> int:
        return self.pg.acting.index(self.osd.whoami)

    # -- logical object reconstruction --------------------------------------
    async def _gather_shards(self, oid: str,
                             need_shards: set[int] | None = None
                             ) -> tuple[dict[int, np.ndarray], int]:
        """Read enough shard buffers to decode; returns (bufs, size)."""
        acting = self.pg.acting
        avail: dict[int, int] = {}           # shard -> osd
        for shard, osd in enumerate(acting):
            if osd >= 0 and self.osd.osd_is_up(osd):
                avail[shard] = osd
        plan = self.codec.minimum_to_decode(
            need_shards or set(range(self.k)), set(avail))
        bufs: dict[int, np.ndarray] = {}
        size = 0
        local = self.my_shard() if self.osd.whoami in acting else None
        remote = []
        for shard in plan:
            if shard == local:
                try:
                    raw = self.store.read(self.coll, oid, 0, None)
                except FileNotFoundError:
                    raw = b""
                bufs[shard] = np.frombuffer(raw, np.uint8)
                sx = self.store.getattr(self.coll, oid, SIZE_XATTR)
                size = int(sx) if sx else 0
            else:
                remote.append((avail[shard], shard))
        if remote:
            replies = await self.osd.fanout_and_wait(
                [(osd, "ec_subop_read",
                  {"pgid": self.pg.pgid, "oid": oid}, [])
                 for osd, _ in remote], collect=True)
            for rep in replies:
                shard = rep.data["shard"]
                bufs[shard] = np.frombuffer(
                    rep.segments[0] if rep.segments else b"", np.uint8)
                size = max(size, rep.data.get("size", 0))
        # normalize buffer lengths (a shard that never saw the object
        # returns empty: zero-fill to the common shard length)
        shard_len = max((len(b) for b in bufs.values()), default=0)
        for s, b in list(bufs.items()):
            if len(b) < shard_len:
                nb = np.zeros(shard_len, np.uint8)
                nb[:len(b)] = b
                bufs[s] = nb
        return bufs, size

    async def _read_logical(self, oid: str) -> bytes:
        bufs, size = await self._gather_shards(oid)
        if not bufs or not any(len(b) for b in bufs.values()):
            return b""
        data = self.sinfo.reconstruct_logical(self.codec, bufs)
        return data[:size]

    # -- write path ---------------------------------------------------------
    async def submit_transaction(self, entry, muts) -> None:
        """Full-object RMW: new logical content -> k+m shard writes."""
        data_muts = [m for m in muts if m["op"] in
                     ("create", "write", "truncate", "zero", "remove")]
        attr_muts = [m for m in muts if m not in data_muts]
        if any(m["op"] != "create" for m in data_muts):
            logical = bytearray(await self._read_logical(entry.oid))
            for m in data_muts:
                if m["op"] == "write":
                    end = m["off"] + len(m["data"])
                    if len(logical) < end:
                        logical.extend(b"\0" * (end - len(logical)))
                    logical[m["off"]:end] = m["data"]
                elif m["op"] == "truncate":
                    if len(logical) < m["size"]:
                        logical.extend(b"\0" * (m["size"] - len(logical)))
                    else:
                        del logical[m["size"]:]
                elif m["op"] == "zero":
                    end = min(m["off"] + m["len"], len(logical))
                    logical[m["off"]:end] = b"\0" * max(0, end - m["off"])
            remove = any(m["op"] == "remove" for m in data_muts)
        else:
            logical, remove = bytearray(), False

        acting = self.pg.acting
        if remove:
            per_shard = [{"remove": True} for _ in acting]
            segs_per_shard = [[] for _ in acting]
        else:
            size = len(logical)
            padded = bytes(logical) + b"\0" * (
                self.sinfo.logical_to_next_stripe_offset(size) - size)
            if padded:
                shards = self.sinfo.encode(self.codec, padded)
            else:
                shards = {i: np.zeros(0, np.uint8)
                          for i in range(len(acting))}
            per_shard, segs_per_shard = [], []
            for shard in range(len(acting)):
                buf = shards[shard].tobytes()
                per_shard.append({"size": size, "shard_len": len(buf),
                                  "attrs": None})
                segs_per_shard.append([buf])
        # local shard applies in-line; remote shards via ec_subop_write
        awaiting = []
        for shard, osd in enumerate(acting):
            if osd < 0:
                continue
            payload = {"pgid": self.pg.pgid, "oid": entry.oid,
                       "shard": shard, "entry": entry.to_dict(),
                       "w": per_shard[shard],
                       "attr_muts": pack_mutations(attr_muts)[0]}
            segs = segs_per_shard[shard] + pack_mutations(attr_muts)[1]
            if osd == self.osd.whoami:
                self.apply_sub_write(entry, payload["w"],
                                     segs_per_shard[shard], attr_muts)
            else:
                awaiting.append((osd, "ec_subop_write", payload, segs))
        if awaiting:
            await self.osd.fanout_and_wait(awaiting)

    def apply_sub_write(self, entry: LogEntry, w: dict,
                        segs: list[bytes], attr_muts: list[dict]) -> None:
        txn = Transaction()
        oid = entry.oid
        if w.get("remove"):
            txn.remove(self.coll, oid)
        else:
            buf = segs[0] if segs else b""
            txn.truncate(self.coll, oid, 0)
            txn.write(self.coll, oid, 0, buf)
            txn.truncate(self.coll, oid, w["shard_len"])
            txn.setattr(self.coll, oid, SIZE_XATTR,
                        str(w["size"]).encode())
        apply_mutations(txn, self.coll, oid, attr_muts)
        self.pg.append_log_and_meta(txn, entry)
        self.store.queue_transaction(txn)

    # -- read path ----------------------------------------------------------
    async def object_read(self, oid, off, length) -> bytes:
        data = await self._read_logical(oid)
        if length is None:
            return data[off:]
        return data[off:off + length]

    async def object_size(self, oid) -> int:
        sx = self.store.getattr(self.coll, oid, SIZE_XATTR)
        if sx is not None:
            return int(sx)
        _, size = await self._gather_shards(oid)
        return size

    async def read_recovery_payload(self, oid, shard) -> dict:
        """Reconstruct the target shard's buffer for a recovering peer."""
        bufs, size = await self._gather_shards(oid, need_shards={shard})
        if shard in bufs:
            buf = bufs[shard]
        else:
            buf = self.sinfo.decode(self.codec, bufs, want={shard})[shard]
        return {"data": buf.tobytes(),
                "xattrs": {SIZE_XATTR: str(size).encode()},
                "omap": {}}
