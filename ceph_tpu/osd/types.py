"""PG-level value types: versions, log entries, pg_info, missing sets.

Modeled on src/osd/osd_types.h: eversion_t (epoch, version) total order,
pg_log_entry_t (:4325) with op/soid/version/prior_version, pg_info_t
(last_update/last_complete/log_tail + history), and pg_missing_t
(need/have per object, drives log-based recovery).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import total_ordering
from typing import Any


@total_ordering
@dataclass(frozen=True)
class EVersion:
    """(epoch, version) — totally ordered op version stamp."""

    epoch: int = 0
    version: int = 0

    def __lt__(self, other: "EVersion") -> bool:
        return (self.epoch, self.version) < (other.epoch, other.version)

    def __bool__(self) -> bool:
        return self.epoch != 0 or self.version != 0

    def to_list(self) -> list[int]:
        return [self.epoch, self.version]

    @classmethod
    def from_list(cls, v) -> "EVersion":
        return cls(int(v[0]), int(v[1]))


ZERO = EVersion()

# op kinds (pg_log_entry_t::Op subset the data path exercises)
MODIFY = "modify"
DELETE = "delete"
ERROR = "error"


@dataclass
class LogEntry:
    """One mutation in a PG's op log.

    ``reqid`` identifies the client request that produced the entry
    (osd_reqid_t analog) — the substrate of duplicate-op detection when
    a client resends a write whose reply was lost.
    """

    op: str
    oid: str
    version: EVersion
    prior_version: EVersion = ZERO
    mutations: list[dict[str, Any]] = field(default_factory=list)
    reqid: tuple[str, int] | None = None

    def is_delete(self) -> bool:
        return self.op == DELETE

    def to_dict(self) -> dict:
        return {"op": self.op, "oid": self.oid,
                "v": self.version.to_list(),
                "pv": self.prior_version.to_list(),
                "m": self.mutations,
                "rq": list(self.reqid) if self.reqid else None}

    @classmethod
    def from_dict(cls, d: dict) -> "LogEntry":
        rq = d.get("rq")
        return cls(op=d["op"], oid=d["oid"],
                   version=EVersion.from_list(d["v"]),
                   prior_version=EVersion.from_list(d["pv"]),
                   mutations=list(d.get("m", [])),
                   reqid=(rq[0], rq[1]) if rq else None)


@dataclass
class PGInfo:
    """Summary of a PG replica's history (pg_info_t)."""

    pgid: str = ""
    last_update: EVersion = ZERO          # newest log entry applied
    last_complete: EVersion = ZERO        # all objects ≤ this recovered
    log_tail: EVersion = ZERO             # oldest entry still in log
    last_epoch_started: int = 0
    same_interval_since: int = 0
    # False while a scan-based whole-PG backfill is in flight: the log
    # was adopted wholesale across a trim gap, so last_update overstates
    # what the data actually holds (pg_info_t::last_backfill analog --
    # True plays the role of last_backfill == MAX)
    backfill_complete: bool = True
    # cursor while backfill_complete is False: every object with name
    # <= last_backfill (lexicographic; the reference walks hobject hash
    # order, PeeringState.h:1928) has been backfilled and receives
    # normal write traffic; "" = nothing backfilled yet.  Persisted so
    # an interrupted backfill RESUMES instead of restarting.
    last_backfill: str = ""

    def is_empty(self) -> bool:
        return not self.last_update

    def to_dict(self) -> dict:
        return {"pgid": self.pgid,
                "last_update": self.last_update.to_list(),
                "last_complete": self.last_complete.to_list(),
                "log_tail": self.log_tail.to_list(),
                "last_epoch_started": self.last_epoch_started,
                "same_interval_since": self.same_interval_since,
                "backfill_complete": self.backfill_complete,
                "last_backfill": self.last_backfill}

    @classmethod
    def from_dict(cls, d: dict) -> "PGInfo":
        return cls(pgid=d["pgid"],
                   last_update=EVersion.from_list(d["last_update"]),
                   last_complete=EVersion.from_list(d["last_complete"]),
                   log_tail=EVersion.from_list(d["log_tail"]),
                   last_epoch_started=d.get("last_epoch_started", 0),
                   same_interval_since=d.get("same_interval_since", 0),
                   backfill_complete=d.get("backfill_complete", True),
                   last_backfill=d.get("last_backfill", ""))


class MissingSet:
    """Objects a replica lacks: oid -> (need, have) (pg_missing_t)."""

    def __init__(self) -> None:
        self.items: dict[str, tuple[EVersion, EVersion]] = {}

    def add(self, oid: str, need: EVersion, have: EVersion) -> None:
        prev = self.items.get(oid)
        if prev is not None:
            have = prev[1]      # keep the original on-disk version
        self.items[oid] = (need, have)

    def rm(self, oid: str, at: EVersion) -> None:
        cur = self.items.get(oid)
        if cur is not None and cur[0] <= at:
            del self.items[oid]

    def revise_need(self, oid: str, need: EVersion) -> None:
        have = self.items.get(oid, (ZERO, ZERO))[1]
        self.items[oid] = (need, have)

    def is_missing(self, oid: str) -> bool:
        return oid in self.items

    def __len__(self) -> int:
        return len(self.items)

    def __bool__(self) -> bool:
        return bool(self.items)

    def to_dict(self) -> dict:
        return {oid: [need.to_list(), have.to_list()]
                for oid, (need, have) in self.items.items()}

    @classmethod
    def from_dict(cls, d: dict) -> "MissingSet":
        ms = cls()
        for oid, (need, have) in d.items():
            ms.items[oid] = (EVersion.from_list(need),
                             EVersion.from_list(have))
        return ms


class PastIntervals:
    """Acting-set history across map epochs (compact form).

    Enough to answer "may this peer have data we need?": the union of
    acting OSDs over intervals since last_epoch_started
    (src/osd/osd_types.h PastIntervals is the heavyweight original).
    """

    def __init__(self) -> None:
        self.intervals: list[dict] = []   # {first, last, acting}

    def note_interval(self, first: int, last: int,
                      acting: list[int]) -> None:
        self.intervals.append({"first": first, "last": last,
                               "acting": list(acting)})

    def probe_targets(self, current_acting: list[int]) -> set[int]:
        osds = {o for o in current_acting if o >= 0}
        for iv in self.intervals:
            osds.update(o for o in iv["acting"] if o >= 0)
        return osds

    def clear_to(self, epoch: int) -> None:
        self.intervals = [iv for iv in self.intervals
                          if iv["last"] >= epoch]

    def to_dict(self) -> dict:
        return {"intervals": self.intervals}

    @classmethod
    def from_dict(cls, d: dict) -> "PastIntervals":
        pi = cls()
        pi.intervals = list(d.get("intervals", []))
        return pi
