"""PG-level value types: versions, log entries, pg_info, missing sets.

Modeled on src/osd/osd_types.h: eversion_t (epoch, version) total order,
pg_log_entry_t (:4325) with op/soid/version/prior_version, pg_info_t
(last_update/last_complete/log_tail + history), and pg_missing_t
(need/have per object, drives log-based recovery).

Each type carries BOTH a dict form (wire JSON) and a denc form
(versioned binary, common/denc.py) -- the persistent PG metadata uses
denc the way the reference encodes pg_info_t/pg_log_entry_t with
ENCODE_START envelopes; byte-stability is pinned by the committed
corpus (tests/fixtures/corpus, tools/dencoder.py).
"""

from __future__ import annotations

import json

from dataclasses import dataclass, field
from functools import total_ordering
from typing import Any

from ..common.denc import Decoder, Encoder


@total_ordering
@dataclass(frozen=True)
class EVersion:
    """(epoch, version) — totally ordered op version stamp."""

    epoch: int = 0
    version: int = 0

    def __lt__(self, other: "EVersion") -> bool:
        return (self.epoch, self.version) < (other.epoch, other.version)

    def __bool__(self) -> bool:
        return self.epoch != 0 or self.version != 0

    def to_list(self) -> list[int]:
        return [self.epoch, self.version]

    @classmethod
    def from_list(cls, v) -> "EVersion":
        return cls(int(v[0]), int(v[1]))

    def denc(self, enc: Encoder) -> None:
        # eversion_t is a fixed struct, no envelope (osd_types.h)
        enc.u32(self.epoch).u64(self.version)

    @classmethod
    def dedenc(cls, dec: Decoder) -> "EVersion":
        return cls(dec.u32(), dec.u64())


ZERO = EVersion()

# op kinds (pg_log_entry_t::Op subset the data path exercises)
MODIFY = "modify"
DELETE = "delete"
ERROR = "error"


@dataclass
class LogEntry:
    """One mutation in a PG's op log.

    ``reqid`` identifies the client request that produced the entry
    (osd_reqid_t analog) — the substrate of duplicate-op detection when
    a client resends a write whose reply was lost.
    """

    op: str
    oid: str
    version: EVersion
    prior_version: EVersion = ZERO
    mutations: list[dict[str, Any]] = field(default_factory=list)
    reqid: tuple[str, int] | None = None

    def is_delete(self) -> bool:
        return self.op == DELETE

    def to_dict(self) -> dict:
        return {"op": self.op, "oid": self.oid,
                "v": self.version.to_list(),
                "pv": self.prior_version.to_list(),
                "m": self.mutations,
                "rq": list(self.reqid) if self.reqid else None}

    @classmethod
    def from_dict(cls, d: dict) -> "LogEntry":
        rq = d.get("rq")
        return cls(op=d["op"], oid=d["oid"],
                   version=EVersion.from_list(d["v"]),
                   prior_version=EVersion.from_list(d["pv"]),
                   mutations=list(d.get("m", [])),
                   reqid=(rq[0], rq[1]) if rq else None)

    def denc(self, enc: Encoder) -> None:
        enc.start(1, 1)
        enc.string(self.op).string(self.oid)
        self.version.denc(enc)
        self.prior_version.denc(enc)
        # mutation payloads are free-form op descriptions; they ride as
        # an opaque blob the way pg_log_entry_t embeds op bufferlists
        enc.blob(json.dumps(self.mutations,
                            separators=(",", ":")).encode())
        enc.optional(self.reqid, lambda e, rq: (e.string(rq[0]),
                                                e.u64(rq[1])))
        enc.finish()

    @classmethod
    def dedenc(cls, dec: Decoder) -> "LogEntry":
        dec.start(1)
        op = dec.string()
        oid = dec.string()
        version = EVersion.dedenc(dec)
        prior = EVersion.dedenc(dec)
        mutations = json.loads(dec.blob() or b"[]")
        reqid = dec.optional(lambda d: (d.string(), d.u64()))
        dec.finish()
        return cls(op=op, oid=oid, version=version,
                   prior_version=prior, mutations=mutations,
                   reqid=reqid)


@dataclass
class PGInfo:
    """Summary of a PG replica's history (pg_info_t)."""

    pgid: str = ""
    last_update: EVersion = ZERO          # newest log entry applied
    last_complete: EVersion = ZERO        # all objects ≤ this recovered
    log_tail: EVersion = ZERO             # oldest entry still in log
    last_epoch_started: int = 0
    same_interval_since: int = 0
    # False while a scan-based whole-PG backfill is in flight: the log
    # was adopted wholesale across a trim gap, so last_update overstates
    # what the data actually holds (pg_info_t::last_backfill analog --
    # True plays the role of last_backfill == MAX)
    backfill_complete: bool = True
    # cursor while backfill_complete is False: every object with name
    # <= last_backfill (lexicographic; the reference walks hobject hash
    # order, PeeringState.h:1928) has been backfilled and receives
    # normal write traffic; "" = nothing backfilled yet.  Persisted so
    # an interrupted backfill RESUMES instead of restarting.
    last_backfill: str = ""

    def is_empty(self) -> bool:
        return not self.last_update

    def to_dict(self) -> dict:
        return {"pgid": self.pgid,
                "last_update": self.last_update.to_list(),
                "last_complete": self.last_complete.to_list(),
                "log_tail": self.log_tail.to_list(),
                "last_epoch_started": self.last_epoch_started,
                "same_interval_since": self.same_interval_since,
                "backfill_complete": self.backfill_complete,
                "last_backfill": self.last_backfill}

    @classmethod
    def from_dict(cls, d: dict) -> "PGInfo":
        return cls(pgid=d["pgid"],
                   last_update=EVersion.from_list(d["last_update"]),
                   last_complete=EVersion.from_list(d["last_complete"]),
                   log_tail=EVersion.from_list(d["log_tail"]),
                   last_epoch_started=d.get("last_epoch_started", 0),
                   same_interval_since=d.get("same_interval_since", 0),
                   backfill_complete=d.get("backfill_complete", True),
                   last_backfill=d.get("last_backfill", ""))

    def denc(self, enc: Encoder) -> None:
        enc.start(1, 1)
        enc.string(self.pgid)
        self.last_update.denc(enc)
        self.last_complete.denc(enc)
        self.log_tail.denc(enc)
        enc.u32(self.last_epoch_started)
        enc.u32(self.same_interval_since)
        enc.boolean(self.backfill_complete)
        enc.string(self.last_backfill)
        enc.finish()

    @classmethod
    def dedenc(cls, dec: Decoder) -> "PGInfo":
        dec.start(1)
        out = cls(pgid=dec.string(),
                  last_update=EVersion.dedenc(dec),
                  last_complete=EVersion.dedenc(dec),
                  log_tail=EVersion.dedenc(dec),
                  last_epoch_started=dec.u32(),
                  same_interval_since=dec.u32(),
                  backfill_complete=dec.boolean(),
                  last_backfill=dec.string())
        dec.finish()
        return out


class MissingSet:
    """Objects a replica lacks: oid -> (need, have) (pg_missing_t)."""

    def __init__(self) -> None:
        self.items: dict[str, tuple[EVersion, EVersion]] = {}

    def add(self, oid: str, need: EVersion, have: EVersion) -> None:
        prev = self.items.get(oid)
        if prev is not None:
            have = prev[1]      # keep the original on-disk version
        self.items[oid] = (need, have)

    def rm(self, oid: str, at: EVersion) -> None:
        cur = self.items.get(oid)
        if cur is not None and cur[0] <= at:
            del self.items[oid]

    def revise_need(self, oid: str, need: EVersion) -> None:
        have = self.items.get(oid, (ZERO, ZERO))[1]
        self.items[oid] = (need, have)

    def is_missing(self, oid: str) -> bool:
        return oid in self.items

    def __len__(self) -> int:
        return len(self.items)

    def __bool__(self) -> bool:
        return bool(self.items)

    def to_dict(self) -> dict:
        return {oid: [need.to_list(), have.to_list()]
                for oid, (need, have) in self.items.items()}

    @classmethod
    def from_dict(cls, d: dict) -> "MissingSet":
        ms = cls()
        for oid, (need, have) in d.items():
            ms.items[oid] = (EVersion.from_list(need),
                             EVersion.from_list(have))
        return ms

    def denc(self, enc: Encoder) -> None:
        enc.start(1, 1)
        enc.map(self.items, lambda e, k: e.string(k),
                lambda e, v: (v[0].denc(e), v[1].denc(e)))
        enc.finish()

    @classmethod
    def dedenc(cls, dec: Decoder) -> "MissingSet":
        dec.start(1)
        ms = cls()
        ms.items = dec.map(
            lambda d: d.string(),
            lambda d: (EVersion.dedenc(d), EVersion.dedenc(d)))
        dec.finish()
        return ms


class PastIntervals:
    """Acting-set history across map epochs (compact form).

    Enough to answer "may this peer have data we need?": the union of
    acting OSDs over intervals since last_epoch_started
    (src/osd/osd_types.h PastIntervals is the heavyweight original).
    """

    def __init__(self) -> None:
        self.intervals: list[dict] = []   # {first, last, acting, rw}

    def note_interval(self, first: int, last: int,
                      acting: list[int], rw: bool = True) -> None:
        """``rw=False`` marks an interval whose primary never got an
        up_thru bump: it provably never served writes (maybe_went_rw,
        osd_types.cc check_new_interval), so its members carry nothing
        recovery could need."""
        self.intervals.append({"first": first, "last": last,
                               "acting": list(acting), "rw": bool(rw)})

    def probe_targets(self, current_acting: list[int]) -> set[int]:
        osds = {o for o in current_acting if o >= 0}
        for iv in self.intervals:
            if not iv.get("rw", True):
                continue             # provably never went read-write
            osds.update(o for o in iv["acting"] if o >= 0)
        return osds

    def clear_to(self, epoch: int) -> None:
        self.intervals = [iv for iv in self.intervals
                          if iv["last"] >= epoch]

    def to_dict(self) -> dict:
        return {"intervals": self.intervals}

    @classmethod
    def from_dict(cls, d: dict) -> "PastIntervals":
        pi = cls()
        pi.intervals = list(d.get("intervals", []))
        return pi

    def denc(self, enc: Encoder) -> None:
        # v2 adds the per-interval maybe_went_rw byte MID-STREAM, so
        # v1 decoders cannot tail-skip it: compat=2 makes them fail
        # cleanly instead of misparsing
        enc.start(2, 2)
        enc.list(self.intervals, lambda e, iv: (
            e.u32(iv["first"]), e.u32(iv["last"]),
            e.list(iv["acting"], lambda e2, o: e2.i64(o)),
            e.u8(1 if iv.get("rw", True) else 0)))
        enc.finish()

    @classmethod
    def dedenc(cls, dec: Decoder) -> "PastIntervals":
        v = dec.start(2)
        pi = cls()

        def one(d):
            iv = {"first": d.u32(), "last": d.u32(),
                  "acting": d.list(lambda d2: d2.i64())}
            iv["rw"] = bool(d.u8()) if v >= 2 else True
            return iv
        pi.intervals = dec.list(one)
        dec.finish()
        return pi
