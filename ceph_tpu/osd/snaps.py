"""Self-managed snapshot machinery: SnapSets, clone naming, SnapMapper.

Semantics from the reference's snap stack: writes carry a SnapContext
(seq + existing snap ids, newest first); the first write to an object
after a newer snap clones the head (clone-on-write) and records the
clone in the object's SnapSet (PrimaryLogPG::make_writeable); snap
reads resolve through the SnapSet to the right clone; a reverse
snap->objects index (SnapMapper, src/osd/SnapMapper.h:339) drives
trimming when the mon marks a snap removed.

Clones are ordinary objects (replication, recovery and backfill move
them like any other), named with a reserved NUL-containing suffix no
client name can collide with.  SnapSets live in a per-PG omap object
rather than a head xattr so they survive head deletion (the reference
keeps a snapdir object for the same reason); both the SnapSet rows and
the SnapMapper rows are written via mutations inside the SAME log
entry as the data op, so replicas and recovery stay in lockstep.
"""

from __future__ import annotations

import json

SNAPSETS_OID = "_snapsets_"      # omap: head oid -> snapset json
SNAPMAPPER_OID = "_snapmapper_"  # omap: "<snap>/<head>" -> ""
CLONE_SEP = "\x00snap:"          # NUL cannot appear in client names
INTERNAL_OIDS = frozenset({SNAPSETS_OID, SNAPMAPPER_OID})


def clone_oid(oid: str, snapid: int) -> str:
    return f"{oid}{CLONE_SEP}{snapid:016x}"


def is_clone(oid: str) -> bool:
    return CLONE_SEP in oid


def clone_parent(oid: str) -> tuple[str, int]:
    head, _, sid = oid.rpartition(CLONE_SEP)
    return head, int(sid, 16)


def snapmapper_key(snapid: int, oid: str) -> str:
    return f"{snapid:016x}/{oid}"


def empty_snapset() -> dict:
    # seq: newest snap this object has seen (cloned for or created
    # under); clones: [[cloneid, [covered snap ids asc], size], ...]
    return {"seq": 0, "clones": []}


def load_snapset(store, coll: str, oid: str) -> dict:
    raw = store.omap_get(coll, SNAPSETS_OID).get(oid)
    if not raw:
        return empty_snapset()
    return json.loads(raw)


def resolve_read(ss: dict, snapid: int) -> int | None:
    """Which object serves a read at ``snapid``?

    Returns the clone id, 0 for the head, or None for "did not exist
    at that snap".  Clones ascend; the serving clone is the FIRST with
    cloneid >= snapid -- it froze the content that was live when the
    snap was taken.  A gap below the clone's covered range means the
    object was created after the snap (find_object_context snap
    resolution)."""
    for cid, covered, _size in sorted(ss.get("clones", [])):
        if cid >= snapid:
            return cid if covered and snapid >= min(covered) else None
    # head serves -- unless the object was born (or reborn) after the
    # snap was taken: born == seq at creation means every snap id <=
    # born predates the object
    return None if snapid <= ss.get("born", 0) else 0
