"""PG scrubbing: cross-shard consistency checking and repair.

src/osd/scrubber analog (pg_scrubber.cc / scrub_backend.cc): the
primary collects a scrub map (per-object size + data crc + attr/omap
digests) from every acting shard, compares them, and flags
inconsistencies.  Replicated PGs majority-vote the authoritative copy
and can repair divergent replicas by pushing it.  EC PGs deep-scrub by
reconstructing the logical object from k shards, re-encoding, and
byte-comparing every stored shard against the re-encode (the parity
consistency check ECBackend gets from per-shard hashinfo crcs).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any

from ..ops.crc32c_batch import crc32c_batch
from .backend import META_OID, ECBackend, SIZE_XATTR

# objects digested per batched CRC call: bounds the payload bytes held
# in RAM at once while keeping the per-call amortization (a collection
# of any size still makes O(n/256) library calls, not O(n))
_DIGEST_BATCH = 256


async def build_scrub_map(store, coll: str,
                          deep: bool = True) -> dict[str, dict]:
    """Digest every object in a PG collection (replica side).

    Async with periodic yields: digesting a whole PG synchronously
    would stall the event loop past the heartbeat grace and get the
    daemon falsely reported down.  Deep-scrub data digests gather the
    object payloads and go through ONE batched ``crc32c_batch`` call
    per chunk of the collection instead of a scalar host call per
    object (the last per-object CRC loop on the scrub path).  Objects
    resident in the store's device shard cache digest WITHOUT a store
    read: the write-time CRC tag (when carried) IS the digest, else
    the resident buffer joins the batched pass directly."""
    import asyncio
    cache = getattr(store, "shard_cache", None)
    out: dict[str, dict] = {}
    pending: list[tuple[str, bytes]] = []   # (oid, payload) awaiting CRC

    def flush_digests() -> None:
        if not pending:
            return
        crcs = crc32c_batch([p for _, p in pending])
        for (oid2, _), crc in zip(pending, crcs):
            out[oid2]["data_digest"] = int(crc)
        pending.clear()

    for i, oid in enumerate(store.list_objects(coll)):
        if i % 16 == 15:
            await asyncio.sleep(0)
        if oid == META_OID:
            continue
        st = store.stat(coll, oid)
        if st is None:
            continue
        entry: dict[str, Any] = {"size": st["size"]}
        attrs = {k: v for k, v in store.getattrs(coll, oid).items()}
        omap = store.omap_get(coll, oid)
        entry["attrs_digest"] = hashlib.sha1(
            json.dumps({k: v.hex() for k, v in sorted(attrs.items())})
            .encode()).hexdigest()
        entry["omap_digest"] = hashlib.sha1(
            json.dumps({k: v.hex() for k, v in sorted(omap.items())})
            .encode()).hexdigest()
        out[oid] = entry
        if deep:
            resident = cache.get(coll, oid) \
                if cache is not None and (coll, oid) in cache else None
            if resident is not None and resident.crc is not None:
                entry["data_digest"] = resident.crc
                from ..os.device_cache import PERF as DATAPATH_PERF
                DATAPATH_PERF.inc("scrub_cached_digests")
                continue
            if resident is not None:
                payload = resident.buf          # no store round trip
            else:
                payload = bytes(store.read(coll, oid, 0, None))
                if cache is not None:
                    cache.note_host_read(len(payload))
            pending.append((oid, payload))
            if len(pending) >= _DIGEST_BATCH:
                flush_digests()
    flush_digests()
    return out


class ScrubResult:
    def __init__(self, pgid: str) -> None:
        self.pgid = pgid
        self.objects_scrubbed = 0
        self.inconsistent: dict[str, dict] = {}   # oid -> detail
        self.repaired: list[str] = []

    @property
    def clean(self) -> bool:
        return not self.inconsistent

    def to_dict(self) -> dict:
        return {"pgid": self.pgid,
                "objects_scrubbed": self.objects_scrubbed,
                "inconsistent": self.inconsistent,
                "repaired": self.repaired,
                "clean": self.clean}


async def scrub_replicated(pg, repair: bool = False) -> ScrubResult:
    """Compare scrub maps across replicas; majority is authoritative."""
    res = ScrubResult(pg.pgid)
    local = await build_scrub_map(pg.osd.store, pg.coll)
    maps: dict[int, dict[str, dict]] = {pg.whoami: local}
    peers = [o for o in pg.acting_peers() if pg.osd.osd_is_up(o)]
    replies = await pg.osd.fanout_and_wait(
        [(o, "pg_scrub_map_req", {"pgid": pg.pgid}, []) for o in peers],
        collect=True, timeout=15)
    for rep in replies:
        maps[rep.data["from_osd"]] = rep.data["map"]
    all_oids = sorted(set().union(*[set(m) for m in maps.values()]))
    res.objects_scrubbed = len(all_oids)
    for oid in all_oids:
        versions: dict[str, list[int]] = {}
        for osd_id, m in maps.items():
            key = json.dumps(m.get(oid), sort_keys=True)
            versions.setdefault(key, []).append(osd_id)
        if len(versions) <= 1:
            continue
        # majority vote picks the authoritative digest set
        auth_key = max(versions, key=lambda k: len(versions[k]))
        bad = {k: v for k, v in versions.items() if k != auth_key}
        res.inconsistent[oid] = {
            "auth_osds": versions[auth_key],
            "bad": [{"osds": osds, "digests": json.loads(k)}
                    for k, osds in bad.items()],
        }
        if repair:
            await _repair_replicated(pg, oid, versions[auth_key], bad)
            res.repaired.append(oid)
    return res


async def _repair_replicated(pg, oid: str, auth_osds: list[int],
                             bad: dict) -> None:
    """Push the authoritative copy over divergent replicas."""
    from ..msg import Message
    if pg.whoami in auth_osds:
        payload = await pg.backend.read_recovery_payload(oid, 0)
    else:
        replies = await pg.osd.fanout_and_wait(
            [(auth_osds[0], "pg_pull",
              {"pgid": pg.pgid, "oid": oid, "shard": 0}, [])],
            collect=True, timeout=10)
        if not replies or replies[0].data.get("err"):
            return
        rep = replies[0]
        payload = {"data": rep.segments[0] if rep.segments else b"",
                   "xattrs": {k: bytes.fromhex(v) for k, v in
                              rep.data.get("xattrs", {}).items()},
                   "omap": {k: bytes.fromhex(v) for k, v in
                            rep.data.get("omap", {}).items()},
                   "absent": rep.data.get("absent", False)}
        pg._apply_recovery_payload(oid, {
            "absent": payload["absent"],
            "xattrs": {k: v.hex() for k, v in payload["xattrs"].items()},
            "omap": {k: v.hex() for k, v in payload["omap"].items()},
        }, [payload["data"]])
    # `bad` values are lists of osd ids keyed by digest json
    bad_osds = [o for osds in bad.values() for o in osds]
    for osd_id in bad_osds:
        if osd_id == pg.whoami:
            continue
        await pg.osd.fanout_and_wait(
            [(osd_id, "pg_push",
              {"pgid": pg.pgid, "oid": oid,
               "absent": payload.get("absent", False),
               "xattrs": {k: v.hex()
                          for k, v in payload["xattrs"].items()},
               "omap": {k: v.hex()
                        for k, v in payload["omap"].items()}},
              [payload["data"]])], collect=True, timeout=10)


async def scrub_ec(pg, repair: bool = False) -> ScrubResult:
    """Deep EC scrub: verify every stored shard against its write-time
    identity, re-encoding only when something disagrees.

    Shards whose bytes are device-cache-resident verify with ONE
    device CRC launch over the resident buffer (``crc32c_resident``)
    against the write-time tag -- zero store reads, zero host passes
    over the payload.  When EVERY acting shard verifies (label ==
    position, tag matches recomputed CRC, one version, consistent
    lengths) the parity relationship is attested transitively: the
    tags were computed IN the encode launch that produced the parity,
    so a fully-tag-verified object needs no reconstruct + re-encode.
    Anything off -- a missing tag, a mismatch, mixed versions --
    falls back to the canonical path: reconstruct from k shards,
    re-encode through the CodecBatcher, byte-compare every stored
    shard (bit rot injected under a shard's tag is caught there)."""
    import numpy as np
    from ..os.device_cache import PERF as DATAPATH_PERF
    res = ScrubResult(pg.pgid)
    backend: ECBackend = pg.backend
    oids = [o for o in pg.osd.store.list_objects(pg.coll)
            if o != META_OID]
    res.objects_scrubbed = len(oids)
    from .backend import (CRC_XATTR, SHARD_XATTR, VER_XATTR, shard_crc,
                          shard_crc_matches)
    for oid in oids:
        # fetch every stored shard + its write-time identity tags
        # (shard label / crc / version) -- scrub is where silent tag
        # rot gets caught.  Local shards ride the device cache; remote
        # shards arrive in ONE parallel gather through the hedged
        # sub-read machinery (the old loop paid one serial round trip
        # per shard), with every reply feeding the per-peer latency
        # EWMA.  A shard whose source outlives the read deadline just
        # falls out to the reconstruct path below.
        stored, n_acting = await backend.collect_shard_states(oid)
        if not stored:
            continue
        # resident buffers verify via the device kernel; the rest in
        # one batched host pass
        have_crcs: dict[int, int] = {}
        host_idx = [i for i, e in enumerate(stored) if not e[5]]
        if host_idx:
            crcs = crc32c_batch([stored[i][1] for i in host_idx])
            have_crcs = {i: int(c) for i, c in zip(host_idx, crcs)}
        for i, e in enumerate(stored):
            if e[5]:
                from ..ops.crc32c_batch import crc32c_resident
                have_crcs[i] = crc32c_resident(e[1])
        vers = {e[4] for e in stored}
        lens = {len(e[1]) for e in stored}
        fast_ok = (len(stored) == n_acting and len(vers) == 1
                   and len(lens) == 1)
        if fast_ok:
            for i, (shard, raw, label, crc, over, _) in \
                    enumerate(stored):
                if label is None or int(label) != shard \
                        or crc is None \
                        or int(crc) != have_crcs[i]:
                    fast_ok = False
                    break
        if fast_ok:
            DATAPATH_PERF.inc("scrub_fast_verifies")
            continue
        # slow path: reconstruct, re-encode, byte-compare
        bufs, size, ver = await backend._gather_shards(
            oid, need_shards=set(range(backend.k)))
        if not bufs:
            continue
        logical = await backend.sinfo.reconstruct_logical_async(
            backend.codec, bufs, batcher=backend.batcher)
        pad = backend.sinfo.logical_to_next_stripe_offset(size)
        canonical = await backend.sinfo.encode_async(
            backend.codec, logical[:pad].ljust(pad, b"\0"),
            batcher=backend.batcher)
        bad_shards: list[int] = []
        bad_tags: list[int] = []
        for i, (shard, raw, label, crc, over, _) in enumerate(stored):
            raw = bytes(raw)
            want = canonical[shard].tobytes()
            if raw != want:
                bad_shards.append(shard)
            elif (label is not None and int(label) != shard) or \
                    not shard_crc_matches(raw, crc,
                                          precomputed=have_crcs[i]):
                bad_tags.append(shard)
        if bad_shards or bad_tags:
            res.inconsistent[oid] = {"bad_shards": bad_shards,
                                     "bad_tags": bad_tags}
            if repair:
                for shard in bad_shards + bad_tags:
                    osd_id = pg.acting[shard]
                    blob = canonical[shard].tobytes()
                    payload = {"pgid": pg.pgid, "oid": oid,
                               "absent": False,
                               "shard": shard,
                               "crc": shard_crc(blob),
                               "xattrs": {
                                   SIZE_XATTR:
                                       str(size).encode().hex(),
                                   VER_XATTR:
                                       f"{ver[0]},{ver[1]}"
                                       .encode().hex(),
                                   SHARD_XATTR:
                                       str(shard).encode().hex(),
                                   CRC_XATTR:
                                       str(shard_crc(blob))
                                       .encode().hex()},
                               "omap": {}}
                    if osd_id == pg.whoami:
                        pg._apply_recovery_payload(oid, payload,
                                                   [blob])
                    else:
                        await pg.osd.fanout_and_wait(
                            [(osd_id, "pg_push", payload, [blob])],
                            collect=True, timeout=10)
                res.repaired.append(oid)
    return res


async def scrub_pg(pg, repair: bool = False) -> ScrubResult:
    # quiesce the pipelined write spine first: a deferred commit still
    # in flight would make replica shard states legitimately lag the
    # primary's, which scrub would misread as inconsistency
    await pg.drain_commits()
    # lint: disable=await-under-lock -- scrub deliberately freezes the PG while it compares shard states; the drain above keeps in-flight commits out of the hold
    async with pg.lock:
        if isinstance(pg.backend, ECBackend):
            return await scrub_ec(pg, repair=repair)
        return await scrub_replicated(pg, repair=repair)
