"""OSD data plane: PGs, log-based recovery, replicated/EC backends.

Functional rendering of src/osd: the PG op path (PrimaryLogPG.cc),
per-PG op logs with divergent-entry rewind (PGLog.h), the peering
protocol that agrees on authoritative history after map changes
(PeeringState.h), and the PGBackend split into replication fan-out
vs erasure-coded read-modify-write (PGBackend.cc:570).
"""

from .types import EVersion, LogEntry, PGInfo, MissingSet, PastIntervals
from .pg_log import PGLog
from .ec_util import StripeInfo
from .scheduler import MClockScheduler, OpClass
from .osd import OSD
from .pg import PG

__all__ = [
    "EVersion", "LogEntry", "PGInfo", "MissingSet", "PastIntervals",
    "PGLog", "StripeInfo", "MClockScheduler", "OpClass", "OSD", "PG",
]
