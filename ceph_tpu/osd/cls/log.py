"""cls_log: time-indexed log entries in an object's omap.

src/cls/log/cls_log.cc: RGW's metadata/data logs append timestamped
entries; readers page through a time window with a resumable marker,
and trim removes a consumed window.  Keys sort by (timestamp, seq) so
the omap's order IS the time order.
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

_SEQ_KEY = "\x01seq"     # sorts before every timestamp key


def _key(ts: float, seq: int) -> str:
    return f"{int(ts * 1e6):020d}.{seq:010d}"


@register("log", "add", CLS_METHOD_RD | CLS_METHOD_WR)
def add_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    try:
        seq = int(hctx.map_get_val(_SEQ_KEY))
    except ClsError:
        seq = 0
    for e in q["entries"]:
        seq += 1
        ts = float(e.get("timestamp", hctx.current_time()))
        hctx.map_set_val(_key(ts, seq), json.dumps({
            "timestamp": ts, "section": e.get("section", ""),
            "name": e.get("name", ""),
            "data": e.get("data", "")}).encode())
    hctx.map_set_val(_SEQ_KEY, str(seq).encode())
    return b""


@register("log", "list", CLS_METHOD_RD)
def list_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    lo = _key(float(q.get("from", 0)), 0)
    # 'to' is EXCLUSIVE (cls_log window semantics): seq 0 at the bound
    # timestamp sorts before every real entry at that timestamp
    hi = _key(float(q["to"]), 0) if q.get("to") else "\x7f"
    marker = q.get("marker", "")
    max_n = int(q.get("max", 1000))
    out, last = [], ""
    for k in hctx.map_get_keys(start_after=marker or "",
                              max_return=1 << 62):
        if k == _SEQ_KEY or k < lo or k >= hi:
            continue
        if len(out) >= max_n:
            return json.dumps({"entries": out, "marker": last,
                               "truncated": True}).encode()
        out.append(json.loads(hctx.map_get_val(k)))
        last = k
    return json.dumps({"entries": out, "marker": last,
                       "truncated": False}).encode()


@register("log", "trim", CLS_METHOD_RD | CLS_METHOD_WR)
def trim_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    lo = _key(float(q.get("from", 0)), 0)
    hi = _key(float(q["to"]), 0) if q.get("to") else \
        (q.get("to_marker") or "\x7f")
    n = 0
    for k in list(hctx.map_get_keys(max_return=1 << 62)):
        if k != _SEQ_KEY and lo <= k < hi:
            hctx.map_remove_key(k)
            n += 1
    if n == 0:
        raise ClsError("ENODATA", "nothing to trim")
    return b""
