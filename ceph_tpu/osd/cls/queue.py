"""cls_queue: a durable FIFO inside one object.

src/cls/queue/cls_queue.cc (rgw's persistent notification queues ride
cls_2pc_queue on top of it): enqueue appends entries under a
monotonic sequence, list pages from a marker in order, remove acks a
consumed prefix.
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

_SEQ = "\x01seq"


def _key(seq: int) -> str:
    return f"e{seq:020d}"


@register("queue", "enqueue", CLS_METHOD_RD | CLS_METHOD_WR)
def enqueue_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    try:
        seq = int(hctx.map_get_val(_SEQ))
    except ClsError:
        seq = 0
    for e in q["entries"]:
        seq += 1
        hctx.map_set_val(_key(seq), json.dumps(e).encode())
    hctx.map_set_val(_SEQ, str(seq).encode())
    return json.dumps({"tail": seq}).encode()


@register("queue", "list", CLS_METHOD_RD)
def list_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    max_n = int(q.get("max", 1000))
    out, last, truncated = [], q.get("marker", ""), False
    for k in hctx.map_get_keys(start_after=q.get("marker", ""),
                              max_return=1 << 62):
        if not k.startswith("e"):
            continue
        if len(out) >= max_n:
            truncated = True
            break
        out.append(json.loads(hctx.map_get_val(k)))
        last = k
    return json.dumps({"entries": out, "marker": last,
                       "truncated": truncated}).encode()


@register("queue", "remove", CLS_METHOD_RD | CLS_METHOD_WR)
def remove_op(hctx, indata: bytes) -> bytes:
    """Ack everything up to AND INCLUDING end_marker."""
    q = json.loads(indata or b"{}")
    end = q["end_marker"]
    n = 0
    for k in list(hctx.map_get_keys(max_return=1 << 62)):
        if k.startswith("e") and k <= end:
            hctx.map_remove_key(k)
            n += 1
    return json.dumps({"removed": n}).encode()
