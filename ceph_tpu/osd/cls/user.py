"""cls_user: per-user bucket registry + usage accounting.

src/cls/user/cls_user.cc: RGW keeps each user's bucket list and
aggregate stats (size/object counts) in a user object's omap, mutated
atomically at the OSD as buckets come, go, and grow.
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

_PREFIX = "bucket:"


def _get(hctx, bucket: str) -> dict | None:
    try:
        return json.loads(hctx.map_get_val(_PREFIX + bucket))
    except ClsError:
        return None


@register("user", "set_buckets_info", CLS_METHOD_RD | CLS_METHOD_WR)
def set_buckets_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    for e in q["entries"]:
        cur = _get(hctx, e["bucket"]) or {"bucket": e["bucket"],
                                          "size": 0, "count": 0,
                                          "creation_time": 0}
        if q.get("add"):
            cur["size"] += int(e.get("size", 0))
            cur["count"] += int(e.get("count", 0))
        else:
            cur["size"] = int(e.get("size", cur["size"]))
            cur["count"] = int(e.get("count", cur["count"]))
        if e.get("creation_time"):
            cur["creation_time"] = e["creation_time"]
        hctx.map_set_val(_PREFIX + e["bucket"],
                         json.dumps(cur).encode())
    return b""


@register("user", "remove_bucket", CLS_METHOD_RD | CLS_METHOD_WR)
def remove_bucket_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    if _get(hctx, q["bucket"]) is None:
        raise ClsError("ENOENT", q["bucket"])
    hctx.map_remove_key(_PREFIX + q["bucket"])
    return b""


@register("user", "list_buckets", CLS_METHOD_RD)
def list_buckets_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    max_n = int(q.get("max", 1000))
    marker = q.get("marker", "")
    out, last, truncated = [], "", False
    for k in hctx.map_get_keys(
            start_after=(_PREFIX + marker) if marker else "",
            max_return=1 << 62):
        if not k.startswith(_PREFIX):
            continue
        if len(out) >= max_n:
            truncated = True
            break
        out.append(json.loads(hctx.map_get_val(k)))
        last = k[len(_PREFIX):]
    return json.dumps({"entries": out, "marker": last,
                       "truncated": truncated}).encode()


@register("user", "get_header", CLS_METHOD_RD)
def get_header_op(hctx, indata: bytes) -> bytes:
    total_size = total_count = buckets = 0
    for k in hctx.map_get_keys(max_return=1 << 62):
        if k.startswith(_PREFIX):
            e = json.loads(hctx.map_get_val(k))
            total_size += e["size"]
            total_count += e["count"]
            buckets += 1
    return json.dumps({"stats": {"size": total_size,
                                 "count": total_count},
                       "buckets": buckets}).encode()
