"""cls_version: object-version conditional updates.

Mirrors src/cls/version/cls_version.cc: a (ver, tag) pair in xattr
"cls_version"; readers can assert equality so read-modify-write cycles
detect concurrent writers (RGW bucket-index and metadata objects use
this as their optimistic concurrency control).
"""

from __future__ import annotations

import json
import os

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

_ATTR = "cls_version"


def _load(hctx) -> dict:
    try:
        return json.loads(hctx.getxattr(_ATTR))
    except ClsError:
        return {"ver": 0, "tag": ""}


def _bump(hctx, ver: dict) -> None:
    ver["tag"] = os.urandom(6).hex()
    hctx.setxattr(_ATTR, json.dumps(ver).encode())


@register("version", "set", CLS_METHOD_RD | CLS_METHOD_WR)
def set_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    _bump(hctx, {"ver": int(q["ver"]), "tag": ""})
    return b""


@register("version", "inc", CLS_METHOD_RD | CLS_METHOD_WR)
def inc_op(hctx, indata: bytes) -> bytes:
    ver = _load(hctx)
    ver["ver"] += 1
    _bump(hctx, ver)
    return b""


@register("version", "inc_conds", CLS_METHOD_RD | CLS_METHOD_WR)
def inc_conds_op(hctx, indata: bytes) -> bytes:
    """Increment only if the caller's (ver, tag) still matches."""
    q = json.loads(indata or b"{}")
    ver = _load(hctx)
    if int(q.get("ver", -1)) != ver["ver"] or \
            q.get("tag", "") != ver["tag"]:
        raise ClsError("ECANCELED", "version changed")
    ver["ver"] += 1
    _bump(hctx, ver)
    return b""


@register("version", "read", CLS_METHOD_RD)
def read_op(hctx, indata: bytes) -> bytes:
    return json.dumps(_load(hctx)).encode()


@register("version", "check_conds", CLS_METHOD_RD)
def check_conds_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    ver = _load(hctx)
    if int(q.get("ver", -1)) != ver["ver"] or \
            q.get("tag", "") != ver["tag"]:
        raise ClsError("ECANCELED", "version changed")
    return b""
