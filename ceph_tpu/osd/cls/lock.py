"""cls_lock: advisory shared/exclusive object locks.

Mirrors src/cls/lock/cls_lock.cc: lock state lives in an object xattr
``lock.<name>`` (the reference keys attr "lock.<name>" the same way,
cls_lock.cc:121 lock_info_t), lockers are (entity, cookie) pairs with
optional expiration; methods lock/unlock/break_lock/get_info/
list_locks follow cls_lock_ops.h semantics.  librbd's exclusive lock
and RGW's reshard/lifecycle locks are the main reference customers.
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

LOCK_NONE = "none"
LOCK_EXCLUSIVE = "exclusive"
LOCK_SHARED = "shared"

_ATTR = "lock."


def _load(hctx, name: str) -> dict:
    try:
        info = json.loads(hctx.getxattr(_ATTR + name))
    except ClsError:
        info = {"type": LOCK_NONE, "tag": "", "lockers": {}}
    # purge expired lockers on every access (cls_lock does this lazily)
    now = hctx.current_time()
    info["lockers"] = {
        k: v for k, v in info["lockers"].items()
        if not v.get("expiration") or v["expiration"] > now}
    if not info["lockers"]:
        info["type"] = LOCK_NONE
    return info


def _store(hctx, name: str, info: dict) -> None:
    if info["lockers"]:
        hctx.setxattr(_ATTR + name, json.dumps(info).encode())
    else:
        try:
            hctx.getxattr(_ATTR + name)
            hctx.rmxattr(_ATTR + name)
        except ClsError:
            pass


def _locker_key(entity: str, cookie: str) -> str:
    return f"{entity}\0{cookie}"


@register("lock", "lock", CLS_METHOD_RD | CLS_METHOD_WR)
def lock_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    name = q["name"]
    ltype = q.get("type", LOCK_EXCLUSIVE)
    cookie = str(q.get("cookie", ""))
    tag = q.get("tag", "")
    desc = q.get("description", "")
    duration = float(q.get("duration", 0))
    renew = bool(q.get("flags", 0) & 1)     # LOCK_FLAG_MAY_RENEW
    if ltype not in (LOCK_EXCLUSIVE, LOCK_SHARED):
        raise ClsError("EINVAL", f"bad lock type {ltype}")
    info = _load(hctx, name)
    key = _locker_key(hctx.entity, cookie)
    if info["type"] != LOCK_NONE:
        if info["tag"] != tag:
            raise ClsError("EBUSY", "tag mismatch")
        if key in info["lockers"]:
            if not renew and info["type"] == ltype:
                raise ClsError("EEXIST", "already held")
        elif info["type"] == LOCK_EXCLUSIVE or ltype == LOCK_EXCLUSIVE:
            raise ClsError("EBUSY", "held by another locker")
    exp = hctx.current_time() + duration if duration else 0
    if key in info["lockers"] and info["type"] != ltype:
        raise ClsError("EBUSY", "would change lock type")
    info["type"] = ltype
    info["tag"] = tag
    info["lockers"][key] = {"description": desc, "expiration": exp}
    _store(hctx, name, info)
    return b""


@register("lock", "unlock", CLS_METHOD_RD | CLS_METHOD_WR)
def unlock_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    info = _load(hctx, q["name"])
    key = _locker_key(hctx.entity, str(q.get("cookie", "")))
    if key not in info["lockers"]:
        raise ClsError("ENOENT", "not held by caller")
    del info["lockers"][key]
    if not info["lockers"]:
        info["type"] = LOCK_NONE
    _store(hctx, q["name"], info)
    return b""


@register("lock", "break_lock", CLS_METHOD_RD | CLS_METHOD_WR)
def break_lock_op(hctx, indata: bytes) -> bytes:
    """Forcibly drop ANOTHER entity's lock (recovery after client death)."""
    q = json.loads(indata or b"{}")
    info = _load(hctx, q["name"])
    key = _locker_key(q["locker"], str(q.get("cookie", "")))
    if key not in info["lockers"]:
        raise ClsError("ENOENT", "no such locker")
    del info["lockers"][key]
    if not info["lockers"]:
        info["type"] = LOCK_NONE
    _store(hctx, q["name"], info)
    return b""


@register("lock", "get_info", CLS_METHOD_RD)
def get_info_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    info = _load(hctx, q["name"])
    return json.dumps({
        "type": info["type"], "tag": info["tag"],
        "lockers": [
            {"entity": k.split("\0")[0], "cookie": k.split("\0")[1],
             **v} for k, v in info["lockers"].items()],
    }).encode()


@register("lock", "list_locks", CLS_METHOD_RD)
def list_locks_op(hctx, indata: bytes) -> bytes:
    names = [k[len(_ATTR):] for k in hctx._ov["xattrs"]
             if k.startswith(_ATTR)]
    return json.dumps(sorted(names)).encode()


@register("lock", "assert_locked", CLS_METHOD_RD)
def assert_locked_op(hctx, indata: bytes) -> bytes:
    """Fails unless the CALLER holds the lock -- composed into op
    vectors so a write commits only while the lock is held
    (rados lock assert, cls_lock.cc assert_locked)."""
    q = json.loads(indata or b"{}")
    info = _load(hctx, q["name"])
    key = _locker_key(hctx.entity, str(q.get("cookie", "")))
    if q.get("type", info["type"]) != info["type"] \
            or key not in info["lockers"]:
        raise ClsError("EBUSY", "lock not held by caller")
    if q.get("tag") is not None and q.get("tag", "") != info["tag"]:
        raise ClsError("EBUSY", "tag mismatch")
    return b""
