"""cls_timeindex: a time-keyed index over opaque values.

src/cls/timeindex/cls_timeindex.cc (rgw sync uses it for its error
repo): entries keyed by (timestamp, key_suffix), listable as a time
window with marker paging, trimmable by range or marker.
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register


def _key(ts: float, suffix: str) -> str:
    return f"{int(ts * 1e6):020d}_{suffix}"


@register("timeindex", "add", CLS_METHOD_RD | CLS_METHOD_WR)
def add_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    for e in q["entries"]:
        ts = float(e.get("timestamp", hctx.current_time()))
        hctx.map_set_val(_key(ts, e["key_suffix"]),
                         json.dumps(e.get("value", "")).encode())
    return b""


@register("timeindex", "list", CLS_METHOD_RD)
def list_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    lo = _key(float(q.get("from", 0)), "")
    # 'to' exclusive: the empty suffix sorts before any real entry at
    # that timestamp
    hi = _key(float(q["to"]), "") if q.get("to") else "\x7f"
    max_n = int(q.get("max", 1000))
    out, last = [], ""
    for k in hctx.map_get_keys(start_after=q.get("marker", ""),
                              max_return=1 << 62):
        if k < lo or k >= hi:
            continue
        if len(out) >= max_n:
            return json.dumps({"entries": out, "marker": last,
                               "truncated": True}).encode()
        ts_us, _, suffix = k.partition("_")
        out.append({"timestamp": int(ts_us) / 1e6,
                    "key_suffix": suffix,
                    "value": json.loads(hctx.map_get_val(k))})
        last = k
    return json.dumps({"entries": out, "marker": last,
                       "truncated": False}).encode()


@register("timeindex", "trim", CLS_METHOD_RD | CLS_METHOD_WR)
def trim_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    lo = q.get("from_marker") or _key(float(q.get("from", 0)), "")
    hi = q.get("to_marker") or (
        _key(float(q["to"]), "") if q.get("to") else "\x7f")
    n = 0
    for k in list(hctx.map_get_keys(max_return=1 << 62)):
        if lo <= k < hi:
            hctx.map_remove_key(k)
            n += 1
    if n == 0:
        raise ClsError("ENODATA", "nothing to trim")
    return b""
