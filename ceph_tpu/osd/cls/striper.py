"""cls_striper: atomic striped-object size bookkeeping.

libradosstriper keeps the logical size in an xattr on the first rados
object; concurrent writers from DIFFERENT clients both read-modify-
write it, so the update must happen atomically at the OSD -- size
only ever grows to the max seen (RadosStriperImpl's size xlock,
rendered as a server-side max instead of a client lock dance).
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

SIZE_XATTR = "striper.size"


@register("striper", "grow_size", CLS_METHOD_RD | CLS_METHOD_WR)
def grow_size_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    try:
        cur = int(hctx.getxattr(SIZE_XATTR))
    except ClsError:
        cur = 0
    new = max(cur, int(q["size"]))
    hctx.setxattr(SIZE_XATTR, str(new).encode())
    return str(new).encode()


@register("striper", "set_size", CLS_METHOD_RD | CLS_METHOD_WR)
def set_size_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    hctx.setxattr(SIZE_XATTR, str(int(q["size"])).encode())
    return b""
