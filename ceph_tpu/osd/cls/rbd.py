"""cls_rbd: RBD image header methods.

Mirrors src/cls/rbd/cls_rbd.cc: image metadata (size, order, features,
object_prefix), the snapshot table + snap context, parent/clone
linkage, and the rbd_directory / rbd_children registry objects.  All
state lives in the header object's omap, mutated server-side so
concurrent clients see atomic transitions (the reference's reason for
putting this in a class rather than client-side read-modify-write).

Encoding is JSON (this stack's wire idiom) rather than ceph denc.
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

# omap keys on the header object
K_META = "rbd_meta"                 # {size, order, object_prefix, features}
K_SNAPSEQ = "snap_seq"
K_SNAP = "snapshot_"                # snapshot_<id:016x> -> {name,size,protected}
K_PARENT = "parent"                 # {pool_id, image_id, snap_id, overlap}


def _meta(hctx) -> dict:
    try:
        return json.loads(hctx.map_get_val(K_META))
    except ClsError:
        raise ClsError("ENOENT", "not an rbd header")


def _snap_key(snap_id: int) -> str:
    return f"{K_SNAP}{int(snap_id):016x}"


def _snaps(hctx) -> list[tuple[int, dict]]:
    out = []
    for k, v in hctx.map_get_all().items():
        if k.startswith(K_SNAP):
            out.append((int(k[len(K_SNAP):], 16), json.loads(v)))
    return sorted(out)


@register("rbd", "create", CLS_METHOD_RD | CLS_METHOD_WR)
def create(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    if hctx.exists():
        raise ClsError("EEXIST")
    order = int(q.get("order", 22))
    if not 12 <= order <= 26:
        raise ClsError("EINVAL", f"order {order} out of range")
    hctx.create(exclusive=True)
    hctx.map_set_vals({
        K_META: json.dumps({
            "size": int(q["size"]), "order": order,
            "object_prefix": q["object_prefix"],
            "features": q.get("features", ["layering"]),
            "stripe_unit": int(q.get("stripe_unit", 1 << order)),
            "stripe_count": int(q.get("stripe_count", 1)),
        }).encode(),
        K_SNAPSEQ: b"0",
    })
    return b""


@register("rbd", "copyup", CLS_METHOD_RD | CLS_METHOD_WR)
def copyup_op(hctx, indata: bytes) -> bytes:
    """Materialize an object ONLY if it does not exist yet
    (cls_rbd copyup): the atomic exists-check-and-write that lets a
    migration/flatten copier race live client writes safely -- whoever
    creates the object first wins, the loser no-ops."""
    if hctx.exists():
        return b""
    if indata:
        hctx.write_full(bytes(indata))
    else:
        hctx.create(exclusive=False)
    return b""


@register("rbd", "get_image_meta", CLS_METHOD_RD)
def get_image_meta(hctx, indata: bytes) -> bytes:
    meta = _meta(hctx)
    meta["snap_seq"] = int(hctx.map_get_val(K_SNAPSEQ))
    meta["snapshots"] = [
        {"id": sid, **s} for sid, s in _snaps(hctx)]
    try:
        meta["parent"] = json.loads(hctx.map_get_val(K_PARENT))
    except ClsError:
        meta["parent"] = None
    return json.dumps(meta).encode()


@register("rbd", "set_size", CLS_METHOD_RD | CLS_METHOD_WR)
def set_size(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    meta = _meta(hctx)
    meta["size"] = int(q["size"])
    hctx.map_set_val(K_META, json.dumps(meta).encode())
    return b""


@register("rbd", "snapshot_add", CLS_METHOD_RD | CLS_METHOD_WR)
def snapshot_add(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    sid = int(q["snap_id"])
    meta = _meta(hctx)
    seq = int(hctx.map_get_val(K_SNAPSEQ))
    if sid <= seq:
        raise ClsError("ESTALE", "snap id not newer than snap_seq")
    for _, s in _snaps(hctx):
        if s["name"] == q["name"]:
            raise ClsError("EEXIST", q["name"])
    hctx.map_set_vals({
        _snap_key(sid): json.dumps({
            "name": q["name"], "size": meta["size"],
            "protected": False}).encode(),
        K_SNAPSEQ: str(sid).encode(),
    })
    return b""


@register("rbd", "snapshot_remove", CLS_METHOD_RD | CLS_METHOD_WR)
def snapshot_remove(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    sid = int(q["snap_id"])
    try:
        s = json.loads(hctx.map_get_val(_snap_key(sid)))
    except ClsError:
        raise ClsError("ENOENT", f"snap {sid}")
    if s.get("protected"):
        raise ClsError("EBUSY", "snap is protected")
    hctx.map_remove_key(_snap_key(sid))
    return b""


@register("rbd", "snapshot_protect", CLS_METHOD_RD | CLS_METHOD_WR)
def snapshot_protect(hctx, indata: bytes) -> bytes:
    return _set_protect(hctx, indata, True)


@register("rbd", "snapshot_unprotect", CLS_METHOD_RD | CLS_METHOD_WR)
def snapshot_unprotect(hctx, indata: bytes) -> bytes:
    return _set_protect(hctx, indata, False)


def _set_protect(hctx, indata: bytes, value: bool) -> bytes:
    q = json.loads(indata)
    key = _snap_key(int(q["snap_id"]))
    try:
        s = json.loads(hctx.map_get_val(key))
    except ClsError:
        raise ClsError("ENOENT")
    s["protected"] = value
    hctx.map_set_val(key, json.dumps(s).encode())
    return b""


@register("rbd", "get_snapcontext", CLS_METHOD_RD)
def get_snapcontext(hctx, indata: bytes) -> bytes:
    seq = int(hctx.map_get_val(K_SNAPSEQ))
    snaps = sorted((sid for sid, _ in _snaps(hctx)), reverse=True)
    return json.dumps({"seq": seq, "snaps": snaps}).encode()


@register("rbd", "set_parent", CLS_METHOD_RD | CLS_METHOD_WR)
def set_parent(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        hctx.map_get_val(K_PARENT)
        raise ClsError("EEXIST", "parent already set")
    except ClsError as e:
        if e.errno_name == "EEXIST":
            raise
    hctx.map_set_val(K_PARENT, json.dumps({
        "pool_id": int(q["pool_id"]), "image_id": q["image_id"],
        "snap_id": int(q["snap_id"]),
        "overlap": int(q["overlap"])}).encode())
    return b""


@register("rbd", "get_parent", CLS_METHOD_RD)
def get_parent(hctx, indata: bytes) -> bytes:
    try:
        return hctx.map_get_val(K_PARENT)
    except ClsError:
        return json.dumps(None).encode()


@register("rbd", "remove_parent", CLS_METHOD_RD | CLS_METHOD_WR)
def remove_parent(hctx, indata: bytes) -> bytes:
    try:
        hctx.map_get_val(K_PARENT)
    except ClsError:
        raise ClsError("ENOENT", "no parent")
    hctx.map_remove_key(K_PARENT)
    return b""


# -- rbd_directory (name <-> id registry object) ----------------------------

@register("rbd", "dir_add_image", CLS_METHOD_RD | CLS_METHOD_WR)
def dir_add_image(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    name, iid = q["name"], q["id"]
    if f"name_{name}" in hctx.map_get_all():
        raise ClsError("EEXIST", name)
    hctx.map_set_vals({f"name_{name}": iid.encode(),
                       f"id_{iid}": name.encode()})
    return b""


@register("rbd", "dir_remove_image", CLS_METHOD_RD | CLS_METHOD_WR)
def dir_remove_image(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    name = q["name"]
    try:
        iid = hctx.map_get_val(f"name_{name}").decode()
    except ClsError:
        raise ClsError("ENOENT", name)
    hctx.map_remove_key(f"name_{name}")
    hctx.map_remove_key(f"id_{iid}")
    return b""


@register("rbd", "dir_get_id", CLS_METHOD_RD)
def dir_get_id(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        return hctx.map_get_val(f"name_{q['name']}")
    except ClsError:
        raise ClsError("ENOENT", q["name"])


@register("rbd", "dir_list", CLS_METHOD_RD)
def dir_list(hctx, indata: bytes) -> bytes:
    if not hctx.exists():
        return json.dumps({}).encode()
    out = {k[5:]: v.decode() for k, v in hctx.map_get_all().items()
           if k.startswith("name_")}
    return json.dumps(out).encode()


# -- rbd_children (parent (pool,image,snap) -> child ids) -------------------

def _child_key(q: dict) -> str:
    return (f"{int(q['pool_id'])}_{q['image_id']}_"
            f"{int(q['snap_id']):016x}")


@register("rbd", "add_child", CLS_METHOD_RD | CLS_METHOD_WR)
def add_child(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    key = _child_key(q)
    try:
        kids = json.loads(hctx.map_get_val(key))
    except ClsError:
        kids = []
    if q["child_id"] not in kids:
        kids.append(q["child_id"])
    if not hctx.exists():
        hctx.create(exclusive=False)
    hctx.map_set_val(key, json.dumps(kids).encode())
    return b""


@register("rbd", "remove_child", CLS_METHOD_RD | CLS_METHOD_WR)
def remove_child(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    key = _child_key(q)
    try:
        kids = json.loads(hctx.map_get_val(key))
    except ClsError:
        raise ClsError("ENOENT")
    if q["child_id"] in kids:
        kids.remove(q["child_id"])
    if kids:
        hctx.map_set_val(key, json.dumps(kids).encode())
    else:
        hctx.map_remove_key(key)
    return b""


@register("rbd", "list_children", CLS_METHOD_RD)
def list_children(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    if not hctx.exists():
        return json.dumps([]).encode()
    try:
        return hctx.map_get_val(_child_key(q))
    except ClsError:
        return json.dumps([]).encode()
