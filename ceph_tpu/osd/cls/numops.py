"""cls_numops: atomic arithmetic on omap values.

src/cls/numops/cls_numops.cc: add/sub/mul/div a decimal value stored
under an omap key, atomically at the OSD -- the read-modify-write no
client-side sequence can make race-free.
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register


def _cur(hctx, key: str) -> float:
    try:
        raw = hctx.map_get_val(key)
    except ClsError:
        return 0.0
    try:
        return float(raw.decode())
    except ValueError as e:
        raise ClsError("EBADMSG", f"non-numeric value under {key}") \
            from e


def _store(hctx, key: str, v: float) -> bytes:
    out = repr(int(v)) if float(v).is_integer() else repr(v)
    hctx.map_set_val(key, out.encode())
    return out.encode()


@register("numops", "add", CLS_METHOD_RD | CLS_METHOD_WR)
def add_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    return _store(hctx, q["key"], _cur(hctx, q["key"])
                  + float(q["value"]))


@register("numops", "sub", CLS_METHOD_RD | CLS_METHOD_WR)
def sub_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    return _store(hctx, q["key"], _cur(hctx, q["key"])
                  - float(q["value"]))


@register("numops", "mul", CLS_METHOD_RD | CLS_METHOD_WR)
def mul_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    return _store(hctx, q["key"], _cur(hctx, q["key"])
                  * float(q["value"]))


@register("numops", "div", CLS_METHOD_RD | CLS_METHOD_WR)
def div_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    d = float(q["value"])
    if d == 0:
        raise ClsError("EINVAL", "division by zero")
    return _store(hctx, q["key"], _cur(hctx, q["key"]) / d)
