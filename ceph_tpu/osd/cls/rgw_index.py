"""cls_rgw (bucket index subset): atomic bucket-index maintenance.

Mirrors the src/cls/rgw/cls_rgw.cc bucket-index ops the gateway's
write path uses: ``prepare`` marks an in-flight op on the key,
``complete`` commits the entry (or removes it for a delete) and drops
the pending marker, ``unlink`` removes an entry, ``list`` pages
entries.  Index entries live in the bucket index object's omap keyed
by object name, so concurrent gateway instances get atomic
read-modify-write through the OSD rather than racing client-side
(the reason the reference keeps the index in a class).
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

_ENTRY = "idx_"          # idx_<object key> -> entry json (current)
_PENDING = "pend_"       # pend_<tag> -> {key, op}
# versioned buckets (cls_rgw's bucket index versioning ops): every
# version of a key lives at vidx_<key>\x00<inverted stamp> so the
# omap's name order lists versions newest-first per key; the idx_
# entry stays the CURRENT pointer (possibly a delete marker)
_VENTRY = "vidx_"


def _vkey(key: str, version_id: str) -> str:
    return f"{_VENTRY}{key}\x00{version_id}"


@register("rgw_index", "prepare", CLS_METHOD_RD | CLS_METHOD_WR)
def prepare(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    if not hctx.exists():
        hctx.create(exclusive=False)
    hctx.map_set_val(_PENDING + q["tag"], json.dumps(
        {"key": q["key"], "op": q.get("op", "put")}).encode())
    return b""


@register("rgw_index", "complete", CLS_METHOD_RD | CLS_METHOD_WR)
def complete(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    tag = q.get("tag")
    if tag is not None:
        try:
            hctx.map_get_val(_PENDING + tag)
            hctx.map_remove_key(_PENDING + tag)
        except ClsError:
            raise ClsError("ECANCELED", "no pending op for tag")
    # the REPLACED entry is returned so the gateway can reclaim its
    # backing data: purging by a client-side pre-read races a
    # concurrent PUT (two writers each pre-read the same old entry and
    # the losing generation's data leaks); the swap must be decided by
    # the atomic op itself (cls_rgw.cc returns the existing dir entry
    # to the completing gateway for the same reason)
    try:
        replaced = hctx.map_get_val(_ENTRY + q["key"])
    except ClsError:
        replaced = b""
    if q.get("op") == "del":
        if not replaced:
            raise ClsError("ENOENT", q["key"])
        hctx.map_remove_key(_ENTRY + q["key"])
    else:
        hctx.map_set_val(_ENTRY + q["key"],
                         json.dumps(q["entry"]).encode())
    return replaced


@register("rgw_index", "unlink", CLS_METHOD_RD | CLS_METHOD_WR)
def unlink(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        removed = hctx.map_get_val(_ENTRY + q["key"])
    except ClsError:
        raise ClsError("ENOENT", q["key"])
    hctx.map_remove_key(_ENTRY + q["key"])
    return removed          # caller reclaims exactly what was unlinked


@register("rgw_index", "get", CLS_METHOD_RD)
def get(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        return hctx.map_get_val(_ENTRY + q["key"])
    except ClsError:
        raise ClsError("ENOENT", q["key"])


@register("rgw_index", "list", CLS_METHOD_RD)
def list_entries(hctx, indata: bytes) -> bytes:
    """Paged listing: {prefix, marker, max} ->
    {entries: [[key, entry], ...], truncated}."""
    q = json.loads(indata or b"{}")
    prefix = q.get("prefix", "")
    marker = q.get("marker", "")
    limit = int(q.get("max", 1000))
    if not hctx.exists():
        return json.dumps({"entries": [], "truncated": False}).encode()
    all_kv = hctx.map_get_all()
    entries = []
    truncated = False
    for k in sorted(all_kv):
        if not k.startswith(_ENTRY):
            continue
        name = k[len(_ENTRY):]
        if not name.startswith(prefix) or name <= marker:
            continue
        e = json.loads(all_kv[k])
        if e.get("delete_marker"):
            continue
        if len(entries) >= limit:
            truncated = True          # one survivor past the page
            break
        entries.append([name, e])
    return json.dumps({"entries": entries,
                       "truncated": truncated}).encode()


@register("rgw_index", "dir_link", CLS_METHOD_RD | CLS_METHOD_WR)
def dir_link(hctx, indata: bytes) -> bytes:
    """Atomic registry insert (bucket directory): fails EEXIST unless
    the existing value's owner matches (idempotent re-create).  The
    check and the write commit in one op -- client-side
    read-modify-write would let two gateways each claim the name."""
    q = json.loads(indata)
    if not hctx.exists():
        hctx.create(exclusive=False)
    try:
        cur = json.loads(hctx.map_get_val("dir_" + q["name"]))
        if cur.get("owner") != q["meta"].get("owner"):
            raise ClsError("EEXIST", q["name"])
        return json.dumps(cur).encode()
    except ClsError as e:
        if e.errno_name == "EEXIST":
            raise
    hctx.map_set_val("dir_" + q["name"],
                     json.dumps(q["meta"]).encode())
    return json.dumps(q["meta"]).encode()


@register("rgw_index", "dir_unlink", CLS_METHOD_RD | CLS_METHOD_WR)
def dir_unlink(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        hctx.map_get_val("dir_" + q["name"])
    except ClsError:
        raise ClsError("ENOENT", q["name"])
    hctx.map_remove_key("dir_" + q["name"])
    return b""


@register("rgw_index", "dir_list", CLS_METHOD_RD)
def rgw_dir_list(hctx, indata: bytes) -> bytes:
    if not hctx.exists():
        return json.dumps({}).encode()
    out = {k[4:]: json.loads(v) for k, v in hctx.map_get_all().items()
           if k.startswith("dir_")}
    return json.dumps(out).encode()


@register("rgw_index", "stats", CLS_METHOD_RD)
def stats(hctx, indata: bytes) -> bytes:
    if not hctx.exists():
        return json.dumps({"count": 0, "bytes": 0}).encode()
    count = tot = 0
    for k, v in hctx.map_get_all().items():
        if k.startswith(_ENTRY):
            e = json.loads(v)
            if e.get("delete_marker"):
                continue
            count += 1
            tot += e.get("size", 0)
    return json.dumps({"count": count, "bytes": tot}).encode()


@register("rgw_index", "version_put", CLS_METHOD_RD | CLS_METHOD_WR)
def version_put(hctx, indata: bytes) -> bytes:
    """Link a NEW version of a key atomically: store the version
    entry, flip the current pointer.  versioning=suspended reuses the
    "null" version id and DISPLACES the previous null version (its
    entry is returned for data reclaim, as `complete` does); enabled
    displaces nothing (old versions stay readable)."""
    q = json.loads(indata)
    key = q["key"]
    entry = q["entry"]
    displaced = b""
    try:
        cur_raw = hctx.map_get_val(_ENTRY + key)
        cur = json.loads(cur_raw)
    except ClsError:
        cur_raw, cur = b"", None
    unversioned_cur = cur is not None and "version_id" not in cur
    if q.get("suspended"):
        entry["version_id"] = "null"
        try:
            displaced = hctx.map_get_val(_vkey(key, "null"))
        except ClsError:
            # only a true UNVERSIONED-era entry is displaced; an
            # enabled-era version must stay readable (its vidx_ row
            # still references the data)
            displaced = cur_raw if unversioned_cur else b""
    elif unversioned_cur:
        # enabling versioning over an unversioned object: S3 preserves
        # it as the "null" version, not as silent loss
        cur["version_id"] = "null"
        hctx.map_set_val(_vkey(key, "null"),
                         json.dumps(cur).encode())
    blob = json.dumps(entry).encode()
    hctx.map_set_val(_vkey(key, entry["version_id"]), blob)
    hctx.map_set_val(_ENTRY + key, blob)
    return displaced


@register("rgw_index", "version_rm", CLS_METHOD_RD | CLS_METHOD_WR)
def version_rm(hctx, indata: bytes) -> bytes:
    """Remove ONE version permanently; if it was the current pointer,
    the next-newest surviving version becomes current (or the key
    vanishes).  Returns the removed entry for data reclaim."""
    q = json.loads(indata)
    key, vid = q["key"], q["version_id"]
    try:
        removed = hctx.map_get_val(_vkey(key, vid))
    except ClsError:
        raise ClsError("ENOENT", f"{key}?versionId={vid}")
    hctx.map_remove_key(_vkey(key, vid))
    try:
        cur = json.loads(hctx.map_get_val(_ENTRY + key))
    except ClsError:
        cur = None
    if cur is not None and cur.get("version_id") == vid:
        pre = _VENTRY + key + "\x00"
        all_kv = hctx.map_get_all()
        survivors = [json.loads(v) for k, v in all_kv.items()
                     if k.startswith(pre)]
        if survivors:
            # next-newest survivor: mtime first (second granularity),
            # then the stamp INSIDE the version id (ids are inverted
            # ns stamps, so plain lexicographic order would resurrect
            # the OLDEST version); "null" ids sort oldest among ties
            def recency(e):
                vid = e.get("version_id", "")
                try:
                    ns = (1 << 64) - int(vid[:16], 16)
                except ValueError:
                    ns = -1
                return (e.get("mtime", ""), ns)
            best = max(survivors, key=recency)
            hctx.map_set_val(_ENTRY + key, json.dumps(best).encode())
        else:
            hctx.map_remove_key(_ENTRY + key)
    return removed


@register("rgw_index", "version_list", CLS_METHOD_RD)
def version_list(hctx, indata: bytes) -> bytes:
    """Paged listing of versions: {prefix, marker, max} ->
    {versions: [[key, version_id, entry, is_latest]...], truncated}."""
    q = json.loads(indata or b"{}")
    prefix = q.get("prefix", "")
    marker = q.get("marker", "")
    limit = int(q.get("max", 1000))
    if not hctx.exists():
        return json.dumps({"versions": [], "truncated": False}).encode()
    all_kv = hctx.map_get_all()
    currents = {}
    for k, v in all_kv.items():
        if k.startswith(_ENTRY):
            currents[k[len(_ENTRY):]] = json.loads(v).get("version_id")
    page = []
    truncated = False
    for k in sorted(all_kv):
        if not k.startswith(_VENTRY):
            continue
        name, _, vid = k[len(_VENTRY):].partition("\x00")
        if not name.startswith(prefix) or k[len(_VENTRY):] <= marker:
            continue
        if len(page) >= limit:
            truncated = True
            break
        entry = json.loads(all_kv[k])
        page.append([name, vid, entry, currents.get(name) == vid])
    return json.dumps({"versions": page,
                       "truncated": truncated,
                       "next_marker": (f"{page[-1][0]}\x00{page[-1][1]}"
                                       if page else "")}).encode()


@register("rgw_index", "get_version", CLS_METHOD_RD)
def get_version(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        return hctx.map_get_val(_vkey(q["key"], q["version_id"]))
    except ClsError:
        raise ClsError("ENOENT", q["key"])


@register("rgw_index", "dir_set", CLS_METHOD_RD | CLS_METHOD_WR)
def dir_set(hctx, indata: bytes) -> bytes:
    """Merge fields into a directory entry's meta atomically (bucket
    versioning state, lifecycle config)."""
    q = json.loads(indata)
    try:
        cur = json.loads(hctx.map_get_val("dir_" + q["name"]))
    except ClsError:
        raise ClsError("ENOENT", q["name"])
    cur.update(q["patch"])
    hctx.map_set_val("dir_" + q["name"], json.dumps(cur).encode())
    return json.dumps(cur).encode()
