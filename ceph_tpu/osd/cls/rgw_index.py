"""cls_rgw (bucket index subset): atomic bucket-index maintenance.

Mirrors the src/cls/rgw/cls_rgw.cc bucket-index ops the gateway's
write path uses: ``prepare`` marks an in-flight op on the key,
``complete`` commits the entry (or removes it for a delete) and drops
the pending marker, ``unlink`` removes an entry, ``list`` pages
entries.  Index entries live in the bucket index object's omap keyed
by object name, so concurrent gateway instances get atomic
read-modify-write through the OSD rather than racing client-side
(the reason the reference keeps the index in a class).
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

_ENTRY = "idx_"          # idx_<object key> -> entry json
_PENDING = "pend_"       # pend_<tag> -> {key, op}


@register("rgw_index", "prepare", CLS_METHOD_RD | CLS_METHOD_WR)
def prepare(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    if not hctx.exists():
        hctx.create(exclusive=False)
    hctx.map_set_val(_PENDING + q["tag"], json.dumps(
        {"key": q["key"], "op": q.get("op", "put")}).encode())
    return b""


@register("rgw_index", "complete", CLS_METHOD_RD | CLS_METHOD_WR)
def complete(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    tag = q.get("tag")
    if tag is not None:
        try:
            hctx.map_get_val(_PENDING + tag)
            hctx.map_remove_key(_PENDING + tag)
        except ClsError:
            raise ClsError("ECANCELED", "no pending op for tag")
    # the REPLACED entry is returned so the gateway can reclaim its
    # backing data: purging by a client-side pre-read races a
    # concurrent PUT (two writers each pre-read the same old entry and
    # the losing generation's data leaks); the swap must be decided by
    # the atomic op itself (cls_rgw.cc returns the existing dir entry
    # to the completing gateway for the same reason)
    try:
        replaced = hctx.map_get_val(_ENTRY + q["key"])
    except ClsError:
        replaced = b""
    if q.get("op") == "del":
        if not replaced:
            raise ClsError("ENOENT", q["key"])
        hctx.map_remove_key(_ENTRY + q["key"])
    else:
        hctx.map_set_val(_ENTRY + q["key"],
                         json.dumps(q["entry"]).encode())
    return replaced


@register("rgw_index", "unlink", CLS_METHOD_RD | CLS_METHOD_WR)
def unlink(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        removed = hctx.map_get_val(_ENTRY + q["key"])
    except ClsError:
        raise ClsError("ENOENT", q["key"])
    hctx.map_remove_key(_ENTRY + q["key"])
    return removed          # caller reclaims exactly what was unlinked


@register("rgw_index", "get", CLS_METHOD_RD)
def get(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        return hctx.map_get_val(_ENTRY + q["key"])
    except ClsError:
        raise ClsError("ENOENT", q["key"])


@register("rgw_index", "list", CLS_METHOD_RD)
def list_entries(hctx, indata: bytes) -> bytes:
    """Paged listing: {prefix, marker, max} ->
    {entries: [[key, entry], ...], truncated}."""
    q = json.loads(indata or b"{}")
    prefix = q.get("prefix", "")
    marker = q.get("marker", "")
    limit = int(q.get("max", 1000))
    if not hctx.exists():
        return json.dumps({"entries": [], "truncated": False}).encode()
    all_kv = hctx.map_get_all()
    keys = sorted(k[len(_ENTRY):] for k in all_kv
                  if k.startswith(_ENTRY))
    keys = [k for k in keys if k.startswith(prefix) and k > marker]
    page = keys[:limit]
    entries = [[k, json.loads(all_kv[_ENTRY + k])] for k in page]
    return json.dumps({"entries": entries,
                       "truncated": len(keys) > limit}).encode()


@register("rgw_index", "dir_link", CLS_METHOD_RD | CLS_METHOD_WR)
def dir_link(hctx, indata: bytes) -> bytes:
    """Atomic registry insert (bucket directory): fails EEXIST unless
    the existing value's owner matches (idempotent re-create).  The
    check and the write commit in one op -- client-side
    read-modify-write would let two gateways each claim the name."""
    q = json.loads(indata)
    if not hctx.exists():
        hctx.create(exclusive=False)
    try:
        cur = json.loads(hctx.map_get_val("dir_" + q["name"]))
        if cur.get("owner") != q["meta"].get("owner"):
            raise ClsError("EEXIST", q["name"])
        return json.dumps(cur).encode()
    except ClsError as e:
        if e.errno_name == "EEXIST":
            raise
    hctx.map_set_val("dir_" + q["name"],
                     json.dumps(q["meta"]).encode())
    return json.dumps(q["meta"]).encode()


@register("rgw_index", "dir_unlink", CLS_METHOD_RD | CLS_METHOD_WR)
def dir_unlink(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    try:
        hctx.map_get_val("dir_" + q["name"])
    except ClsError:
        raise ClsError("ENOENT", q["name"])
    hctx.map_remove_key("dir_" + q["name"])
    return b""


@register("rgw_index", "dir_list", CLS_METHOD_RD)
def rgw_dir_list(hctx, indata: bytes) -> bytes:
    if not hctx.exists():
        return json.dumps({}).encode()
    out = {k[4:]: json.loads(v) for k, v in hctx.map_get_all().items()
           if k.startswith("dir_")}
    return json.dumps(out).encode()


@register("rgw_index", "stats", CLS_METHOD_RD)
def stats(hctx, indata: bytes) -> bytes:
    if not hctx.exists():
        return json.dumps({"count": 0, "bytes": 0}).encode()
    count = tot = 0
    for k, v in hctx.map_get_all().items():
        if k.startswith(_ENTRY):
            count += 1
            tot += json.loads(v).get("size", 0)
    return json.dumps({"count": count, "bytes": tot}).encode()
