"""cls_journal: atomic append-only journal bookkeeping.

The src/cls/journal/cls_journal.cc subset librbd journaling needs:
sequence allocation + entry append commit atomically in the OSD
(two writers cannot claim one sequence), registered CLIENTS record
their replay positions, and trim may only reclaim entries every
client has consumed.  Entries live in the journal object's omap as
``entry.<seq>`` (zero-padded so omap name order is replay order);
clients as ``client.<id>`` -> {"position": seq}.
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

_SEQ = "seq"                   # next sequence number
_ENTRY = "entry."
_CLIENT = "client."


def _ekey(seq: int) -> str:
    return f"{_ENTRY}{seq:016d}"


@register("journal", "append", CLS_METHOD_RD | CLS_METHOD_WR)
def append(hctx, indata: bytes) -> bytes:
    """Allocate the next sequence and store the entry in ONE op.
    indata: raw entry payload.  Returns the allocated seq as text."""
    if not hctx.exists():
        hctx.create(exclusive=False)
    try:
        seq = int(hctx.map_get_val(_SEQ))
    except ClsError:
        seq = 0
    hctx.map_set_val(_ekey(seq), indata)
    hctx.map_set_val(_SEQ, str(seq + 1).encode())
    return str(seq).encode()


@register("journal", "get_entries", CLS_METHOD_RD)
def get_entries(hctx, indata: bytes) -> bytes:
    """{after, max} -> {"entries": [[seq, hex-payload]...]}."""
    q = json.loads(indata or b"{}")
    after = int(q.get("after", -1))
    limit = int(q.get("max", 64))
    if not hctx.exists():
        return json.dumps({"entries": []}).encode()
    out = []
    start = _ekey(after) if after >= 0 else _ENTRY
    for k in hctx.map_get_keys(start_after=start, max_return=10000):
        if not k.startswith(_ENTRY):
            continue
        out.append([int(k[len(_ENTRY):]),
                    hctx.map_get_val(k).hex()])
        if len(out) >= limit:
            break
    return json.dumps({"entries": out}).encode()


@register("journal", "client_register", CLS_METHOD_RD | CLS_METHOD_WR)
def client_register(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    if not hctx.exists():
        hctx.create(exclusive=False)
    key = _CLIENT + q["id"]
    try:
        return hctx.map_get_val(key)      # idempotent re-register
    except ClsError:
        pass
    state = {"id": q["id"], "position": int(q.get("position", -1))}
    hctx.map_set_val(key, json.dumps(state).encode())
    return json.dumps(state).encode()


@register("journal", "client_commit", CLS_METHOD_RD | CLS_METHOD_WR)
def client_commit(hctx, indata: bytes) -> bytes:
    """Advance a client's replay position (monotone)."""
    q = json.loads(indata)
    key = _CLIENT + q["id"]
    try:
        state = json.loads(hctx.map_get_val(key))
    except ClsError:
        raise ClsError("ENOENT", q["id"])
    state["position"] = max(state["position"], int(q["position"]))
    hctx.map_set_val(key, json.dumps(state).encode())
    return json.dumps(state).encode()


@register("journal", "client_list", CLS_METHOD_RD)
def client_list(hctx, indata: bytes) -> bytes:
    if not hctx.exists():
        return json.dumps([]).encode()
    out = [json.loads(v) for k, v in hctx.map_get_all().items()
           if k.startswith(_CLIENT)]
    return json.dumps(out).encode()


@register("journal", "client_unregister", CLS_METHOD_RD | CLS_METHOD_WR)
def client_unregister(hctx, indata: bytes) -> bytes:
    q = json.loads(indata)
    hctx.map_remove_key(_CLIENT + q["id"])
    return b""


@register("journal", "trim", CLS_METHOD_RD | CLS_METHOD_WR)
def trim(hctx, indata: bytes) -> bytes:
    """Reclaim entries every registered client has consumed.  With no
    clients nothing trims (an unwatched journal keeps history until a
    client registers or the feature is disabled)."""
    if not hctx.exists():
        return b"0"
    clients = [json.loads(hctx.map_get_val(k))
               for k in hctx.map_get_keys(start_after=_CLIENT[:-1],
                                          max_return=10000)
               if k.startswith(_CLIENT)]
    if not clients:
        return b"0"
    floor = min(c["position"] for c in clients)
    n = 0
    for k in hctx.map_get_keys(start_after=_ENTRY[:-1],
                               max_return=100000):
        if k.startswith(_ENTRY) and int(k[len(_ENTRY):]) <= floor:
            hctx.map_remove_key(k)
            n += 1
    return str(n).encode()


@register("journal", "get_seq", CLS_METHOD_RD)
def get_seq(hctx, indata: bytes) -> bytes:
    """Next sequence to be allocated (head = this - 1); payload-free."""
    if not hctx.exists():
        return b"0"
    try:
        return hctx.map_get_val(_SEQ)
    except ClsError:
        return b"0"
