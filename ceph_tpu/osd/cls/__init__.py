"""Object classes (cls): server-side methods executed inside the OSD.

The reference loads classes as shared objects (ClassHandler::open_class,
src/osd/ClassHandler.cc:171) and runs their methods inside the PG op
vector via the CEPH_OSD_OP_CALL op (PrimaryLogPG::do_osd_ops "call"
case); methods mutate the object through the objclass API
(src/objclass/class_api.cc: cls_cxx_read/write/getxattr/map_set_val...)
so their effects commit atomically with the surrounding ops.

Here classes are python modules registered at import time (the dlopen
analog -- `ceph_tpu.osd.cls.<name>` imports on first use) and methods
run against the PG's pending-write overlay: reads observe earlier ops
in the vector, writes append resolved logical mutations to the same
transaction the rest of the vector commits in.

Method contract: ``fn(hctx, indata: bytes) -> bytes | None``; raise
ClsError("ENOENT"/...) to fail the op (which aborts the whole write
vector, as a negative cls return does in the reference).
"""

from __future__ import annotations

import importlib
import time

CLS_METHOD_RD = 1
CLS_METHOD_WR = 2

_REGISTRY: dict[str, dict[str, tuple[int, object]]] = {}

# in-tree modules, loaded on first call (dlopen-on-demand analog)
_KNOWN = ("lock", "refcount", "version", "rbd", "rgw_index",
          "journal", "numops", "log", "timeindex", "user", "queue",
          "striper")


class ClsError(Exception):
    def __init__(self, errno_name: str, detail: str = "") -> None:
        super().__init__(f"{errno_name}{': ' + detail if detail else ''}")
        self.errno_name = errno_name
        self.detail = detail


def register(cls_name: str, method: str, flags: int):
    """Decorator: register ``fn`` as ``<cls>.<method>`` (cls_register_cxx_method)."""
    def deco(fn):
        _REGISTRY.setdefault(cls_name, {})[method] = (flags, fn)
        return fn
    return deco


def _load(cls_name: str) -> dict[str, tuple[int, object]]:
    if cls_name not in _REGISTRY and cls_name in _KNOWN:
        importlib.import_module(f"{__name__}.{cls_name}")
    if cls_name not in _REGISTRY:
        raise ClsError("EOPNOTSUPP", f"no such class {cls_name}")
    return _REGISTRY[cls_name]


class HCtx:
    """The objclass handle passed to methods (cls_method_context_t).

    Backed by the PG's pending-write overlay dict; every write both
    lands in ``sink`` (logical ops later resolved into the op vector's
    transaction) and is applied to the overlay so later reads -- by
    this method, later methods, or later ops in the vector -- see it.
    """

    def __init__(self, pg, oid: str, overlay: dict, sink: list[dict],
                 entity: str, writable: bool) -> None:
        self._pg = pg
        self.oid = oid
        self._ov = overlay
        self._sink = sink
        self.entity = entity
        self._writable = writable

    # -- helpers ------------------------------------------------------------
    def _emit(self, op: dict) -> None:
        if not self._writable:
            raise ClsError("EPERM", "write from RD-only method/context")
        self._sink.append(op)
        self._pg._apply_overlay(self._ov, [op])

    def exists(self) -> bool:
        return bool(self._ov["exists"])

    # -- data ---------------------------------------------------------------
    def read(self, off: int = 0, length: int | None = None) -> bytes:
        if not self._ov["exists"]:
            raise ClsError("ENOENT")
        d = self._ov["data"]
        return bytes(d[off:] if length is None else d[off:off + length])

    def stat(self) -> int:
        if not self._ov["exists"]:
            raise ClsError("ENOENT")
        return len(self._ov["data"])

    def create(self, exclusive: bool = True) -> None:
        if exclusive and self._ov["exists"]:
            raise ClsError("EEXIST")
        self._emit({"op": "create"})

    def write(self, off: int, data: bytes) -> None:
        self._emit({"op": "write", "off": int(off), "data": bytes(data)})

    def write_full(self, data: bytes) -> None:
        self._emit({"op": "writefull", "data": bytes(data)})

    def truncate(self, size: int) -> None:
        self._emit({"op": "truncate", "size": int(size)})

    def remove(self) -> None:
        if not self._ov["exists"]:
            raise ClsError("ENOENT")
        self._emit({"op": "remove"})

    # -- xattrs -------------------------------------------------------------
    def getxattr(self, name: str) -> bytes:
        v = self._ov["xattrs"].get(name)
        if v is None:
            raise ClsError("ENODATA", name)
        return bytes(v)

    def setxattr(self, name: str, value: bytes) -> None:
        self._emit({"op": "setxattr", "name": name, "value": bytes(value)})

    def rmxattr(self, name: str) -> None:
        self._emit({"op": "rmxattr", "name": name})

    # -- omap ---------------------------------------------------------------
    def map_get_val(self, key: str) -> bytes:
        v = self._ov["omap"].get(key)
        if v is None:
            raise ClsError("ENOENT", key)
        return bytes(v)

    def map_get_all(self) -> dict[str, bytes]:
        return {k: bytes(v) for k, v in self._ov["omap"].items()}

    def map_get_keys(self, start_after: str = "",
                     max_return: int = 1000) -> list[str]:
        return sorted(k for k in self._ov["omap"]
                      if k > start_after)[:max_return]

    def map_set_val(self, key: str, value: bytes) -> None:
        self.map_set_vals({key: value})

    def map_set_vals(self, kv: dict[str, bytes]) -> None:
        self._emit({"op": "omap_set",
                    "kv": {k: bytes(v) for k, v in kv.items()}})

    def map_remove_key(self, key: str) -> None:
        self._emit({"op": "omap_rm", "keys": [key]})

    def map_clear(self) -> None:
        self._emit({"op": "omap_clear"})

    # -- misc ---------------------------------------------------------------
    def current_time(self) -> float:
        return time.time()

    def gen_snap_id(self):
        """Pool-unique monotonically increasing id (cls_rbd snap ids
        come from the mon in the reference; here the PG primary's mon
        channel is not reachable from cls context, so rbd allocates
        snap ids client-side via selfmanaged snaps)."""
        raise ClsError("EOPNOTSUPP")


def call(pg, oid: str, overlay: dict, sink: list[dict], entity: str,
         cls_name: str, method: str, indata: bytes,
         read_only_ctx: bool = False) -> bytes:
    """Execute ``<cls>.<method>``; returns the method's output bytes.

    Raises ClsError on failure (caller aborts the op vector)."""
    methods = _load(cls_name)
    if method not in methods:
        raise ClsError("EOPNOTSUPP", f"{cls_name}.{method}")
    flags, fn = methods[method]
    writable = bool(flags & CLS_METHOD_WR) and not read_only_ctx
    if read_only_ctx and (flags & CLS_METHOD_WR):
        raise ClsError("EROFS", f"{cls_name}.{method} on snap read")
    hctx = HCtx(pg, oid, overlay, sink, entity, writable)
    out = fn(hctx, bytes(indata))
    return b"" if out is None else bytes(out)
