"""cls_refcount: tag-based object refcounting.

Mirrors src/cls/refcount/cls_refcount.cc: a set of string tags lives
in xattr "refcount"; ``put`` on the last tag removes the object
(RGW uses this to share tail objects between copies).
"""

from __future__ import annotations

import json

from . import CLS_METHOD_RD, CLS_METHOD_WR, ClsError, register

_ATTR = "refcount"


def _load(hctx) -> list[str]:
    try:
        return json.loads(hctx.getxattr(_ATTR))
    except ClsError:
        return []


@register("refcount", "get", CLS_METHOD_RD | CLS_METHOD_WR)
def get_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    refs = _load(hctx)
    refs.append(q["tag"])
    hctx.setxattr(_ATTR, json.dumps(refs).encode())
    return b""


@register("refcount", "put", CLS_METHOD_RD | CLS_METHOD_WR)
def put_op(hctx, indata: bytes) -> bytes:
    q = json.loads(indata or b"{}")
    refs = _load(hctx)
    if not refs:
        # implicit ref: an object without the attr has one unnamed ref
        # (cls_refcount wildcard semantics); putting it removes it
        hctx.remove()
        return b""
    if q["tag"] not in refs:
        raise ClsError("ENOENT", q["tag"])
    refs.remove(q["tag"])
    if refs:
        hctx.setxattr(_ATTR, json.dumps(refs).encode())
    else:
        hctx.remove()
    return b""


@register("refcount", "list", CLS_METHOD_RD)
def list_op(hctx, indata: bytes) -> bytes:
    return json.dumps(_load(hctx)).encode()
