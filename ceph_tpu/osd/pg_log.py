"""Per-PG op log: the substrate of log-based recovery.

Mirrors the semantics of src/osd/PGLog.h: an ordered list of LogEntry
bounded by (tail, head]; merge_log (:1247) folds an authoritative log
into ours, rewinding divergent local entries (:1241) and populating the
missing set; proc_replica_log (:933) computes what a replica is missing
from its own log vs the authoritative one.
"""

from __future__ import annotations

from .types import (
    EVersion, LogEntry, MissingSet, PGInfo, ZERO, DELETE,
)


class PGLog:
    def __init__(self, tail: EVersion = ZERO, head: EVersion = ZERO,
                 entries: list[LogEntry] | None = None) -> None:
        self.tail = tail
        self.head = head
        self.entries: list[LogEntry] = list(entries or [])

    # -- basic ops ----------------------------------------------------------
    def add(self, entry: LogEntry) -> None:
        assert entry.version > self.head, (entry.version, self.head)
        self.entries.append(entry)
        self.head = entry.version

    def trim(self, to: EVersion) -> None:
        """Drop entries ≤ `to` (they are durably applied everywhere)."""
        if to <= self.tail:
            return
        self.entries = [e for e in self.entries if e.version > to]
        self.tail = to
        if self.head < self.tail:
            self.head = self.tail

    def last_entry_of(self, oid: str) -> LogEntry | None:
        for e in reversed(self.entries):
            if e.oid == oid:
                return e
        return None

    def last_version_of(self, oid: str) -> EVersion | None:
        e = self.last_entry_of(oid)
        return None if e is None else e.version

    def objects(self) -> dict[str, LogEntry]:
        """oid -> newest entry touching it."""
        out: dict[str, LogEntry] = {}
        for e in self.entries:
            out[e.oid] = e
        return out

    def entries_after(self, v: EVersion) -> list[LogEntry]:
        return [e for e in self.entries if e.version > v]

    # -- merge machinery ----------------------------------------------------
    def rewind_divergent(self, newhead: EVersion,
                         missing: MissingSet) -> list[LogEntry]:
        """Throw away local entries > newhead (they never committed
        cluster-wide).  Objects they touched must be restored to their
        authoritative version — record them missing at prior_version.

        PGLog.h:1241 rewind_divergent_log.
        """
        divergent = [e for e in self.entries if e.version > newhead]
        self.entries = [e for e in self.entries if e.version <= newhead]
        self.head = newhead
        # oldest divergent entry per object tells us the version the
        # object must return to
        first_div: dict[str, LogEntry] = {}
        for e in divergent:
            first_div.setdefault(e.oid, e)
        for oid, e in first_div.items():
            if e.prior_version:
                # restore to the pre-divergence version (even if that
                # version predates our log tail — recovery pulls the
                # authoritative copy from a peer either way)
                missing.add(oid, need=e.prior_version, have=ZERO)
            else:
                # object was created by a divergent entry: simply gone
                missing.items.pop(oid, None)
        return divergent

    def _last_common(self, auth_entries: list[LogEntry],
                     auth_tail: EVersion) -> EVersion:
        """Newest local version the authoritative log agrees with.

        Local entries older than the auth tail were trimmed there and
        count as agreed; anything after the returned version that the
        auth log lacks is divergent (merge_log's splice-point scan).
        """
        auth_versions = {e.version for e in auth_entries}
        for e in reversed(self.entries):
            if e.version in auth_versions or e.version <= auth_tail:
                return e.version
        return self.tail

    def overlaps(self, auth_info: PGInfo) -> bool:
        """Can log-based recovery bridge us to this authoritative log?

        False when our head predates the auth log's tail: entries in
        the gap were trimmed there, so objects whose last modification
        fell inside it would silently stay stale.  The caller must fall
        back to whole-PG backfill (reference: last_backfill machinery,
        PeeringState.h:645-680 Backfilling)."""
        return self.head >= auth_info.log_tail

    def merge(self, auth_entries: list[LogEntry], auth_info: PGInfo,
              missing: MissingSet) -> list[LogEntry]:
        """Fold the authoritative log into ours (PGLog.h:1247 merge_log).

        Find the newest entry both logs agree on; local entries past it
        are divergent (they never committed cluster-wide) and are
        rewound; auth entries past it are appended and their objects
        marked missing until recovered.  Returns the divergent entries
        so the PG can clean up objects they created.

        When the logs do NOT overlap (see overlaps()), the local log is
        replaced wholesale: splicing across a gap would fabricate a
        continuous history that hides trimmed modifications.  The caller
        is responsible for scan-based backfill of the data.
        """
        if not self.overlaps(auth_info):
            self.entries = list(auth_entries)
            self.tail = auth_info.log_tail
            self.head = (auth_entries[-1].version if auth_entries
                         else auth_info.last_update)
            for e in auth_entries:
                if e.is_delete():
                    missing.items.pop(e.oid, None)
                else:
                    missing.add(e.oid, need=e.version, have=ZERO)
            return []
        lu = self._last_common(auth_entries, auth_info.log_tail)
        divergent: list[LogEntry] = []
        if lu < self.head:
            divergent = self.rewind_divergent(lu, missing)
        for e in auth_entries:
            if e.version <= self.head:
                continue
            self.add(e)
            if e.is_delete():
                missing.items.pop(e.oid, None)
            else:
                missing.add(e.oid, need=e.version, have=e.prior_version)
        if self.tail < auth_info.log_tail and not self.entries:
            self.tail = auth_info.log_tail
        return divergent

    @staticmethod
    def proc_replica_log(replica_info: PGInfo, replica_entries: list[LogEntry],
                         auth_log: "PGLog") -> MissingSet:
        """What is `replica` missing relative to the authoritative log?

        PGLog.h:933.  Two sources: (a) auth entries past the replica's
        last_update; (b) replica divergent entries past the auth head.
        """
        missing = MissingSet()
        for e in auth_log.entries_after(replica_info.last_update):
            if e.is_delete():
                missing.items.pop(e.oid, None)
            else:
                missing.add(e.oid, need=e.version, have=e.prior_version)
        replica_view = PGLog(tail=ZERO, head=replica_info.last_update,
                             entries=list(replica_entries))
        lu = replica_view._last_common(auth_log.entries, auth_log.tail)
        divergent = [e for e in replica_entries if e.version > lu]
        first_div: dict[str, LogEntry] = {}
        for e in divergent:
            first_div.setdefault(e.oid, e)
        for oid, e in first_div.items():
            auth_e = auth_log.last_entry_of(oid)
            if auth_e is not None:
                if auth_e.is_delete():
                    # authoritatively deleted: nothing to push, the
                    # replica just removes it (mirrors merge())
                    missing.items.pop(oid, None)
                else:
                    missing.add(oid, need=auth_e.version, have=ZERO)
            elif e.prior_version:
                missing.add(oid, need=e.prior_version, have=ZERO)
        return missing

    # -- (de)serialization --------------------------------------------------
    def to_dict(self) -> dict:
        return {"tail": self.tail.to_list(), "head": self.head.to_list(),
                "entries": [e.to_dict() for e in self.entries]}

    @classmethod
    def from_dict(cls, d: dict) -> "PGLog":
        return cls(tail=EVersion.from_list(d["tail"]),
                   head=EVersion.from_list(d["head"]),
                   entries=[LogEntry.from_dict(e) for e in d["entries"]])

    def denc(self, enc) -> None:
        enc.start(1, 1)
        self.tail.denc(enc)
        self.head.denc(enc)
        enc.list(self.entries, lambda e, le: le.denc(e))
        enc.finish()

    @classmethod
    def dedenc(cls, dec) -> "PGLog":
        dec.start(1)
        out = cls(tail=EVersion.dedenc(dec), head=EVersion.dedenc(dec),
                  entries=dec.list(lambda d: LogEntry.dedenc(d)))
        dec.finish()
        return out

