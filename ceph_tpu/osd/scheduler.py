"""Op scheduler: dmClock-style QoS across client/recovery/best-effort.

Implements the dmClock tagging scheme the reference's mClockScheduler
uses (src/osd/scheduler/mClockScheduler.cc over vendored src/dmclock):
each class has (reservation r, weight w, limit l) in ops/sec; every op
gets a reservation tag and a weight tag; dispatch serves reservation
tags that are due first (guaranteeing r), then weight tags subject to
limit (proportional sharing of spare capacity).
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass
from enum import Enum
from typing import Any


class OpClass(str, Enum):
    CLIENT = "client"
    RECOVERY = "recovery"
    BEST_EFFORT = "best_effort"


@dataclass
class ClassSpec:
    reservation: float   # guaranteed ops/sec (0 = none)
    weight: float        # proportional share of spare capacity
    limit: float         # max ops/sec (0 = unlimited)


# defaults mirror the shape of mclock's high_client profile: clients are
# reservation-guaranteed, recovery is weight-limited so it cannot starve
# client I/O.
DEFAULT_SPECS: dict[OpClass, ClassSpec] = {
    OpClass.CLIENT: ClassSpec(reservation=1000.0, weight=2.0, limit=0.0),
    OpClass.RECOVERY: ClassSpec(reservation=100.0, weight=1.0, limit=500.0),
    OpClass.BEST_EFFORT: ClassSpec(reservation=0.0, weight=1.0, limit=200.0),
}


@dataclass(frozen=True)
class _Tags:
    r: float
    w: float
    l: float


class _ClassState:
    __slots__ = ("spec", "prev", "queue")

    def __init__(self, spec: ClassSpec) -> None:
        self.spec = spec
        self.prev = _Tags(0.0, 0.0, 0.0)   # tags of the last enqueued op
        self.queue: list[tuple[int, _Tags, Any]] = []


class MClockScheduler:
    def __init__(self, specs: dict[OpClass, ClassSpec] | None = None,
                 clock=time.monotonic, perf=None) -> None:
        self.clock = clock
        self._last_now = float("-inf")
        # observability sink (the OSD's "scheduler" perf set): queue
        # depth per class as gauges, enqueue/dispatch totals per class
        # as counters, so QoS behavior is REPORTED, not inferred
        self.perf = perf
        self._seq = itertools.count()
        self.classes = {c: _ClassState(s)
                        for c, s in (specs or DEFAULT_SPECS).items()}

    def _now(self) -> float:
        """Clock read clamped against regression.

        Tags are times: the default clock is ``time.monotonic`` (an
        NTP step on the wall clock must never starve a class whose
        tags suddenly sit in the future, nor burst one whose tags fell
        into the past), and any injected clock gets the same guarantee
        by clamping -- a backwards step freezes `now` instead of
        rewinding the tag arithmetic.
        """
        now = self.clock()
        if now < self._last_now:
            now = self._last_now
        else:
            self._last_now = now
        return now

    def __len__(self) -> int:
        return sum(len(st.queue) for st in self.classes.values())

    def _note_depth(self, op_class: OpClass) -> None:
        if self.perf is not None:
            st = self.classes[op_class]
            self.perf.set_gauge(f"depth_{op_class.value}",
                                len(st.queue))
            self.perf.set_gauge("depth_total", len(self))

    def enqueue(self, op_class: OpClass, item: Any) -> None:
        """Stamp the op with its own dmclock tags.

        Each tag advances from the previous op's tag by 1/rate, floored
        at now (the dmClock tag formula): an idle class restarts at
        `now`; a backlogged class spaces ops 1/rate apart.
        """
        st = self.classes[op_class]
        now = self._now()
        sp = st.spec
        tags = _Tags(
            r=(max(st.prev.r + 1.0 / sp.reservation, now)
               if sp.reservation > 0 else float("inf")),
            w=max(st.prev.w + 1.0 / sp.weight, now) if sp.weight > 0
              else float("inf"),
            l=(max(st.prev.l + 1.0 / sp.limit, now)
               if sp.limit > 0 else 0.0),
        )
        st.prev = tags
        heapq.heappush(st.queue, (next(self._seq), tags, item))
        if self.perf is not None:
            self.perf.inc(f"enqueued_{op_class.value}")
            self._note_depth(op_class)

    def dequeue(self) -> tuple[OpClass, Any] | None:
        """Pick per dmclock, comparing HEAD-of-queue op tags:
        reservation tags that are due first, then weight tags among
        classes whose head op is under its limit.
        """
        now = self._now()
        best_c, best_tag = None, None
        lane = "reservation"
        for c, st in self.classes.items():
            if not st.queue:
                continue
            head = st.queue[0][1]
            if head.r <= now and (best_tag is None or head.r < best_tag):
                best_c, best_tag = c, head.r
        if best_c is None:
            lane = "weight"
            for c, st in self.classes.items():
                if not st.queue:
                    continue
                head = st.queue[0][1]
                if head.l > now:
                    continue
                if best_tag is None or head.w < best_tag:
                    best_c, best_tag = c, head.w
        if best_c is None:
            # every head op is limit-deferred: fall back to global FIFO
            # so the queue still drains (the real scheduler would wait)
            lane = "fifo"
            candidates = [(st.queue[0][0], c)
                          for c, st in self.classes.items() if st.queue]
            if not candidates:
                return None
            best_c = min(candidates)[1]
        st = self.classes[best_c]
        _, _, item = heapq.heappop(st.queue)
        if self.perf is not None:
            self.perf.inc(f"dispatched_{best_c.value}")
            self.perf.inc(f"lane_{lane}")
            self._note_depth(best_c)
        return best_c, item
