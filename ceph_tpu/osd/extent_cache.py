"""Stripe-granular extent cache for the EC read-modify-write pipeline.

The analog of the reference's ExtentCache (src/osd/ExtentCache.h:120):
there it keeps in-flight write extents so overlapping RMW ops read from
the cache rather than racing disk; here do_op already serializes writes
per PG, so the cache's job is the sequential-overwrite hot path -- a
small overwrite re-reads the stripes the previous overwrite just wrote,
and those bytes are sitting right here.  Entries are whole stripes of
LOGICAL bytes keyed (oid, stripe_index), LRU-evicted under a byte
budget, and invalidated whenever shard content changes outside the RMW
path (recovery pushes, backfill, peering resets).
"""

from __future__ import annotations

from collections import OrderedDict


class ExtentCache:
    def __init__(self, max_bytes: int = 8 << 20) -> None:
        self.max_bytes = max_bytes
        self._lru: OrderedDict[tuple[str, int], bytes] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(self, oid: str, stripe: int) -> bytes | None:
        entry = self._lru.get((oid, stripe))
        if entry is None:
            self.misses += 1
            return None
        self.hits += 1
        self._lru.move_to_end((oid, stripe))
        return entry

    def put(self, oid: str, stripe: int, data: bytes) -> None:
        key = (oid, stripe)
        old = self._lru.pop(key, None)
        if old is not None:
            self._bytes -= len(old)
        self._lru[key] = data
        self._bytes += len(data)
        while self._bytes > self.max_bytes and self._lru:
            _, evicted = self._lru.popitem(last=False)
            self._bytes -= len(evicted)

    def invalidate(self, oid: str) -> None:
        for key in [k for k in self._lru if k[0] == oid]:
            self._bytes -= len(self._lru.pop(key))

    def truncate_beyond(self, oid: str, stripe: int) -> None:
        """Drop cached stripes at index >= stripe (object shrank)."""
        for key in [k for k in self._lru
                    if k[0] == oid and k[1] >= stripe]:
            self._bytes -= len(self._lru.pop(key))

    def clear(self) -> None:
        self._lru.clear()
        self._bytes = 0

    @property
    def used_bytes(self) -> int:
        return self._bytes
