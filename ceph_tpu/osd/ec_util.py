"""EC stripe math: logical object space <-> per-shard chunk space.

Mirrors src/osd/ECUtil.h stripe_info_t (:27-117): a pool-wide
stripe_width = k * chunk_size; a logical object offset maps to
(stripe index, chunk offset); shard s of an object holds the
concatenation of that object's chunk s across all stripes.
ECUtil::encode/decode (:21,134) drive the plugin per whole stripe.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np


def parse_stripe_unit(codec, value) -> int:
    """Validate a profile's stripe_unit (OSDMonitor.cc:7782-7813
    prepare_pool_stripe_width mirror): it must parse as a positive
    integer and divide evenly into codec-aligned chunks, or the pool's
    stripe geometry silently diverges from what the profile claims.
    Raises ValueError with the reference's spirit of message.
    """
    try:
        su = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"stripe_unit {value!r} is not an integer") from None
    if su <= 0:
        raise ValueError(f"stripe_unit {su} must be > 0")
    align = codec.get_alignment()
    if su % align:
        raise ValueError(
            f"stripe_unit {su} must be a multiple of the codec "
            f"alignment {align} (the codec would round chunks up and "
            f"desync the stripe geometry)")
    return su


class StripeInfo:
    def __init__(self, k: int, m: int, stripe_width: int) -> None:
        assert stripe_width % k == 0, (stripe_width, k)
        self.k = k
        self.m = m
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // k

    @classmethod
    def for_codec(cls, codec, stripe_unit: int = 4096) -> "StripeInfo":
        """Build a StripeInfo whose chunk_size matches the codec's
        aligned get_chunk_size — the same adjustment pool creation does
        (OSDMonitor::prepare_pool_stripe_width, OSDMonitor.cc:7782).
        """
        k = codec.get_data_chunk_count()
        m = codec.get_coding_chunk_count()
        chunk = codec.get_chunk_size(stripe_unit * k)
        return cls(k, m, chunk * k)

    def _check_codec(self, codec) -> None:
        # codecs align chunks up (SIMD_ALIGN); a mismatched stripe_width
        # would slice shard buffers at the wrong boundaries
        cs = codec.get_chunk_size(self.stripe_width)
        assert cs == self.chunk_size, (
            f"stripe_width {self.stripe_width} gives codec chunk_size "
            f"{cs}, StripeInfo expects {self.chunk_size}; build via "
            f"StripeInfo.for_codec")

    # -- offset maps (ECUtil.h:58-96) ---------------------------------------
    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset if rem == 0 else offset + self.stripe_width - rem

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0, offset
        return (offset // self.stripe_width) * self.chunk_size

    def chunk_aligned_logical_offset_to_chunk_offset(
            self, offset: int) -> int:
        return self.aligned_logical_offset_to_chunk_offset(
            self.logical_to_prev_stripe_offset(offset))

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0, offset
        return (offset // self.chunk_size) * self.stripe_width

    def object_size_to_shard_size(self, size: int) -> int:
        """On-shard bytes for a logical object of `size` bytes."""
        return self.aligned_logical_offset_to_chunk_offset(
            self.logical_to_next_stripe_offset(size))

    def offset_len_to_stripe_bounds(
            self, offset: int, length: int) -> tuple[int, int]:
        """Expand [offset, offset+length) to stripe-aligned bounds."""
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start

    # -- stripe encode/decode drivers (ECUtil.cc:21,134) --------------------
    def encode(self, codec, data: bytes) -> dict[int, np.ndarray]:
        """Encode whole stripes of `data` into k+m shard buffers.

        `data` must be stripe-aligned (pad first).  Each shard buffer is
        the concatenation of its chunk across stripes.
        """
        self._check_codec(codec)
        assert len(data) % self.stripe_width == 0, len(data)
        n_stripes = len(data) // self.stripe_width
        want = set(range(self.k + self.m))
        shards: dict[int, list[np.ndarray]] = {i: [] for i in want}
        for s in range(n_stripes):
            stripe = data[s * self.stripe_width:(s + 1) * self.stripe_width]
            encoded = codec.encode(want, stripe)
            for i in want:
                # lint: disable=device-path-host-sync -- scalar host fallback for codecs without batch entry points
                shards[i].append(np.asarray(encoded[i], dtype=np.uint8))
        return {i: (np.concatenate(bufs) if bufs
                    else np.zeros(0, np.uint8))
                for i, bufs in shards.items()}

    async def encode_async(self, codec, data: bytes, batcher=None,
                           with_crc: bool = False):
        """Batched analog of encode(): every stripe of ``data`` rides
        ONE ``encode_batch`` launch, and with a CodecBatcher the launch
        is shared with other concurrently-submitting ops (cross-PG
        coalescing).  Byte-identical to encode(); codecs without batch
        entry points fall back transparently.

        With ``with_crc`` the result is ``(shards, crcs)`` where
        ``crcs[i]`` is the CRC32C of shard i's whole buffer: per-stripe
        chunk CRCs come back from the codec launch itself (or one host
        batched pass on fallback) and are folded across the stripe axis
        with the GF(2) combine -- the write path stamps them without
        ever re-hashing shard bytes.
        """
        from .codec_batcher import CodecBatcher
        if batcher is None or not CodecBatcher.supports(codec):
            if batcher is not None:
                batcher.note_fallback()
            shards = self.encode(codec, data)
            if not with_crc:
                return shards
            return shards, self._shard_crcs(shards)
        self._check_codec(codec)
        assert len(data) % self.stripe_width == 0, len(data)
        n = len(data) // self.stripe_width
        if n == 0:
            out0 = {i: np.zeros(0, np.uint8)
                    for i in range(self.k + self.m)}
            if not with_crc:
                return out0
            return out0, self._shard_crcs(out0)
        arr = np.frombuffer(data, np.uint8).reshape(
            n, self.k, self.chunk_size)
        if with_crc:
            parity, chunk_crcs = await batcher.encode(codec, arr,
                                                      with_crc=True)
        else:
            parity = await batcher.encode(codec, arr)
        # shard placement honors the codec's chunk remapping: data
        # chunk i lives at position chunk_index(i), parity row r at the
        # r-th coding position (layered codes like lrc interleave
        # coding positions between data groups; identity-mapped codecs
        # reduce to out[i]=data_i, out[k+r]=parity_r exactly as before)
        cpos = self.coding_positions(codec)
        out: dict[int, np.ndarray] = {}
        for i in range(self.k):
            out[codec.chunk_index(i)] = np.ascontiguousarray(
                arr[:, i]).reshape(-1)
        for r in range(self.m):
            out[cpos[r]] = np.ascontiguousarray(
                parity[:, r]).reshape(-1)
        if not with_crc:
            return out
        from ..ops.crc32c_batch import fold_chunk_crcs
        folded = fold_chunk_crcs(chunk_crcs, self.chunk_size)
        # folded column order is the launch order (data 0..k-1, then
        # parity rows); re-key by shard position like `out`
        crcs = {codec.chunk_index(i): int(folded[i])
                for i in range(self.k)}
        for r in range(self.m):
            crcs[cpos[r]] = int(folded[self.k + r])
        return out, crcs

    @staticmethod
    def _shard_crcs(shards: dict[int, np.ndarray]) -> dict[int, int]:
        """Whole-shard CRCs in one batched pass (fallback path)."""
        from ..ops.crc32c_batch import crc32c_batch
        ids = sorted(shards)
        crcs = crc32c_batch([shards[i] for i in ids])
        return {i: int(c) for i, c in zip(ids, crcs)}

    async def decode_async(self, codec,
                           shard_bufs: Mapping[int, np.ndarray],
                           want: set[int] | None = None,
                           batcher=None) -> dict[int, np.ndarray]:
        """Batched analog of decode(): all stripes' reconstructions in
        one ``decode_batch`` launch, grouped in the batcher by erasure
        signature (the DecodeTableCache keying) so concurrent recovery
        reads with the same down-shard pattern coalesce."""
        from .codec_batcher import CodecBatcher
        from ..gf.matrices import decode_index_for
        want = (set(self.data_positions(codec)) if want is None
                else set(want))
        have = set(shard_bufs)
        k, m = self.k, self.m
        erasures = sorted(i for i in range(k + m) if i not in have)
        if batcher is None or not CodecBatcher.supports(codec):
            if batcher is not None:
                batcher.note_fallback()
            return self.decode(codec, shard_bufs, want)
        self._check_codec(codec)
        lens = {len(b) for b in shard_bufs.values()}
        assert len(lens) == 1, lens
        shard_len = lens.pop()
        assert shard_len % self.chunk_size == 0, shard_len
        n = shard_len // self.chunk_size
        cs = self.chunk_size
        if n == 0:
            return {i: np.zeros(0, np.uint8) for i in want}
        if want <= have or not erasures:
            # lint: disable=device-path-host-sync -- view-normalizes gathered/cache-resident ndarrays (no copy, no transfer)
            return {i: np.asarray(shard_bufs[i], dtype=np.uint8)
                    for i in want}
        if hasattr(codec, "decode_plan"):
            # layered/regenerating codecs (ec/linear_codec.py) pick
            # their OWN sources -- the LRC local group is fewer than k
            # chunks, which the positional decode-index contract below
            # cannot express -- and pack (sources, lost) into the
            # batcher's grouping extra so same-pattern repairs share a
            # launch
            plan = codec.decode_plan(set(want), have)
            if plan is not None:
                src, lost = plan
                survivors = np.stack(
                    # lint: disable=device-path-host-sync -- the single input marshal: gathered buffers stacked once for the launch
                    [np.asarray(shard_bufs[p], dtype=np.uint8)
                     .reshape(n, cs) for p in src], axis=1)
                rec = await batcher.decode(
                    codec, codec.pack_decode_extra(src, lost),
                    survivors)
                out2: dict[int, np.ndarray] = {}
                for i in want:
                    if i in shard_bufs:
                        # lint: disable=device-path-host-sync -- view passthrough of gathered shards alongside decoded ones
                        out2[i] = np.asarray(shard_bufs[i],
                                             dtype=np.uint8)
                    else:
                        out2[i] = np.ascontiguousarray(
                            rec[:, lost.index(i)]).reshape(-1)
                return out2
            return self.decode(codec, shard_bufs, want)
        if len(erasures) > m or len(have) < k:
            # unrecoverable: let the per-stripe driver raise its
            # canonical IOError
            return self.decode(codec, shard_bufs, want)
        decode_index = decode_index_for(k, set(erasures))
        survivors = np.stack(
            # lint: disable=device-path-host-sync -- the single input marshal: network/cache-resident buffers stacked once for the launch
            [np.asarray(shard_bufs[i], dtype=np.uint8).reshape(n, cs)
             for i in decode_index], axis=1)          # (n, k, cs)
        rec = await batcher.decode(codec, tuple(erasures), survivors)
        out: dict[int, np.ndarray] = {}
        for i in want:
            if i in shard_bufs:
                # lint: disable=device-path-host-sync -- view passthrough of gathered/cache-resident shards alongside decoded ones
                out[i] = np.asarray(shard_bufs[i], dtype=np.uint8)
            else:
                out[i] = np.ascontiguousarray(
                    rec[:, erasures.index(i)]).reshape(-1)
        return out

    async def reconstruct_logical_async(
            self, codec, shard_bufs: Mapping[int, np.ndarray],
            batcher=None) -> bytes:
        dpos = self.data_positions(codec)
        data_shards = await self.decode_async(codec, shard_bufs,
                                              want=set(dpos),
                                              batcher=batcher)
        return self._interleave_logical(codec, data_shards)

    @staticmethod
    def data_positions(codec) -> list[int]:
        """Shard ids hosting data chunks 0..k-1 (mapped codes like lrc
        place data at chunk_index(i), not i)."""
        k = codec.get_data_chunk_count()
        idx = getattr(codec, "chunk_index", None)
        return [idx(i) if idx else i for i in range(k)]

    @classmethod
    def coding_positions(cls, codec) -> list[int]:
        """Shard ids hosting coding chunks, ascending (the order the
        batched encode entry points emit parity rows in)."""
        dpos = set(cls.data_positions(codec))
        n = codec.get_chunk_count()
        return [p for p in range(n) if p not in dpos]

    def decode(self, codec, shard_bufs: Mapping[int, np.ndarray],
               want: set[int] | None = None) -> dict[int, np.ndarray]:
        """Reconstruct shard buffers (possibly all) from available shards.

        Every shard buffer covers the same chunk range; decode runs
        per-stripe through the plugin and reconcatenates.
        """
        self._check_codec(codec)
        want = (set(self.data_positions(codec)) if want is None
                else set(want))
        lens = {len(b) for b in shard_bufs.values()}
        assert len(lens) == 1, lens
        shard_len = lens.pop()
        assert shard_len % self.chunk_size == 0, shard_len
        n_stripes = shard_len // self.chunk_size
        out: dict[int, list[np.ndarray]] = {i: [] for i in want}
        for s in range(n_stripes):
            lo, hi = s * self.chunk_size, (s + 1) * self.chunk_size
            # lint: disable=device-path-host-sync -- scalar host fallback (unrecoverable-stripe error path)
            chunks = {i: np.asarray(b[lo:hi], dtype=np.uint8)
                      for i, b in shard_bufs.items()}
            decoded = codec.decode(want, chunks)
            for i in want:
                out[i].append(decoded[i])
        return {i: (np.concatenate(bufs) if bufs
                    else np.zeros(0, np.uint8))
                for i, bufs in out.items()}

    def reconstruct_logical(self, codec,
                            shard_bufs: Mapping[int, np.ndarray]) -> bytes:
        """Rebuild the logical byte stream from shard buffers."""
        dpos = self.data_positions(codec)
        data_shards = self.decode(codec, shard_bufs, want=set(dpos))
        return self._interleave_logical(codec, data_shards)

    def _interleave_logical(self, codec,
                            data_shards: Mapping[int, np.ndarray]) -> bytes:
        dpos = self.data_positions(codec)
        shard_len = len(next(iter(data_shards.values())))
        n_stripes = shard_len // self.chunk_size
        if n_stripes == 0 or not dpos:
            return b""
        # one materialization for the whole stream: stacking to
        # (n_stripes, k, cs) puts bytes in stripe-major interleave
        # order, vs the old per-stripe-per-shard asarray+tobytes hop
        stacked = np.stack(
            [data_shards[p].reshape(n_stripes, self.chunk_size)
             for p in dpos], axis=1)
        return stacked.tobytes()
