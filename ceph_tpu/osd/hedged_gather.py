"""Straggler-tolerant hedged gathers for the EC read spine.

A degraded read, scrub collection or recovery pull used to await a
FIXED shard set: one slow OSD set the whole op's latency ("Rateless
Codes for Near-Perfect Load Balancing..." frames the fix -- treat slow
shards like slow workers and decode from the first sufficient set to
arrive).  This module is that engine:

* ``PeerLatencyEWMA`` -- per-peer latency estimator (EWMA mean + EWMA
  absolute deviation -> an adaptive quantile estimate per peer).  The
  hedge timer is armed off the COHORT estimate (the median of the
  candidate peers' quantile estimates), not any single peer's own
  history: a persistently slow peer must not get to define its own
  "normal", and a plan whose only outstanding source is the straggler
  still hedges at the healthy cohort's pace.

* ``HedgedGather`` -- issues the minimum sub-read set as INDIVIDUAL
  awaitables (``OSD.start_request``), arms the hedge timer, and when it
  fires requests up to ``h`` extra shards chosen by the caller
  (``minimum_to_decode_with_cost`` with EWMA costs, so the LRC
  plugin's locality preference composes).  The gather completes on the
  FIRST sufficient verified set; outstanding sub-reads are cancelled
  AND awaited (reaped -- no orphan tasks), and a cancelled sub-read's
  late reply is dropped at the tid-waiter layer so it cannot crosstalk
  into a later op.  Every hedge fired/won/wasted and every extra byte
  read is counted in the ``ec_hedge`` perf set.

Config (``osd_ec_hedge_*``) is snapshot at construction -- the gather
loop never reads the config dict (hot-path-config-read discipline).
"""

from __future__ import annotations

import asyncio
from statistics import NormalDist, median

# MAD -> sigma for a normal distribution: sigma = MAD * sqrt(pi/2)
_MAD_TO_SIGMA = 1.2533141373155003


class PeerLatencyEWMA:
    """Per-peer sub-read latency EWMA + adaptive quantile estimate.

    ``observe()`` feeds one completed sub-read; ``estimate()`` returns
    the peer's q-quantile service-time estimate (EWMA mean + z * sigma
    with sigma recovered from the EWMA absolute deviation), or None
    while the peer is cold (< min_samples).  ``cohort_delay()`` is what
    the hedge timer arms on: the MEDIAN estimate across the candidate
    peers -- robust to one straggler skewing the cohort view.
    """

    def __init__(self, alpha: float = 0.2, quantile: float = 0.9,
                 min_samples: int = 8) -> None:
        self.alpha = float(alpha)
        self.quantile = min(max(float(quantile), 0.5), 0.999)
        self.min_samples = max(1, int(min_samples))
        self._z = NormalDist().inv_cdf(self.quantile)
        # peer -> [n, ewma_mean, ewma_abs_dev]
        self._stats: dict[int, list[float]] = {}

    @classmethod
    def from_config(cls, config: dict) -> "PeerLatencyEWMA":
        cfg = config if isinstance(config, dict) else {}
        return cls(
            alpha=float(cfg.get("osd_ec_hedge_ewma_alpha", 0.2)),
            quantile=float(cfg.get("osd_ec_hedge_quantile", 0.9)),
            min_samples=int(cfg.get("osd_ec_hedge_min_samples", 8)))

    def observe(self, peer: int, seconds: float) -> None:
        seconds = max(0.0, float(seconds))
        st = self._stats.get(peer)
        if st is None:
            # first sample seeds the mean; deviation starts at half the
            # sample so the early estimate is wide, not overconfident
            self._stats[peer] = [1, seconds, seconds / 2.0]
            return
        err = seconds - st[1]
        st[1] += self.alpha * err
        st[2] += self.alpha * (abs(err) - st[2])
        st[0] += 1

    def samples(self, peer: int) -> int:
        st = self._stats.get(peer)
        return 0 if st is None else int(st[0])

    def estimate(self, peer: int) -> float | None:
        """q-quantile service-time estimate; None while cold."""
        st = self._stats.get(peer)
        if st is None or st[0] < self.min_samples:
            return None
        return max(0.0, st[1] + self._z * _MAD_TO_SIGMA * st[2])

    def cohort_delay(self, peers) -> float | None:
        """Median of the warm peers' quantile estimates (None = the
        cohort is entirely cold and the caller should use its
        conservative default)."""
        ests = [e for e in (self.estimate(p) for p in set(peers))
                if e is not None]
        if not ests:
            return None
        return float(median(ests))

    def cost_us(self, peer: int, default_s: float) -> int:
        """Integer microsecond cost for minimum_to_decode_with_cost
        (cold peers cost the conservative default: prefer sources with
        a warm, fast history over unknowns)."""
        est = self.estimate(peer)
        return int(round((default_s if est is None else est) * 1e6))


class GatherOutcome:
    """What one hedged gather did (the caller folds this into its own
    failed/fetched bookkeeping)."""

    __slots__ = ("completed", "accepted", "timed_out", "cancelled",
                 "hedged", "hedge_fired")

    def __init__(self) -> None:
        self.completed = False       # sufficiency reached
        self.accepted: set = set()   # shards verified into the result
        self.timed_out: set = set()  # outstanding at deadline (failure)
        self.cancelled: set = set()  # cancelled after sufficiency (NOT
        #                              failures: merely slow)
        self.hedged: set = set()     # shards issued by the hedge
        self.hedge_fired = False


class HedgedGather:
    """First-k-of-(k+h) sub-read engine, one per OSD (shared by every
    ECBackend, scrub and recovery consumer on the daemon)."""

    def __init__(self, osd, tracker: PeerLatencyEWMA, perf=None, *,
                 enabled: bool = True, delay_min: float = 0.002,
                 delay_max: float = 1.0, max_extra: int = 2) -> None:
        self._osd = osd
        self.tracker = tracker
        self.perf = perf
        self.enabled = bool(enabled)
        self.delay_min = float(delay_min)
        self.delay_max = float(delay_max)
        self.max_extra = max(0, int(max_extra))

    @classmethod
    def from_config(cls, osd, config: dict, perf=None,
                    tracker: PeerLatencyEWMA | None = None
                    ) -> "HedgedGather":
        """ONE config read, at construction (the snapshot discipline)."""
        cfg = config if isinstance(config, dict) else {}
        return cls(
            osd,
            tracker or PeerLatencyEWMA.from_config(cfg),
            perf=perf,
            enabled=bool(cfg.get("osd_ec_hedge_enabled", True)),
            delay_min=float(cfg.get("osd_ec_hedge_delay_min", 0.002)),
            delay_max=float(cfg.get("osd_ec_hedge_delay_max", 1.0)),
            max_extra=int(cfg.get("osd_ec_hedge_max_extra", 2)))

    def note(self, key: str, by: int = 1) -> None:
        if self.perf is not None:
            self.perf.inc(key, by)

    def hedge_delay(self, peers) -> float:
        """The armed delay: adaptive cohort quantile, clamped.  A cold
        cohort gets delay_max -- hedge conservatively until the EWMA
        has evidence."""
        d = self.tracker.cohort_delay(peers)
        if d is None:
            return self.delay_max
        return min(max(d, self.delay_min), self.delay_max)

    # -- the gather core -----------------------------------------------------
    async def gather_shards(self, plan: dict, *, on_reply,
                            sufficient=None, hedge_pool=None,
                            choose_extras=None,
                            timeout: float = 10.0) -> GatherOutcome:
        """Issue ``plan`` ({shard: (peer_osd, mtype, payload)}) as
        individual sub-reads; complete on the first sufficient set.

        ``on_reply(shard, msg_or_None)`` feeds each arrival (None =
        send failure) to the caller, which verifies and accumulates.
        ``sufficient()`` returns the accepted shard set once it can
        decode (falsy = keep waiting); None means "complete when every
        request arrived" (scrub's collect-all mode -- no hedging).
        ``choose_extras(h)`` returns up to h extra sub-reads ({shard:
        (peer, mtype, payload)}) from ``hedge_pool`` when the timer
        fires.

        Outstanding sub-reads are ALWAYS cancelled and awaited on exit
        (even on exception) -- no orphan tasks, and the popped tid
        waiter drops any late reply on the messenger floor.
        """
        loop = asyncio.get_event_loop()
        tasks: dict[int, tuple[asyncio.Task, int, float]] = {}
        out = GatherOutcome()
        self.note("gathers")

        def _start(shard: int, peer: int, mtype: str,
                   payload: dict) -> None:
            _tid, task = self._osd.start_request(peer, mtype, payload,
                                                 [])
            tasks[shard] = (task, peer, loop.time())
            self.note("subreads")

        for shard, (peer, mtype, payload) in plan.items():
            _start(shard, peer, mtype, payload)
        pending = set(tasks)
        pool = dict(hedge_pool or {})
        armed = (self.enabled and sufficient is not None and pool
                 and choose_extras is not None and self.max_extra > 0)
        hedge_at = None
        if armed:
            cohort = {peer for peer, _, _ in plan.values()}
            cohort |= {peer for peer, _, _ in pool.values()}
            delay = self.hedge_delay(cohort)
            hedge_at = loop.time() + delay
            self.note("hedges_armed")
            if self.perf is not None:
                self.perf.tinc("hedge_delay", delay)
        deadline = loop.time() + timeout

        def _drain() -> bool:
            """Feed completed tasks to the caller; True if any."""
            arrived = [s for s in pending if tasks[s][0].done()]
            for s in arrived:
                pending.discard(s)
                task, peer, t0 = tasks[s]
                msg = None
                if not task.cancelled() and task.exception() is None:
                    msg = task.result()
                    self.tracker.observe(peer, loop.time() - t0)
                    self.note("ewma_observations")
                    nbytes = sum(len(seg) for seg in msg.segments)
                    self.note("subread_bytes", nbytes)
                    if s in out.hedged:
                        self.note("hedge_bytes", nbytes)
                on_reply(s, msg)
            return bool(arrived)

        try:
            while True:
                _drain()
                acc = sufficient() if sufficient is not None else None
                if sufficient is not None and acc:
                    out.completed = True
                    out.accepted = set(acc)
                    break
                if not pending:
                    # everything answered (or failed) and still not
                    # sufficient: the caller's retry ladder takes over
                    out.completed = sufficient is None
                    break
                now = loop.time()
                if now >= deadline:
                    break
                wait_until = deadline
                if hedge_at is not None and not out.hedge_fired:
                    wait_until = min(wait_until, hedge_at)
                await asyncio.wait(
                    [tasks[s][0] for s in pending],
                    timeout=max(wait_until - now, 1e-4),
                    return_when=asyncio.FIRST_COMPLETED)
                if (hedge_at is not None and not out.hedge_fired
                        and loop.time() >= hedge_at):
                    extras = choose_extras(self.max_extra)
                    if extras:
                        out.hedge_fired = True
                        self.note("hedges_fired")
                        for s, (peer, mtype, payload) in extras.items():
                            if s in tasks:
                                continue
                            _start(s, peer, mtype, payload)
                            out.hedged.add(s)
                            pending.add(s)
                            self.note("hedge_subreads")
                    else:
                        # nothing sound to add: disarm instead of
                        # polling the chooser every wake
                        hedge_at = None
                        self.note("hedges_noop")
        finally:
            leftovers = [s for s in pending if not tasks[s][0].done()]
            for s in leftovers:
                tasks[s][0].cancel()
            if leftovers:
                # REAP: awaiting the cancelled tasks runs their
                # finally-blocks (tid waiters popped) before the next
                # op can possibly reuse the wire
                await asyncio.gather(
                    *(tasks[s][0] for s in leftovers),
                    return_exceptions=True)
                self.note("cancelled_subreads", len(leftovers))
            if out.completed:
                out.cancelled = set(leftovers)
                if pending - set(leftovers):
                    # sufficiency beat sub-reads that were already done
                    # but not drained; fold them in as cancelled too
                    out.cancelled |= pending - set(leftovers)
            else:
                out.timed_out = set(pending)
        if out.completed and pending:
            self.note("first_set_completions")
        if out.hedge_fired:
            if out.completed and (out.accepted & out.hedged):
                self.note("hedges_won")
            else:
                self.note("hedges_wasted")
        return out

    # -- hedged single-reply fan-out (recovery pulls) ------------------------
    async def first_reply(self, targets: list[int], mtype: str,
                          payload: dict, segments=(), *,
                          timeout: float = 10.0, accept=None):
        """Hedge one request across equivalent sources: issue to
        ``targets[0]``, escalate to the next source when the cohort
        quantile elapses (or the current source answers with a
        rejected reply), return the first accepted reply.  Losers are
        cancelled and reaped.  Returns None on exhaustion/deadline --
        the caller's retry path is unchanged."""
        loop = asyncio.get_event_loop()
        tasks: dict[int, tuple[asyncio.Task, float]] = {}
        seen: set[int] = set()
        idx = 0
        self.note("first_replies")

        def _start_next() -> None:
            nonlocal idx
            t = targets[idx]
            idx += 1
            _tid, task = self._osd.start_request(t, mtype,
                                                 dict(payload),
                                                 list(segments))
            tasks[t] = (task, loop.time())
            self.note("subreads")

        _start_next()
        armed = self.enabled and len(targets) > 1
        delay = self.hedge_delay(targets)
        if armed:
            self.note("hedges_armed")
            if self.perf is not None:
                self.perf.tinc("hedge_delay", delay)
        next_hedge = loop.time() + delay
        deadline = loop.time() + timeout
        winner = None
        fired = False
        try:
            while winner is None:
                live = [t for t in tasks if not tasks[t][0].done()]
                for t in list(tasks):
                    task, t0 = tasks[t]
                    if t in seen or not task.done():
                        continue
                    seen.add(t)
                    if task.cancelled() or task.exception() is not None:
                        continue
                    msg = task.result()
                    self.tracker.observe(t, loop.time() - t0)
                    self.note("ewma_observations")
                    self.note("subread_bytes",
                              sum(len(s) for s in msg.segments))
                    if accept is None or accept(msg):
                        winner = (t, msg)
                        break
                if winner is not None:
                    break
                now = loop.time()
                if now >= deadline:
                    break
                can_add = armed and idx < len(targets)
                if not live:
                    if not can_add:
                        break               # exhausted
                    _start_next()           # all answers rejected:
                    fired = True            # escalate immediately
                    self.note("hedges_fired")
                    next_hedge = loop.time() + delay
                    continue
                wait_until = min(deadline,
                                 next_hedge if can_add else deadline)
                await asyncio.wait(
                    [tasks[t][0] for t in live],
                    timeout=max(wait_until - now, 1e-4),
                    return_when=asyncio.FIRST_COMPLETED)
                if can_add and loop.time() >= next_hedge:
                    _start_next()
                    fired = True
                    self.note("hedges_fired")
                    next_hedge = loop.time() + delay
        finally:
            leftovers = [t for t in tasks if not tasks[t][0].done()]
            for t in leftovers:
                tasks[t][0].cancel()
            if leftovers:
                await asyncio.gather(
                    *(tasks[t][0] for t in leftovers),
                    return_exceptions=True)
                self.note("cancelled_subreads", len(leftovers))
        if fired:
            if winner is not None and winner[0] != targets[0]:
                self.note("hedges_won")
            else:
                self.note("hedges_wasted")
        return None if winner is None else winner[1]
