"""Object stores: transactional local storage under PGs.

API rendering of the reference's ObjectStore contract
(src/os/ObjectStore.h:63: queue_transactions :239, read :484, omap :708):
collections (one per PG) of objects, each with byte data, xattrs, and an
omap; all mutations batched in atomic Transactions.

Backends: MemStore (RAM, tests/dev -- the reference has src/os/memstore);
DBStore (SQLite WAL, relational schema); KVStore (everything through
the KeyValueDB abstraction -- the kstore role, os/kv.py holding the
KeyValueDB.h contract); BlockStore (raw-block BlueStore analog with
KV-backed metadata -- the performance store).
"""

from .transaction import Transaction  # noqa: F401
from .store import ObjectStore, MemStore, DBStore  # noqa: F401
from .kv import KeyValueDB, KVTransaction, MemKVDB, SqliteKVDB  # noqa: F401
from .kvstore import KVStore  # noqa: F401
