"""Object stores: transactional local storage under PGs.

API rendering of the reference's ObjectStore contract
(src/os/ObjectStore.h:63: queue_transactions :239, read :484, omap :708):
collections (one per PG) of objects, each with byte data, xattrs, and an
omap; all mutations batched in atomic Transactions.

Backends: MemStore (RAM, tests/dev -- the reference has src/os/memstore);
DBStore (SQLite WAL -- the RocksDB-backed BlueStore role: atomic commit
via the WAL journal, data+metadata+omap in one transactional store).
"""

from .transaction import Transaction  # noqa: F401
from .store import ObjectStore, MemStore, DBStore  # noqa: F401
