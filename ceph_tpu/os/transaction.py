"""ObjectStore transactions: ordered op lists applied atomically.

Op vocabulary follows src/os/Transaction.h (the subset the OSD data path
exercises): touch/write/zero/truncate/remove, xattr set/rm, omap
set/rmkeys/clear, clone, collection create/remove.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass
class Op:
    op: str
    coll: str
    oid: str = ""
    args: dict[str, Any] = field(default_factory=dict)


class Transaction:
    def __init__(self) -> None:
        self.ops: list[Op] = []

    # -- collections --------------------------------------------------------
    def create_collection(self, coll: str) -> "Transaction":
        self.ops.append(Op("mkcoll", coll))
        return self

    def remove_collection(self, coll: str) -> "Transaction":
        self.ops.append(Op("rmcoll", coll))
        return self

    # -- object data --------------------------------------------------------
    def touch(self, coll: str, oid: str) -> "Transaction":
        self.ops.append(Op("touch", coll, oid))
        return self

    def write(self, coll: str, oid: str, offset: int,
              data: bytes) -> "Transaction":
        self.ops.append(Op("write", coll, oid,
                           {"offset": offset, "data": bytes(data)}))
        return self

    def zero(self, coll: str, oid: str, offset: int,
             length: int) -> "Transaction":
        self.ops.append(Op("zero", coll, oid,
                           {"offset": offset, "length": length}))
        return self

    def truncate(self, coll: str, oid: str, size: int) -> "Transaction":
        self.ops.append(Op("truncate", coll, oid, {"size": size}))
        return self

    def remove(self, coll: str, oid: str) -> "Transaction":
        self.ops.append(Op("remove", coll, oid))
        return self

    def clone(self, coll: str, src: str, dst: str) -> "Transaction":
        self.ops.append(Op("clone", coll, src, {"dst": dst}))
        return self

    # -- xattrs -------------------------------------------------------------
    def setattr(self, coll: str, oid: str, name: str,
                value: bytes) -> "Transaction":
        self.ops.append(Op("setattr", coll, oid,
                           {"name": name, "value": bytes(value)}))
        return self

    def rmattr(self, coll: str, oid: str, name: str) -> "Transaction":
        self.ops.append(Op("rmattr", coll, oid, {"name": name}))
        return self

    # -- omap ---------------------------------------------------------------
    def omap_setkeys(self, coll: str, oid: str,
                     kv: dict[str, bytes]) -> "Transaction":
        self.ops.append(Op("omap_setkeys", coll, oid,
                           {"kv": {k: bytes(v) for k, v in kv.items()}}))
        return self

    def omap_rmkeys(self, coll: str, oid: str,
                    keys: list[str]) -> "Transaction":
        self.ops.append(Op("omap_rmkeys", coll, oid, {"keys": list(keys)}))
        return self

    def omap_clear(self, coll: str, oid: str) -> "Transaction":
        self.ops.append(Op("omap_clear", coll, oid))
        return self

    def append(self, other: "Transaction") -> "Transaction":
        self.ops.extend(other.ops)
        return self

    def __len__(self) -> int:
        return len(self.ops)
