"""Device-resident shard-buffer cache: hot shard bytes stop
round-tripping the host.

Every hop of the OSD data path -- encode -> CRC -> blockstore ->
read-verify -> scrub -> degraded-read decode -- used to marshal shard
bytes through the store independently: the write path materialized the
encode launch's output to commit it, and every subsequent consumer
(scrub digest, CRC re-verify, ranged RMW read, decode gather) paid a
fresh ``store.read`` (pread + per-block checksum verify + extent
assembly) plus its own ``tobytes`` hops.  PR 5 proved fusing ONE hop
(CRC into the encode launch) is worth ~30x; this cache generalizes the
pattern to the whole spine: the bytes a write just encoded stay
RESIDENT, and every later consumer reads the resident buffer instead
of re-materializing it.

Keying: ``(coll, oid)`` on this OSD's store.  Each OSD holds exactly
one shard of an EC object (the write-time pin in ``SHARD_XATTR``), so
per-store keys are cluster-wide ``(object, shard)`` keys -- the entry
mirrors the shard label alongside the bytes.

Coherence rules (the correctness boundary -- tests/test_datapath_cache.py):

* **store-boundary invalidation**: every ``ObjectStore`` implementation
  invalidates the key BEFORE applying any transaction op that can
  change the object's content or identity xattrs (write/zero/truncate/
  remove/clone-dst/setattr/rmattr; rmcoll drops the collection).  All
  mutation paths -- client writes, recovery pushes, backfill, scrub
  repair, test bit-rot injection -- go through ``queue_transaction``,
  so nothing can mutate stored shard bytes without dropping the cached
  copy.  Producers re-``put`` the fresh content AFTER their txn commits.
* **entries are verified content**: a ``put`` happens only with bytes
  that just committed (the write path) or that were read through the
  store's checksum-on-read path (the read-through fill), with the
  whole-shard CRC tag carried when known.
* **daemon death is invalidation**: the cache is process memory
  attached to a mounted store; an OSD kill drops it, a revive remounts
  the store with a fresh (empty) cache -- stale bytes cannot survive a
  kill/revive (``BlockStore._reset_state`` clears an attached cache
  explicitly for in-process remounts).
* **bounded**: LRU under ``max_bytes`` with per-entry ``entry_max``
  (one huge cold object must not churn the whole working set).

Device residency: entries hold the contiguous uint8 buffer (on the CPU
backend that IS the device buffer) and ``device_view`` lazily
``device_put``s it once per residency, memoized -- a decode launch that
pulls surviving shards from the cache re-uses the upload instead of
re-transferring per launch.  The module stays importable without jax
(blockstore and the scrub path are jax-free); the device hop imports
lazily.

Observability: the process-wide ``PERF`` ("datapath") set -- hits,
misses, host bytes avoided vs read, evictions, resident bytes -- is
adopted into OSD perf dumps next to "integrity" and "ec_batch", and
``bench.py --datapath`` uses it to PROVE cache-hit reads and scrub
verifies move zero shard bytes across the host boundary.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..common.perf import PerfCounters

# process-wide datapath counter set; OSDs adopt it into their perf
# dumps (PerfCountersCollection.adopt), like "integrity"
PERF = PerfCounters("datapath")


class ShardEntry:
    """One resident shard: the bytes plus the identity the read path
    would otherwise fetch from xattrs (size / version / write-time
    shard label / whole-shard CRC tag)."""

    __slots__ = ("buf", "size", "ver", "shard", "crc", "_dev")

    def __init__(self, buf: np.ndarray, size: int, ver: tuple,
                 shard: int | None, crc: int | None) -> None:
        self.buf = buf
        self.size = int(size)
        self.ver = (int(ver[0]), int(ver[1]))
        self.shard = None if shard is None else int(shard)
        self.crc = None if crc is None else int(crc)
        self._dev = None                 # lazy device_put, memoized

    @property
    def nbytes(self) -> int:
        return self.buf.nbytes


class DeviceShardCache:
    """Bounded LRU of device-resident shard buffers keyed (coll, oid)."""

    def __init__(self, max_bytes: int = 64 << 20,
                 entry_max: int = 8 << 20) -> None:
        self.max_bytes = int(max_bytes)
        self.entry_max = int(entry_max)
        self._lru: OrderedDict[tuple[str, str], ShardEntry] = \
            OrderedDict()
        self._by_coll: dict[str, set[str]] = {}
        self._bytes = 0

    @classmethod
    def from_config(cls, conf) -> "DeviceShardCache | None":
        """Construction-time snapshot of the cache knobs (nothing is
        looked up per read).  Returns None when disabled."""
        if not conf.get("osd_datapath_cache_enabled", True):
            return None
        return cls(
            max_bytes=int(conf.get("osd_datapath_cache_bytes",
                                   64 << 20)),
            entry_max=int(conf.get("osd_datapath_cache_entry_max",
                                   8 << 20)))

    # -- accounting helpers ---------------------------------------------------
    def _gauges(self) -> None:
        PERF.set_gauge("resident_bytes", self._bytes)
        PERF.set_gauge("resident_entries", len(self._lru))

    @staticmethod
    def note_host_read(nbytes: int) -> None:
        """A consumer materialized shard bytes through the store (the
        host round trip the cache exists to avoid).  Called at every
        miss-path fill so the bench can assert the steady-state delta
        is ZERO on cache-hit reads and scrub verifies."""
        PERF.inc("host_reads")
        PERF.inc("host_bytes_read", int(nbytes))

    # -- reads ----------------------------------------------------------------
    def get(self, coll: str, oid: str) -> ShardEntry | None:
        entry = self._lru.get((coll, oid))
        if entry is None:
            PERF.inc("misses")
            return None
        self._lru.move_to_end((coll, oid))
        PERF.inc("hits")
        PERF.inc("host_bytes_avoided", entry.nbytes)
        return entry

    def device_view(self, coll: str, oid: str):
        """The entry's buffer as a device array, uploaded at most once
        per residency (decode launches over cached survivors re-use
        it).  Falls back to the host buffer when jax is unavailable."""
        entry = self._lru.get((coll, oid))
        if entry is None:
            return None
        if entry._dev is None:
            try:
                import jax
            except ImportError:          # jax-free deployments
                return entry.buf
            entry._dev = jax.device_put(entry.buf)
            PERF.inc("device_uploads")
            PERF.inc("device_upload_bytes", entry.nbytes)
        return entry._dev

    # -- writes ---------------------------------------------------------------
    def put(self, coll: str, oid: str, buf, *, size: int, ver: tuple,
            shard: int | None = None, crc: int | None = None) -> None:
        """Insert freshly committed / store-verified shard content.
        Oversize buffers are skipped (counted), never cached."""
        arr = np.ascontiguousarray(
            np.frombuffer(buf, np.uint8) if isinstance(
                buf, (bytes, bytearray, memoryview))
            else np.asarray(buf, np.uint8).reshape(-1))
        if arr.nbytes > self.entry_max:
            PERF.inc("put_oversize")
            self.invalidate(coll, oid)
            return
        key = (coll, oid)
        old = self._lru.pop(key, None)
        if old is not None:
            self._bytes -= old.nbytes
        self._lru[key] = ShardEntry(arr, size, ver, shard, crc)
        self._by_coll.setdefault(coll, set()).add(oid)
        self._bytes += arr.nbytes
        PERF.inc("puts")
        PERF.inc("put_bytes", arr.nbytes)
        while self._bytes > self.max_bytes and self._lru:
            (c, o), ev = self._lru.popitem(last=False)
            self._bytes -= ev.nbytes
            self._by_coll.get(c, set()).discard(o)
            PERF.inc("evictions")
            PERF.inc("evicted_bytes", ev.nbytes)
        self._gauges()

    # -- coherence ------------------------------------------------------------
    def invalidate(self, coll: str, oid: str | None = None) -> None:
        """Drop one key (or a whole collection) -- the store calls this
        BEFORE applying any mutating transaction op."""
        if oid is None:
            for o in list(self._by_coll.get(coll, ())):
                self._drop(coll, o)
            self._by_coll.pop(coll, None)
        else:
            self._drop(coll, oid)
        self._gauges()

    def _drop(self, coll: str, oid: str) -> None:
        entry = self._lru.pop((coll, oid), None)
        if entry is not None:
            self._bytes -= entry.nbytes
            self._by_coll.get(coll, set()).discard(oid)
            PERF.inc("invalidations")

    def note_txn(self, txn) -> None:
        """Invalidate every key a transaction can mutate (content ops
        AND identity-xattr ops -- entries mirror size/ver/crc, so a
        bare setattr desyncs them too).  Conservative by design: a
        producer that wants residency re-puts after its txn commits."""
        for op in txn.ops:
            if op.op in ("write", "zero", "truncate", "remove",
                         "setattr", "rmattr"):
                self.invalidate(op.coll, op.oid)
            elif op.op == "clone":
                self.invalidate(op.coll, op.args["dst"])
            elif op.op == "rmcoll":
                self.invalidate(op.coll)

    def clear(self) -> None:
        n = len(self._lru)
        self._lru.clear()
        self._by_coll.clear()
        self._bytes = 0
        if n:
            PERF.inc("invalidations", n)
        self._gauges()

    # -- introspection --------------------------------------------------------
    @property
    def used_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._lru)

    def __contains__(self, key: tuple[str, str]) -> bool:
        return key in self._lru
