"""BlockStore: raw-file block store with allocator, WAL, checksums,
and KV-backed metadata.

The BlueStore analog (src/os/bluestore/BlueStore.cc): object data lives
in a single raw block file this store ALLOCATES itself -- no filesystem
per object, no sqlite row per write.  The moving parts map one-to-one:

  * 4 KiB allocation units managed by a free-list allocator
    (src/os/bluestore/Allocator.h; contiguous-first, scatter fallback);
  * every transaction commits by appending ONE crc-framed record to a
    write-ahead log; a flusher drains the submit queue and fsyncs in
    GROUPS (_kv_sync_thread, BlueStore.cc:14643) -- durable on return;
  * small writes defer: the payload rides the WAL record and the block
    write happens without its own fsync (deferred writes,
    BlueStore.cc:15334 queue_transactions); replay re-applies them.
    Large writes go redirect-on-write to fresh blocks, fsynced before
    the WAL record commits (new-extent writes need no data in the log);
  * crc32c per block, verified on every read (checksum-on-read,
    BlueStore verify_csum);
  * clones share blocks by refcount (SharedBlob); a deferred in-place
    write to a shared block is forced down the redirect path (COW);
  * metadata (onodes: size, block map, xattrs; omap; per-block csums)
    lives in a KeyValueDB (os/kv.py -- the KeyValueDB.h role, sqlite
    engine) exactly as BlueStore keeps onodes in RocksDB: a bounded
    LRU onode cache serves reads, mutations accumulate as in-memory
    dirty overlays, and a checkpoint flushes ONLY the dirty entries in
    one atomic KV batch before truncating the WAL.  Memory stays
    bounded at any object count; checkpoints are incremental, not
    wholesale.

Layout under ``path/``: ``block`` (data), ``wal`` (log), ``md.db``
(KeyValueDB).
"""

from __future__ import annotations

import json
import os
import struct
import threading
from collections import OrderedDict

from ..common.denc import Decoder, Encoder
from ..native import crc32c
from ..ops.crc32c_batch import crc32c_batch, crc32c_rows
from .kv import SqliteKVDB
from .store import ObjectStore
from .transaction import Transaction

BLOCK = 4096                     # allocation/checksum unit
DEFERRED_MAX = 16 * BLOCK        # <=64 KiB writes take the WAL path
WAL_CKPT_BYTES = 8 << 20         # checkpoint + truncate past this
QUAR_MAX_BLOCKS = 4096           # force a checkpoint past 16 MiB of
                                 # quarantined frees (space amp bound)
ONODE_CACHE_MAX = 512            # clean onodes held in RAM
CSUM_CACHE_MAX = 1 << 16         # cached per-block crcs
REC_MAGIC = b"BSR1"

# KV prefixes (BlueStore's column families)
P_ONODE = "O"       # c\0o -> onode blob (size, blocks, xattrs)
P_OMAP = "M"        # c\0o\0key -> value
P_CSUM = "C"        # u64be(dev) -> u32le(crc)
P_STATE = "S"       # "seq" -> u64le
P_COLL = "L"        # coll -> b""


def _crc(data) -> int:
    return crc32c(bytes(data))


def _okey(c: str, o: str) -> bytes:
    return f"{c}\x00{o}".encode()


def _mkey(c: str, o: str, k: str = "") -> bytes:
    return f"{c}\x00{o}\x00{k}".encode()


class _Onode:
    __slots__ = ("size", "blocks", "xattrs", "dirty")

    def __init__(self) -> None:
        self.size = 0
        self.blocks: dict[int, int] = {}    # logical blk -> device blk
        self.xattrs: dict[str, bytes] = {}
        self.dirty = True                   # new onodes need a flush

    def encode(self) -> bytes:
        enc = Encoder()
        enc.start(1, 1)
        enc.u64(self.size)
        enc.map(self.blocks, lambda e, k: e.u64(k),
                lambda e, v: e.u64(v))
        enc.map(self.xattrs, lambda e, k: e.string(k),
                lambda e, v: e.blob(v))
        enc.finish()
        return enc.bytes()

    @classmethod
    def decode(cls, blob: bytes) -> "_Onode":
        dec = Decoder(blob)
        dec.start(1)
        on = cls()
        on.size = dec.u64()
        on.blocks = dec.map(Decoder.u64, Decoder.u64)
        on.xattrs = dec.map(Decoder.string, Decoder.blob)
        dec.finish()
        on.dirty = False
        return on


class Allocator:
    """Free-list block allocator: contiguous run first, scatter
    fallback, grow-the-device last (Allocator.h role)."""

    def __init__(self) -> None:
        self.free: set[int] = set()
        self.high = 0                # device size in blocks

    def alloc(self, n: int) -> list[int]:
        out: list[int] = []
        if len(self.free) >= n:
            run = self._find_run(n)
            if run is not None:
                out = list(range(run, run + n))
        if not out:
            take = sorted(self.free)[:n]
            out = take
        self.free -= set(out)
        while len(out) < n:
            out.append(self.high)
            self.high += 1
        return out

    def _find_run(self, n: int) -> int | None:
        run_start = None
        run_len = 0
        prev = None
        for b in sorted(self.free):
            if prev is not None and b == prev + 1:
                run_len += 1
            else:
                run_start, run_len = b, 1
            if run_len >= n:
                return run_start
            prev = b
        return None

    def release(self, blocks) -> None:
        self.free.update(blocks)


class BlockStore(ObjectStore):
    def __init__(self, path: str) -> None:
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.kv: SqliteKVDB | None = None
        # in-memory state is disk-derived: (re)set at every mount
        self._reset_state()
        self._block_fd = -1
        self._wal_fd = -1
        self._wal_size = 0
        self._mounted = False
        # kv-sync group commit: submitters enqueue (record, event) and
        # block; the flusher writes+fsyncs EVERYTHING queued in one go
        self._submit: list[tuple[bytes, threading.Event]] = []
        self._submit_lock = threading.Lock()
        self._submit_cv = threading.Condition(self._submit_lock)
        self._flusher: threading.Thread | None = None
        self._stop = False
        # serializes apply+commit+checkpoint across submitter threads
        # (MemStore holds a lock for the same contract)
        self._txn_lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------
    def _f(self, name: str) -> str:
        return os.path.join(self.path, name)

    def _reset_state(self) -> None:
        """In-memory state rebuilt from disk truth at every mount (a
        prior failed txn leaves nothing behind).  Everything here is
        an OVERLAY over the KV: committed-but-not-checkpointed
        mutations, bounded caches, and the allocator."""
        # bounded LRU of onodes; dirty entries are flush-pinned (never
        # evicted until a checkpoint writes them to the KV)
        self._oncache: OrderedDict[tuple, _Onode] = OrderedDict()
        # objects removed since the last checkpoint (pending KV rm)
        self._removed: set[tuple] = set()
        # omap overlay: (c,o) -> {key -> value | None=deleted}
        self._om_dirty: dict[tuple, dict[str, bytes | None]] = {}
        # full-clear markers (applied before the overlay on reads;
        # rm_range at checkpoint) -- also shields a recreated object
        # from its prior incarnation's KV rows
        self._om_cleared: set[tuple] = set()
        # csum overlay + bounded cache (dev -> crc | None=dropped)
        self._csum_dirty: dict[int, int | None] = {}
        self._csum_cache: OrderedDict[int, int] = OrderedDict()
        # collections: tiny cardinality, full set in RAM
        self._coll_set: set[str] = set()
        self._coll_dirty: dict[str, bool] = {}   # c -> exists
        self.alloc = Allocator()
        self.refcnt: dict[int, int] = {}    # shared blocks only (>1)
        self._seq = 0
        # deferred writes staged this txn but not yet on the device:
        # later ops in the SAME txn must read through this overlay
        self._pending: dict[int, bytes] = {}
        # freed blocks quarantined until the WAL is truncated: a live
        # WAL record may still carry a deferred payload for them, and
        # replay after a crash would pwrite that stale payload over
        # whatever a reallocation put there (BlueStore holds frees
        # until the kv log no longer references the extent)
        self._quarantine: set[int] = set()
        # a txn that died mid-commit leaves memory inconsistent with
        # the log: refuse further work, like BlueStore's abort path
        self._failed = False
        # a (re)mount rebuilds truth from disk: any device-resident
        # shard buffers from the previous incarnation are unverifiable
        # (a kill may have lost their final txn) -- drop them all
        if self.shard_cache is not None:
            self.shard_cache.clear()
        # observability: KV ops in the last checkpoint batch (proves
        # incremental flushing -- tests assert it stays proportional
        # to the delta, not the store size)
        self._last_ckpt_ops = 0

    def mount(self) -> None:
        if self._mounted:
            return
        self._reset_state()
        self._block_fd = os.open(self._f("block"),
                                 os.O_RDWR | os.O_CREAT, 0o644)
        self.kv = SqliteKVDB(self._f("md.db"))
        seq = self.kv.get(P_STATE, b"seq")
        self._seq = struct.unpack("<Q", seq)[0] if seq else 0
        self._coll_set = {k.decode()
                          for k, _ in self.kv.get_range(P_COLL)}
        good = self._replay_wal()
        self._rebuild_allocator()
        self._wal_fd = os.open(self._f("wal"),
                               os.O_RDWR | os.O_CREAT | os.O_APPEND,
                               0o644)
        if os.fstat(self._wal_fd).st_size > good:
            # cut the torn tail NOW: records appended after garbage
            # would be unreachable by every future replay
            os.ftruncate(self._wal_fd, good)
            os.fsync(self._wal_fd)
        self._wal_size = good
        if good > 0:
            # checkpoint the replayed state so the WAL holds no stale
            # deferred payloads: only then is the rebuilt free list
            # safe to allocate from (see _quarantine)
            self._checkpoint()
        self._stop = False
        self._flusher = threading.Thread(target=self._kv_sync,
                                         daemon=True)
        self._flusher.start()
        self._mounted = True

    def umount(self) -> None:
        if not self._mounted:
            return
        with self._submit_cv:
            self._stop = True
            self._submit_cv.notify()
        self._flusher.join()
        if not self._failed:
            self._checkpoint()
        # on failure: do NOT checkpoint -- the in-memory state is
        # half-applied and the WAL (which never got the failed txn's
        # record) is the only consistent truth; remount replays it
        os.close(self._wal_fd)
        os.close(self._block_fd)
        self.kv.close()
        self._mounted = False

    def _ensure(self) -> None:
        if not self._mounted:
            self.mount()        # resets a prior failure from disk
            return
        if self._failed:
            # reads too: the in-memory maps may hold the half-applied
            # txn (new csums over old device content), so serving them
            # would misreport corruption or leak uncommitted state
            raise IOError("blockstore failed mid-commit; "
                          "remount required")

    # -- kv-sync flusher (group commit) --------------------------------------
    def _kv_sync(self) -> None:
        while True:
            with self._submit_cv:
                while not self._submit and not self._stop:
                    self._submit_cv.wait()
                if self._stop and not self._submit:
                    return
                batch, self._submit = self._submit, []
            buf = b"".join(rec for rec, _ in batch)
            os.write(self._wal_fd, buf)
            os.fsync(self._wal_fd)
            self._wal_size += len(buf)
            for _, ev in batch:
                ev.set()

    def _wal_commit(self, record: bytes) -> None:
        ev = threading.Event()
        with self._submit_cv:
            self._submit.append((record, ev))
            self._submit_cv.notify()
        ev.wait()

    # -- transaction apply ----------------------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        """Apply + durably commit one transaction.

        Data placement happens NOW (large writes hit fresh blocks and
        fsync; small writes merge in place, payload deferred into the
        log); the metadata delta commits as one WAL record via the
        group flusher.  On return the transaction is crash-durable.

        The call BLOCKS the submitting thread on the log fsync, as the
        reference's queue_transactions blocks its submitter until
        kv-sync acks; under asyncio that stalls the loop for one local
        fsync (~0.1-1 ms) per txn -- acceptable against multi-second
        heartbeat grace, and the price of ack==durable semantics."""
        self._ensure()
        with self._txn_lock:
            # validate-then-apply, as MemStore: missing collections
            # fail the whole transaction up front (mkcolls earlier in
            # the same txn count); under the lock so the set is stable
            pending = set(self._coll_set)
            for op in txn.ops:
                if op.op == "mkcoll":
                    pending.add(op.coll)
                elif op.coll not in pending:
                    raise KeyError(f"no collection {op.coll}")
            if self._failed:
                raise IOError("blockstore failed mid-commit; "
                              "remount required")
            # cache coherence: drop resident copies of every object
            # this txn can mutate BEFORE applying (even a failed apply
            # must not leave a stale resident buffer behind)
            self._note_txn_for_cache(txn)
            try:
                self._commit_locked(txn)
            except BaseException:
                self._failed = True
                raise
            finally:
                self._pending.clear()

    def _commit_locked(self, txn: Transaction) -> None:
        self._seq += 1
        delta: dict = {"seq": self._seq, "ops": []}
        ctx = {"sync": False, "deferred": [], "to_release": []}
        for op in txn.ops:
            self._apply_op(op, delta, ctx)
        if ctx["sync"]:
            # metadata must never point at data the device might not
            # hold: new-extent data syncs BEFORE the WAL record lands
            os.fsync(self._block_fd)
        meta = json.dumps(delta, separators=(",", ":")).encode()
        rec = (REC_MAGIC + struct.pack("<II", len(meta), _crc(meta))
               + meta)
        self._wal_commit(rec)
        # deferred in-place writes land only AFTER the record is
        # durable: overwriting the old content first would destroy a
        # previously committed write if we crashed before the log
        # caught up (exactly BlueStore's deferred ordering)
        for dev, content in ctx["deferred"]:
            os.pwrite(self._block_fd, content, dev * BLOCK)
        self._quarantine.update(ctx["to_release"])
        self._pending.clear()
        self._evict()
        if (self._wal_size > WAL_CKPT_BYTES
                or len(self._quarantine) > QUAR_MAX_BLOCKS):
            self._checkpoint()

    # each ops entry in a delta is self-contained for idempotent
    # replay: resulting block assignments, csums, payloads -- never
    # read-modify state
    def _apply_op(self, op, delta: dict, ctx: dict) -> None:
        c, oid = op.coll, op.oid
        a = op.args
        if op.op == "mkcoll":
            if c not in self._coll_set:
                self._coll_set.add(c)
                self._coll_dirty[c] = True
            delta["ops"].append({"op": "mkcoll", "c": c})
        elif op.op == "rmcoll":
            for o in self._list_objects(c):
                self._free_object(c, o, ctx)
            self._coll_set.discard(c)
            self._coll_dirty[c] = False
            delta["ops"].append({"op": "rmcoll", "c": c})
        elif op.op == "touch":
            self._onode(c, oid, create=True)
            delta["ops"].append({"op": "touch", "c": c, "o": oid})
        elif op.op == "write":
            self._do_write(c, oid, a["offset"], a["data"], delta, ctx)
        elif op.op == "zero":
            self._do_write(c, oid, a["offset"],
                           b"\x00" * a["length"], delta, ctx)
        elif op.op == "truncate":
            self._do_truncate(c, oid, a["size"], delta, ctx)
        elif op.op == "remove":
            self._free_object(c, oid, ctx)
            delta["ops"].append({"op": "remove", "c": c, "o": oid})
        elif op.op == "clone":
            self._do_clone(c, oid, a["dst"], delta, ctx)
        elif op.op == "setattr":
            on = self._onode(c, oid, create=True)
            on.xattrs[a["name"]] = a["value"]
            on.dirty = True
            delta["ops"].append({"op": "setattr", "c": c, "o": oid,
                                 "n": a["name"],
                                 "v": a["value"].hex()})
        elif op.op == "rmattr":
            on = self._onode(c, oid, create=True)
            on.xattrs.pop(a["name"], None)
            on.dirty = True
            delta["ops"].append({"op": "rmattr", "c": c, "o": oid,
                                 "n": a["name"]})
        elif op.op == "omap_setkeys":
            self._onode(c, oid, create=True)
            self._om_dirty.setdefault((c, oid), {}).update(a["kv"])
            delta["ops"].append({"op": "omap_setkeys", "c": c,
                                 "o": oid,
                                 "kv": {k: v.hex()
                                        for k, v in a["kv"].items()}})
        elif op.op == "omap_rmkeys":
            self._onode(c, oid, create=True)
            d = self._om_dirty.setdefault((c, oid), {})
            for k in a["keys"]:
                d[k] = None
            delta["ops"].append({"op": "omap_rmkeys", "c": c, "o": oid,
                                 "keys": list(a["keys"])})
        elif op.op == "omap_clear":
            self._onode(c, oid, create=True)
            self._om_cleared.add((c, oid))
            self._om_dirty.pop((c, oid), None)
            delta["ops"].append({"op": "omap_clear", "c": c, "o": oid})
        else:
            raise ValueError(f"unknown op {op.op}")

    # -- onode cache ----------------------------------------------------------
    def _onode(self, c: str, oid: str,
               create: bool = False) -> _Onode | None:
        key = (c, oid)
        on = self._oncache.get(key)
        if on is not None:
            self._oncache.move_to_end(key)
            return on
        if key not in self._removed:
            blob = self.kv.get(P_ONODE, _okey(c, oid)) \
                if self.kv is not None else None
            if blob is not None:
                on = _Onode.decode(blob)
                self._oncache[key] = on
                self._evict()    # read-heavy paths must stay bounded
                return on
        if not create:
            return None
        self._removed.discard(key)
        on = _Onode()
        self._oncache[key] = on
        return on

    def _evict(self) -> None:
        """Drop least-recently-used CLEAN onodes past the cache bound;
        dirty onodes are pinned until a checkpoint flushes them."""
        while len(self._csum_cache) > CSUM_CACHE_MAX:
            self._csum_cache.popitem(last=False)
        excess = len(self._oncache) - ONODE_CACHE_MAX
        if excess <= 0:
            return
        for key in [k for k, v in self._oncache.items()
                    if not v.dirty][:excess]:
            del self._oncache[key]

    # -- csums ----------------------------------------------------------------
    def _get_csum(self, dev: int) -> int | None:
        if dev in self._csum_dirty:
            return self._csum_dirty[dev]
        got = self._csum_cache.get(dev)
        if got is not None:
            self._csum_cache.move_to_end(dev)
            return got
        raw = self.kv.get(P_CSUM, struct.pack(">Q", dev))
        if raw is None:
            return None
        crc = struct.unpack("<I", raw)[0]
        self._csum_cache[dev] = crc
        return crc

    def _set_csum(self, dev: int, crc: int | None) -> None:
        self._csum_dirty[dev] = crc
        if crc is None:
            self._csum_cache.pop(dev, None)
        else:
            self._csum_cache[dev] = crc

    # -- data path ------------------------------------------------------------
    def _read_dev_block(self, dev_blk: int, verify: bool = True) -> bytes:
        pend = self._pending.get(dev_blk)
        if pend is not None:
            return pend
        buf = os.pread(self._block_fd, BLOCK, dev_blk * BLOCK)
        buf = buf.ljust(BLOCK, b"\x00")
        if verify:
            want = self._get_csum(dev_blk)
            if want is not None and _crc(buf) != want:
                raise IOError(
                    f"checksum mismatch on device block {dev_blk}")
        return buf

    def _deref(self, dev_blk: int, ctx: dict) -> None:
        n = self.refcnt.get(dev_blk, 1)
        if n > 1:
            self.refcnt[dev_blk] = n - 1
        else:
            self.refcnt.pop(dev_blk, None)
            self._set_csum(dev_blk, None)
            # never straight back to the allocator: a live WAL record
            # (this txn's or an earlier uncheckpointed one) may carry a
            # deferred payload for this block, and replay would smear
            # it over whatever a reallocation wrote here.  Quarantined
            # until the WAL is truncated (_checkpoint).
            ctx["to_release"].append(dev_blk)

    def _do_write(self, c: str, oid: str, offset: int, data: bytes,
                  delta: dict, ctx: dict) -> None:
        on = self._onode(c, oid, create=True)
        end = offset + len(data)
        lb0, lb1 = offset // BLOCK, (end + BLOCK - 1) // BLOCK
        deferred = len(data) <= DEFERRED_MAX
        assign: dict[int, int] = {}
        contents: list[tuple[int, bytes]] = []   # (dev, final bytes)
        payloads: list[list] = []      # [dev_blk, hex] for replay
        pwrites: list[tuple[int, bytes]] = []
        for lb in range(lb0, lb1):
            blk_off = lb * BLOCK
            s = max(offset, blk_off) - blk_off
            e = min(end, blk_off + BLOCK) - blk_off
            piece = data[max(offset, blk_off) - offset:
                         min(end, blk_off + BLOCK) - offset]
            old_dev = on.blocks.get(lb)
            partial = (s > 0 or e < BLOCK) and blk_off < on.size
            shared = (old_dev is not None
                      and self.refcnt.get(old_dev, 1) > 1)
            if partial and old_dev is not None:
                base = bytearray(self._read_dev_block(old_dev))
            else:
                base = bytearray(BLOCK)
            base[s:e] = piece
            content = bytes(base)
            if deferred and old_dev is not None and not shared:
                # deferred small write: merge IN PLACE, payload rides
                # the WAL, no per-block fsync (replay restores it)
                dev = old_dev
            else:
                # redirect-on-write: fresh block (also the COW path
                # for blocks a clone still references)
                dev = self.alloc.alloc(1)[0]
                if old_dev is not None:
                    self._deref(old_dev, ctx)
            if deferred and dev == old_dev:
                # in-place overwrite: must not hit the device until
                # the WAL record is durable
                ctx["deferred"].append((dev, content))
                self._pending[dev] = content
            else:
                pwrites.append((dev, content))
            assign[lb] = dev
            contents.append((dev, content))
            if deferred:
                payloads.append([dev, content.hex()])
        for dev, content in pwrites:
            os.pwrite(self._block_fd, content, dev * BLOCK)
        on.blocks.update(assign)
        # per-block checksums for the whole write extent in ONE batched
        # pass (the per-block scalar call was the last host CRC loop on
        # the block write path)
        csums: dict[int, int] = {
            dev: int(crc) for (dev, _), crc in zip(
                contents, crc32c_batch([b for _, b in contents]))}
        for dev, crc in csums.items():
            self._set_csum(dev, crc)
        on.size = max(on.size, end)
        on.dirty = True
        delta["ops"].append({
            "op": "write", "c": c, "o": oid, "size": on.size,
            "assign": {str(k): v for k, v in assign.items()},
            "csums": {str(k): v for k, v in csums.items()},
            "payloads": payloads if deferred else []})
        if not deferred:
            ctx["sync"] = True

    def _do_truncate(self, c: str, oid: str, size: int,
                     delta: dict, ctx: dict) -> None:
        on = self._onode(c, oid, create=True)
        keep = (size + BLOCK - 1) // BLOCK
        for lb in [b for b in on.blocks if b >= keep]:
            self._deref(on.blocks.pop(lb), ctx)
        if size % BLOCK and size < on.size \
                and size // BLOCK in on.blocks:
            # zero the tail of the last kept block through the write
            # path: it COWs shared blocks and keeps deferred ordering
            self._do_write(c, oid, size,
                           b"\x00" * (BLOCK - size % BLOCK), delta,
                           ctx)
        on.size = size
        on.dirty = True
        delta["ops"].append({"op": "truncate", "c": c, "o": oid,
                             "size": size})

    def _do_clone(self, c: str, src: str, dst: str,
                  delta: dict, ctx: dict) -> None:
        son = self._onode(c, src)
        if son is None:
            return                      # MemStore contract: no-op
        src_omap = self._omap_get(c, src)
        self._free_object(c, dst, ctx)
        don = self._onode(c, dst, create=True)
        don.size = son.size
        don.blocks = dict(son.blocks)
        don.xattrs = dict(son.xattrs)
        don.dirty = True
        self._om_cleared.add((c, dst))
        self._om_dirty[(c, dst)] = dict(src_omap)
        for dev in son.blocks.values():
            self.refcnt[dev] = self.refcnt.get(dev, 1) + 1
        # the record carries the COPIED state: replay must not re-read
        # the source, which a checkpoint that landed before the crash
        # may have advanced past the clone point (idempotent replay)
        delta["ops"].append({
            "op": "clone", "c": c, "o": src, "dst": dst,
            "size": don.size,
            "blocks": {str(k): v for k, v in don.blocks.items()},
            "xattrs": {k: v.hex() for k, v in don.xattrs.items()},
            "omap": {k: v.hex() for k, v in src_omap.items()}})

    def _free_object(self, c: str, oid: str, ctx: dict) -> None:
        on = self._onode(c, oid)
        if on is None:
            return
        for dev in on.blocks.values():
            self._deref(dev, ctx)
        self._oncache.pop((c, oid), None)
        self._removed.add((c, oid))
        self._om_dirty.pop((c, oid), None)
        self._om_cleared.add((c, oid))

    # -- replay / checkpoint --------------------------------------------------
    def _replay_op(self, d: dict) -> None:
        op, c = d["op"], d.get("c")
        oid = d.get("o")
        ctx = {"sync": False, "deferred": [], "to_release": []}
        if op == "mkcoll":
            if c not in self._coll_set:
                self._coll_set.add(c)
                self._coll_dirty[c] = True
        elif op == "rmcoll":
            for o in self._list_objects(c):
                self._free_object(c, o, ctx)
            self._coll_set.discard(c)
            self._coll_dirty[c] = False
        elif op == "touch":
            self._onode(c, oid, create=True)
        elif op == "write":
            on = self._onode(c, oid, create=True)
            assign = {int(k): v for k, v in d["assign"].items()}
            on.blocks.update(assign)
            on.size = max(on.size, d["size"])
            on.dirty = True
            for k, v in d["csums"].items():
                self._set_csum(int(k), v)
            for dev, hexdata in d["payloads"]:
                os.pwrite(self._block_fd, bytes.fromhex(hexdata),
                          dev * BLOCK)
        elif op == "truncate":
            on = self._onode(c, oid, create=True)
            keep = (d["size"] + BLOCK - 1) // BLOCK
            for lb in [b for b in on.blocks if b >= keep]:
                on.blocks.pop(lb)
            on.size = d["size"]
            on.dirty = True
        elif op == "remove":
            on = self._onode(c, oid)
            if on is not None:
                self._oncache.pop((c, oid), None)
                self._removed.add((c, oid))
                self._om_dirty.pop((c, oid), None)
                self._om_cleared.add((c, oid))
        elif op == "clone":
            # self-contained: the record's copied state, never the
            # source's current (possibly post-checkpoint) state
            don = self._onode(c, d["dst"], create=True)
            don.size = d["size"]
            don.blocks = {int(k): v for k, v in d["blocks"].items()}
            don.xattrs = {k: bytes.fromhex(v)
                          for k, v in d["xattrs"].items()}
            don.dirty = True
            self._om_cleared.add((c, d["dst"]))
            self._om_dirty[(c, d["dst"])] = {
                k: bytes.fromhex(v) for k, v in d["omap"].items()}
        elif op == "setattr":
            on = self._onode(c, oid, create=True)
            on.xattrs[d["n"]] = bytes.fromhex(d["v"])
            on.dirty = True
        elif op == "rmattr":
            on = self._onode(c, oid, create=True)
            on.xattrs.pop(d["n"], None)
            on.dirty = True
        elif op == "omap_setkeys":
            self._onode(c, oid, create=True)
            self._om_dirty.setdefault((c, oid), {}).update(
                {k: bytes.fromhex(v) for k, v in d["kv"].items()})
        elif op == "omap_rmkeys":
            self._onode(c, oid, create=True)
            od = self._om_dirty.setdefault((c, oid), {})
            for k in d["keys"]:
                od[k] = None
        elif op == "omap_clear":
            self._onode(c, oid, create=True)
            self._om_cleared.add((c, oid))
            self._om_dirty.pop((c, oid), None)

    def _replay_wal(self) -> int:
        """Apply intact records; returns the byte offset of the first
        torn/corrupt record (the good prefix length)."""
        try:
            with open(self._f("wal"), "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return 0
        pos = 0
        while pos + 12 <= len(raw):
            if raw[pos:pos + 4] != REC_MAGIC:
                break                   # torn tail: stop cleanly
            ln, want = struct.unpack_from("<II", raw, pos + 4)
            body = raw[pos + 12:pos + 12 + ln]
            if len(body) < ln or _crc(body) != want:
                break                   # torn/corrupt record: stop
            delta = json.loads(body)
            self._seq = max(self._seq, delta["seq"])
            for d in delta["ops"]:
                self._replay_op(d)
            pos += 12 + ln
        return pos

    def _all_onodes(self):
        """(key, onode) for every live object: KV rows shadowed by the
        cache/removed overlay, then dirty cache-only entries."""
        seen = set()
        if self.kv is not None:
            for kraw, blob in self.kv.get_range(P_ONODE):
                c, _, o = kraw.decode().partition("\x00")
                key = (c, o)
                if key in self._removed:
                    continue
                seen.add(key)
                on = self._oncache.get(key)
                yield key, (on if on is not None
                            else _Onode.decode(blob))
        for key, on in list(self._oncache.items()):
            if key not in seen and key not in self._removed:
                yield key, on

    def _rebuild_allocator(self) -> None:
        """Used-block census from the onode maps (mount-time fsck the
        way BlueStore rebuilds its freelist)."""
        used: dict[int, int] = {}
        for _, on in self._all_onodes():
            for dev in on.blocks.values():
                used[dev] = used.get(dev, 0) + 1
        self.refcnt = {b: n for b, n in used.items() if n > 1}
        high = max(used, default=-1) + 1
        self.alloc.high = high
        self.alloc.free = set(range(high)) - set(used)

    def _checkpoint(self) -> None:
        """Flush the dirty overlays -- and ONLY them -- to the KV in
        one atomic batch, then truncate the WAL (BlueStore's kv_sync
        commit; incremental where the old design rewrote everything)."""
        kvt = self.kv.transaction()
        nops = 1
        kvt.set(P_STATE, b"seq", struct.pack("<Q", self._seq))
        for c, exists in self._coll_dirty.items():
            nops += 1
            if exists:
                kvt.set(P_COLL, c.encode(), b"")
            else:
                kvt.rm(P_COLL, c.encode())
        for (c, o) in self._removed:
            nops += 1
            kvt.rm(P_ONODE, _okey(c, o))
        for (c, o) in self._om_cleared:
            nops += 1
            kvt.rm_range(P_OMAP, _mkey(c, o), _mkey(c, o) + b"\xff")
        for key, on in self._oncache.items():
            if on.dirty:
                nops += 1
                kvt.set(P_ONODE, _okey(*key), on.encode())
        for (c, o), od in self._om_dirty.items():
            for k, v in od.items():
                nops += 1
                if v is None:
                    kvt.rm(P_OMAP, _mkey(c, o, k))
                else:
                    kvt.set(P_OMAP, _mkey(c, o, k), v)
        for dev, crc in self._csum_dirty.items():
            nops += 1
            if crc is None:
                kvt.rm(P_CSUM, struct.pack(">Q", dev))
            else:
                kvt.set(P_CSUM, struct.pack(">Q", dev),
                        struct.pack("<I", crc))
        # data must be on disk before the metadata that references it
        os.fsync(self._block_fd)
        self.kv.submit(kvt, sync=True)
        self._last_ckpt_ops = nops
        for on in self._oncache.values():
            on.dirty = False
        self._removed.clear()
        self._om_dirty.clear()
        self._om_cleared.clear()
        self._csum_dirty.clear()
        self._coll_dirty.clear()
        if self._wal_fd >= 0:
            os.ftruncate(self._wal_fd, 0)
            os.fsync(self._wal_fd)
            self._wal_size = 0
        else:
            with open(self._f("wal"), "wb"):
                pass
        # the WAL no longer references any freed block: quarantined
        # frees are finally safe to hand back to the allocator
        if self._quarantine:
            self.alloc.release(self._quarantine)
            self._quarantine.clear()
        self._evict()

    # -- reads ----------------------------------------------------------------
    def read(self, coll, oid, offset=0, length=None):
        from ..common.throttle import injector
        injector.maybe_raise("objectstore_read")   # EIO injection site
        # reads mutate the shared LRU caches (move_to_end / insert /
        # evict), so they serialize with writers on the same lock the
        # txn path holds -- the pre-KV design's lock-free reads were
        # pure dict lookups, these are not
        with self._txn_lock:
            self._ensure()
            return self._read_locked(coll, oid, offset, length)

    def _read_locked(self, coll, oid, offset=0, length=None):
        on = self._onode(coll, oid)
        if coll not in self._coll_set or on is None:
            raise FileNotFoundError(f"{coll}/{oid}")
        if length is None:
            length = max(0, on.size - offset)
        length = max(0, min(length, on.size - offset))
        if length == 0:
            return b""
        import numpy as np
        lb0, lb1 = offset // BLOCK, (offset + length + BLOCK - 1) // BLOCK
        nblk = lb1 - lb0
        # ONE materialization for the whole extent: device blocks land
        # directly into a (nblk, BLOCK) buffer (contiguous device runs
        # collapse to single preads), and checksum-on-read verifies
        # row views of that SAME buffer in one batched crc32c_rows pass
        # -- the old path built a bytes object per 4 KiB block and
        # re-marshaled them all into the batched CRC call.  Pending-
        # overlay blocks carry this txn's in-memory content and are
        # exempt from verify, as before.
        out = np.zeros(nblk * BLOCK, np.uint8)
        fills: list[tuple[int, int]] = []        # (row, dev) to pread
        for lb in range(lb0, lb1):
            dev = on.blocks.get(lb)
            if dev is None:
                continue                         # hole: stays zeros
            row = lb - lb0
            pend = self._pending.get(dev)
            if pend is not None:
                out[row * BLOCK:(row + 1) * BLOCK] = \
                    np.frombuffer(pend, np.uint8)
                continue
            fills.append((row, dev))
        i = 0
        while i < len(fills):                    # coalesce device runs
            j = i + 1
            while j < len(fills) \
                    and fills[j][0] == fills[j - 1][0] + 1 \
                    and fills[j][1] == fills[j - 1][1] + 1:
                j += 1
            row0, dev0 = fills[i]
            buf = os.pread(self._block_fd, (j - i) * BLOCK,
                           dev0 * BLOCK)
            out[row0 * BLOCK:row0 * BLOCK + len(buf)] = \
                np.frombuffer(buf, np.uint8)     # short read: zeros
            i = j
        rows = out.reshape(nblk, BLOCK)
        verify: list[tuple[int, int, int]] = []  # (row, dev, want)
        for row, dev in fills:
            want = self._get_csum(dev)
            if want is not None:
                verify.append((row, dev, want))
        if verify:
            if len(verify) == nblk:
                crcs = crc32c_rows(rows)
            else:
                crcs = crc32c_rows(
                    rows[np.fromiter((r for r, _, _ in verify),
                                     np.intp, count=len(verify))])
            for (_, dev, want), got in zip(verify, crcs):
                if int(got) != want:
                    raise IOError(
                        f"checksum mismatch on device block {dev}")
        s = offset - lb0 * BLOCK
        return out[s:s + length].tobytes()

    def stat(self, coll, oid):
        with self._txn_lock:
            self._ensure()
            on = self._onode(coll, oid)
            if coll not in self._coll_set or on is None:
                return None
            return {"size": on.size}

    def getattr(self, coll, oid, name):
        with self._txn_lock:
            self._ensure()
            on = self._onode(coll, oid)
            return None if on is None else on.xattrs.get(name)

    def getattrs(self, coll, oid):
        with self._txn_lock:
            self._ensure()
            on = self._onode(coll, oid)
            return {} if on is None else dict(on.xattrs)

    def omap_get(self, coll, oid):
        with self._txn_lock:
            self._ensure()
            return self._omap_get(coll, oid)

    def _omap_get(self, coll, oid):
        key = (coll, oid)
        out: dict[str, bytes] = {}
        if key not in self._om_cleared and key not in self._removed \
                and self.kv is not None:
            base = _mkey(coll, oid)
            for kraw, v in self.kv.get_range(P_OMAP, base,
                                             base + b"\xff"):
                out[kraw[len(base):].decode()] = v
        for k, v in self._om_dirty.get(key, {}).items():
            if v is None:
                out.pop(k, None)
            else:
                out[k] = v
        return out

    def list_collections(self):
        with self._txn_lock:
            self._ensure()
            return sorted(self._coll_set)

    def list_objects(self, coll):
        with self._txn_lock:
            self._ensure()
            return self._list_objects(coll)

    def _list_objects(self, coll):
        names = set()
        if self.kv is not None:
            pref = f"{coll}\x00".encode()
            for kraw, _ in self.kv.get_range(P_ONODE, pref,
                                             pref + b"\xff"):
                names.add(kraw[len(pref):].decode())
        for (c, o), on in self._oncache.items():
            if c == coll and on.dirty:
                names.add(o)
        names -= {o for (c, o) in self._removed if c == coll}
        return sorted(names)

    def list_objects_range(self, coll, begin, limit):
        with self._txn_lock:
            self._ensure()
            names = [o for o in self._list_objects(coll) if o > begin]
            return names[:limit]

    def collection_exists(self, coll):
        with self._txn_lock:
            self._ensure()
            return coll in self._coll_set
