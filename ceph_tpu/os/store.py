"""ObjectStore backends: MemStore (RAM) and DBStore (SQLite WAL).

DBStore plays BlueStore's role at this framework's scale: a single
transactional store with write-ahead logging gives the atomic
data+metadata commit the OSD relies on for log-based recovery
(the reference gets this from RocksDB WAL + deferred writes,
src/os/bluestore/BlueStore.cc:15334 queue_transactions).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Iterable

from .transaction import Transaction


class ObjectStore:
    """Abstract store: collections of objects (data, xattrs, omap)."""

    # device-resident shard cache (os/device_cache.py), attached by the
    # OSD.  EVERY implementation must call _note_txn_for_cache() before
    # applying a transaction: the store boundary is where ALL mutation
    # paths (client writes, recovery pushes, scrub repair, test bit-rot
    # injection) converge, so invalidating here is what makes the cache
    # provably coherent with stored bytes.
    shard_cache = None

    def attach_shard_cache(self, cache) -> None:
        self.shard_cache = cache

    def _note_txn_for_cache(self, txn: Transaction) -> None:
        if self.shard_cache is not None:
            self.shard_cache.note_txn(txn)

    def mount(self) -> None: ...
    def umount(self) -> None: ...

    def queue_transaction(self, txn: Transaction) -> None:
        raise NotImplementedError

    # reads
    def read(self, coll: str, oid: str, offset: int = 0,
             length: int | None = None) -> bytes:
        raise NotImplementedError

    def stat(self, coll: str, oid: str) -> dict | None:
        raise NotImplementedError

    def exists(self, coll: str, oid: str) -> bool:
        return self.stat(coll, oid) is not None

    def getattr(self, coll: str, oid: str, name: str) -> bytes | None:
        raise NotImplementedError

    def getattrs(self, coll: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, coll: str, oid: str) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get_keys(self, coll: str, oid: str,
                      keys: Iterable[str]) -> dict[str, bytes]:
        omap = self.omap_get(coll, oid)
        return {k: omap[k] for k in keys if k in omap}

    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def list_objects(self, coll: str) -> list[str]:
        raise NotImplementedError

    def list_objects_range(self, coll: str, begin: str,
                           limit: int) -> list[str]:
        """Up to ``limit`` object names > ``begin`` in name order.

        Backends override with an indexed scan; the fallback sorts the
        full listing (correct, O(N log N) per page)."""
        names = sorted(o for o in self.list_objects(coll) if o > begin)
        return names[:limit]

    def collection_exists(self, coll: str) -> bool:
        return coll in self.list_collections()


class _MemObject:
    __slots__ = ("data", "xattrs", "omap")

    def __init__(self) -> None:
        self.data = bytearray()
        self.xattrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}

    def clone(self) -> "_MemObject":
        o = _MemObject()
        o.data = bytearray(self.data)
        o.xattrs = dict(self.xattrs)
        o.omap = dict(self.omap)
        return o


class MemStore(ObjectStore):
    def __init__(self) -> None:
        self._colls: dict[str, dict[str, _MemObject]] = {}
        self._lock = threading.Lock()

    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:
            # validate-then-apply gives all-or-nothing on the common
            # failure modes (missing collection); mkcolls earlier in the
            # same txn count
            pending = set(self._colls)
            for op in txn.ops:
                if op.op == "mkcoll":
                    pending.add(op.coll)
                elif op.coll not in pending:
                    raise KeyError(f"no collection {op.coll}")
            self._note_txn_for_cache(txn)
            for op in txn.ops:
                self._apply(op)

    def _obj(self, coll: str, oid: str) -> _MemObject:
        objs = self._colls[coll]
        if oid not in objs:
            objs[oid] = _MemObject()
        return objs[oid]

    def _apply(self, op) -> None:
        if op.op == "mkcoll":
            self._colls.setdefault(op.coll, {})
        elif op.op == "rmcoll":
            self._colls.pop(op.coll, None)
        elif op.op == "touch":
            self._obj(op.coll, op.oid)
        elif op.op == "write":
            o = self._obj(op.coll, op.oid)
            off, data = op.args["offset"], op.args["data"]
            if len(o.data) < off:
                o.data.extend(b"\x00" * (off - len(o.data)))
            o.data[off:off + len(data)] = data
        elif op.op == "zero":
            o = self._obj(op.coll, op.oid)
            off, ln = op.args["offset"], op.args["length"]
            if len(o.data) < off + ln:
                o.data.extend(b"\x00" * (off + ln - len(o.data)))
            o.data[off:off + ln] = b"\x00" * ln
        elif op.op == "truncate":
            o = self._obj(op.coll, op.oid)
            size = op.args["size"]
            if len(o.data) > size:
                del o.data[size:]
            else:
                o.data.extend(b"\x00" * (size - len(o.data)))
        elif op.op == "remove":
            self._colls[op.coll].pop(op.oid, None)
        elif op.op == "clone":
            src = self._colls[op.coll].get(op.oid)
            if src is not None:
                self._colls[op.coll][op.args["dst"]] = src.clone()
        elif op.op == "setattr":
            self._obj(op.coll, op.oid).xattrs[op.args["name"]] = \
                op.args["value"]
        elif op.op == "rmattr":
            self._obj(op.coll, op.oid).xattrs.pop(op.args["name"], None)
        elif op.op == "omap_setkeys":
            self._obj(op.coll, op.oid).omap.update(op.args["kv"])
        elif op.op == "omap_rmkeys":
            o = self._obj(op.coll, op.oid)
            for k in op.args["keys"]:
                o.omap.pop(k, None)
        elif op.op == "omap_clear":
            self._obj(op.coll, op.oid).omap.clear()
        else:
            raise ValueError(f"unknown op {op.op}")

    def read(self, coll, oid, offset=0, length=None):
        from ..common.throttle import injector
        injector.maybe_raise("objectstore_read")   # EIO injection site
        o = self._colls.get(coll, {}).get(oid)
        if o is None:
            raise FileNotFoundError(f"{coll}/{oid}")
        end = len(o.data) if length is None else offset + length
        return bytes(o.data[offset:end])

    def stat(self, coll, oid):
        o = self._colls.get(coll, {}).get(oid)
        if o is None:
            return None
        return {"size": len(o.data)}

    def getattr(self, coll, oid, name):
        o = self._colls.get(coll, {}).get(oid)
        return None if o is None else o.xattrs.get(name)

    def getattrs(self, coll, oid):
        o = self._colls.get(coll, {}).get(oid)
        return {} if o is None else dict(o.xattrs)

    def omap_get(self, coll, oid):
        o = self._colls.get(coll, {}).get(oid)
        return {} if o is None else dict(o.omap)

    def list_collections(self):
        return sorted(self._colls)

    def list_objects(self, coll):
        return sorted(self._colls.get(coll, {}))

    def list_objects_range(self, coll, begin, limit):
        import heapq
        return heapq.nsmallest(
            limit, (o for o in self._colls.get(coll, {}) if o > begin))


class DBStore(ObjectStore):
    """SQLite-WAL-backed store: one DB file per OSD.

    Schema: objects(coll, oid, data BLOB), xattrs, omap -- all mutations
    for one Transaction commit in one SQLite transaction (atomicity =
    crash consistency; WAL mode keeps commits sequential-write-friendly).
    """

    def __init__(self, path: str) -> None:
        self.path = path
        self._local = threading.local()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._init_schema()

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=30)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    def _init_schema(self) -> None:
        conn = self._conn()
        with conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS colls (coll TEXT PRIMARY KEY)")
            conn.execute(
                "CREATE TABLE IF NOT EXISTS objects ("
                "coll TEXT, oid TEXT, data BLOB NOT NULL DEFAULT x'', "
                "PRIMARY KEY (coll, oid))")
            conn.execute(
                "CREATE TABLE IF NOT EXISTS xattrs ("
                "coll TEXT, oid TEXT, name TEXT, value BLOB, "
                "PRIMARY KEY (coll, oid, name))")
            conn.execute(
                "CREATE TABLE IF NOT EXISTS omap ("
                "coll TEXT, oid TEXT, key TEXT, value BLOB, "
                "PRIMARY KEY (coll, oid, key))")

    def queue_transaction(self, txn: Transaction) -> None:
        self._note_txn_for_cache(txn)
        conn = self._conn()
        with conn:
            for op in txn.ops:
                self._apply(conn, op)

    def _get_data(self, conn, coll, oid) -> bytearray | None:
        row = conn.execute(
            "SELECT data FROM objects WHERE coll=? AND oid=?",
            (coll, oid)).fetchone()
        return None if row is None else bytearray(row[0])

    def _put_data(self, conn, coll, oid, data: bytes) -> None:
        conn.execute(
            "INSERT INTO objects (coll, oid, data) VALUES (?,?,?) "
            "ON CONFLICT(coll, oid) DO UPDATE SET data=excluded.data",
            (coll, oid, bytes(data)))

    def _apply(self, conn, op) -> None:
        if op.op == "mkcoll":
            conn.execute("INSERT OR IGNORE INTO colls VALUES (?)", (op.coll,))
            return
        if op.op == "rmcoll":
            conn.execute("DELETE FROM colls WHERE coll=?", (op.coll,))
            for t in ("objects", "xattrs", "omap"):
                conn.execute(f"DELETE FROM {t} WHERE coll=?", (op.coll,))
            return
        row = conn.execute("SELECT 1 FROM colls WHERE coll=?",
                           (op.coll,)).fetchone()
        if row is None:
            raise KeyError(f"no collection {op.coll}")
        if op.op == "touch":
            if self._get_data(conn, op.coll, op.oid) is None:
                self._put_data(conn, op.coll, op.oid, b"")
        elif op.op == "write":
            data = self._get_data(conn, op.coll, op.oid) or bytearray()
            off, buf = op.args["offset"], op.args["data"]
            if len(data) < off:
                data.extend(b"\x00" * (off - len(data)))
            data[off:off + len(buf)] = buf
            self._put_data(conn, op.coll, op.oid, data)
        elif op.op == "zero":
            data = self._get_data(conn, op.coll, op.oid) or bytearray()
            off, ln = op.args["offset"], op.args["length"]
            if len(data) < off + ln:
                data.extend(b"\x00" * (off + ln - len(data)))
            data[off:off + ln] = b"\x00" * ln
            self._put_data(conn, op.coll, op.oid, data)
        elif op.op == "truncate":
            data = self._get_data(conn, op.coll, op.oid) or bytearray()
            size = op.args["size"]
            if len(data) > size:
                del data[size:]
            else:
                data.extend(b"\x00" * (size - len(data)))
            self._put_data(conn, op.coll, op.oid, data)
        elif op.op == "remove":
            conn.execute("DELETE FROM objects WHERE coll=? AND oid=?",
                         (op.coll, op.oid))
            conn.execute("DELETE FROM xattrs WHERE coll=? AND oid=?",
                         (op.coll, op.oid))
            conn.execute("DELETE FROM omap WHERE coll=? AND oid=?",
                         (op.coll, op.oid))
        elif op.op == "clone":
            dst = op.args["dst"]
            data = self._get_data(conn, op.coll, op.oid)
            if data is not None:
                self._put_data(conn, op.coll, dst, data)
                for t in ("xattrs", "omap"):
                    conn.execute(
                        f"DELETE FROM {t} WHERE coll=? AND oid=?",
                        (op.coll, dst))
                conn.execute(
                    "INSERT INTO xattrs SELECT coll, ?, name, value "
                    "FROM xattrs WHERE coll=? AND oid=?",
                    (dst, op.coll, op.oid))
                conn.execute(
                    "INSERT INTO omap SELECT coll, ?, key, value "
                    "FROM omap WHERE coll=? AND oid=?",
                    (dst, op.coll, op.oid))
        elif op.op == "setattr":
            conn.execute(
                "INSERT INTO xattrs VALUES (?,?,?,?) "
                "ON CONFLICT(coll, oid, name) "
                "DO UPDATE SET value=excluded.value",
                (op.coll, op.oid, op.args["name"], op.args["value"]))
        elif op.op == "rmattr":
            conn.execute(
                "DELETE FROM xattrs WHERE coll=? AND oid=? AND name=?",
                (op.coll, op.oid, op.args["name"]))
        elif op.op == "omap_setkeys":
            for k, v in op.args["kv"].items():
                conn.execute(
                    "INSERT INTO omap VALUES (?,?,?,?) "
                    "ON CONFLICT(coll, oid, key) "
                    "DO UPDATE SET value=excluded.value",
                    (op.coll, op.oid, k, v))
        elif op.op == "omap_rmkeys":
            for k in op.args["keys"]:
                conn.execute(
                    "DELETE FROM omap WHERE coll=? AND oid=? AND key=?",
                    (op.coll, op.oid, k))
        elif op.op == "omap_clear":
            conn.execute("DELETE FROM omap WHERE coll=? AND oid=?",
                         (op.coll, op.oid))
        else:
            raise ValueError(f"unknown op {op.op}")

    def read(self, coll, oid, offset=0, length=None):
        from ..common.throttle import injector
        injector.maybe_raise("objectstore_read")   # EIO injection site
        data = self._get_data(self._conn(), coll, oid)
        if data is None:
            raise FileNotFoundError(f"{coll}/{oid}")
        end = len(data) if length is None else offset + length
        return bytes(data[offset:end])

    def stat(self, coll, oid):
        row = self._conn().execute(
            "SELECT length(data) FROM objects WHERE coll=? AND oid=?",
            (coll, oid)).fetchone()
        return None if row is None else {"size": row[0]}

    def getattr(self, coll, oid, name):
        row = self._conn().execute(
            "SELECT value FROM xattrs WHERE coll=? AND oid=? AND name=?",
            (coll, oid, name)).fetchone()
        return None if row is None else row[0]

    def getattrs(self, coll, oid):
        rows = self._conn().execute(
            "SELECT name, value FROM xattrs WHERE coll=? AND oid=?",
            (coll, oid)).fetchall()
        return {r[0]: r[1] for r in rows}

    def omap_get(self, coll, oid):
        rows = self._conn().execute(
            "SELECT key, value FROM omap WHERE coll=? AND oid=?",
            (coll, oid)).fetchall()
        return {r[0]: r[1] for r in rows}

    def list_collections(self):
        return [r[0] for r in self._conn().execute(
            "SELECT coll FROM colls ORDER BY coll")]

    def list_objects(self, coll):
        return [r[0] for r in self._conn().execute(
            "SELECT oid FROM objects WHERE coll=? ORDER BY oid", (coll,))]

    def list_objects_range(self, coll, begin, limit):
        return [r[0] for r in self._conn().execute(
            "SELECT oid FROM objects WHERE coll=? AND oid>? "
            "ORDER BY oid LIMIT ?", (coll, begin, limit))]


def make_default_store():
    """Store factory for daemons booted without an explicit store.

    CEPH_TPU_STORE selects the backend: "mem" (default),
    "block" (BlockStore in a fresh directory under
    $CEPH_TPU_STORE_DIR or /tmp), or "block:<dir>" (that directory --
    a restart on the same dir remounts the same data)."""
    import os as _os
    spec = _os.environ.get("CEPH_TPU_STORE", "mem")
    if spec == "mem":
        return MemStore()
    if spec == "block" or spec.startswith("block:"):
        from .blockstore import BlockStore
        _, _, path = spec.partition(":")
        if not path:
            import tempfile
            base = _os.environ.get("CEPH_TPU_STORE_DIR", "/tmp")
            path = tempfile.mkdtemp(prefix="ceph_tpu_bs_", dir=base)
        return BlockStore(path)
    raise ValueError(f"unknown CEPH_TPU_STORE {spec!r}")
