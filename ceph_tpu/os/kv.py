"""KeyValueDB: the ordered-KV abstraction behind the object stores.

src/kv/KeyValueDB.h role: stores talk to an interface (get / ordered
iteration / atomic write batches over prefixed namespaces), never to a
concrete engine.  The reference ships RocksDB behind it; here the
default engine is sqlite (baked into the image) with an in-memory
engine for tests -- and the contract is narrow enough that a RocksDB
or LMDB engine drops in without touching the stores.

Prefixes partition the keyspace the way the reference's column-family
prefixes do (BlueStore's O/ M / C namespaces).  Keys are bytes and
iterate in lexicographic order within a prefix.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Iterator


class KVTransaction:
    """An atomic write batch (KeyValueDB::Transaction).  Ops apply in
    order; the whole batch commits or none of it does."""

    def __init__(self) -> None:
        self.ops: list[tuple] = []

    def set(self, prefix: str, key: bytes, value: bytes) -> "KVTransaction":
        self.ops.append(("set", prefix, bytes(key), bytes(value)))
        return self

    def rm(self, prefix: str, key: bytes) -> "KVTransaction":
        self.ops.append(("rm", prefix, bytes(key)))
        return self

    def rm_range(self, prefix: str, start: bytes,
                 end: bytes | None) -> "KVTransaction":
        """Remove [start, end) within prefix; end=None means to the
        prefix's end."""
        self.ops.append(("rm_range", prefix, bytes(start),
                         None if end is None else bytes(end)))
        return self


class KeyValueDB:
    """Engine interface.  All methods are thread-safe per engine."""

    def get(self, prefix: str, key: bytes) -> bytes | None:
        raise NotImplementedError

    def get_range(self, prefix: str, start: bytes = b"",
                  end: bytes | None = None,
                  limit: int | None = None
                  ) -> Iterator[tuple[bytes, bytes]]:
        """Ordered iteration over [start, end) within prefix."""
        raise NotImplementedError

    def transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit(self, txn: KVTransaction, sync: bool = True) -> None:
        """Apply the batch atomically; sync=True means durable on
        return (the kv_sync_thread contract)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemKVDB(KeyValueDB):
    """Ordered in-memory engine (tests / MemStore)."""

    def __init__(self) -> None:
        self._data: dict[str, dict[bytes, bytes]] = {}
        self._lock = threading.Lock()

    def get(self, prefix, key):
        with self._lock:
            return self._data.get(prefix, {}).get(bytes(key))

    def get_range(self, prefix, start=b"", end=None, limit=None):
        with self._lock:
            keys = sorted(k for k in self._data.get(prefix, {})
                          if k >= start and (end is None or k < end))
            if limit is not None:
                keys = keys[:limit]
            items = [(k, self._data[prefix][k]) for k in keys]
        yield from items

    def submit(self, txn, sync=True):
        with self._lock:
            for op in txn.ops:
                if op[0] == "set":
                    self._data.setdefault(op[1], {})[op[2]] = op[3]
                elif op[0] == "rm":
                    self._data.get(op[1], {}).pop(op[2], None)
                elif op[0] == "rm_range":
                    d = self._data.get(op[1], {})
                    for k in [k for k in d
                              if k >= op[2] and (op[3] is None
                                                 or k < op[3])]:
                        del d[k]


class SqliteKVDB(KeyValueDB):
    """sqlite engine: one table, (prefix, key) primary key, WAL mode.

    The BlueStore checkpoint path calls submit(sync=True) rarely and
    in large batches, which is exactly the shape sqlite's WAL likes.
    """

    def __init__(self, path: str) -> None:
        self.path = path
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        self._local = threading.local()
        conn = self._conn()
        with conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                "prefix TEXT NOT NULL, key BLOB NOT NULL, "
                "value BLOB NOT NULL, PRIMARY KEY (prefix, key)) "
                "WITHOUT ROWID")

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=30)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=FULL")
            self._local.conn = conn
        return conn

    def get(self, prefix, key):
        row = self._conn().execute(
            "SELECT value FROM kv WHERE prefix=? AND key=?",
            (prefix, bytes(key))).fetchone()
        return None if row is None else row[0]

    def get_range(self, prefix, start=b"", end=None, limit=None):
        q = "SELECT key, value FROM kv WHERE prefix=? AND key>=?"
        args: list = [prefix, bytes(start)]
        if end is not None:
            q += " AND key<?"
            args.append(bytes(end))
        q += " ORDER BY key"
        if limit is not None:
            q += " LIMIT ?"
            args.append(limit)
        cur = self._conn().execute(q, args)
        while True:
            rows = cur.fetchmany(256)
            if not rows:
                return
            yield from rows

    def submit(self, txn, sync=True):
        conn = self._conn()
        with conn:
            for op in txn.ops:
                if op[0] == "set":
                    conn.execute(
                        "INSERT OR REPLACE INTO kv VALUES (?,?,?)",
                        (op[1], op[2], op[3]))
                elif op[0] == "rm":
                    conn.execute(
                        "DELETE FROM kv WHERE prefix=? AND key=?",
                        (op[1], op[2]))
                elif op[0] == "rm_range":
                    if op[3] is None:
                        conn.execute(
                            "DELETE FROM kv WHERE prefix=? AND key>=?",
                            (op[1], op[2]))
                    else:
                        conn.execute(
                            "DELETE FROM kv WHERE prefix=? AND "
                            "key>=? AND key<?", (op[1], op[2], op[3]))

    def close(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
