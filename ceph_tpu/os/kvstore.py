"""KVStore: an ObjectStore kept entirely in a KeyValueDB.

The kstore analog (src/os/kstore/KStore.cc): every object -- data,
xattrs, omap -- lives as rows in the ordered KV behind the KeyValueDB
interface (os/kv.py), and each Transaction becomes ONE atomic KV batch
(atomicity = crash consistency, no separate WAL needed).  Not the
performance store (BlockStore is); it exists because a pure-KV engine
is the simplest correct store and exercises the same KeyValueDB
contract a RocksDB engine would.

Data layout: object payload is chunked into fixed KV rows so partial
writes rewrite only the touched stripes (KStore's stripe_size).
"""

from __future__ import annotations

import struct
import threading

from .kv import KeyValueDB, MemKVDB, SqliteKVDB
from .store import ObjectStore
from .transaction import Transaction

STRIPE = 65536            # kstore stripe_size: data row granularity

P_DATA = "D"              # c\0o\0u64be(stripe) -> bytes
P_META = "O"              # c\0o -> size u64le
P_XATTR = "X"             # c\0o\0name -> bytes
P_OMAP = "M"              # c\0o\0key -> bytes
P_COLL = "L"              # coll -> b""


def _k(c: str, o: str, tail: bytes = b"") -> bytes:
    base = f"{c}\x00{o}".encode()
    return base + (b"\x00" + tail if tail else b"")


def _stripe_key(c: str, o: str, idx: int) -> bytes:
    return _k(c, o, struct.pack(">Q", idx))


class KVStore(ObjectStore):
    def __init__(self, path: str | None = None,
                 kv: KeyValueDB | None = None) -> None:
        if kv is not None:
            self.kv = kv
        elif path is None or path == ":memory:":
            self.kv = MemKVDB()
        else:
            self.kv = SqliteKVDB(path)
        self._lock = threading.Lock()

    def mount(self) -> None:
        pass

    def umount(self) -> None:
        self.kv.close()

    # -- transactions --------------------------------------------------------
    def queue_transaction(self, txn: Transaction) -> None:
        with self._lock:
            colls = {k.decode()
                     for k, _ in self.kv.get_range(P_COLL)}
            for op in txn.ops:
                if op.op == "mkcoll":
                    colls.add(op.coll)
                elif op.coll not in colls:
                    raise KeyError(f"no collection {op.coll}")
            kvt = self.kv.transaction()
            for op in txn.ops:
                self._apply(kvt, op)
            self.kv.submit(kvt, sync=True)

    def _size(self, c: str, o: str) -> int | None:
        raw = self.kv.get(P_META, _k(c, o))
        return None if raw is None else struct.unpack("<Q", raw)[0]

    def _size_in(self, kvt, c: str, o: str) -> int | None:
        """Size as seen by the txn so far: later ops in one batch must
        observe earlier staged writes, not just the committed KV."""
        key = _k(c, o)
        for op in reversed(kvt.ops):
            if op[1] != P_META:
                continue
            if op[0] == "set" and op[2] == key:
                return struct.unpack("<Q", op[3])[0]
            if op[0] == "rm" and op[2] == key:
                return None
        return self._size(c, o)

    def _merged_range(self, kvt, prefix: str, start: bytes,
                      end: bytes) -> dict[bytes, bytes]:
        """Committed rows in [start, end) with the batch's staged ops
        applied in order (set/rm/rm_range)."""
        out = dict(self.kv.get_range(prefix, start, end))
        for op in kvt.ops:
            if op[1] != prefix:
                continue
            if op[0] == "set" and start <= op[2] < end:
                out[op[2]] = op[3]
            elif op[0] == "rm" and start <= op[2] < end:
                out.pop(op[2], None)
            elif op[0] == "rm_range":
                for k in [k for k in out
                          if k >= op[2] and (op[3] is None
                                             or k < op[3])]:
                    del out[k]
        return out

    def _set_size(self, kvt, c: str, o: str, size: int) -> None:
        kvt.set(P_META, _k(c, o), struct.pack("<Q", size))

    def _rm_object(self, kvt, c: str, o: str) -> None:
        kvt.rm(P_META, _k(c, o))
        for pref in (P_DATA, P_XATTR, P_OMAP):
            kvt.rm_range(pref, _k(c, o) + b"\x00",
                         _k(c, o) + b"\x00\xff")

    def _read_stripe(self, c: str, o: str, idx: int) -> bytes:
        raw = self.kv.get(P_DATA, _stripe_key(c, o, idx))
        return raw if raw is not None else b""

    def _apply(self, kvt, op) -> None:
        c, o, a = op.coll, op.oid, op.args
        if op.op == "mkcoll":
            kvt.set(P_COLL, c.encode(), b"")
        elif op.op == "rmcoll":
            pref = f"{c}\x00".encode()
            for k in self._merged_range(kvt, P_META, pref,
                                        pref + b"\xff"):
                self._rm_object(kvt, c, k[len(pref):].decode())
            kvt.rm(P_COLL, c.encode())
        elif op.op == "touch":
            if self._size_in(kvt, c, o) is None:
                self._set_size(kvt, c, o, 0)
        elif op.op == "write":
            self._write(kvt, c, o, a["offset"], a["data"])
        elif op.op == "zero":
            self._write(kvt, c, o, a["offset"],
                        b"\x00" * a["length"])
        elif op.op == "truncate":
            size = a["size"]
            old = self._size_in(kvt, c, o) or 0
            first_dead = (size + STRIPE - 1) // STRIPE
            kvt.rm_range(P_DATA, _stripe_key(c, o, first_dead),
                         _k(c, o) + b"\x00\xff")
            if size % STRIPE and size < old:
                idx = size // STRIPE
                key = _stripe_key(c, o, idx)
                st = self._merged_range(kvt, P_DATA, key,
                                        key + b"\x00").get(key, b"")
                kvt.set(P_DATA, key, st[:size % STRIPE])
            self._set_size(kvt, c, o, size)
        elif op.op == "remove":
            self._rm_object(kvt, c, o)
        elif op.op == "clone":
            dst = a["dst"]
            src_size = self._size_in(kvt, c, o)
            if src_size is None:
                return
            self._rm_object(kvt, c, dst)
            for pref in (P_DATA, P_XATTR, P_OMAP):
                base = _k(c, o) + b"\x00"
                for k, v in self._merged_range(
                        kvt, pref, base, base + b"\xff").items():
                    kvt.set(pref, _k(c, dst) + b"\x00"
                            + k[len(base):], v)
            self._set_size(kvt, c, dst, src_size)
        elif op.op == "setattr":
            if self._size_in(kvt, c, o) is None:
                self._set_size(kvt, c, o, 0)
            kvt.set(P_XATTR, _k(c, o, a["name"].encode()), a["value"])
        elif op.op == "rmattr":
            kvt.rm(P_XATTR, _k(c, o, a["name"].encode()))
        elif op.op == "omap_setkeys":
            if self._size_in(kvt, c, o) is None:
                self._set_size(kvt, c, o, 0)
            for k, v in a["kv"].items():
                kvt.set(P_OMAP, _k(c, o, k.encode()), v)
        elif op.op == "omap_rmkeys":
            for k in a["keys"]:
                kvt.rm(P_OMAP, _k(c, o, k.encode()))
        elif op.op == "omap_clear":
            kvt.rm_range(P_OMAP, _k(c, o) + b"\x00",
                         _k(c, o) + b"\x00\xff")
        else:
            raise ValueError(f"unknown op {op.op}")

    def _write(self, kvt, c: str, o: str, offset: int,
               data: bytes) -> None:
        end = offset + len(data)
        i0, i1 = offset // STRIPE, (end + STRIPE - 1) // STRIPE
        # batch-local overlay: two writes to one stripe in a single
        # txn must compose (the second reads the first's bytes, which
        # are not in the KV yet); bounded to the TOUCHED stripes, not
        # the whole object
        staged = self._merged_range(kvt, P_DATA,
                                    _stripe_key(c, o, i0),
                                    _stripe_key(c, o, i1))
        for i in range(i0, i1):
            base_off = i * STRIPE
            s = max(offset, base_off) - base_off
            e = min(end, base_off + STRIPE) - base_off
            key = _stripe_key(c, o, i)
            prev = staged.get(key)
            if prev is None:
                prev = self._read_stripe(c, o, i)
            st = bytearray(prev.ljust(e, b"\x00"))
            st[s:e] = data[max(offset, base_off) - offset:
                           min(end, base_off + STRIPE) - offset]
            kvt.set(P_DATA, key, bytes(st))
        old = self._size_in(kvt, c, o) or 0
        self._set_size(kvt, c, o, max(old, end))

    # -- reads ----------------------------------------------------------------
    def read(self, coll, oid, offset=0, length=None):
        from ..common.throttle import injector
        injector.maybe_raise("objectstore_read")   # EIO injection site
        size = self._size(coll, oid)
        if size is None:
            raise FileNotFoundError(f"{coll}/{oid}")
        if length is None:
            length = max(0, size - offset)
        length = max(0, min(length, size - offset))
        if length == 0:
            return b""
        out = bytearray()
        i0, i1 = offset // STRIPE, (offset + length + STRIPE - 1) // STRIPE
        for i in range(i0, i1):
            out += self._read_stripe(coll, oid, i).ljust(STRIPE, b"\x00")
        s = offset - i0 * STRIPE
        return bytes(out[s:s + length])

    def stat(self, coll, oid):
        size = self._size(coll, oid)
        return None if size is None else {"size": size}

    def getattr(self, coll, oid, name):
        return self.kv.get(P_XATTR, _k(coll, oid, name.encode()))

    def getattrs(self, coll, oid):
        base = _k(coll, oid) + b"\x00"
        return {k[len(base):].decode(): v
                for k, v in self.kv.get_range(P_XATTR, base,
                                              base + b"\xff")}

    def omap_get(self, coll, oid):
        base = _k(coll, oid) + b"\x00"
        return {k[len(base):].decode(): v
                for k, v in self.kv.get_range(P_OMAP, base,
                                              base + b"\xff")}

    def list_collections(self):
        return sorted(k.decode() for k, _ in self.kv.get_range(P_COLL))

    def list_objects(self, coll):
        pref = f"{coll}\x00".encode()
        return sorted(k[len(pref):].decode()
                      for k, _ in self.kv.get_range(P_META, pref,
                                                    pref + b"\xff"))

    def list_objects_range(self, coll, begin, limit):
        names = [o for o in self.list_objects(coll) if o > begin]
        return names[:limit]

    def collection_exists(self, coll):
        return self.kv.get(P_COLL, coll.encode()) is not None
