"""Compressor plugins (src/compressor analog).

Same registry pattern as the erasure-code plugins (dlopen == module
import): ``Compressor.create(name)`` returns a codec with
compress/decompress, used standalone, by the messenger's on-wire
compression (msg/messenger.py), and available to stores.  Backends
map to what the runtime ships: zlib/zstd/lzma/bz2 (snappy and lz4
have no bundled python module and are gated with a clear error, the
way the reference fails a missing plugin .so).
"""

from __future__ import annotations

import bz2
import lzma
import zlib


class CompressorError(Exception):
    pass


class Compressor:
    """Base: subclasses define _compress/_decompress and name."""

    name = ""

    def compress(self, data: bytes) -> bytes:
        return self._compress(bytes(data))

    def decompress(self, data: bytes,
                   max_length: int | None = None) -> bytes:
        """``max_length`` bounds the materialized output: a crafted
        frame claiming a small raw size must fail BEFORE expanding to
        gigabytes (decompression bomb), not after."""
        try:
            if max_length is None:
                return self._decompress(bytes(data))
            out = self._decompress_bounded(bytes(data), max_length + 1)
        except CompressorError:
            raise
        except Exception as e:
            raise CompressorError(
                f"{self.name}: corrupt input: {e}") from e
        if len(out) > max_length:
            raise CompressorError(
                f"{self.name}: output exceeds declared size "
                f"{max_length}")
        return out

    def _decompress_bounded(self, data: bytes, cap: int) -> bytes:
        """Incremental decompress producing at most ``cap`` bytes."""
        raise NotImplementedError

    @staticmethod
    def create(name: str, **kw) -> "Compressor":
        cls = _PLUGINS.get(name)
        if cls is None:
            if name in ("snappy", "lz4", "zstd"):
                raise CompressorError(
                    f"compressor plugin {name}: backend library not "
                    f"available in this runtime "
                    f"(have {sorted(_PLUGINS)})")
            raise CompressorError(f"unknown compressor {name}")
        return cls(**kw)

    @staticmethod
    def available() -> list[str]:
        return sorted(_PLUGINS)


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 5) -> None:
        self.level = level

    def _compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def _decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)

    def _decompress_bounded(self, data: bytes, cap: int) -> bytes:
        return zlib.decompressobj().decompress(data, cap)


try:
    import zstandard as _zstandard
except ImportError:        # registry gates the plugin cleanly below
    _zstandard = None


class ZstdCompressor(Compressor):
    name = "zstd"

    def __init__(self, level: int = 3) -> None:
        if _zstandard is None:
            raise CompressorError(
                "compressor plugin zstd: zstandard not installed")
        self._c = _zstandard.ZstdCompressor(level=level)
        self._d = _zstandard.ZstdDecompressor()

    def _compress(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def _decompress(self, data: bytes) -> bytes:
        return self._d.decompress(data)

    def _decompress_bounded(self, data: bytes, cap: int) -> bytes:
        # max_output_size is IGNORED when the frame header embeds a
        # content size (attacker-controlled), so the one-shot API can
        # still materialize a bomb; the stream reader honors the read
        # bound unconditionally
        import io
        out = bytearray()
        with self._d.stream_reader(io.BytesIO(data)) as r:
            while len(out) < cap:
                chunk = r.read(cap - len(out))
                if not chunk:
                    break
                out += chunk
        return bytes(out)


class LzmaCompressor(Compressor):
    name = "lzma"

    def __init__(self, preset: int = 1) -> None:
        self.preset = preset

    def _compress(self, data: bytes) -> bytes:
        return lzma.compress(data, preset=self.preset)

    def _decompress(self, data: bytes) -> bytes:
        return lzma.decompress(data)

    def _decompress_bounded(self, data: bytes, cap: int) -> bytes:
        return lzma.LZMADecompressor().decompress(data, cap)


class Bz2Compressor(Compressor):
    name = "bz2"

    def __init__(self, level: int = 5) -> None:
        self.level = level

    def _compress(self, data: bytes) -> bytes:
        return bz2.compress(data, self.level)

    def _decompress(self, data: bytes) -> bytes:
        return bz2.decompress(data)

    def _decompress_bounded(self, data: bytes, cap: int) -> bytes:
        return bz2.BZ2Decompressor().decompress(data, cap)


_PLUGINS = {c.name: c for c in (ZlibCompressor, LzmaCompressor,
                                Bz2Compressor)}
if _zstandard is not None:
    _PLUGINS["zstd"] = ZstdCompressor

__all__ = ["Compressor", "CompressorError"]
