"""Compressor plugins (src/compressor analog).

Same registry pattern as the erasure-code plugins (dlopen == module
import): ``Compressor.create(name)`` returns a codec with
compress/decompress, used standalone, by the messenger's on-wire
compression (msg/messenger.py), and available to stores.  Backends
map to what the runtime ships: zlib/zstd/lzma/bz2 (snappy and lz4
have no bundled python module and are gated with a clear error, the
way the reference fails a missing plugin .so).
"""

from __future__ import annotations

import bz2
import lzma
import zlib


class CompressorError(Exception):
    pass


class Compressor:
    """Base: subclasses define _compress/_decompress and name."""

    name = ""

    def compress(self, data: bytes) -> bytes:
        return self._compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        try:
            return self._decompress(bytes(data))
        except Exception as e:
            raise CompressorError(
                f"{self.name}: corrupt input: {e}") from e

    @staticmethod
    def create(name: str, **kw) -> "Compressor":
        cls = _PLUGINS.get(name)
        if cls is None:
            if name in ("snappy", "lz4", "zstd"):
                raise CompressorError(
                    f"compressor plugin {name}: backend library not "
                    f"available in this runtime "
                    f"(have {sorted(_PLUGINS)})")
            raise CompressorError(f"unknown compressor {name}")
        return cls(**kw)

    @staticmethod
    def available() -> list[str]:
        return sorted(_PLUGINS)


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 5) -> None:
        self.level = level

    def _compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def _decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)


try:
    import zstandard as _zstandard
except ImportError:        # registry gates the plugin cleanly below
    _zstandard = None


class ZstdCompressor(Compressor):
    name = "zstd"

    def __init__(self, level: int = 3) -> None:
        if _zstandard is None:
            raise CompressorError(
                "compressor plugin zstd: zstandard not installed")
        self._c = _zstandard.ZstdCompressor(level=level)
        self._d = _zstandard.ZstdDecompressor()

    def _compress(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def _decompress(self, data: bytes) -> bytes:
        return self._d.decompress(data)


class LzmaCompressor(Compressor):
    name = "lzma"

    def __init__(self, preset: int = 1) -> None:
        self.preset = preset

    def _compress(self, data: bytes) -> bytes:
        return lzma.compress(data, preset=self.preset)

    def _decompress(self, data: bytes) -> bytes:
        return lzma.decompress(data)


class Bz2Compressor(Compressor):
    name = "bz2"

    def __init__(self, level: int = 5) -> None:
        self.level = level

    def _compress(self, data: bytes) -> bytes:
        return bz2.compress(data, self.level)

    def _decompress(self, data: bytes) -> bytes:
        return bz2.decompress(data)


_PLUGINS = {c.name: c for c in (ZlibCompressor, LzmaCompressor,
                                Bz2Compressor)}
if _zstandard is not None:
    _PLUGINS["zstd"] = ZstdCompressor

__all__ = ["Compressor", "CompressorError"]
