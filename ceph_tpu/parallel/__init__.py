"""Device-mesh parallelism: sharded erasure coding and bulk placement.

The reference moves chunk shards between OSD processes over its
AsyncMessenger (SURVEY.md section 2.8); on TPU the same dataflow is XLA
collectives over ICI: stripe batches shard across a 'stripe' (data) axis,
the k+m chunk shards map onto a 'shard' axis, and parity assembly is an
all_gather/psum instead of a message fan-out.
"""

from .sharded_ec import (  # noqa: F401
    lrc_make_mesh,
    lrc_sharded_encode,
    lrc_sharded_local_repair,
    make_data_mesh,
    make_mesh,
    sharded_cross_recovery,
    sharded_encode,
    sharded_ec_step,
    sharded_rmw,
)


def __getattr__(name):
    # MeshCodec lazily: importing ceph_tpu.parallel must not force the
    # jax.sharding stack onto daemons that never take an EC launch
    if name in ("MeshCodec", "clear_mesh_cache"):
        from . import mesh_codec
        return getattr(mesh_codec, name)
    raise AttributeError(name)
