"""MeshCodec: the multichip mesh as a live OSD codec engine.

MULTICHIP_r05 proved the sharded dry runs (parallel/sharded_ec.py) do
sharded encode, LRC local repair and delta-encoded partial-stripe RMW
byte-exact over an 8-device mesh -- but nothing in the OSD path called
them.  This module is the promotion: a shard_map-compiled launch
family the per-OSD CodecBatcher feeds its coalesced stripe batches,
so one launch encodes the batches of many PGs across every chip in
the slice ("a rack of OSDs per TPU slice").

Shape of the thing:

  * the stripe-batch axis partitions across all visible devices via a
    1-D ('stripe',) Mesh + NamedSharding -- stripes are independent,
    so the per-device block needs NO collective (unlike the dry-run's
    (stripe, shard) mesh, whose all_gather pays an ICI hop the data
    plane does not need);
  * launches compile ONCE per (matrix, B, k, L, crc) family and the
    compiled executables are cached PROCESS-WIDE keyed by the mesh --
    every OSD of an in-process cluster shares one compile (the same
    lesson as the VectorCrush digest cache);
  * the fused CRC32C side-path (ops/crc32c_batch.crc32c_chunks_traced)
    rides inside the same jitted program, so chunk checksums come back
    from the one device round trip that produced the parity;
  * stripe buffers are DONATED (``donate_argnums``): the launch owns
    the device copy of the input batch -- callers must never read it
    again (the donated-buffer-aliasing lint rule), XLA may free or
    reuse it instead of keeping it alive for a defensive copy, and the
    RMW delta path genuinely ALIASES the old-parity buffer in place
    (shapes match, pinned by test_mesh_codec) -- writes stop paying
    the keep-both-copies host<->device discipline;
  * single-device is just a 1-device mesh: the CPU tier-1 suite runs
    the identical partitioned program, and
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` runs the
    real 8-way SPMD program on CPU (tests/test_mesh_codec.py,
    ``bench.py --osd-path --mesh --smoke``).

Config is SNAPSHOT at construction (CodecBatcher.from_config): the
mesh never holds a config object and no ``conf.get`` runs inside the
launch loop (pinned by the test_mesh_codec micro-assertion).
"""

from __future__ import annotations

import functools
import warnings

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharded_ec import _gf_matmul_bits, make_data_mesh
from ..ops.gf2kernels import bitmatrix_i8, bucket_batch

try:                                   # jax >= 0.5 top-level export
    from jax import shard_map
except ImportError:                    # 0.4.x keeps it experimental
    from jax.experimental.shard_map import shard_map

# encode (B,k,L)->(B,m,L) and decode (B,k,L)->(B,r,L) donate a buffer
# whose shape matches no output; XLA then frees it early instead of
# aliasing and jax warns that the donation "was not usable".  The early
# free is exactly what we want (no defensive copy, no double-residency
# of the batch), so the advisory warning is noise on this path.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


@functools.lru_cache(maxsize=8)
def _shared_mesh(n_devices: int) -> Mesh:
    """One Mesh instance per device count, shared process-wide so the
    compiled-executable caches below hit across every MeshCodec (and
    therefore every OSD) in the process."""
    return make_data_mesh(n_devices or None)


@functools.lru_cache(maxsize=256)
def _w_device(mesh: Mesh, mat_bytes: bytes, r: int, k: int):
    """Replicated device-resident bit-matrix: one upload per
    (mesh, coefficient matrix), ever."""
    mat = np.frombuffer(mat_bytes, np.uint8).reshape(r, k)
    return jax.device_put(bitmatrix_i8(mat),
                          NamedSharding(mesh, P(None, None)))


def _stripe_block(w_local, chunks):
    """Per-device block: my slice of the stripe batch through the GF
    bit-matmul.  No collective -- stripes are independent."""
    bl, kk, ll = chunks.shape
    flat = chunks.transpose(1, 0, 2).reshape(kk, bl * ll)
    rows = _gf_matmul_bits(w_local, flat)
    return rows.reshape(-1, bl, ll).transpose(1, 0, 2)


@functools.lru_cache(maxsize=512)
def _compiled_apply(mesh: Mesh, b: int, k: int, lane: int,
                    with_crc: bool, donate: bool):
    """One launch: (8r,8k) W x (B,k,L) stripes -> (B,r,L) [+ chunk
    CRCs].  The batch axis shards over 'stripe'; W replicates.  The
    stripe buffer (arg 1) is donated -- consumed by the launch, never
    read again (the donated-buffer-aliasing lint rule guards callers).
    """
    sharded = shard_map(
        _stripe_block, mesh=mesh,
        in_specs=(P(None, None), P("stripe", None, None)),
        out_specs=P("stripe", None, None))
    if not with_crc:
        return jax.jit(sharded, donate_argnums=(1,) if donate else ())

    def fn(w, data):
        from ..ops.crc32c_batch import crc32c_chunks_traced
        parity = sharded(w, data)
        crcs = jnp.concatenate([crc32c_chunks_traced(data),
                                crc32c_chunks_traced(parity)], axis=1)
        return parity, crcs

    return jax.jit(fn, donate_argnums=(1,) if donate else ())


@functools.lru_cache(maxsize=256)
def _compiled_apply_sched(mesh: Mesh, digest: str, b: int, k: int,
                          lane: int, with_crc: bool, donate: bool):
    """The scheduled twin of ``_compiled_apply``: the CSE-minimized
    XOR schedule (ops/xor_schedule.py, looked up by matrix digest) is
    BAKED into the program instead of taking W as an operand, so the
    executable cache keys on the digest.  Same sharding, same fused
    CRC side-path, same donation contract: the stripe buffer (arg 0)
    is donated -- consumed by the launch, never read again."""
    from ..ops.xor_schedule import apply_bits_traced, registered
    sched = registered(digest)

    def block(chunks):
        bl, kk, ll = chunks.shape
        flat = chunks.transpose(1, 0, 2).reshape(kk, bl * ll)
        rows = apply_bits_traced(sched, flat)
        return rows.reshape(-1, bl, ll).transpose(1, 0, 2)

    sharded = shard_map(
        block, mesh=mesh,
        in_specs=(P("stripe", None, None),),
        out_specs=P("stripe", None, None))
    if not with_crc:
        return jax.jit(sharded, donate_argnums=(0,) if donate else ())

    def fn(data):
        from ..ops.crc32c_batch import crc32c_chunks_traced
        parity = sharded(data)
        crcs = jnp.concatenate([crc32c_chunks_traced(data),
                                crc32c_chunks_traced(parity)], axis=1)
        return parity, crcs

    return jax.jit(fn, donate_argnums=(0,) if donate else ())


@functools.lru_cache(maxsize=256)
def _compiled_rmw_sched(mesh: Mesh, digest: str, b: int, m: int,
                        k: int, lane: int, donate: bool):
    """Scheduled RMW: new_parity = old_parity XOR schedule(delta) in
    one launch, old-parity donated and ALIASED in place exactly like
    the dense ``_compiled_rmw`` (shapes match)."""
    from ..ops.xor_schedule import apply_bits_traced, registered
    sched = registered(digest)

    def block(oldp, delta):
        bl, kk, ll = delta.shape
        flat = delta.transpose(1, 0, 2).reshape(kk, bl * ll)
        rows = apply_bits_traced(sched, flat)
        return jnp.bitwise_xor(
            oldp, rows.reshape(-1, bl, ll).transpose(1, 0, 2))

    sharded = shard_map(
        block, mesh=mesh,
        in_specs=(P("stripe", None, None), P("stripe", None, None)),
        out_specs=P("stripe", None, None))
    return jax.jit(sharded,
                   donate_argnums=(0, 1) if donate else ())


@functools.lru_cache(maxsize=256)
def _compiled_rmw(mesh: Mesh, b: int, m: int, k: int, lane: int,
                  donate: bool):
    """Delta-encoded partial-stripe RMW in one launch: new_parity =
    old_parity XOR encode(delta) (GF linearity; the sharded rendering
    of ECCommon.cc:704's pipeline).  old_parity (arg 1) is donated and
    ALIASES the output buffer -- shapes match, so the update is truly
    in place on device."""
    def block(w_local, oldp, delta):
        return jnp.bitwise_xor(oldp, _stripe_block(w_local, delta))

    sharded = shard_map(
        block, mesh=mesh,
        in_specs=(P(None, None), P("stripe", None, None),
                  P("stripe", None, None)),
        out_specs=P("stripe", None, None))
    return jax.jit(sharded,
                   donate_argnums=(1, 2) if donate else ())


@functools.lru_cache(maxsize=256)
def _decode_matrix_cached(mat_bytes: bytes, rows: int, k_total: int,
                          k: int, erasures: tuple) -> np.ndarray:
    """build_decode_matrix product for codecs without their own
    DecodeTableCache; same construction as the tpu plugin's, so the
    mesh decode is byte-identical to decode_batch."""
    from ..gf import build_decode_matrix
    enc = np.frombuffer(mat_bytes, np.uint8).reshape(rows, k_total)
    matrix, _ = build_decode_matrix(enc, k, list(erasures))
    return matrix


def clear_mesh_cache() -> None:
    for fn in (_shared_mesh, _w_device, _compiled_apply, _compiled_rmw,
               _compiled_apply_sched, _compiled_rmw_sched,
               _decode_matrix_cached):
        fn.cache_clear()


class MeshCodec:
    """A multi-chip slice presented as one giant erasure codec.

    ``encode``/``decode``/``rmw`` each run EXACTLY ONE device launch
    for a whole (B, k, L) stripe batch, partitioned over every mesh
    device, byte-identical to the per-stripe host codec.  B must be a
    multiple of the device count -- ``pad_batch`` gives the bucketed
    size the CodecBatcher pads to.
    """

    def __init__(self, n_devices: int = 0, donate: bool = True,
                 perf=None) -> None:
        self.mesh = _shared_mesh(int(n_devices))
        self.n_devices = self.mesh.devices.size
        self.donate = bool(donate)
        self.perf = perf
        self._data_sharding = NamedSharding(self.mesh,
                                            P("stripe", None, None))
        if perf is not None:
            perf.set_gauge("mesh_devices", self.n_devices)

    # -- capability gate ----------------------------------------------------
    @staticmethod
    def supports(codec) -> bool:
        """The mesh speaks two coefficient-matrix dialects: the jax
        codec family (the ``encode_batch_crc`` marker -- the encode
        matrix drives the launch directly and the decode matrix is the
        same build_decode_matrix product decode_batch uses) and the
        flat sub-chunk family (the ``mesh_flat_ok`` marker,
        ec/linear_codec.py -- chunks reshape to alpha sub-chunk rows
        around the same launches, matrices come from
        ``parity_matrix``/``decode_flat_matrix``; fused CRC stays with
        the first dialect, whose CRCs are chunk-granular)."""
        if getattr(codec, "mesh_flat_ok", False):
            return True
        return (hasattr(codec, "encode_batch_crc")
                and getattr(codec, "encode_matrix", None) is not None
                and not codec.get_chunk_mapping())

    @staticmethod
    def _flat(codec) -> bool:
        return getattr(codec, "mesh_flat_ok", False)

    def pad_batch(self, total: int) -> int:
        """Bucketed launch batch: power-of-two (bounded jit cache) AND
        a multiple of the device count (the 'stripe' axis must divide
        evenly).  Zero rows are byte-exact padding, as ever."""
        b = max(bucket_batch(total), 1)
        n = self.n_devices
        return b if b % n == 0 else ((b + n - 1) // n) * n

    # -- launches ------------------------------------------------------------
    def _count(self, b: int, total: int | None = None) -> None:
        if self.perf is not None:
            self.perf.inc("mesh_launches")
            self.perf.inc("mesh_padded_stripes", b)

    def _put(self, arr: np.ndarray):
        """Host batch -> device, already laid out stripe-sharded, so
        the launch consumes it without a resharding copy.  The device
        buffer is DONATED to the launch: do not read it afterwards."""
        return jax.device_put(np.ascontiguousarray(arr, np.uint8),
                              self._data_sharding)

    def _sched_launch(self, fn, dev_batch):
        """``dev_batch`` is DONATED to the compiled scheduled launch:
        the launch owns it; never read it after this call (the
        donated-buffer-aliasing ROOTS name this entry point)."""
        return fn(dev_batch)

    def _sched_rmw_launch(self, fn, dev_old, dev_delta):
        """Both device buffers are DONATED (old parity aliases the
        output in place); never read either after this call."""
        return fn(dev_old, dev_delta)

    def _apply_sched(self, matrix: np.ndarray, batch: np.ndarray,
                     with_crc: bool):
        """The scheduled engine for this batch, or None (dense wins
        per the cost model, or the scheduled launch failed/parity-
        rejected and the dense path must serve)."""
        from ..ops import xor_schedule as XS
        b, k, lane = batch.shape
        sched = XS.want_scheduled(bitmatrix_i8(matrix), lane,
                                  jax.default_backend())
        if sched is None:
            return None
        key = (sched.digest, "mesh", b, k, lane)
        if XS._sched_health.get(key) is False:
            return None
        try:
            fn = _compiled_apply_sched(self.mesh, sched.digest, b, k,
                                       lane, with_crc, self.donate)
            out = self._sched_launch(fn, self._put(batch))
            if key not in XS._sched_health:
                # one-time byte-parity gate vs the host oracle on a
                # small slice (batch is the HOST copy: still readable)
                from ..gf import gf_matmul
                parity = out[0] if with_crc else out
                ncheck = min(256, lane)
                # lint: disable=device-path-host-sync -- one-time parity gate vs the host oracle, bounded slice
                got = np.asarray(parity[:1, :, :ncheck])
                if not np.array_equal(
                        got[0], gf_matmul(matrix,
                                          batch[0, :, :ncheck])):
                    XS._sched_health[key] = False
                    XS.STATS.note_fallback()
                    return None
                XS._sched_health[key] = True
            self._count(b)
            XS.STATS.note_launch(sched)
            return out
        except Exception:
            XS._sched_health[key] = False
            XS.STATS.note_fallback()
            return None

    def _apply(self, matrix: np.ndarray, batch: np.ndarray,
               with_crc: bool):
        b, k, lane = batch.shape
        assert b % self.n_devices == 0, (b, self.n_devices)
        matrix = np.ascontiguousarray(matrix, np.uint8)
        out = self._apply_sched(matrix, batch, with_crc)
        if out is not None:
            return out
        w = _w_device(self.mesh, matrix.tobytes(), *matrix.shape)
        fn = _compiled_apply(self.mesh, b, k, lane, with_crc,
                             self.donate)
        out = fn(w, self._put(batch))
        self._count(b)
        return out

    def encode(self, codec, batch: np.ndarray, with_crc: bool = False,
               out_np: bool = True):
        """(B, k, L) data chunks -> (B, m, L) parity in one sharded
        launch; ``with_crc`` adds the (B, k+m) chunk CRCs computed
        inside the SAME launch (no second round trip, no host
        re-scan).  ``out_np=False`` leaves the result on device (the
        pipelined batcher defers the materialization past its overlap
        window)."""
        if self._flat(codec):
            # sub-chunk dialect: (B, k, L) -> (B, k*alpha, L/alpha)
            # rows around the same sharded launch; fused CRC is the
            # other dialect's contract (the batcher routes CRC wants
            # through the host batched pass for flat codecs)
            assert not with_crc, "flat dialect has no fused CRC"
            a = codec.alpha
            b, kc, lane = batch.shape
            out = self._apply(codec.parity_matrix,
                              batch.reshape(b, kc * a, lane // a),
                              False)
            out = out.reshape(b, -1, lane)
            if not out_np:
                return out
            # lint: disable=device-path-host-sync -- the single post-launch materialization
            return np.asarray(out)
        mat = codec.encode_matrix[codec.k:]
        if not with_crc:
            out = self._apply(mat, batch, False)
            if not out_np:
                return out
            # lint: disable=device-path-host-sync -- the single post-launch materialization
            return np.asarray(out)
        out, crcs = self._apply(mat, batch, True)
        from ..ops.crc32c_batch import PERF
        PERF.inc("fused_launches")
        PERF.inc("fused_crcs", int(batch.shape[0])
                 * (batch.shape[1] + out.shape[1]))
        if not out_np:
            return out, crcs
        # lint: disable=device-path-host-sync -- the single post-launch materialization
        return np.asarray(out), np.asarray(crcs)

    def decode(self, codec, erasures, batch: np.ndarray,
               out_np: bool = True):
        """(B, k, L) survivors (decode-index order, the decode_batch
        contract) -> (B, len(erasures), L) recovered chunks."""
        erasures = tuple(int(e) for e in erasures)
        if self._flat(codec):
            # the packed (sources, lost) extra selects the SAME cached
            # repair matrix decode_batch uses; survivors reshape to
            # sub-chunk rows around the launch
            matrix = codec.decode_flat_matrix(list(erasures))
            a = codec.alpha
            b, s, lane = batch.shape
            out = self._apply(matrix,
                              batch.reshape(b, s * a, lane // a),
                              False)
            out = out.reshape(b, -1, lane)
            if not out_np:
                return out
            # lint: disable=device-path-host-sync -- the single post-launch materialization
            return np.asarray(out)
        if hasattr(codec, "decode_matrix_for"):
            # the plugin's DecodeTableCache: the SAME matrix object
            # decode_batch would use
            matrix = codec.decode_matrix_for(list(erasures))
        else:
            enc = np.ascontiguousarray(codec.encode_matrix, np.uint8)
            matrix = _decode_matrix_cached(enc.tobytes(), *enc.shape,
                                           codec.k, erasures)
        out = self._apply(matrix, batch, False)
        if not out_np:
            return out
        # lint: disable=device-path-host-sync -- the single post-launch materialization
        return np.asarray(out)

    def rmw(self, codec, old_parity: np.ndarray,
            delta: np.ndarray, out_np: bool = True):
        """Partial-stripe RMW: (B, m, L) old parity + (B, k, L) delta
        (zeros outside the written range) -> (B, m, L) new parity.
        One launch; the old-parity device buffer is donated and
        aliased in place."""
        b, k, lane = delta.shape
        m = old_parity.shape[1]
        assert b % self.n_devices == 0, (b, self.n_devices)
        if self._flat(codec):
            # GF linearity holds per sub-chunk row identically
            a = codec.alpha
            out = self._rmw_flat(codec, old_parity, delta, a)
            if self.perf is not None:
                self.perf.inc("mesh_rmw_launches")
            if not out_np:
                return out
            # lint: disable=device-path-host-sync -- the single post-launch materialization
            return np.asarray(out)
        mat = np.ascontiguousarray(codec.encode_matrix[codec.k:],
                                   np.uint8)
        out = self._rmw_sched(mat, old_parity, delta)
        if out is None:
            w = _w_device(self.mesh, mat.tobytes(), *mat.shape)
            fn = _compiled_rmw(self.mesh, b, m, k, lane, self.donate)
            out = fn(w, self._put(old_parity), self._put(delta))
            self._count(b)
        if self.perf is not None:
            self.perf.inc("mesh_rmw_launches")
        if not out_np:
            return out
        # lint: disable=device-path-host-sync -- the single post-launch materialization
        return np.asarray(out)

    def _rmw_flat(self, codec, old_parity: np.ndarray,
                  delta: np.ndarray, a: int):
        """Flat-dialect RMW: both operands reshape to sub-chunk rows,
        then the standard scheduled/dense RMW ladder serves with the
        codec's parity matrix."""
        b, m, lane = old_parity.shape
        k = delta.shape[1]
        oldr = old_parity.reshape(b, m * a, lane // a)
        deltar = delta.reshape(b, k * a, lane // a)
        mat = codec.parity_matrix
        out = self._rmw_sched(mat, oldr, deltar)
        if out is None:
            w = _w_device(self.mesh, mat.tobytes(), *mat.shape)
            fn = _compiled_rmw(self.mesh, b, m * a, k * a, lane // a,
                               self.donate)
            out = fn(w, self._put(oldr), self._put(deltar))
            self._count(b)
        return out.reshape(b, m, lane)

    def _rmw_sched(self, mat: np.ndarray, old_parity: np.ndarray,
                   delta: np.ndarray):
        """Scheduled RMW launch, or None (dense serves)."""
        from ..ops import xor_schedule as XS
        b, k, lane = delta.shape
        m = old_parity.shape[1]
        sched = XS.want_scheduled(bitmatrix_i8(mat), lane,
                                  jax.default_backend())
        if sched is None:
            return None
        key = (sched.digest, "mesh_rmw", b, k, lane)
        if XS._sched_health.get(key) is False:
            return None
        try:
            fn = _compiled_rmw_sched(self.mesh, sched.digest, b, m, k,
                                     lane, self.donate)
            out = self._sched_rmw_launch(fn, self._put(old_parity),
                                         self._put(delta))
            if key not in XS._sched_health:
                from ..gf import gf_matmul
                ncheck = min(256, lane)
                # lint: disable=device-path-host-sync -- one-time parity gate vs the host oracle, bounded slice
                got = np.asarray(out[:1, :, :ncheck])
                want = old_parity[0, :, :ncheck] ^ gf_matmul(
                    mat, delta[0, :, :ncheck])
                if not np.array_equal(got[0], want):
                    XS._sched_health[key] = False
                    XS.STATS.note_fallback()
                    return None
                XS._sched_health[key] = True
            self._count(b)
            XS.STATS.note_launch(sched)
            return out
        except Exception:
            XS._sched_health[key] = False
            XS.STATS.note_fallback()
            return None
