"""Sharded erasure coding over a jax device Mesh.

Dataflow (the TPU-native rendering of the EC write fan-out,
src/osd/ECBackend.cc:1467 -> MOSDECSubOpWrite per shard):

  * stripes shard across the 'stripe' mesh axis (data parallel: each PG
    batch is independent, like PGs are independent in RADOS);
  * the k data chunks shard across the 'shard' mesh axis (the analog of
    chunk shards living on k+m distinct OSDs);
  * parity needs all k chunks: an all_gather over 'shard' rides ICI --
    this is the communication the reference does with messenger fan-out;
  * each 'shard' row computes a slice of the m parity rows
    (reduce-style split), so compute is balanced across the axis.

The same module drives dryrun_multichip (virtual CPU mesh) and real
multi-chip runs: only the mesh construction differs.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..ops.gf2kernels import bitmatrix_i8


def make_mesh(n_devices: int | None = None, shard_axis: int = 2) -> Mesh:
    """(stripe, shard) mesh over the first n devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    devs = np.asarray(devs[:n])
    shard = shard_axis if n % shard_axis == 0 else 1
    return Mesh(devs.reshape(n // shard, shard), ("stripe", "shard"))


def _gf_matmul_bits(w_i8: jnp.ndarray, data_u8: jnp.ndarray) -> jnp.ndarray:
    """(8r,8k) x (k,N) -> (r,N); same math as ops.gf2kernels."""
    k, n = data_u8.shape
    d = data_u8.astype(jnp.int32)
    planes = [((d >> s) & 1) for s in range(8)]
    bits = jnp.stack(planes, axis=1).reshape(8 * k, n).astype(jnp.int8)
    acc = jax.lax.dot_general(
        w_i8, bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32) & 1
    r = w_i8.shape[0] // 8
    b = acc.reshape(r, 8, n)
    shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
    return (b << shifts).sum(axis=1).astype(jnp.uint8)


def sharded_encode(mesh: Mesh, encode_matrix: np.ndarray, k: int,
                   data: jnp.ndarray) -> jnp.ndarray:
    """(B, k, L) -> (B, m, L) with B over 'stripe' and k over 'shard'.

    Requires B % mesh.stripe == 0 and k % mesh.shard == 0.
    """
    m = encode_matrix.shape[0] - k
    w = jnp.asarray(bitmatrix_i8(encode_matrix[k:]))
    n_shard = mesh.shape["shard"]
    # parity rows are split across the shard axis; pad m up if needed
    m_pad = ((m + n_shard - 1) // n_shard) * n_shard

    def block(w_local, chunks):
        # chunks: (B_local, k_local, L): my slice of the data shards
        gathered = jax.lax.all_gather(
            chunks, "shard", axis=1, tiled=True)  # (B_local, k, L)
        bl, kk, ll = gathered.shape
        flat = gathered.transpose(1, 0, 2).reshape(kk, bl * ll)
        parity = _gf_matmul_bits(w_local, flat)  # (m_local, B*L)
        out = parity.reshape(-1, bl, ll).transpose(1, 0, 2)
        return out

    w_full = jnp.zeros((8 * m_pad, w.shape[1]), jnp.int8).at[:8 * m].set(w)
    fn = shard_map(
        block, mesh=mesh,
        in_specs=(P("shard", None), P("stripe", "shard", None)),
        out_specs=P("stripe", "shard", None),
    )
    out = fn(w_full, data)
    return out.reshape(data.shape[0], m_pad, data.shape[2])[:, :m]


def sharded_ec_step(mesh: Mesh, encode_matrix: np.ndarray,
                    decode_matrix: np.ndarray, decode_index: list[int],
                    erasures: list[int], k: int, data: jnp.ndarray):
    """One full EC pipeline step under jit: encode -> degrade -> recover.

    Returns (parity, recovered, global_crc_like_checksum).  The checksum
    psum over 'stripe' is the analog of the commit-ack reduction (all
    shards confirm before the client reply, ECCommon.cc:789).
    """
    parity = sharded_encode(mesh, encode_matrix, k, data)
    full = jnp.concatenate([data, parity], axis=1)
    survivors = full[:, jnp.asarray(decode_index), :]
    wdec = jnp.asarray(bitmatrix_i8(decode_matrix))

    def dec_block(w_local, chunks):
        bl, kk, ll = chunks.shape
        flat = chunks.transpose(1, 0, 2).reshape(kk, bl * ll)
        rec = _gf_matmul_bits(w_local, flat)
        return rec.reshape(-1, bl, ll).transpose(1, 0, 2)

    dec = shard_map(
        dec_block, mesh=mesh,
        in_specs=(P(None, None), P("stripe", None, None)),
        out_specs=P("stripe", None, None),
    )
    recovered = dec(wdec, survivors)

    def checksum_block(p):
        s = jnp.sum(p.astype(jnp.uint32))
        return jax.lax.psum(s, "stripe")[None]

    csum = shard_map(
        checksum_block, mesh=mesh,
        in_specs=(P("stripe", None, None),),
        out_specs=P("stripe"),
    )(recovered)
    return parity, recovered, csum
