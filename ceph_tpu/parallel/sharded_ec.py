"""Sharded erasure coding over a jax device Mesh.

Dataflow (the TPU-native rendering of the EC write fan-out,
src/osd/ECBackend.cc:1467 -> MOSDECSubOpWrite per shard):

  * stripes shard across the 'stripe' mesh axis (data parallel: each PG
    batch is independent, like PGs are independent in RADOS);
  * the k data chunks shard across the 'shard' mesh axis (the analog of
    chunk shards living on k+m distinct OSDs);
  * parity needs all k chunks: an all_gather over 'shard' rides ICI --
    this is the communication the reference does with messenger fan-out;
  * each 'shard' row computes a slice of the m parity rows
    (reduce-style split), so compute is balanced across the axis.

The same module drives dryrun_multichip (virtual CPU mesh) and real
multi-chip runs: only the mesh construction differs.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:                                   # jax >= 0.5 top-level export
    from jax import shard_map
except ImportError:                    # 0.4.x keeps it experimental
    from jax.experimental.shard_map import shard_map

from ..gf import build_decode_matrix, gen_rs_matrix
from ..ops.gf2kernels import bitmatrix_i8


def make_mesh(n_devices: int | None = None, shard_axis: int = 2) -> Mesh:
    """(stripe, shard) mesh over the first n devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    devs = np.asarray(devs[:n])
    shard = shard_axis if n % shard_axis == 0 else 1
    return Mesh(devs.reshape(n // shard, shard), ("stripe", "shard"))


def make_data_mesh(n_devices: int | None = None) -> Mesh:
    """1-D ('stripe',) mesh over the first n visible devices.

    The live OSD data plane (parallel/mesh_codec.py) partitions only
    the stripe-batch axis: every stripe is independent, so the sharded
    encode/decode needs ZERO collectives -- each chip computes the
    parity of its batch slice and a multi-chip slice behaves like one
    giant codec.  A single device degenerates to a 1-device mesh on
    the identical code path (how the CPU tier-1 suite exercises it,
    and why ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
    gives the real 8-way program on a laptop)."""
    devs = jax.devices()
    n = min(n_devices or len(devs), len(devs))
    # lint: disable=device-path-host-sync -- marshals the DEVICE LIST into the Mesh, once at construction; no batch data flows here
    return Mesh(np.asarray(devs[:n]), ("stripe",))


def _gf_matmul_bits(w_i8: jnp.ndarray, data_u8: jnp.ndarray) -> jnp.ndarray:
    """(8r,8k) x (k,N) -> (r,N); same math as ops.gf2kernels."""
    k, n = data_u8.shape
    d = data_u8.astype(jnp.int32)
    planes = [((d >> s) & 1) for s in range(8)]
    bits = jnp.stack(planes, axis=1).reshape(8 * k, n).astype(jnp.int8)
    acc = jax.lax.dot_general(
        w_i8, bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32) & 1
    r = w_i8.shape[0] // 8
    b = acc.reshape(r, 8, n)
    shifts = jnp.arange(8, dtype=jnp.int32).reshape(1, 8, 1)
    return (b << shifts).sum(axis=1).astype(jnp.uint8)


def _sharded_gf_apply(mesh: Mesh, matrix: np.ndarray,
                      x: jnp.ndarray) -> jnp.ndarray:
    """Apply a GF(2^8) matrix to shard-axis-scattered chunks: every
    device all_gathers the input shards over 'shard' (the ICI hop),
    computes ITS slice of the output rows, and the row slices
    reassemble on the shard axis.  The shared scaffolding under both
    the parity encode and the recovery decode."""
    r = matrix.shape[0]
    w = jnp.asarray(bitmatrix_i8(matrix))
    n_shard = mesh.shape["shard"]
    r_pad = ((r + n_shard - 1) // n_shard) * n_shard
    w_full = jnp.zeros((8 * r_pad, w.shape[1]),
                       jnp.int8).at[:8 * r].set(w)

    def block(w_local, chunks):
        gathered = jax.lax.all_gather(
            chunks, "shard", axis=1, tiled=True)
        bl, kk, ll = gathered.shape
        flat = gathered.transpose(1, 0, 2).reshape(kk, bl * ll)
        rows = _gf_matmul_bits(w_local, flat)
        return rows.reshape(-1, bl, ll).transpose(1, 0, 2)

    out = shard_map(
        block, mesh=mesh,
        in_specs=(P("shard", None), P("stripe", "shard", None)),
        out_specs=P("stripe", "shard", None),
    )(w_full, x)
    return out[:, :r]


def sharded_encode(mesh: Mesh, encode_matrix: np.ndarray, k: int,
                   data: jnp.ndarray) -> jnp.ndarray:
    """(B, k, L) -> (B, m, L) with B over 'stripe' and k over 'shard'.

    Requires B % mesh.stripe == 0 and k % mesh.shard == 0.
    """
    return _sharded_gf_apply(mesh, encode_matrix[k:], data)


def sharded_ec_step(mesh: Mesh, encode_matrix: np.ndarray,
                    decode_matrix: np.ndarray, decode_index: list[int],
                    erasures: list[int], k: int, data: jnp.ndarray):
    """One full EC pipeline step under jit: encode -> degrade -> recover.

    Returns (parity, recovered, global_crc_like_checksum).  The checksum
    psum over 'stripe' is the analog of the commit-ack reduction (all
    shards confirm before the client reply, ECCommon.cc:789).
    """
    parity = sharded_encode(mesh, encode_matrix, k, data)
    full = jnp.concatenate([data, parity], axis=1)
    survivors = full[:, jnp.asarray(decode_index), :]
    wdec = jnp.asarray(bitmatrix_i8(decode_matrix))

    def dec_block(w_local, chunks):
        bl, kk, ll = chunks.shape
        flat = chunks.transpose(1, 0, 2).reshape(kk, bl * ll)
        rec = _gf_matmul_bits(w_local, flat)
        return rec.reshape(-1, bl, ll).transpose(1, 0, 2)

    dec = shard_map(
        dec_block, mesh=mesh,
        in_specs=(P(None, None), P("stripe", None, None)),
        out_specs=P("stripe", None, None),
    )
    recovered = dec(wdec, survivors)

    def checksum_block(p):
        s = jnp.sum(p.astype(jnp.uint32))
        return jax.lax.psum(s, "stripe")[None]

    csum = shard_map(
        checksum_block, mesh=mesh,
        in_specs=(P("stripe", None, None),),
        out_specs=P("stripe"),
    )(recovered)
    return parity, recovered, csum


def sharded_rmw(mesh: Mesh, encode_matrix: np.ndarray, k: int,
                old_parity: jnp.ndarray,
                delta: jnp.ndarray) -> jnp.ndarray:
    """Partial-stripe read-modify-write parity update (the sharded
    rendering of ECCommon.cc:704-789's RMW pipeline): GF(2^8) codes
    are linear, so new_parity = old_parity XOR encode(new XOR old)
    touches only the changed bytes' encode -- no full-stripe re-read.
    ``delta`` is (B, k, L) with zeros outside the written range; the
    encode rides the same (stripe, shard) mesh + ICI all_gather as the
    full-stripe path.
    """
    pdelta = sharded_encode(mesh, encode_matrix, k, delta)
    return jnp.bitwise_xor(old_parity, pdelta)


def sharded_cross_recovery(mesh: Mesh, decode_matrix: np.ndarray,
                           survivors: jnp.ndarray) -> jnp.ndarray:
    """Reconstruct erased shards when the SURVIVORS are sharded over
    the 'shard' mesh axis -- each device holds only its slice, so the
    reconstruction needs a cross-chip all_gather over ICI first (the
    network reads ECBackend recovery issues to the surviving OSDs,
    ECCommon.cc recovery reads), then decodes locally.  Survivors:
    (B, k, L), k divisible by the shard axis.
    """
    return _sharded_gf_apply(mesh, decode_matrix, survivors)


# -- LRC over mesh sub-axes --------------------------------------------------
#
# The locality structure of an LRC code (ec/plugins/lrc.py; reference
# src/erasure-code/lrc/ErasureCodeLrc.h:47-134) maps onto the device mesh:
# each local group lives on one slice of the 'group' axis.  Encoding the
# global parities needs all k data chunks once (all_gather over 'group',
# the ICI hop); local parities and -- the whole point -- single-shard
# REPAIR are computed entirely inside the group's mesh slice with no
# collective at all.  This is the TPU rendering of "repair reads stay
# inside the failure domain".


def lrc_make_mesh(n_devices: int, n_groups: int) -> Mesh:
    """(stripe, group) mesh: group axis carries the LRC local groups."""
    devs = np.asarray(jax.devices()[:n_devices])
    return Mesh(devs.reshape(n_devices // n_groups, n_groups),
                ("stripe", "group"))


def lrc_sharded_encode(mesh: Mesh, k: int, m: int, l: int,
                       data: jnp.ndarray) -> jnp.ndarray:
    """LRC k/m/l encode over a (stripe, group) mesh.

    ``data`` is (B, n_groups, kg, L): group-major data chunks, sharded
    P('stripe', 'group', None, None).  Returns (B, n_groups, kg+mg+1, L)
    full group-major chunk layout (data + global parity slots + local
    parity), same sharding.  Byte-identical to the host `lrc` plugin's
    encode for the k/m/l profile.
    """
    lgc = (k + m) // l
    kg, mg = k // lgc, m // lgc
    gen_g = gen_rs_matrix(k + m, k)          # global layer
    gen_l = gen_rs_matrix(l + 1, l)          # local layers (m=1)
    wg = jnp.asarray(bitmatrix_i8(gen_g[k:]))      # (8m, 8k)
    wl = jnp.asarray(bitmatrix_i8(gen_l[l:]))      # (8, 8l)

    def block(wg_all, wl_all, chunks):
        # chunks: (B_loc, 1, kg, L) = my group's data shard
        bl, _, _, ll = chunks.shape
        gidx = jax.lax.axis_index("group")
        # ICI hop: every group needs all k data chunks for its global
        # parity rows
        gathered = jax.lax.all_gather(
            chunks, "group", axis=1, tiled=True)   # (B_loc, lgc, kg, L)
        flat = gathered.reshape(bl, k, ll).transpose(1, 0, 2) \
                       .reshape(k, bl * ll)
        # my mg rows of the global parity (rows gidx*mg ..)
        wg_mine = jax.lax.dynamic_slice_in_dim(
            wg_all, gidx * 8 * mg, 8 * mg, axis=0)
        gp = _gf_matmul_bits(wg_mine, flat)        # (mg, B*L)
        gp = gp.reshape(mg, bl, ll).transpose(1, 0, 2)  # (B_loc, mg, L)
        # local parity over my l = kg+mg chunks, no collective
        mine = chunks[:, 0]                        # (B_loc, kg, L)
        lchunks = jnp.concatenate([mine, gp], axis=1)   # (B_loc, l, L)
        lflat = lchunks.transpose(1, 0, 2).reshape(l, bl * ll)
        lp = _gf_matmul_bits(wl_all, lflat)
        lp = lp.reshape(1, bl, ll).transpose(1, 0, 2)
        out = jnp.concatenate([mine, gp, lp], axis=1)  # (B_loc, l+1, L)
        return out[:, None]

    fn = shard_map(
        block, mesh=mesh,
        in_specs=(P(None, None), P(None, None),
                  P("stripe", "group", None, None)),
        out_specs=P("stripe", "group", None, None),
    )
    return fn(wg, wl, data)


def lrc_sharded_local_repair(mesh: Mesh, k: int, m: int, l: int,
                             lost_local_pos: int,
                             chunks: jnp.ndarray) -> jnp.ndarray:
    """Repair ONE lost chunk per group from the group's surviving l
    chunks -- no collective: the repair never leaves the mesh slice.

    ``chunks``: (B, n_groups, l+1, L) group-major layout from
    lrc_sharded_encode; ``lost_local_pos`` in [0, l+1) names the lost
    position within every group (the dry run loses the same local slot
    in each group; per-group positions would shard the decode matrix).
    Returns (B, n_groups, 1, L): the reconstructed chunk per group.
    """
    gen_l = gen_rs_matrix(l + 1, l)
    dec, idx = build_decode_matrix(gen_l, l, [lost_local_pos])
    wd = jnp.asarray(bitmatrix_i8(dec))            # (8, 8l)
    sel = jnp.asarray(idx)

    def block(wd_all, chunks_):
        bl, _, _, ll = chunks_.shape
        mine = chunks_[:, 0]                       # (B_loc, l+1, L)
        srcs = mine[:, sel]                        # (B_loc, l, L)
        flat = srcs.transpose(1, 0, 2).reshape(l, bl * ll)
        rec = _gf_matmul_bits(wd_all, flat)
        return rec.reshape(1, bl, ll).transpose(1, 0, 2)[:, None]

    fn = shard_map(
        block, mesh=mesh,
        in_specs=(P(None, None), P("stripe", "group", None, None)),
        out_specs=P("stripe", "group", None, None),
    )
    return fn(wd, chunks)
