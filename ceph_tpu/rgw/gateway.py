"""RGW frontend: asyncio HTTP server speaking the S3 REST dialect.

The request pump mirrors src/rgw/rgw_process.cc:265 process_request:
parse -> authenticate (AWS SigV4, src/rgw/rgw_auth_s3.cc) -> resolve
op -> execute against the SAL store -> emit XML.  One handler task per
connection (the asio frontend's strand-per-connection analog).

Supported: bucket create/delete/list, ListObjectsV2 (prefix/delimiter/
continuation), object PUT/GET(ranged)/HEAD/DELETE, x-amz-copy-source
copies, multipart initiate/upload-part/complete/abort, SigV4 auth with
UNSIGNED-PAYLOAD or signed-payload hashes.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import re
import time
import urllib.parse
from xml.etree import ElementTree as ET
from xml.sax.saxutils import escape

from .store import RgwError, RgwStore

MAX_BODY = 1 << 30
XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_signature(secret: str, date_stamp: str, region: str,
                    service: str, string_to_sign: str) -> str:
    k = _sign(("AWS4" + secret).encode(), date_stamp)
    k = _sign(k, region)
    k = _sign(k, service)
    k = _sign(k, "aws4_request")
    return hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()


class HttpRequest:
    def __init__(self, method, path, query, headers, body):
        self.method = method
        self.raw_path = path
        self.path = urllib.parse.unquote(path)
        self.query = query                  # dict[str, str]
        self.headers = headers              # lowercased keys
        self.body = body


class Gateway:
    def __init__(self, store: RgwStore, region: str = "default") -> None:
        self.store = store
        self.region = region
        from .swift import SwiftFrontend
        self.swift = SwiftFrontend(store)
        self._server: asyncio.AbstractServer | None = None
        self.addr: tuple[str, int] | None = None

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._serve_conn,
                                                  host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- connection handling -------------------------------------------------
    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                status, headers, body = await self._handle(req)
                await self._respond(writer, req, status, headers, body)
                if req.headers.get("connection", "").lower() == "close":
                    break
        except (ConnectionError, asyncio.IncompleteReadError,
                asyncio.TimeoutError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _read_request(self, reader) -> HttpRequest | None:
        try:
            line = await asyncio.wait_for(reader.readline(), 300)
        except asyncio.TimeoutError:
            return None
        if not line:
            return None
        try:
            method, target, _version = line.decode().split(" ", 2)
        except ValueError:
            return None
        headers: dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        parsed = urllib.parse.urlsplit(target)
        query = dict(urllib.parse.parse_qsl(parsed.query,
                                            keep_blank_values=True))
        n = int(headers.get("content-length", "0") or "0")
        if n > MAX_BODY:
            return None
        body = await reader.readexactly(n) if n else b""
        return HttpRequest(method.upper(), parsed.path, query, headers,
                           body)

    async def _respond(self, writer, req, status, headers, body):
        reason = {200: "OK", 201: "Created", 204: "No Content",
                  206: "Partial Content", 400: "Bad Request",
                  401: "Unauthorized", 403: "Forbidden",
                  404: "Not Found", 405: "Method Not Allowed",
                  409: "Conflict", 416: "Range Not Satisfiable",
                  500: "Internal Server Error",
                  501: "Not Implemented"}.get(status, "Error")
        headers.setdefault("content-length", str(len(body)))
        headers.setdefault("x-amz-request-id", f"{time.time_ns():x}")
        lines = [f"HTTP/1.1 {status} {reason}"]
        lines += [f"{k}: {v}" for k, v in headers.items()]
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
        if req.method != "HEAD":
            writer.write(body)
        await writer.drain()

    # -- auth (AWS SigV4, src/rgw/rgw_auth_s3.cc) ---------------------------
    async def _authenticate(self, req: HttpRequest) -> dict:
        auth = req.headers.get("authorization", "")
        m = re.match(
            r"AWS4-HMAC-SHA256 Credential=([^/]+)/(\d+)/([^/]+)/([^/]+)"
            r"/aws4_request,\s*SignedHeaders=([^,]+),\s*Signature=(\w+)",
            auth)
        if not m:
            raise RgwError("AccessDenied", 403, "missing/bad auth header")
        access_key, date_stamp, region, service, signed_hdrs, sig = \
            m.groups()
        user = await self.store.get_user(access_key)
        if user is None:
            raise RgwError("InvalidAccessKeyId", 403, access_key)
        payload_hash = req.headers.get(
            "x-amz-content-sha256", "UNSIGNED-PAYLOAD")
        if payload_hash not in ("UNSIGNED-PAYLOAD",
                                "STREAMING-UNSIGNED-PAYLOAD-TRAILER"):
            if hashlib.sha256(req.body).hexdigest() != payload_hash:
                raise RgwError("XAmzContentSHA256Mismatch", 400)
        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}="
            f"{urllib.parse.quote(v, safe='-_.~')}"
            for k, v in sorted(req.query.items()))
        names = signed_hdrs.split(";")
        canonical_headers = "".join(
            f"{h}:{' '.join(req.headers.get(h, '').split())}\n"
            for h in names)
        canonical = "\n".join([
            req.method, urllib.parse.quote(req.path, safe="/-_.~"),
            canonical_query, canonical_headers, signed_hdrs,
            payload_hash])
        amz_date = req.headers.get("x-amz-date", "")
        scope = f"{date_stamp}/{region}/{service}/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])
        want = sigv4_signature(user["secret"], date_stamp, region,
                               service, string_to_sign)
        if not hmac.compare_digest(want, sig):
            raise RgwError("SignatureDoesNotMatch", 403)
        return user

    # -- dispatch ------------------------------------------------------------
    async def _handle(self, req: HttpRequest):
        if self.swift.routes(req.path):
            # the Swift dialect shares the store but not the auth or
            # the XML (rgw serves both APIs from one daemon); its
            # errors must also surface as HTTP, never a torn socket
            try:
                return await self.swift.handle(req)
            except (ValueError, KeyError) as e:
                return 400, {"content-type": "text/plain"}, \
                    f"BadRequest: {type(e).__name__}".encode()
            except Exception:                 # noqa: BLE001
                return 500, {"content-type": "text/plain"}, \
                    b"InternalError"
        try:
            user = await self._authenticate(req)
            parts = req.path.lstrip("/").split("/", 1)
            bucket = parts[0]
            key = parts[1] if len(parts) > 1 else ""
            if not bucket:
                return await self._list_buckets(user)
            if not key:
                return await self._bucket_op(req, user, bucket)
            return await self._object_op(req, user, bucket, key)
        except RgwError as e:
            return self._error_response(e)
        except (ValueError, KeyError, ET.ParseError) as e:
            # malformed request params/XML must yield an HTTP error,
            # not a torn-down connection with no status line
            return self._error_response(
                RgwError("InvalidRequest", 400,
                         f"{type(e).__name__}: {e}"))
        except Exception as e:              # noqa: BLE001 -- last resort
            return self._error_response(
                RgwError("InternalError", 500, type(e).__name__))

    @staticmethod
    def _error_response(e: RgwError):
        body = (f'<?xml version="1.0" encoding="UTF-8"?>'
                f"<Error><Code>{e.code}</Code>"
                f"<Message>{escape(str(e))}</Message></Error>"
                ).encode()
        return e.status, {"content-type": "application/xml"}, body

    async def _list_buckets(self, user):
        buckets = await self.store.list_buckets(owner=user["uid"])
        items = "".join(
            f"<Bucket><Name>{escape(b['name'])}</Name>"
            f"<CreationDate>{b['created']}</CreationDate></Bucket>"
            for b in buckets)
        body = (f'<?xml version="1.0" encoding="UTF-8"?>'
                f'<ListAllMyBucketsResult xmlns="{XMLNS}">'
                f"<Owner><ID>{escape(user['uid'])}</ID></Owner>"
                f"<Buckets>{items}</Buckets>"
                f"</ListAllMyBucketsResult>").encode()
        return 200, {"content-type": "application/xml"}, body

    async def _bucket_op(self, req, user, bucket):
        q = req.query
        if req.method == "PUT" and "versioning" in q:
            root = ET.fromstring(req.body)
            ns = root.tag.partition("}")[0] + "}" \
                if root.tag.startswith("{") else ""
            status = root.findtext(f"{ns}Status") or ""
            await self.store.set_bucket_versioning(bucket, status)
            return 200, {}, b""
        if req.method == "GET" and "versioning" in q:
            state = await self.store.get_bucket_versioning(bucket)
            inner = f"<Status>{state}</Status>" if state else ""
            return 200, {"content-type": "application/xml"}, (
                f'<?xml version="1.0"?>'
                f'<VersioningConfiguration xmlns="{XMLNS}">{inner}'
                f"</VersioningConfiguration>").encode()
        if req.method == "PUT" and "lifecycle" in q:
            await self.store.set_bucket_lifecycle(
                bucket, self._parse_lifecycle(req.body))
            return 200, {}, b""
        if req.method == "GET" and "lifecycle" in q:
            rules = await self.store.get_bucket_lifecycle(bucket)
            return 200, {"content-type": "application/xml"}, \
                self._lifecycle_xml(rules)
        if req.method == "DELETE" and "lifecycle" in q:
            await self.store.delete_bucket_lifecycle(bucket)
            return 204, {}, b""
        if req.method == "PUT" and "notification" in q:
            # S3 PutBucketNotificationConfiguration: Topic + Event
            # elements per TopicConfiguration (rgw_rest_pubsub)
            root = ET.fromstring(req.body)
            ns = root.tag.partition("}")[0] + "}" \
                if root.tag.startswith("{") else ""
            configs = []
            for tc in root.findall(f"{ns}TopicConfiguration"):
                cfg = {"id": tc.findtext(f"{ns}Id") or "",
                       "topic": (tc.findtext(f"{ns}Topic") or ""
                                 ).rsplit(":", 1)[-1],
                       "events": [e.text for e in
                                  tc.findall(f"{ns}Event")
                                  if e.text]}
                fr = tc.find(f"{ns}Filter")
                if fr is not None:
                    filt = {}
                    for rule in fr.iter(f"{ns}FilterRule"):
                        n = rule.findtext(f"{ns}Name") or ""
                        v = rule.findtext(f"{ns}Value") or ""
                        filt[n.lower()] = v
                    cfg["filter"] = filt
                configs.append(cfg)
            await self.store.notify.put_bucket_notification(
                bucket, configs)
            return 200, {}, b""
        if req.method == "GET" and "notification" in q:
            configs = await self.store.notify.get_bucket_notification(
                bucket)
            from xml.sax.saxutils import escape
            parts = []
            for c in configs:
                evs = "".join(f"<Event>{escape(e)}</Event>"
                              for e in c.get("events", []))
                filt = ""
                rules = "".join(
                    f"<FilterRule><Name>{escape(n)}</Name>"
                    f"<Value>{escape(v)}</Value></FilterRule>"
                    for n, v in (c.get("filter") or {}).items())
                if rules:
                    filt = (f"<Filter><S3Key>{rules}</S3Key>"
                            f"</Filter>")
                parts.append(
                    f"<TopicConfiguration>"
                    f"<Id>{escape(c.get('id', ''))}</Id>"
                    f"<Topic>{escape(c['topic'])}</Topic>{evs}{filt}"
                    f"</TopicConfiguration>")
            return 200, {"content-type": "application/xml"}, (
                f'<?xml version="1.0"?>'
                f'<NotificationConfiguration xmlns="{XMLNS}">'
                f"{''.join(parts)}</NotificationConfiguration>"
            ).encode()
        if req.method == "GET" and "versions" in q:
            return await self._list_versions(req, bucket)
        if req.method == "PUT":
            await self.store.create_bucket(bucket, user["uid"])
            return 200, {"location": f"/{bucket}"}, b""
        if req.method == "DELETE":
            await self.store.delete_bucket(bucket)
            return 204, {}, b""
        if req.method in ("GET", "HEAD"):
            if "uploads" in q:
                return 200, {"content-type": "application/xml"}, (
                    f'<?xml version="1.0"?><ListMultipartUploadsResult '
                    f'xmlns="{XMLNS}"></ListMultipartUploadsResult>'
                ).encode()
            return await self._list_objects_v2(req, bucket)
        raise RgwError("MethodNotAllowed", 400, req.method)

    @staticmethod
    def _parse_lifecycle(body: bytes) -> list[dict]:
        root = ET.fromstring(body)
        ns = root.tag.partition("}")[0] + "}" \
            if root.tag.startswith("{") else ""
        rules = []
        for r in root.findall(f"{ns}Rule"):
            rule = {"id": r.findtext(f"{ns}ID") or "",
                    "prefix": (r.findtext(f"{ns}Prefix")
                               or r.findtext(f"{ns}Filter/{ns}Prefix")
                               or ""),
                    "enabled": (r.findtext(f"{ns}Status") or
                                "Enabled") == "Enabled"}
            exp = r.find(f"{ns}Expiration")
            if exp is not None:
                days = exp.findtext(f"{ns}Days")
                if days:
                    rule["days"] = int(days)
                if (exp.findtext(f"{ns}ExpiredObjectDeleteMarker")
                        or "").lower() == "true":
                    rule["expired_delete_marker"] = True
            nce = r.find(f"{ns}NoncurrentVersionExpiration")
            if nce is not None:
                nd = nce.findtext(f"{ns}NoncurrentDays")
                if nd:
                    rule["noncurrent_days"] = int(nd)
            rules.append(rule)
        return rules

    @staticmethod
    def _lifecycle_xml(rules: list[dict]) -> bytes:
        items = []
        for r in rules:
            exp = ""
            if r.get("days") is not None:
                exp += f"<Days>{r['days']}</Days>"
            if r.get("expired_delete_marker"):
                exp += ("<ExpiredObjectDeleteMarker>true"
                        "</ExpiredObjectDeleteMarker>")
            nce = (f"<NoncurrentVersionExpiration><NoncurrentDays>"
                   f"{r['noncurrent_days']}</NoncurrentDays>"
                   f"</NoncurrentVersionExpiration>"
                   if r.get("noncurrent_days") is not None else "")
            items.append(
                f"<Rule><ID>{escape(r.get('id', ''))}</ID>"
                f"<Prefix>{escape(r.get('prefix', ''))}</Prefix>"
                f"<Status>"
                f"{'Enabled' if r.get('enabled', True) else 'Disabled'}"
                f"</Status>"
                + (f"<Expiration>{exp}</Expiration>" if exp else "")
                + nce + "</Rule>")
        return (f'<?xml version="1.0"?>'
                f'<LifecycleConfiguration xmlns="{XMLNS}">'
                + "".join(items)
                + "</LifecycleConfiguration>").encode()

    async def _list_versions(self, req, bucket):
        prefix = req.query.get("prefix", "")
        key_marker = req.query.get("key-marker", "")
        vid_marker = req.query.get("version-id-marker", "")
        # internal marker is "key\x00vid"; a bare key-marker resumes
        # AFTER every version of that key (\x01 sorts past them all)
        if key_marker and vid_marker:
            marker = f"{key_marker}\x00{vid_marker}"
        elif key_marker:
            marker = key_marker + "\x01"
        else:
            marker = ""
        max_keys = int(req.query.get("max-keys", "1000"))
        out = await self.store.list_object_versions(
            bucket, prefix=prefix, marker=marker, max_keys=max_keys)
        items = []
        for key, vid, e, is_latest in out["versions"]:
            latest = "true" if is_latest else "false"
            if e.get("delete_marker"):
                items.append(
                    f"<DeleteMarker><Key>{escape(key)}</Key>"
                    f"<VersionId>{vid}</VersionId>"
                    f"<IsLatest>{latest}</IsLatest>"
                    f"<LastModified>{e['mtime']}</LastModified>"
                    f"</DeleteMarker>")
            else:
                items.append(
                    f"<Version><Key>{escape(key)}</Key>"
                    f"<VersionId>{vid}</VersionId>"
                    f"<IsLatest>{latest}</IsLatest>"
                    f"<LastModified>{e['mtime']}</LastModified>"
                    f"<ETag>&quot;{e['etag']}&quot;</ETag>"
                    f"<Size>{e['size']}</Size></Version>")
        trunc = "true" if out["truncated"] else "false"
        nxt = ""
        if out["truncated"] and out.get("next_marker"):
            nk, _, nv = out["next_marker"].partition("\x00")
            nxt = (f"<NextKeyMarker>{escape(nk)}</NextKeyMarker>"
                   f"<NextVersionIdMarker>{escape(nv)}"
                   f"</NextVersionIdMarker>")
        return 200, {"content-type": "application/xml"}, (
            f'<?xml version="1.0"?>'
            f'<ListVersionsResult xmlns="{XMLNS}">'
            f"<Name>{escape(bucket)}</Name>"
            f"<Prefix>{escape(prefix)}</Prefix>"
            f"<IsTruncated>{trunc}</IsTruncated>{nxt}"
            + "".join(items) + "</ListVersionsResult>").encode()

    async def _list_objects_v2(self, req, bucket):
        prefix = req.query.get("prefix", "")
        delim = req.query.get("delimiter", "")
        max_keys = int(req.query.get("max-keys", "1000"))
        marker = req.query.get("continuation-token",
                               req.query.get("start-after",
                                             req.query.get("marker", "")))
        out = await self.store.list_objects(
            bucket, prefix=prefix, marker=marker, max_keys=max_keys,
            delimiter=delim)
        contents = "".join(
            f"<Contents><Key>{escape(k)}</Key>"
            f"<LastModified>{e['mtime']}</LastModified>"
            f"<ETag>&quot;{e['etag']}&quot;</ETag>"
            f"<Size>{e['size']}</Size>"
            f"<StorageClass>STANDARD</StorageClass></Contents>"
            for k, e in out["entries"])
        commons = "".join(
            f"<CommonPrefixes><Prefix>{escape(p)}</Prefix>"
            f"</CommonPrefixes>" for p in out["prefixes"])
        trunc = "true" if out["truncated"] else "false"
        nct = (f"<NextContinuationToken>{escape(out['next_marker'])}"
               f"</NextContinuationToken>" if out["truncated"] else "")
        body = (f'<?xml version="1.0" encoding="UTF-8"?>'
                f'<ListBucketResult xmlns="{XMLNS}">'
                f"<Name>{escape(bucket)}</Name>"
                f"<Prefix>{escape(prefix)}</Prefix>"
                f"<KeyCount>{len(out['entries'])}</KeyCount>"
                f"<MaxKeys>{max_keys}</MaxKeys>"
                f"<IsTruncated>{trunc}</IsTruncated>{nct}"
                f"{contents}{commons}</ListBucketResult>").encode()
        return 200, {"content-type": "application/xml"}, body

    async def _object_op(self, req, user, bucket, key):
        q = req.query
        if req.method == "POST" and "uploads" in q:
            uid = await self.store.initiate_multipart(
                bucket, key, user["uid"],
                req.headers.get("content-type", ""))
            body = (f'<?xml version="1.0"?>'
                    f'<InitiateMultipartUploadResult xmlns="{XMLNS}">'
                    f"<Bucket>{escape(bucket)}</Bucket>"
                    f"<Key>{escape(key)}</Key>"
                    f"<UploadId>{uid}</UploadId>"
                    f"</InitiateMultipartUploadResult>").encode()
            return 200, {"content-type": "application/xml"}, body
        if req.method == "PUT" and "uploadId" in q:
            part = await self.store.put_part(
                bucket, key, q["uploadId"], int(q["partNumber"]),
                req.body)
            return 200, {"etag": f'"{part["etag"]}"'}, b""
        if req.method == "POST" and "uploadId" in q:
            root = ET.fromstring(req.body)
            ns = root.tag.partition("}")[0] + "}" \
                if root.tag.startswith("{") else ""
            nums = sorted(int(p.findtext(f"{ns}PartNumber"))
                          for p in root.findall(f"{ns}Part"))
            entry = await self.store.complete_multipart(
                bucket, key, q["uploadId"], nums)
            body = (f'<?xml version="1.0"?>'
                    f'<CompleteMultipartUploadResult xmlns="{XMLNS}">'
                    f"<Bucket>{escape(bucket)}</Bucket>"
                    f"<Key>{escape(key)}</Key>"
                    f"<ETag>&quot;{entry['etag']}&quot;</ETag>"
                    f"</CompleteMultipartUploadResult>").encode()
            return 200, {"content-type": "application/xml"}, body
        if req.method == "DELETE" and "uploadId" in q:
            await self.store.abort_multipart(bucket, key, q["uploadId"])
            return 204, {}, b""
        if req.method == "PUT":
            src = req.headers.get("x-amz-copy-source")
            if src:
                sb, _, sk = urllib.parse.unquote(
                    src.lstrip("/")).partition("/")
                src_entry, data = await self.store.get_object(sb, sk)
                # S3 CopyObject defaults to the COPY metadata
                # directive: source content-type + x-amz-meta carry over
                replace = req.headers.get(
                    "x-amz-metadata-directive", "COPY") == "REPLACE"
                entry = await self.store.put_object(
                    bucket, key, data, owner=user["uid"],
                    content_type=(req.headers.get("content-type", "")
                                  if replace
                                  else src_entry.get("content_type", "")),
                    meta=({k[len("x-amz-meta-"):]: v
                           for k, v in req.headers.items()
                           if k.startswith("x-amz-meta-")}
                          if replace else src_entry.get("meta", {})))
                body = (f'<?xml version="1.0"?><CopyObjectResult>'
                        f"<ETag>&quot;{entry['etag']}&quot;</ETag>"
                        f"<LastModified>{entry['mtime']}</LastModified>"
                        f"</CopyObjectResult>").encode()
                return 200, {"content-type": "application/xml"}, body
            meta = {k[len("x-amz-meta-"):]: v
                    for k, v in req.headers.items()
                    if k.startswith("x-amz-meta-")}
            entry = await self.store.put_object(
                bucket, key, req.body, owner=user["uid"],
                content_type=req.headers.get("content-type", ""),
                meta=meta)
            hdrs = {"etag": f'"{entry["etag"]}"'}
            if entry.get("version_id"):
                hdrs["x-amz-version-id"] = entry["version_id"]
            return 200, hdrs, b""
        if req.method in ("GET", "HEAD"):
            off, length = 0, None
            status = 200
            vid = q.get("versionId")
            rng = req.headers.get("range")
            entry = await self.store.get_entry(bucket, key, vid)
            if entry.get("delete_marker"):
                raise RgwError("MethodNotAllowed", 405,
                               "the specified version is a delete "
                               "marker")
            if rng:
                m = re.match(r"bytes=(\d*)-(\d*)$", rng)
                if not m or (not m.group(1) and not m.group(2)):
                    raise RgwError("InvalidRange", 416, rng)
                if m.group(1):
                    off = int(m.group(1))
                    end = (int(m.group(2)) if m.group(2)
                           else entry["size"] - 1)
                else:                       # suffix range: last N bytes
                    off = max(0, entry["size"] - int(m.group(2)))
                    end = entry["size"] - 1
                if off >= entry["size"]:
                    raise RgwError("InvalidRange", 416, rng)
                end = min(end, entry["size"] - 1)
                length = end - off + 1
                status = 206
            if req.method == "HEAD":
                data = b""
            else:
                entry, data = await self.store.get_object(
                    bucket, key, off, length, version_id=vid)
            headers = {
                "content-type": entry.get("content_type")
                or "binary/octet-stream",
                "etag": f'"{entry["etag"]}"',
                "last-modified": entry["mtime"],
                "content-length": str(len(data) if req.method == "GET"
                                      else (length if length is not None
                                            else entry["size"])),
                "accept-ranges": "bytes",
            }
            for mk, mv in entry.get("meta", {}).items():
                headers[f"x-amz-meta-{mk}"] = mv
            if entry.get("version_id"):
                headers["x-amz-version-id"] = entry["version_id"]
            if status == 206:
                headers["content-range"] = (
                    f"bytes {off}-{off + length - 1}/{entry['size']}")
            return status, headers, data
        if req.method == "DELETE":
            if "versionId" in q:
                await self.store.delete_version(bucket, key,
                                                q["versionId"])
                return 204, {"x-amz-version-id": q["versionId"]}, b""
            marker_vid = await self.store.delete_object(bucket, key)
            hdrs = {}
            if marker_vid:
                hdrs = {"x-amz-delete-marker": "true",
                        "x-amz-version-id": marker_vid}
            return 204, hdrs, b""
        raise RgwError("MethodNotAllowed", 400, req.method)
