"""RGW bucket notifications: topics + reliable (persistent) delivery.

src/rgw/rgw_notify.cc role: buckets carry notification configurations
naming a TOPIC; object mutations (PUT/DELETE/multipart
complete/lifecycle expiration) that match a config's event types and
prefix/suffix filter become S3-shaped event records.  Reliable
("persistent") delivery is a per-topic QUEUE in RADOS: the event is
committed to the queue omap BEFORE the data op acks (the reference's
2-phase reserve/commit), and a delivery loop drains it to the topic's
endpoint, removing entries only after the endpoint acks -- so a
gateway crash mid-delivery redelivers from the durable queue instead
of losing the event (at-least-once; consumers dedup on the event id).

Endpoints: ``inproc://<name>`` dispatches to a handler registered in
this process (tests, embedded consumers); ``log://`` records to the
cluster log only.  An HTTP endpoint type would slot beside them (no
egress in this environment).
"""

from __future__ import annotations

import asyncio
import json
import os
import time

TOPICS_OID = "notify.topics"
QUEUE_FMT = "notify.queue.{topic}"

# handler registry for inproc:// endpoints: name -> async fn(event)
_INPROC: dict[str, object] = {}


def register_inproc_endpoint(name: str, handler) -> None:
    _INPROC[name] = handler


def _match(config: dict, event_type: str, key: str) -> bool:
    evs = [e for e in config.get("events", ["s3:ObjectCreated:*",
                                            "s3:ObjectRemoved:*"])
           if isinstance(e, str)]
    ok = any(event_type == e
             or (e.endswith("*") and event_type.startswith(e[:-1]))
             for e in evs)
    if not ok:
        return False
    f = config.get("filter", {})
    if f.get("prefix") and not key.startswith(f["prefix"]):
        return False
    if f.get("suffix") and not key.endswith(f["suffix"]):
        return False
    return True


class NotificationManager:
    def __init__(self, store) -> None:
        self.store = store
        self.ioctx = store.ioctx
        self._deliver_task: asyncio.Task | None = None
        self._seq = 0
        # per-manager entropy in queue keys: two gateways emitting in
        # the same millisecond with equal counters must not collide
        # (an omap overwrite would silently DROP an event)
        self._token = os.urandom(4).hex()
        self.stats = {"published": 0, "delivered": 0, "failed": 0}

    # -- topics ---------------------------------------------------------------
    async def create_topic(self, name: str, endpoint: str) -> dict:
        topic = {"name": name, "endpoint": endpoint,
                 "created": time.time()}
        await self.ioctx.set_omap(TOPICS_OID, {
            name: json.dumps(topic).encode()})
        return topic

    async def delete_topic(self, name: str) -> None:
        """Deleting a topic DROPS its undelivered events (S3
        semantics) -- and must reclaim the durable queue object, not
        orphan it."""
        from ..client.rados import RadosError
        try:
            await self.ioctx.rm_omap_keys(TOPICS_OID, [name])
        except RadosError:
            pass
        try:
            await self.ioctx.remove(QUEUE_FMT.format(topic=name))
        except RadosError:
            pass

    async def list_topics(self) -> dict[str, dict]:
        from ..client.rados import RadosError
        try:
            omap = await self.ioctx.get_omap(TOPICS_OID)
        except RadosError:
            return {}
        return {k: json.loads(v) for k, v in omap.items()}

    # -- bucket configuration -------------------------------------------------
    async def put_bucket_notification(self, bucket_name: str,
                                      configs: list[dict]) -> None:
        """configs: [{"id", "topic", "events": [...],
        "filter": {"prefix", "suffix"}}]"""
        topics = await self.list_topics()
        for c in configs:
            if c["topic"] not in topics:
                from .store import RgwError
                raise RgwError("NoSuchTopic", 404, c["topic"])
        bucket = await self.store.get_bucket(bucket_name)
        bucket["notifications"] = configs
        await self.store._save_bucket(bucket)

    async def get_bucket_notification(self,
                                      bucket_name: str) -> list[dict]:
        bucket = await self.store.get_bucket(bucket_name)
        return bucket.get("notifications", [])

    # -- event publication (called by the store BEFORE the op acks) ----------
    async def emit(self, bucket: dict, event_type: str, key: str,
                   size: int = 0, etag: str = "",
                   version_id: str = "") -> None:
        configs = bucket.get("notifications") or []
        matched = [c for c in configs if _match(c, event_type, key)]
        if not matched:
            return
        topics = await self.list_topics()
        for c in matched:
            topic = topics.get(c["topic"])
            if topic is None:
                continue              # topic deleted after config
            self._seq += 1
            eid = f"{int(time.time() * 1000):x}-{os.urandom(4).hex()}"
            event = {
                "eventVersion": "2.2",
                "eventName": event_type,
                "eventTime": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime()),
                "eventId": eid,
                "configurationId": c.get("id", ""),
                "s3": {"bucket": {"name": bucket["name"]},
                       "object": {"key": key, "size": size,
                                  "eTag": etag,
                                  "versionId": version_id}},
            }
            # durable BEFORE the data op acks: the queue IS the
            # delivery guarantee (rgw_notify's reserve/commit)
            qoid = QUEUE_FMT.format(topic=c["topic"])
            qkey = (f"{int(time.time() * 1000):016d}"
                    f".{self._seq:08d}.{self._token}")
            await self.ioctx.set_omap(qoid, {
                qkey: json.dumps(event).encode()})
            self.stats["published"] += 1

    # -- delivery -------------------------------------------------------------
    async def deliver_once(self) -> int:
        """Drain every topic queue once; returns events delivered.
        Entries are removed only AFTER the endpoint acks -- a crash
        in between redelivers (at-least-once)."""
        from ..client.rados import RadosError
        n = 0
        for name, topic in (await self.list_topics()).items():
            qoid = QUEUE_FMT.format(topic=name)
            try:
                queue = await self.ioctx.get_omap(qoid)
            except RadosError:
                continue
            for qkey in sorted(queue):
                event = json.loads(queue[qkey])
                try:
                    await self._deliver(topic, event)
                except Exception:
                    self.stats["failed"] += 1
                    break             # keep order: retry next round
                await self.ioctx.rm_omap_keys(qoid, [qkey])
                self.stats["delivered"] += 1
                n += 1
        return n

    async def _deliver(self, topic: dict, event: dict) -> None:
        ep = topic["endpoint"]
        if ep.startswith("inproc://"):
            handler = _INPROC.get(ep[len("inproc://"):])
            if handler is None:
                raise RuntimeError(f"no inproc handler for {ep}")
            await handler(event)
        elif ep.startswith("log://"):
            pass                      # observability-only endpoint
        else:
            raise RuntimeError(f"unsupported endpoint {ep}")

    def start(self, interval: float = 0.5) -> None:
        if self._deliver_task is None or self._deliver_task.done():
            self._deliver_task = asyncio.ensure_future(
                self._loop(interval))

    async def stop(self) -> None:
        if self._deliver_task is not None:
            self._deliver_task.cancel()
            try:
                await self._deliver_task
            except (asyncio.CancelledError, Exception):
                pass
            self._deliver_task = None

    async def _loop(self, interval: float) -> None:
        try:
            while True:
                try:
                    await self.deliver_once()
                except Exception:
                    pass
                await asyncio.sleep(interval)
        except asyncio.CancelledError:
            pass
