"""RGW Swift dialect: the OpenStack object API over the same store.

src/rgw/rgw_rest_swift.cc role: one SAL store, two REST dialects.
Swift's shape -- TempAuth tokens, /v1/AUTH_<account>/<container>/<obj>
paths, JSON container listings, X-Object-Meta-* headers, marker
paging -- maps onto the exact bucket/object machinery S3 uses, so
objects PUT via S3 are GETtable via Swift and vice versa.

Supported: auth (/auth/v1.0 TempAuth: X-Auth-User/X-Auth-Key ->
X-Auth-Token), account GET (container listing), container
PUT/GET/DELETE/HEAD (object listing with prefix/marker/limit), object
PUT/GET/HEAD/DELETE with metadata headers.  Not supported (as in many
radosgw deployments): large-object manifests, ACL headers, versioning
via the Swift dialect.
"""

from __future__ import annotations

import json
import os
import time

from .store import RgwError, RgwStore

TOKEN_TTL = 3600.0


class SwiftFrontend:
    """Handles Swift-dialect requests inside the Gateway's HTTP
    server (path-routed: /auth/v1.0 and /v1/...)."""

    def __init__(self, store: RgwStore) -> None:
        self.store = store
        # token -> {user, expires}; TempAuth keeps tokens in memory
        # exactly like this (rgw_swift_auth.cc TempURL aside)
        self._tokens: dict[str, dict] = {}

    def routes(self, path: str) -> bool:
        # /swift/v1 keeps the dialect out of the S3 bucket namespace
        # (an S3 bucket named "v1" must stay reachable); radosgw
        # mounts swift under a distinct prefix for the same reason
        return path == "/auth/v1.0" or path.startswith("/swift/v1/")

    async def handle(self, req) -> tuple[int, dict, bytes]:
        try:
            if req.path == "/auth/v1.0":
                return await self._auth(req)
            return await self._api(req)
        except RgwError as e:
            return e.status, {"content-type": "text/plain"}, \
                f"{e.code}".encode()

    # -- TempAuth -------------------------------------------------------------
    async def _auth(self, req) -> tuple[int, dict, bytes]:
        user_hdr = req.headers.get("x-auth-user", "")
        key = req.headers.get("x-auth-key", "")
        # X-Auth-User is "<account>:<user>"; the access key doubles as
        # the account id the way radosgw's swift subusers do
        access = user_hdr.split(":", 1)[0]
        user = await self.store.get_user(access)
        if user is None or user["secret"] != key:
            raise RgwError("AccessDenied", 401, "bad credentials")
        token = "AUTH_tk" + os.urandom(16).hex()
        self._tokens[token] = {"user": user,
                               "expires": time.time() + TOKEN_TTL}
        return 200, {
            "x-auth-token": token,
            "x-storage-token": token,
            "x-storage-url": f"/swift/v1/AUTH_{user['uid']}"}, b""

    def _user_for(self, req) -> dict:
        tok = self._tokens.get(req.headers.get("x-auth-token", ""))
        if tok is None or tok["expires"] < time.time():
            raise RgwError("AccessDenied", 401, "bad or expired token")
        return tok["user"]

    # -- /v1/AUTH_<account>[/container[/object]] ------------------------------
    async def _api(self, req) -> tuple[int, dict, bytes]:
        user = self._user_for(req)
        parts = req.path[len("/swift/v1/"):].split("/", 2)
        account = parts[0]
        if account != f"AUTH_{user['uid']}":
            raise RgwError("AccessDenied", 403, account)
        container = parts[1] if len(parts) > 1 and parts[1] else ""
        obj = parts[2] if len(parts) > 2 else ""
        if not container:
            return await self._account(req, user)
        if not obj:
            return await self._container(req, user, container)
        return await self._object(req, user, container, obj)

    async def _account(self, req, user) -> tuple[int, dict, bytes]:
        if req.method not in ("GET", "HEAD"):
            raise RgwError("MethodNotAllowed", 405, req.method)
        buckets = await self.store.list_buckets(owner=user["uid"])
        out = [{"name": b["name"]} for b in buckets]
        return 200, {"content-type": "application/json",
                     "x-account-container-count": str(len(out))}, \
            json.dumps(out).encode()

    async def _container(self, req, user,
                         container: str) -> tuple[int, dict, bytes]:
        if req.method == "PUT":
            try:
                await self.store.create_bucket(container, user["uid"])
            except RgwError as e:
                if e.code != "BucketAlreadyExists":
                    raise
            return 201, {}, b""
        if req.method == "DELETE":
            try:
                await self.store.delete_bucket(container)
            except RgwError as e:
                if e.code == "BucketNotEmpty":
                    raise RgwError("Conflict", 409, container) from e
                raise
            return 204, {}, b""
        if req.method in ("GET", "HEAD"):
            listing = await self.store.list_objects(
                container,
                prefix=req.query.get("prefix", ""),
                marker=req.query.get("marker", ""),
                max_keys=int(req.query.get("limit", "10000")))
            rows = [{"name": k, "bytes": e["size"],
                     "hash": e["etag"],
                     "content_type": e.get("content_type", ""),
                     "last_modified": e["mtime"]}
                    for k, e in listing["entries"]]
            hdrs = {"content-type": "application/json",
                    "x-container-object-count": str(len(rows))}
            if req.method == "HEAD":
                return 204, hdrs, b""
            return 200, hdrs, json.dumps(rows).encode()
        raise RgwError("MethodNotAllowed", 405, req.method)

    async def _object(self, req, user, container: str,
                      obj: str) -> tuple[int, dict, bytes]:
        if req.method == "PUT":
            meta = {k[len("x-object-meta-"):]: v
                    for k, v in req.headers.items()
                    if k.startswith("x-object-meta-")}
            entry = await self.store.put_object(
                container, obj, req.body, owner=user["uid"],
                content_type=req.headers.get("content-type", ""),
                meta=meta)
            return 201, {"etag": entry["etag"]}, b""
        if req.method in ("GET", "HEAD"):
            entry = await self.store.get_entry(container, obj)
            hdrs = {"etag": entry["etag"],
                    "content-type": entry.get("content_type")
                    or "application/octet-stream",
                    "content-length": str(entry["size"]),
                    "last-modified": entry["mtime"]}
            for k, v in (entry.get("meta") or {}).items():
                hdrs[f"x-object-meta-{k}"] = v
            if req.method == "HEAD":
                return 200, hdrs, b""
            _entry, data = await self.store.get_object(container, obj)
            return 200, hdrs, data
        if req.method == "DELETE":
            await self.store.delete_object(container, obj)
            return 204, {}, b""
        raise RgwError("MethodNotAllowed", 405, req.method)
