"""Minimal SigV4-signing S3 client (the s3cmd/boto smoke-test analog).

Signs exactly the canonical form gateway.py verifies; used by the test
suite to exercise the REAL HTTP path and usable as a library client.
"""

from __future__ import annotations

import asyncio
import hashlib
import time
import urllib.parse
from xml.etree import ElementTree as ET

from .gateway import sigv4_signature


class S3Error(Exception):
    def __init__(self, status: int, code: str, body: bytes) -> None:
        super().__init__(f"{status} {code}")
        self.status = status
        self.code = code
        self.body = body


class S3Client:
    def __init__(self, addr: tuple[str, int], access_key: str,
                 secret: str, region: str = "default") -> None:
        self.addr = tuple(addr)
        self.access_key = access_key
        self.secret = secret
        self.region = region

    async def request(self, method: str, path: str,
                      query: dict | None = None, body: bytes = b"",
                      headers: dict | None = None,
                      sign_payload: bool = True):
        query = dict(query or {})
        headers = {k.lower(): v for k, v in (headers or {}).items()}
        now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        date_stamp = time.strftime("%Y%m%d", now)
        payload_hash = (hashlib.sha256(body).hexdigest()
                        if sign_payload else "UNSIGNED-PAYLOAD")
        headers.update({
            "host": f"{self.addr[0]}:{self.addr[1]}",
            "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash,
            "content-length": str(len(body)),
        })
        signed = ";".join(sorted(
            h for h in headers
            if h in ("host", "content-type") or h.startswith("x-amz-")))
        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='-_.~')}="
            f"{urllib.parse.quote(v, safe='-_.~')}"
            for k, v in sorted(query.items()))
        canonical_headers = "".join(
            f"{h}:{' '.join(headers[h].split())}\n"
            for h in signed.split(";"))
        canonical = "\n".join([
            method, urllib.parse.quote(path, safe="/-_.~"),
            canonical_query, canonical_headers, signed, payload_hash])
        scope = f"{date_stamp}/{self.region}/s3/aws4_request"
        sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canonical.encode()).hexdigest()])
        sig = sigv4_signature(self.secret, date_stamp, self.region,
                              "s3", sts)
        headers["authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}")

        qs = ("?" + urllib.parse.urlencode(query)) if query else ""
        reader, writer = await asyncio.open_connection(*self.addr)
        try:
            lines = [f"{method} {urllib.parse.quote(path, safe='/-_.~')}"
                     f"{qs} HTTP/1.1"]
            lines += [f"{k}: {v}" for k, v in headers.items()]
            writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
            writer.write(body)
            await writer.drain()
            status_line = await reader.readline()
            status = int(status_line.split()[1])
            rhead: dict[str, str] = {}
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                k, _, v = h.decode().partition(":")
                rhead[k.strip().lower()] = v.strip()
            n = int(rhead.get("content-length", "0") or "0")
            rbody = await reader.readexactly(n) if n and method != "HEAD" \
                else b""
            if status >= 400:
                code = ""
                try:
                    code = ET.fromstring(rbody).findtext("Code") or ""
                except ET.ParseError:
                    pass
                raise S3Error(status, code, rbody)
            return status, rhead, rbody
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    # -- convenience wrappers -----------------------------------------------
    async def create_bucket(self, bucket: str) -> None:
        await self.request("PUT", f"/{bucket}")

    async def delete_bucket(self, bucket: str) -> None:
        await self.request("DELETE", f"/{bucket}")

    async def list_buckets(self) -> list[str]:
        _, _, body = await self.request("GET", "/")
        root = ET.fromstring(body)
        ns = {"s3": root.tag[1:].partition("}")[0]}
        return [e.text for e in root.findall(
            ".//s3:Bucket/s3:Name", ns)]

    async def put_object(self, bucket: str, key: str, data: bytes,
                         **kw) -> str:
        _, h, _ = await self.request("PUT", f"/{bucket}/{key}",
                                     body=data, **kw)
        return h.get("etag", "").strip('"')

    async def get_object(self, bucket: str, key: str,
                         range_: str | None = None) -> bytes:
        headers = {"range": range_} if range_ else None
        _, _, body = await self.request("GET", f"/{bucket}/{key}",
                                        headers=headers)
        return body

    async def head_object(self, bucket: str, key: str) -> dict:
        _, h, _ = await self.request("HEAD", f"/{bucket}/{key}")
        return h

    async def delete_object(self, bucket: str, key: str,
                            version_id: str | None = None) -> dict:
        q = {"versionId": version_id} if version_id else None
        _, h, _ = await self.request("DELETE", f"/{bucket}/{key}",
                                     query=q)
        return {"delete_marker": h.get("x-amz-delete-marker") == "true",
                "version_id": h.get("x-amz-version-id")}

    # -- versioning ----------------------------------------------------------
    async def put_bucket_versioning(self, bucket: str,
                                    status: str) -> None:
        body = (f'<VersioningConfiguration>'
                f"<Status>{status}</Status>"
                f"</VersioningConfiguration>").encode()
        await self.request("PUT", f"/{bucket}",
                           query={"versioning": ""}, body=body)

    async def get_bucket_versioning(self, bucket: str) -> str:
        _, _, body = await self.request("GET", f"/{bucket}",
                                        query={"versioning": ""})
        root = ET.fromstring(body)
        ns = root.tag.partition("}")[0] + "}" \
            if root.tag.startswith("{") else ""
        return root.findtext(f"{ns}Status") or ""

    async def get_object_version(self, bucket: str, key: str,
                                 version_id: str) -> bytes:
        _, _, body = await self.request(
            "GET", f"/{bucket}/{key}", query={"versionId": version_id})
        return body

    async def list_object_versions(self, bucket: str,
                                   prefix: str = "") -> list[dict]:
        _, _, body = await self.request(
            "GET", f"/{bucket}", query={"versions": "",
                                        "prefix": prefix})
        root = ET.fromstring(body)
        ns = root.tag.partition("}")[0] + "}" \
            if root.tag.startswith("{") else ""
        out = []
        for tag, marker in (("Version", False), ("DeleteMarker", True)):
            for v in root.findall(f"{ns}{tag}"):
                out.append({
                    "key": v.findtext(f"{ns}Key"),
                    "version_id": v.findtext(f"{ns}VersionId"),
                    "is_latest": v.findtext(f"{ns}IsLatest") == "true",
                    "delete_marker": marker,
                    "size": int(v.findtext(f"{ns}Size") or 0)})
        out.sort(key=lambda r: (r["key"], r["version_id"] or ""))
        return out

    # -- lifecycle -----------------------------------------------------------
    async def put_bucket_lifecycle(self, bucket: str,
                                   rules_xml: bytes) -> None:
        await self.request("PUT", f"/{bucket}",
                           query={"lifecycle": ""}, body=rules_xml)

    async def get_bucket_lifecycle(self, bucket: str) -> bytes:
        _, _, body = await self.request("GET", f"/{bucket}",
                                        query={"lifecycle": ""})
        return body

    async def delete_bucket_lifecycle(self, bucket: str) -> None:
        await self.request("DELETE", f"/{bucket}",
                           query={"lifecycle": ""})

    async def copy_object(self, src_bucket: str, src_key: str,
                          bucket: str, key: str) -> None:
        await self.request(
            "PUT", f"/{bucket}/{key}",
            headers={"x-amz-copy-source": f"/{src_bucket}/{src_key}"})

    async def list_objects(self, bucket: str, prefix: str = "",
                           delimiter: str = "",
                           max_keys: int = 1000,
                           continuation: str = "") -> dict:
        q = {"list-type": "2", "max-keys": str(max_keys)}
        if prefix:
            q["prefix"] = prefix
        if delimiter:
            q["delimiter"] = delimiter
        if continuation:
            q["continuation-token"] = continuation
        _, _, body = await self.request("GET", f"/{bucket}", query=q)
        root = ET.fromstring(body)
        ns = {"s3": root.tag[1:].partition("}")[0]}
        return {
            "keys": [e.text for e in root.findall(
                ".//s3:Contents/s3:Key", ns)],
            "prefixes": [e.text for e in root.findall(
                ".//s3:CommonPrefixes/s3:Prefix", ns)],
            "truncated": root.findtext("s3:IsTruncated", "false",
                                       ns) == "true",
            "next": root.findtext("s3:NextContinuationToken", "", ns),
        }

    # -- multipart ----------------------------------------------------------
    async def initiate_multipart(self, bucket: str, key: str) -> str:
        _, _, body = await self.request("POST", f"/{bucket}/{key}",
                                        query={"uploads": ""})
        root = ET.fromstring(body)
        ns = {"s3": root.tag[1:].partition("}")[0]}
        return root.findtext("s3:UploadId", "", ns)

    async def upload_part(self, bucket: str, key: str, upload_id: str,
                          part: int, data: bytes) -> str:
        _, h, _ = await self.request(
            "PUT", f"/{bucket}/{key}",
            query={"partNumber": str(part), "uploadId": upload_id},
            body=data)
        return h.get("etag", "").strip('"')

    async def complete_multipart(self, bucket: str, key: str,
                                 upload_id: str,
                                 parts: list[int]) -> str:
        xml = ("<CompleteMultipartUpload>"
               + "".join(f"<Part><PartNumber>{n}</PartNumber></Part>"
                         for n in parts)
               + "</CompleteMultipartUpload>").encode()
        _, _, body = await self.request(
            "POST", f"/{bucket}/{key}", query={"uploadId": upload_id},
            body=xml)
        root = ET.fromstring(body)
        ns = {"s3": root.tag[1:].partition("}")[0]}
        return (root.findtext("s3:ETag", "", ns) or "").strip('"')

    async def abort_multipart(self, bucket: str, key: str,
                              upload_id: str) -> None:
        await self.request("DELETE", f"/{bucket}/{key}",
                           query={"uploadId": upload_id})
