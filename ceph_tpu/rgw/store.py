"""RGW SAL layer: users, buckets, objects over RADOS.

The rados-driver schema (a compressed rendering of
src/rgw/driver/rados/rgw_rados.cc):

    rgw_users                 omap: access_key -> {secret, uid, display}
    rgw_buckets               omap: bucket -> {id, owner, created}
    bucket_index.<id>         per-bucket index (cls rgw_index omap)
    <id>__shadow_<key>        object data (striped when large)
    <id>__multipart_<key>.<uploadid>.<n>   multipart part data

Object data rides the client-side striper (one logical object -> many
RADOS objects) the way RGW manifests split heads from tails
(rgw_obj_manifest); the head's index entry carries size/etag/manifest.
Writes go through the cls_rgw-style prepare/complete dance so a
crashed gateway leaves a pending marker, not a half-linked entry.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from ..client.rados import RadosError
from ..client.striper import Layout, RadosStriper

USERS_OID = "rgw_users"
BUCKETS_OID = "rgw_buckets"


class RgwError(Exception):
    """Carries the S3 error code (NoSuchBucket, NoSuchKey...)."""

    def __init__(self, code: str, status: int, detail: str = "") -> None:
        super().__init__(f"{code}: {detail}" if detail else code)
        self.code = code
        self.status = status


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime())


class RgwStore:
    def __init__(self, ioctx, stripe_unit: int = 1 << 22) -> None:
        self.ioctx = ioctx
        self.striper = RadosStriper(
            ioctx, Layout(stripe_unit=stripe_unit,
                          object_size=stripe_unit))

    # -- users (RGWUserCtl / radosgw-admin user create) ---------------------
    async def create_user(self, uid: str, display_name: str,
                          access_key: str | None = None,
                          secret: str | None = None) -> dict:
        access_key = access_key or os.urandom(10).hex().upper()
        secret = secret or os.urandom(20).hex()
        user = {"uid": uid, "display_name": display_name,
                "access_key": access_key, "secret": secret}
        await self.ioctx.set_omap(USERS_OID,
                                  {access_key: json.dumps(user).encode()})
        return user

    async def get_user(self, access_key: str) -> dict | None:
        try:
            omap = await self.ioctx.get_omap(USERS_OID)
        except RadosError:
            return None
        raw = omap.get(access_key)
        return json.loads(raw) if raw else None

    # -- buckets ------------------------------------------------------------
    async def _buckets(self) -> dict[str, dict]:
        try:
            raw = await self.ioctx.exec(BUCKETS_OID, "rgw_index",
                                        "dir_list", b"")
        except RadosError:
            return {}
        return json.loads(raw)

    async def create_bucket(self, name: str, owner: str) -> dict:
        # the exists/owner check and the insert commit atomically in
        # the OSD (cls dir_link) -- two concurrent gateways racing the
        # same name must not both win with different bucket ids
        b = {"id": os.urandom(8).hex(), "owner": owner,
             "created": _now_iso(), "name": name}
        try:
            raw = await self.ioctx.exec(
                BUCKETS_OID, "rgw_index", "dir_link",
                json.dumps({"name": name, "meta": b}).encode())
        except RadosError as e:
            if e.errno_name == "EEXIST":
                raise RgwError("BucketAlreadyExists", 409, name) from e
            raise
        return json.loads(raw)     # existing meta on idempotent re-create

    async def get_bucket(self, name: str) -> dict:
        b = (await self._buckets()).get(name)
        if b is None:
            raise RgwError("NoSuchBucket", 404, name)
        return b

    async def delete_bucket(self, name: str) -> None:
        b = await self.get_bucket(name)
        listing = await self.list_objects(name, max_keys=1)
        if listing["entries"]:
            raise RgwError("BucketNotEmpty", 409, name)
        try:
            await self.ioctx.exec(BUCKETS_OID, "rgw_index", "dir_unlink",
                                  json.dumps({"name": name}).encode())
        except RadosError as e:
            if e.errno_name != "ENOENT":
                raise
        try:
            await self.ioctx.remove(self._index(b))
        except RadosError:
            pass

    async def list_buckets(self, owner: str | None = None) -> list[dict]:
        out = [b for b in (await self._buckets()).values()
               if owner is None or b["owner"] == owner]
        return sorted(out, key=lambda b: b["name"])

    # -- objects ------------------------------------------------------------
    def _index(self, bucket: dict) -> str:
        return f"bucket_index.{bucket['id']}"

    def _data_oid(self, bucket: dict, key: str,
                  tag: str = "") -> str:
        # tagged oids give overwrite PUTs a fresh generation: the old
        # generation stays readable until the index flips (rgw keeps
        # old head/tail objects alive until the index transaction
        # lands, then GCs them -- rgw_rados.cc write path)
        base = f"{bucket['id']}__shadow_{key}"
        return f"{base}.{tag}" if tag else base

    def _part_oid(self, bucket: dict, key: str, upload_id: str,
                  part: int) -> str:
        return f"{bucket['id']}__multipart_{key}.{upload_id}.{part}"

    async def _purge_data(self, bucket: dict, key: str,
                          entry: dict | None) -> None:
        """Remove an entry's backing data -- manifest parts for a
        completed multipart object, the shadow object otherwise.  An
        overwrite that skips this leaks the old parts forever (the
        index entry was their only reference)."""
        if entry and "manifest" in entry:
            for part in entry["manifest"]:
                await self.striper.remove(part["oid"])
        oid = (entry or {}).get("data_oid") or self._data_oid(bucket, key)
        await self.striper.remove(oid)

    async def put_object(self, bucket_name: str, key: str, data: bytes,
                         owner: str = "", content_type: str = "",
                         meta: dict | None = None) -> dict:
        bucket = await self.get_bucket(bucket_name)
        tag = os.urandom(8).hex()
        idx = self._index(bucket)
        await self.ioctx.exec(idx, "rgw_index", "prepare", json.dumps(
            {"tag": tag, "key": key, "op": "put"}).encode())
        # atomic replace: the new generation lands under a fresh tagged
        # oid while the old one stays live; the index 'complete' is the
        # commit point, RETURNS the entry it displaced (decided inside
        # the atomic op -- a client-side pre-read races a concurrent
        # PUT), and only then is that displaced data reclaimed.  A
        # crash mid-PUT leaves the old object intact (the orphan new
        # tag is garbage, never reachable).
        soid = self._data_oid(bucket, key, tag)
        try:
            if data:
                await self.striper.write(soid, data, 0)
            etag = hashlib.md5(data).hexdigest()
            entry = {"size": len(data), "etag": etag, "mtime": _now_iso(),
                     "owner": owner, "content_type": content_type,
                     "data_oid": soid, "meta": meta or {}}
            raw = await self.ioctx.exec(
                idx, "rgw_index", "complete",
                json.dumps({"tag": tag, "key": key,
                            "entry": entry}).encode())
        except Exception:
            try:                      # best-effort: the original error
                await self.striper.remove(soid)   # must survive
            except Exception:
                pass
            raise
        await self._purge_replaced(bucket, key, raw, soid)
        return entry

    async def _purge_replaced(self, bucket: dict, key: str,
                              raw: bytes, new_oid: str) -> None:
        """Reclaim the entry the index swap displaced (never the one
        just linked: a same-oid no-op guard keeps a legacy undiffer-
        entiated overwrite from deleting its own data)."""
        if not raw:
            return
        old = json.loads(raw)
        old_oid = old.get("data_oid") or self._data_oid(bucket, key)
        if old_oid == new_oid:
            return
        await self._purge_data(bucket, key, old)

    async def put_object_manifest(self, bucket_name: str, key: str,
                                  parts: list[dict], owner: str,
                                  content_type: str, etag: str,
                                  meta: dict | None = None) -> dict:
        """Link a multipart manifest as the object (complete-upload)."""
        bucket = await self.get_bucket(bucket_name)
        size = sum(p["size"] for p in parts)
        entry = {"size": size, "etag": etag, "mtime": _now_iso(),
                 "owner": owner, "content_type": content_type,
                 "meta": meta or {},
                 "manifest": [{"oid": p["oid"], "size": p["size"]}
                              for p in parts]}
        # index flip first; the swap's displaced entry (returned by
        # the atomic op) is reclaimed only after commit
        raw = await self.ioctx.exec(
            self._index(bucket), "rgw_index", "complete",
            json.dumps({"key": key, "entry": entry}).encode())
        await self._purge_replaced(bucket, key, raw, "")
        return entry

    async def get_entry(self, bucket_name: str, key: str) -> dict:
        bucket = await self.get_bucket(bucket_name)
        try:
            raw = await self.ioctx.exec(
                self._index(bucket), "rgw_index", "get",
                json.dumps({"key": key}).encode())
        except RadosError as e:
            raise RgwError("NoSuchKey", 404, key) from e
        return json.loads(raw)

    async def get_object(self, bucket_name: str, key: str,
                         off: int = 0,
                         length: int | None = None) -> tuple[dict, bytes]:
        bucket = await self.get_bucket(bucket_name)
        entry = await self.get_entry(bucket_name, key)
        if length is None:
            length = entry["size"] - off
        length = max(0, min(length, entry["size"] - off))
        if "manifest" in entry:
            data = await self._read_manifest(entry["manifest"], off,
                                             length)
        else:
            oid = entry.get("data_oid") or self._data_oid(bucket, key)
            data = await self.striper.read(oid, length, off)
        return entry, data

    async def _read_manifest(self, manifest: list[dict], off: int,
                             length: int) -> bytes:
        out = []
        pos = 0
        for part in manifest:
            pend = pos + part["size"]
            if pend > off and pos < off + length:
                s = max(0, off - pos)
                n = min(part["size"], off + length - pos) - s
                out.append(await self.striper.read(part["oid"], n, s))
            pos = pend
            if pos >= off + length:
                break
        return b"".join(out)

    async def delete_object(self, bucket_name: str, key: str) -> None:
        bucket = await self.get_bucket(bucket_name)
        try:
            raw = await self.ioctx.exec(
                self._index(bucket), "rgw_index", "unlink",
                json.dumps({"key": key}).encode())
        except RadosError as e:
            if e.errno_name == "ENOENT":
                return                    # S3 DELETE is idempotent
            raise
        # purge exactly what the atomic unlink removed: two racing
        # deletes cannot double-free, and a racing PUT's fresh
        # generation is never touched
        await self._purge_replaced(bucket, key, raw, "")

    async def list_objects(self, bucket_name: str, prefix: str = "",
                           marker: str = "", max_keys: int = 1000,
                           delimiter: str = "") -> dict:
        bucket = await self.get_bucket(bucket_name)
        entries: list[list] = []
        prefixes: set[str] = set()
        truncated = False
        cursor = marker
        while True:
            raw = json.loads(await self.ioctx.exec(
                self._index(bucket), "rgw_index", "list",
                json.dumps({"prefix": prefix, "marker": cursor,
                            "max": max_keys + 1}).encode()))
            page = raw["entries"]
            if not page:
                truncated = False
                break
            full = False
            for i, (k, e) in enumerate(page):
                cursor = k
                if delimiter:
                    rest = k[len(prefix):]
                    if delimiter in rest:
                        prefixes.add(
                            prefix + rest.split(delimiter)[0] + delimiter)
                        continue
                entries.append([k, e])
                if len(entries) >= max_keys:
                    # more results iff the page has unconsumed items
                    # or the index said there are further pages
                    truncated = (i + 1 < len(page)
                                 or bool(raw["truncated"]))
                    full = True
                    break
            if full:
                break
            if not raw["truncated"]:
                truncated = False
                break
        return {"entries": entries, "truncated": truncated,
                "prefixes": sorted(prefixes),
                "next_marker": entries[-1][0] if entries else ""}

    # -- multipart ----------------------------------------------------------
    async def initiate_multipart(self, bucket_name: str, key: str,
                                 owner: str,
                                 content_type: str = "") -> str:
        bucket = await self.get_bucket(bucket_name)
        upload_id = os.urandom(12).hex()
        await self.ioctx.set_omap(
            f"rgw_uploads.{bucket['id']}",
            {upload_id: json.dumps({
                "key": key, "owner": owner,
                "content_type": content_type,
                "started": _now_iso()}).encode()})
        return upload_id

    async def _upload_meta(self, bucket: dict, upload_id: str) -> dict:
        try:
            omap = await self.ioctx.get_omap(
                f"rgw_uploads.{bucket['id']}")
        except RadosError:
            omap = {}
        raw = omap.get(upload_id)
        if raw is None:
            raise RgwError("NoSuchUpload", 404, upload_id)
        return json.loads(raw)

    async def put_part(self, bucket_name: str, key: str, upload_id: str,
                       part_number: int, data: bytes) -> dict:
        bucket = await self.get_bucket(bucket_name)
        await self._upload_meta(bucket, upload_id)
        oid = self._part_oid(bucket, key, upload_id, part_number)
        await self.striper.remove(oid)
        await self.striper.write(oid, data, 0)
        # record the part so abort can find EXACTLY the uploaded parts
        # (a dense 1..n probe loses parts after a gap)
        await self.ioctx.set_omap(
            f"rgw_uploads.{bucket['id']}",
            {f"{upload_id}.part.{part_number}":
             str(len(data)).encode()})
        return {"etag": hashlib.md5(data).hexdigest(),
                "size": len(data), "oid": oid}

    async def complete_multipart(self, bucket_name: str, key: str,
                                 upload_id: str,
                                 part_numbers: list[int]) -> dict:
        bucket = await self.get_bucket(bucket_name)
        up = await self._upload_meta(bucket, upload_id)
        parts = []
        md5s = []
        for n in part_numbers:
            oid = self._part_oid(bucket, key, upload_id, n)
            size = await self.striper.size(oid)
            if size == 0:
                raise RgwError("InvalidPart", 400, f"part {n}")
            buf = await self.striper.read(oid)
            md5s.append(hashlib.md5(buf).digest())
            parts.append({"oid": oid, "size": size})
        etag = (hashlib.md5(b"".join(md5s)).hexdigest()
                + f"-{len(parts)}")
        entry = await self.put_object_manifest(
            bucket_name, key, parts, up["owner"], up["content_type"],
            etag)
        uploaded = await self._uploaded_parts(bucket, upload_id)
        # parts uploaded but not referenced by the manifest (retries,
        # gaps, unused numbers) are garbage now
        for n in set(uploaded) - set(part_numbers):
            await self.striper.remove(
                self._part_oid(bucket, key, upload_id, n))
        await self.ioctx.rm_omap_keys(
            f"rgw_uploads.{bucket['id']}",
            [upload_id] + [f"{upload_id}.part.{n}" for n in uploaded])
        return entry

    async def _uploaded_parts(self, bucket: dict,
                              upload_id: str) -> list[int]:
        try:
            omap = await self.ioctx.get_omap(
                f"rgw_uploads.{bucket['id']}")
        except RadosError:
            return []
        pre = f"{upload_id}.part."
        return sorted(int(k[len(pre):]) for k in omap
                      if k.startswith(pre))

    async def abort_multipart(self, bucket_name: str, key: str,
                              upload_id: str) -> None:
        bucket = await self.get_bucket(bucket_name)
        await self._upload_meta(bucket, upload_id)
        parts = await self._uploaded_parts(bucket, upload_id)
        for n in parts:
            await self.striper.remove(
                self._part_oid(bucket, key, upload_id, n))
        await self.ioctx.rm_omap_keys(
            f"rgw_uploads.{bucket['id']}",
            [upload_id] + [f"{upload_id}.part.{n}" for n in parts])
