"""RGW SAL layer: users, buckets, objects over RADOS.

The rados-driver schema (a compressed rendering of
src/rgw/driver/rados/rgw_rados.cc):

    rgw_users                 omap: access_key -> {secret, uid, display}
    rgw_buckets               omap: bucket -> {id, owner, created}
    bucket_index.<id>         per-bucket index (cls rgw_index omap)
    <id>__shadow_<key>        object data (striped when large)
    <id>__multipart_<key>.<uploadid>.<n>   multipart part data

Object data rides the client-side striper (one logical object -> many
RADOS objects) the way RGW manifests split heads from tails
(rgw_obj_manifest); the head's index entry carries size/etag/manifest.
Writes go through the cls_rgw-style prepare/complete dance so a
crashed gateway leaves a pending marker, not a half-linked entry.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from ..client.rados import RadosError
from ..client.striper import Layout, RadosStriper

# version ids sort NEWEST-FIRST lexicographically (inverted ns stamp +
# entropy), so the index omap's name order is the S3 version order
def _new_version_id() -> str:
    inv = (1 << 64) - time.time_ns()
    return f"{inv:016x}{os.urandom(4).hex()}"

USERS_OID = "rgw_users"
BUCKETS_OID = "rgw_buckets"


class RgwError(Exception):
    """Carries the S3 error code (NoSuchBucket, NoSuchKey...)."""

    def __init__(self, code: str, status: int, detail: str = "") -> None:
        super().__init__(f"{code}: {detail}" if detail else code)
        self.code = code
        self.status = status


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime())


class RgwStore:
    def __init__(self, ioctx, stripe_unit: int = 1 << 22) -> None:
        from .notify import NotificationManager
        self.ioctx = ioctx
        self.striper = RadosStriper(
            ioctx, Layout(stripe_unit=stripe_unit,
                          object_size=stripe_unit))
        self.notify = NotificationManager(self)

    # -- users (RGWUserCtl / radosgw-admin user create) ---------------------
    async def create_user(self, uid: str, display_name: str,
                          access_key: str | None = None,
                          secret: str | None = None) -> dict:
        access_key = access_key or os.urandom(10).hex().upper()
        secret = secret or os.urandom(20).hex()
        user = {"uid": uid, "display_name": display_name,
                "access_key": access_key, "secret": secret}
        await self.ioctx.set_omap(USERS_OID,
                                  {access_key: json.dumps(user).encode()})
        return user

    async def get_user(self, access_key: str) -> dict | None:
        try:
            omap = await self.ioctx.get_omap(USERS_OID)
        except RadosError:
            return None
        raw = omap.get(access_key)
        return json.loads(raw) if raw else None

    # -- buckets ------------------------------------------------------------
    async def _buckets(self) -> dict[str, dict]:
        try:
            raw = await self.ioctx.exec(BUCKETS_OID, "rgw_index",
                                        "dir_list", b"")
        except RadosError:
            return {}
        return json.loads(raw)

    async def create_bucket(self, name: str, owner: str) -> dict:
        # the exists/owner check and the insert commit atomically in
        # the OSD (cls dir_link) -- two concurrent gateways racing the
        # same name must not both win with different bucket ids
        b = {"id": os.urandom(8).hex(), "owner": owner,
             "created": _now_iso(), "name": name}
        try:
            raw = await self.ioctx.exec(
                BUCKETS_OID, "rgw_index", "dir_link",
                json.dumps({"name": name, "meta": b}).encode())
        except RadosError as e:
            if e.errno_name == "EEXIST":
                raise RgwError("BucketAlreadyExists", 409, name) from e
            raise
        return json.loads(raw)     # existing meta on idempotent re-create

    async def get_bucket(self, name: str) -> dict:
        b = (await self._buckets()).get(name)
        if b is None:
            raise RgwError("NoSuchBucket", 404, name)
        return b

    async def delete_bucket(self, name: str) -> None:
        b = await self.get_bucket(name)
        listing = await self.list_objects(name, max_keys=1)
        if listing["entries"]:
            raise RgwError("BucketNotEmpty", 409, name)
        # versions and delete markers also block deletion (S3 returns
        # BucketNotEmpty until every version is purged) -- the plain
        # listing hides marker-topped keys
        versions = await self.list_object_versions(name, max_keys=1)
        if versions["versions"]:
            raise RgwError("BucketNotEmpty", 409, name)
        try:
            await self.ioctx.exec(BUCKETS_OID, "rgw_index", "dir_unlink",
                                  json.dumps({"name": name}).encode())
        except RadosError as e:
            if e.errno_name != "ENOENT":
                raise
        try:
            await self.ioctx.remove(self._index(b))
        except RadosError:
            pass

    async def list_buckets(self, owner: str | None = None) -> list[dict]:
        out = [b for b in (await self._buckets()).values()
               if owner is None or b["owner"] == owner]
        return sorted(out, key=lambda b: b["name"])

    # -- objects ------------------------------------------------------------
    def _index(self, bucket: dict) -> str:
        return f"bucket_index.{bucket['id']}"

    def _data_oid(self, bucket: dict, key: str,
                  tag: str = "") -> str:
        # tagged oids give overwrite PUTs a fresh generation: the old
        # generation stays readable until the index flips (rgw keeps
        # old head/tail objects alive until the index transaction
        # lands, then GCs them -- rgw_rados.cc write path)
        base = f"{bucket['id']}__shadow_{key}"
        return f"{base}.{tag}" if tag else base

    def _part_oid(self, bucket: dict, key: str, upload_id: str,
                  part: int) -> str:
        return f"{bucket['id']}__multipart_{key}.{upload_id}.{part}"

    async def _purge_data(self, bucket: dict, key: str,
                          entry: dict | None) -> None:
        """Remove an entry's backing data -- manifest parts for a
        completed multipart object, the shadow object otherwise.  An
        overwrite that skips this leaks the old parts forever (the
        index entry was their only reference)."""
        if entry and "manifest" in entry:
            for part in entry["manifest"]:
                await self.striper.remove(part["oid"])
        oid = (entry or {}).get("data_oid") or self._data_oid(bucket, key)
        await self.striper.remove(oid)

    # -- versioning ---------------------------------------------------------
    async def set_bucket_versioning(self, name: str,
                                    state: str) -> None:
        """state: "Enabled" | "Suspended" (PutBucketVersioning)."""
        if state not in ("Enabled", "Suspended"):
            raise RgwError("IllegalVersioningConfiguration", 400, state)
        await self.get_bucket(name)
        await self.ioctx.exec(BUCKETS_OID, "rgw_index", "dir_set",
                              json.dumps({"name": name, "patch": {
                                  "versioning": state}}).encode())

    async def get_bucket_versioning(self, name: str) -> str:
        return (await self.get_bucket(name)).get("versioning", "")

    async def _save_bucket(self, bucket: dict) -> None:
        """Patch mutable bucket metadata (notifications etc.)."""
        await self.ioctx.exec(
            BUCKETS_OID, "rgw_index", "dir_set",
            json.dumps({"name": bucket["name"], "patch": {
                k: bucket.get(k)
                for k in ("notifications",)}}).encode())

    async def list_object_versions(self, bucket_name: str,
                                   prefix: str = "", marker: str = "",
                                   max_keys: int = 1000) -> dict:
        bucket = await self.get_bucket(bucket_name)
        raw = json.loads(await self.ioctx.exec(
            self._index(bucket), "rgw_index", "version_list",
            json.dumps({"prefix": prefix, "marker": marker,
                        "max": max_keys}).encode()))
        return raw

    async def delete_version(self, bucket_name: str, key: str,
                             version_id: str) -> None:
        """Permanent removal of one version (DELETE ?versionId=)."""
        bucket = await self.get_bucket(bucket_name)
        try:
            raw = await self.ioctx.exec(
                self._index(bucket), "rgw_index", "version_rm",
                json.dumps({"key": key,
                            "version_id": version_id}).encode())
        except RadosError as e:
            if e.errno_name == "ENOENT":
                return                    # idempotent
            raise
        entry = json.loads(raw)
        if not entry.get("delete_marker"):
            await self._purge_data(bucket, key, entry)

    # -- lifecycle (rgw_lc.cc compressed) ------------------------------------
    async def set_bucket_lifecycle(self, name: str,
                                   rules: list[dict]) -> None:
        """rules: [{id, prefix, days, noncurrent_days, enabled}]."""
        await self.get_bucket(name)
        await self.ioctx.exec(BUCKETS_OID, "rgw_index", "dir_set",
                              json.dumps({"name": name, "patch": {
                                  "lifecycle": rules}}).encode())

    async def get_bucket_lifecycle(self, name: str) -> list[dict]:
        rules = (await self.get_bucket(name)).get("lifecycle")
        if not rules:
            raise RgwError("NoSuchLifecycleConfiguration", 404, name)
        return rules

    async def delete_bucket_lifecycle(self, name: str) -> None:
        await self.get_bucket(name)
        await self.ioctx.exec(BUCKETS_OID, "rgw_index", "dir_set",
                              json.dumps({"name": name, "patch": {
                                  "lifecycle": None}}).encode())

    @staticmethod
    def _mtime_age(mtime: str, now: float) -> float:
        import calendar
        t = calendar.timegm(time.strptime(mtime,
                                          "%Y-%m-%dT%H:%M:%S.000Z"))
        return now - t

    async def lc_process(self, bucket_name: str,
                         now: float | None = None) -> int:
        """Run this bucket's lifecycle rules once (RGWLC::process):
        expire current objects past Days (delete, or delete-marker on
        versioned buckets), reap noncurrent versions past
        NoncurrentDays, and drop expired delete markers that are the
        only thing left of a key.  Returns the action count."""
        bucket = await self.get_bucket(bucket_name)
        rules = [r for r in bucket.get("lifecycle") or []
                 if r.get("enabled", True)]
        if not rules:
            return 0
        now = time.time() if now is None else now
        versioned = bool(bucket.get("versioning"))
        actions = 0
        for rule in rules:
            prefix = rule.get("prefix", "")
            days = rule.get("days")
            if days is not None:
                listing = await self.list_objects(
                    bucket_name, prefix=prefix, max_keys=100000)
                for key, entry in listing["entries"]:
                    if self._mtime_age(entry["mtime"],
                                       now) >= days * 86400:
                        await self.delete_object(bucket_name, key,
                                                 notify=False)
                        await self.notify.emit(
                            bucket, "s3:ObjectLifecycle:Expiration:"
                            "Current", key)
                        actions += 1
            nc_days = rule.get("noncurrent_days")
            if versioned and nc_days is not None:
                vl = await self.list_object_versions(
                    bucket_name, prefix=prefix, max_keys=100000)
                for key, vid, entry, is_latest in vl["versions"]:
                    if is_latest:
                        continue
                    if self._mtime_age(entry["mtime"],
                                       now) >= nc_days * 86400:
                        await self.delete_version(bucket_name, key,
                                                  vid)
                        await self.notify.emit(
                            bucket, "s3:ObjectLifecycle:Expiration:"
                            "NoncurrentVersion", key, version_id=vid)
                        actions += 1
            if versioned and rule.get("expired_delete_marker"):
                vl = await self.list_object_versions(
                    bucket_name, prefix=prefix, max_keys=100000)
                per_key: dict[str, list] = {}
                for row in vl["versions"]:
                    per_key.setdefault(row[0], []).append(row)
                for key, rows in per_key.items():
                    if len(rows) == 1 and rows[0][2].get(
                            "delete_marker"):
                        await self.delete_version(bucket_name, key,
                                                  rows[0][1])
                        actions += 1
        return actions

    async def put_object(self, bucket_name: str, key: str, data: bytes,
                         owner: str = "", content_type: str = "",
                         meta: dict | None = None) -> dict:
        bucket = await self.get_bucket(bucket_name)
        versioning = bucket.get("versioning", "")
        if versioning:
            return await self._put_object_versioned(
                bucket, key, data, owner, content_type, meta,
                suspended=versioning == "Suspended")
        tag = os.urandom(8).hex()
        idx = self._index(bucket)
        await self.ioctx.exec(idx, "rgw_index", "prepare", json.dumps(
            {"tag": tag, "key": key, "op": "put"}).encode())
        # atomic replace: the new generation lands under a fresh tagged
        # oid while the old one stays live; the index 'complete' is the
        # commit point, RETURNS the entry it displaced (decided inside
        # the atomic op -- a client-side pre-read races a concurrent
        # PUT), and only then is that displaced data reclaimed.  A
        # crash mid-PUT leaves the old object intact (the orphan new
        # tag is garbage, never reachable).
        soid = self._data_oid(bucket, key, tag)
        try:
            if data:
                await self.striper.write(soid, data, 0)
            etag = hashlib.md5(data).hexdigest()
            entry = {"size": len(data), "etag": etag, "mtime": _now_iso(),
                     "owner": owner, "content_type": content_type,
                     "data_oid": soid, "meta": meta or {}}
            raw = await self.ioctx.exec(
                idx, "rgw_index", "complete",
                json.dumps({"tag": tag, "key": key,
                            "entry": entry}).encode())
        except Exception:
            try:                      # best-effort: the original error
                await self.striper.remove(soid)   # must survive
            except Exception:
                pass
            raise
        await self._purge_replaced(bucket, key, raw, soid)
        await self.notify.emit(bucket, "s3:ObjectCreated:Put", key,
                               size=len(data), etag=etag)
        return entry

    async def _put_object_versioned(self, bucket: dict, key: str,
                                    data: bytes, owner: str,
                                    content_type: str,
                                    meta: dict | None,
                                    suspended: bool) -> dict:
        """Versioned PUT: every write is a NEW generation under its
        own version id (rgw_rados versioned write path); Enabled keeps
        old versions readable, Suspended reuses the "null" id and
        reclaims only its previous occupant."""
        vid = "null" if suspended else _new_version_id()
        # the DATA oid is always a fresh generation, even for the
        # reused "null" id: overwriting the live null version's bytes
        # in place would corrupt it on a crash mid-PUT, and the error
        # path below must only ever remove bytes nothing references
        tag = vid if not suspended else f"null.{os.urandom(6).hex()}"
        soid = self._data_oid(bucket, key, tag)
        try:
            if data:
                await self.striper.write(soid, data, 0)
            entry = {"size": len(data),
                     "etag": hashlib.md5(data).hexdigest(),
                     "mtime": _now_iso(), "owner": owner,
                     "content_type": content_type, "data_oid": soid,
                     "version_id": vid, "meta": meta or {}}
            raw = await self.ioctx.exec(
                self._index(bucket), "rgw_index", "version_put",
                json.dumps({"key": key, "entry": entry,
                            "suspended": suspended}).encode())
        except Exception:
            try:
                await self.striper.remove(soid)
            except Exception:
                pass
            raise
        await self._purge_replaced(bucket, key, raw, soid)
        await self.notify.emit(bucket, "s3:ObjectCreated:Put", key,
                               size=len(data),
                               etag=entry["etag"], version_id=vid)
        return entry

    async def put_delete_marker(self, bucket: dict, key: str,
                                suspended: bool,
                                notify: bool = True) -> str:
        """S3 DELETE in a versioned bucket: a delete MARKER becomes
        the current version; data stays."""
        vid = "null" if suspended else _new_version_id()
        entry = {"size": 0, "etag": "", "mtime": _now_iso(),
                 "delete_marker": True, "version_id": vid, "meta": {}}
        raw = await self.ioctx.exec(
            self._index(bucket), "rgw_index", "version_put",
            json.dumps({"key": key, "entry": entry,
                        "suspended": suspended}).encode())
        await self._purge_replaced(bucket, key, raw, "")
        if notify:
            await self.notify.emit(
                bucket, "s3:ObjectRemoved:DeleteMarkerCreated", key,
                version_id=vid)
        return vid

    async def _purge_replaced(self, bucket: dict, key: str,
                              raw: bytes, new_oid: str) -> None:
        """Reclaim the entry the index swap displaced (never the one
        just linked: a same-oid no-op guard keeps a legacy undiffer-
        entiated overwrite from deleting its own data)."""
        if not raw:
            return
        old = json.loads(raw)
        old_oid = old.get("data_oid") or self._data_oid(bucket, key)
        if old_oid == new_oid:
            return
        await self._purge_data(bucket, key, old)

    async def put_object_manifest(self, bucket_name: str, key: str,
                                  parts: list[dict], owner: str,
                                  content_type: str, etag: str,
                                  meta: dict | None = None) -> dict:
        """Link a multipart manifest as the object (complete-upload)."""
        bucket = await self.get_bucket(bucket_name)
        size = sum(p["size"] for p in parts)
        entry = {"size": size, "etag": etag, "mtime": _now_iso(),
                 "owner": owner, "content_type": content_type,
                 "meta": meta or {},
                 "manifest": [{"oid": p["oid"], "size": p["size"]}
                              for p in parts]}
        # index flip first; the swap's displaced entry (returned by
        # the atomic op) is reclaimed only after commit
        raw = await self.ioctx.exec(
            self._index(bucket), "rgw_index", "complete",
            json.dumps({"key": key, "entry": entry}).encode())
        await self._purge_replaced(bucket, key, raw, "")
        await self.notify.emit(
            bucket, "s3:ObjectCreated:CompleteMultipartUpload", key,
            size=entry.get("size", 0), etag=entry.get("etag", ""))
        return entry

    async def get_entry(self, bucket_name: str, key: str,
                        version_id: str | None = None) -> dict:
        bucket = await self.get_bucket(bucket_name)
        try:
            if version_id:
                raw = await self.ioctx.exec(
                    self._index(bucket), "rgw_index", "get_version",
                    json.dumps({"key": key,
                                "version_id": version_id}).encode())
            else:
                raw = await self.ioctx.exec(
                    self._index(bucket), "rgw_index", "get",
                    json.dumps({"key": key}).encode())
        except RadosError as e:
            raise RgwError("NoSuchKey", 404, key) from e
        entry = json.loads(raw)
        if entry.get("delete_marker") and not version_id:
            raise RgwError("NoSuchKey", 404, key)
        return entry

    async def get_object(self, bucket_name: str, key: str,
                         off: int = 0,
                         length: int | None = None,
                         version_id: str | None = None
                         ) -> tuple[dict, bytes]:
        bucket = await self.get_bucket(bucket_name)
        entry = await self.get_entry(bucket_name, key, version_id)
        if length is None:
            length = entry["size"] - off
        length = max(0, min(length, entry["size"] - off))
        if "manifest" in entry:
            data = await self._read_manifest(entry["manifest"], off,
                                             length)
        else:
            oid = entry.get("data_oid") or self._data_oid(bucket, key)
            data = await self.striper.read(oid, length, off)
        return entry, data

    async def _read_manifest(self, manifest: list[dict], off: int,
                             length: int) -> bytes:
        out = []
        pos = 0
        for part in manifest:
            pend = pos + part["size"]
            if pend > off and pos < off + length:
                s = max(0, off - pos)
                n = min(part["size"], off + length - pos) - s
                out.append(await self.striper.read(part["oid"], n, s))
            pos = pend
            if pos >= off + length:
                break
        return b"".join(out)

    async def delete_object(self, bucket_name: str, key: str,
                            notify: bool = True) -> str | None:
        bucket = await self.get_bucket(bucket_name)
        versioning = bucket.get("versioning", "")
        if versioning:
            return await self.put_delete_marker(
                bucket, key, suspended=versioning == "Suspended",
                notify=notify)
        try:
            raw = await self.ioctx.exec(
                self._index(bucket), "rgw_index", "unlink",
                json.dumps({"key": key}).encode())
        except RadosError as e:
            if e.errno_name == "ENOENT":
                return                    # S3 DELETE is idempotent
            raise
        # purge exactly what the atomic unlink removed: two racing
        # deletes cannot double-free, and a racing PUT's fresh
        # generation is never touched
        await self._purge_replaced(bucket, key, raw, "")
        if notify:
            await self.notify.emit(bucket, "s3:ObjectRemoved:Delete",
                                   key)

    async def list_objects(self, bucket_name: str, prefix: str = "",
                           marker: str = "", max_keys: int = 1000,
                           delimiter: str = "") -> dict:
        bucket = await self.get_bucket(bucket_name)
        entries: list[list] = []
        prefixes: set[str] = set()
        truncated = False
        cursor = marker
        while True:
            raw = json.loads(await self.ioctx.exec(
                self._index(bucket), "rgw_index", "list",
                json.dumps({"prefix": prefix, "marker": cursor,
                            "max": max_keys + 1}).encode()))
            page = raw["entries"]
            if not page:
                truncated = False
                break
            full = False
            for i, (k, e) in enumerate(page):
                cursor = k
                if delimiter:
                    rest = k[len(prefix):]
                    if delimiter in rest:
                        prefixes.add(
                            prefix + rest.split(delimiter)[0] + delimiter)
                        continue
                entries.append([k, e])
                if len(entries) >= max_keys:
                    # more results iff the page has unconsumed items
                    # or the index said there are further pages
                    truncated = (i + 1 < len(page)
                                 or bool(raw["truncated"]))
                    full = True
                    break
            if full:
                break
            if not raw["truncated"]:
                truncated = False
                break
        return {"entries": entries, "truncated": truncated,
                "prefixes": sorted(prefixes),
                "next_marker": entries[-1][0] if entries else ""}

    # -- multipart ----------------------------------------------------------
    async def initiate_multipart(self, bucket_name: str, key: str,
                                 owner: str,
                                 content_type: str = "") -> str:
        bucket = await self.get_bucket(bucket_name)
        upload_id = os.urandom(12).hex()
        await self.ioctx.set_omap(
            f"rgw_uploads.{bucket['id']}",
            {upload_id: json.dumps({
                "key": key, "owner": owner,
                "content_type": content_type,
                "started": _now_iso()}).encode()})
        return upload_id

    async def _upload_meta(self, bucket: dict, upload_id: str) -> dict:
        try:
            omap = await self.ioctx.get_omap(
                f"rgw_uploads.{bucket['id']}")
        except RadosError:
            omap = {}
        raw = omap.get(upload_id)
        if raw is None:
            raise RgwError("NoSuchUpload", 404, upload_id)
        return json.loads(raw)

    async def put_part(self, bucket_name: str, key: str, upload_id: str,
                       part_number: int, data: bytes) -> dict:
        bucket = await self.get_bucket(bucket_name)
        await self._upload_meta(bucket, upload_id)
        oid = self._part_oid(bucket, key, upload_id, part_number)
        await self.striper.remove(oid)
        await self.striper.write(oid, data, 0)
        # record the part so abort can find EXACTLY the uploaded parts
        # (a dense 1..n probe loses parts after a gap)
        await self.ioctx.set_omap(
            f"rgw_uploads.{bucket['id']}",
            {f"{upload_id}.part.{part_number}":
             str(len(data)).encode()})
        return {"etag": hashlib.md5(data).hexdigest(),
                "size": len(data), "oid": oid}

    async def complete_multipart(self, bucket_name: str, key: str,
                                 upload_id: str,
                                 part_numbers: list[int]) -> dict:
        bucket = await self.get_bucket(bucket_name)
        up = await self._upload_meta(bucket, upload_id)
        parts = []
        md5s = []
        for n in part_numbers:
            oid = self._part_oid(bucket, key, upload_id, n)
            size = await self.striper.size(oid)
            if size == 0:
                raise RgwError("InvalidPart", 400, f"part {n}")
            buf = await self.striper.read(oid)
            md5s.append(hashlib.md5(buf).digest())
            parts.append({"oid": oid, "size": size})
        etag = (hashlib.md5(b"".join(md5s)).hexdigest()
                + f"-{len(parts)}")
        entry = await self.put_object_manifest(
            bucket_name, key, parts, up["owner"], up["content_type"],
            etag)
        uploaded = await self._uploaded_parts(bucket, upload_id)
        # parts uploaded but not referenced by the manifest (retries,
        # gaps, unused numbers) are garbage now
        for n in set(uploaded) - set(part_numbers):
            await self.striper.remove(
                self._part_oid(bucket, key, upload_id, n))
        await self.ioctx.rm_omap_keys(
            f"rgw_uploads.{bucket['id']}",
            [upload_id] + [f"{upload_id}.part.{n}" for n in uploaded])
        return entry

    async def _uploaded_parts(self, bucket: dict,
                              upload_id: str) -> list[int]:
        try:
            omap = await self.ioctx.get_omap(
                f"rgw_uploads.{bucket['id']}")
        except RadosError:
            return []
        pre = f"{upload_id}.part."
        return sorted(int(k[len(pre):]) for k in omap
                      if k.startswith(pre))

    async def abort_multipart(self, bucket_name: str, key: str,
                              upload_id: str) -> None:
        bucket = await self.get_bucket(bucket_name)
        await self._upload_meta(bucket, upload_id)
        parts = await self._uploaded_parts(bucket, upload_id)
        for n in parts:
            await self.striper.remove(
                self._part_oid(bucket, key, upload_id, n))
        await self.ioctx.rm_omap_keys(
            f"rgw_uploads.{bucket['id']}",
            [upload_id] + [f"{upload_id}.part.{n}" for n in parts])
