"""RGW analog: S3-style object gateway over RADOS.

Reference: src/rgw (op layer rgw_op.cc, request pump
rgw_process.cc:265, SAL driver abstraction driver/rados).  store.py is
the SAL layer; gateway.py the asio-frontend + auth + XML analog.
"""

from .store import RgwStore, RgwError
from .gateway import Gateway

__all__ = ["RgwStore", "RgwError", "Gateway"]
