"""ClientSwarm: N concurrent librados users driving one op schedule.

Clients are coroutines over real ``IoCtx`` handles (client/rados.py →
Objecter → messenger), multiplexed over a configurable number of
Rados connections so the messenger layer sees realistic connection
fan-in.  Per-op latency goes into log-bucketed histograms per op
class — p50/p95/p99/p99.9 without storing a sample per op — and the
process-wide ``workload`` perf set (adopted into OSD perf dumps)
counts ops/bytes/errors.

Issue disciplines:

* closed loop — each client issues its next op when the previous one
  completes; with ``target_qps`` set, op i additionally never issues
  before ``t0 + i/qps`` (rate-limited closed loop, the convergence
  mode the tests pin);
* open loop — ops fire AT schedule time regardless of completions
  (queueing delay shows up as latency, not as reduced offered load),
  with a safety-valve in-flight cap whose stalls are counted, never
  hidden.
"""

from __future__ import annotations

import asyncio
import time

from ..client.rados import IoCtx, Rados, RadosError
from ..client.objecter import ObjecterError
from ..common.config import ConfigProxy
from .histogram import LatencyHistogram
from .spec import KINDS, Op, WorkloadSpec, payload_for
from .stats import PERF


class PhaseResult:
    """One phase's outcome: deterministic tallies + measured timings."""

    def __init__(self, label: str) -> None:
        self.label = label
        self.hists = {k: LatencyHistogram() for k in KINDS}
        self.ops = 0
        self.bytes_read = 0
        self.bytes_written = 0
        self.errors: list[dict] = []
        self.wedged = 0
        self.open_loop_stalls = 0
        self.elapsed = 0.0

    @property
    def failed(self) -> int:
        return len(self.errors)

    def to_dict(self) -> dict:
        total_bytes = self.bytes_read + self.bytes_written
        lat = {k: h.summary() for k, h in self.hists.items()
               if h.n}
        return {
            "label": self.label,
            "ops": self.ops,
            "failed_ops": self.failed,
            "wedged_ops": self.wedged,
            "bytes_read": self.bytes_read,
            "bytes_written": self.bytes_written,
            "open_loop_stalls": self.open_loop_stalls,
            "errors": self.errors[:16],      # first few, not megabytes
            "timing": {
                "elapsed_s": round(self.elapsed, 3),
                "ops_per_s": round(self.ops / self.elapsed, 1)
                if self.elapsed else 0.0,
                "GiBps": round(total_bytes / self.elapsed / 2**30, 4)
                if self.elapsed else 0.0,
                "latency": lat,
            },
        }


class ClientSwarm:
    def __init__(self, spec: WorkloadSpec, mon_addr,
                 conf: ConfigProxy | None = None) -> None:
        self.spec = spec
        self.mon_addr = tuple(mon_addr)
        self.conf = conf or ConfigProxy()
        self.handles: list[Rados] = []
        self.ioctxs: list[IoCtx] = []
        self.op_timeout = float(self.conf.get("loadgen_op_timeout"))

    # -- lifecycle ----------------------------------------------------------
    async def start(self) -> None:
        n = min(int(self.conf.get("loadgen_rados_handles")),
                max(1, self.spec.n_clients))
        for i in range(n):
            r = Rados(self.mon_addr, name=f"client.loadgen{i}")
            await r.connect()
            self.handles.append(r)
        io0 = await self.handles[0].open_ioctx(self.spec.pool)
        self.ioctxs = [io0] + [
            IoCtx(r, self.spec.pool, io0.pool_id)
            for r in self.handles[1:]]

    async def shutdown(self) -> None:
        for r in self.handles:
            await r.shutdown()
        self.handles, self.ioctxs = [], []

    def _io(self, client_idx: int) -> IoCtx:
        return self.ioctxs[client_idx % len(self.ioctxs)]

    # -- one op -------------------------------------------------------------
    async def _do_op(self, op: Op, io: IoCtx,
                     res: PhaseResult) -> None:
        t0 = time.perf_counter()
        try:
            if op.kind == "read":
                data = await asyncio.wait_for(
                    io.read(op.oid), self.op_timeout)
                res.bytes_read += len(data)
                PERF.inc("bytes_read", len(data))
            elif op.kind == "write":
                await asyncio.wait_for(
                    io.write_full(op.oid,
                                  payload_for(self.spec, op.size)),
                    self.op_timeout)
                res.bytes_written += op.size
                PERF.inc("bytes_written", op.size)
            else:                      # rmw: partial overwrite
                await asyncio.wait_for(
                    io.write(op.oid, payload_for(self.spec, op.size),
                             offset=op.off),
                    self.op_timeout)
                res.bytes_written += op.size
                PERF.inc("bytes_written", op.size)
        except asyncio.TimeoutError:
            res.wedged += 1
            res.errors.append({"op": op.kind, "oid": op.oid,
                               "err": "WEDGED"})
            PERF.inc("op_wedged")
            PERF.inc("op_errors")
            return
        except (RadosError, ObjecterError, ConnectionError,
                OSError) as e:
            res.errors.append({"op": op.kind, "oid": op.oid,
                               "err": str(e)[:120]})
            PERF.inc("op_errors")
            return
        res.hists[op.kind].record(time.perf_counter() - t0)
        res.ops += 1
        PERF.inc(f"ops_{op.kind}")

    # -- phases -------------------------------------------------------------
    async def preload(self) -> PhaseResult:
        """Write the whole working set (the load phase)."""
        res = PhaseResult("load")
        sem = asyncio.Semaphore(
            int(self.conf.get("loadgen_preload_concurrency")))
        t0 = time.perf_counter()

        async def one(i: int, op: Op) -> None:
            async with sem:
                await self._do_op(op, self._io(i), res)

        await asyncio.gather(*(one(i, op) for i, op in
                               enumerate(self.spec.preload_ops())))
        res.elapsed = time.perf_counter() - t0
        return res

    async def run_phase(self, ops: list[Op], label: str,
                        mode: str | None = None,
                        target_qps: float | None = None) -> PhaseResult:
        mode = mode or self.spec.mode
        target_qps = (self.spec.target_qps if target_qps is None
                      else target_qps)
        if mode == "open":
            return await self._run_open(ops, label, target_qps)
        return await self._run_closed(ops, label, target_qps)

    async def _run_closed(self, ops: list[Op], label: str,
                          qps: float) -> PhaseResult:
        """N clients, each issuing when its previous op completes;
        with a QPS target, op i is additionally held until its
        schedule time t0 + i/qps."""
        res = PhaseResult(label)
        it = iter(enumerate(ops))
        t0 = time.perf_counter()

        async def client(idx: int) -> None:
            for i, op in it:
                if qps > 0:
                    due = t0 + i / qps
                    delay = due - time.perf_counter()
                    if delay > 0:
                        await asyncio.sleep(delay)
                await self._do_op(op, self._io(idx), res)

        await asyncio.gather(*(client(c)
                               for c in range(self.spec.n_clients)))
        res.elapsed = time.perf_counter() - t0
        return res

    async def _run_open(self, ops: list[Op], label: str,
                        qps: float) -> PhaseResult:
        """Dispatch at schedule time, completions decoupled: queueing
        shows up as tail latency instead of lowering offered load."""
        res = PhaseResult(label)
        cap = int(self.conf.get("loadgen_open_max_inflight"))
        sem = asyncio.Semaphore(cap)
        tasks: list[asyncio.Task] = []
        t0 = time.perf_counter()

        async def one(i: int, op: Op) -> None:
            try:
                await self._do_op(op, self._io(i), res)
            finally:
                sem.release()

        for i, op in enumerate(ops):
            due = t0 + i / qps
            delay = due - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            if sem.locked():
                # offered load exceeded the safety valve: record the
                # stall -- the run is no longer truly open-loop
                res.open_loop_stalls += 1
                PERF.inc("open_loop_stalls")
            await sem.acquire()
            tasks.append(asyncio.ensure_future(one(i, op)))
        await asyncio.gather(*tasks)
        res.elapsed = time.perf_counter() - t0
        return res
