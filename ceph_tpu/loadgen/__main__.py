"""CLI: python -m ceph_tpu.loadgen [--osds 8 --objects 1000 ...]

Runs one WorkloadSpec through the driver and prints the JSON report
(progress to stderr).  ``bench.py --cluster`` wraps the same engine
in the round-bench JSON contract; this entry is for interactive
exploration of the knobs.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from .driver import degradation_ratios, run_workload
from .spec import WorkloadSpec


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ceph_tpu.loadgen")
    p.add_argument("--osds", type=int, default=8)
    p.add_argument("--pg-num", type=int, default=64)
    p.add_argument("--pool-type", default="erasure",
                   choices=["erasure", "replicated"])
    p.add_argument("--k", type=int, default=2)
    p.add_argument("--m", type=int, default=1)
    p.add_argument("--size", type=int, default=3,
                   help="replica count (replicated pools)")
    p.add_argument("--objects", type=int, default=1000)
    p.add_argument("--obj-kib", type=int, default=16)
    p.add_argument("--size-dist", default="fixed",
                   choices=["fixed", "uniform", "lognormal"])
    p.add_argument("--ops", type=int, default=2000)
    p.add_argument("--read-frac", type=float, default=0.5)
    p.add_argument("--write-frac", type=float, default=0.35)
    p.add_argument("--rmw-frac", type=float, default=0.15)
    p.add_argument("--popularity", default="zipf",
                   choices=["zipf", "uniform"])
    p.add_argument("--zipf-s", type=float, default=1.1)
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--mode", default="closed",
                   choices=["closed", "open"])
    p.add_argument("--qps", type=float, default=0.0)
    p.add_argument("--recovery-ops", type=int, default=0,
                   help="ops per interference sub-phase (0 = skip "
                        "the kill/revive phases)")
    p.add_argument("--kill-osds", type=int, default=1)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--quiet", action="store_true")
    return p


def spec_from_args(args) -> WorkloadSpec:
    return WorkloadSpec(
        n_osds=args.osds, pg_num=args.pg_num,
        pool_type=args.pool_type, ec_k=args.k, ec_m=args.m,
        replica_size=args.size,
        n_objects=args.objects, obj_size=args.obj_kib * 1024,
        size_dist=args.size_dist,
        n_ops=args.ops, read_frac=args.read_frac,
        write_frac=args.write_frac, rmw_frac=args.rmw_frac,
        popularity=args.popularity, zipf_s=args.zipf_s,
        n_clients=args.clients, mode=args.mode, target_qps=args.qps,
        recovery_ops=args.recovery_ops, kill_osds=args.kill_osds,
        seed=args.seed).validate()


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    def log(msg: str) -> None:
        if not args.quiet:
            print(msg, file=sys.stderr, flush=True)

    report = asyncio.new_event_loop().run_until_complete(
        run_workload(spec_from_args(args), log=log))
    report["p99_degradation"] = {
        phase: degradation_ratios(report, phase)
        for phase in ("degraded", "backfill")
        if phase in report.get("phases", {})}
    print(json.dumps(report, indent=1), flush=True)
    failed = sum(ph.get("failed_ops", 0)
                 for ph in report["phases"].values())
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
