"""SimCluster: mon + N OSDs in one process, scaled past toy size.

The vstart-style bring-up that ``bench.py --osd-path`` and
``tools/chaos.py`` each grew privately, factored out and scaled: OSDs
boot in small concurrent batches (serial boot of 64+ daemons pays one
mon round trip each), large clusters get slower heartbeats plus the
capped heartbeat fanout (``osd_heartbeat_max_peers``) so the ping
mesh stays O(N), and the kill/revive/wait helpers the chaos driver
pioneered live here for any harness to reuse.

``ChaosCluster`` (tools/chaos.py) subclasses this and adds its raw
messenger client; the loadgen swarm talks librados instead.
"""

from __future__ import annotations

import asyncio
import time

from ..common.faults import MessageFaultInjector
from ..mon import Monitor
from ..osd import OSD

# bring-up concurrency: mon paxos serializes the boots anyway; small
# batches overlap messenger setup without racing id assignment hard
BOOT_BATCH = 8


class SimCluster:
    """Mon + N OSDs with kill/revive helpers and perf aggregation."""

    def __init__(self, mon: Monitor, osds: list[OSD],
                 faults: MessageFaultInjector | None = None) -> None:
        self.mon = mon
        self.osds = osds
        self.faults = faults

    @classmethod
    async def create(cls, n_osds: int = 3, *,
                     mon_config: dict | None = None,
                     osd_config: dict | None = None,
                     faults: MessageFaultInjector | None = None,
                     log=None) -> "SimCluster":
        cls._tune_placement_for_scale(n_osds)
        mon = Monitor(rank=0, config={"mon_osd_min_down_reporters": 1,
                                      **(mon_config or {})})
        addr = await mon.start()
        cfg = dict(cls.scaled_osd_config(n_osds))
        cfg.update(osd_config or {})
        osds: list[OSD] = []

        async def boot(i: int) -> OSD:
            osd = OSD(host=f"host{i}", config=cfg,
                      fault_injector=faults)
            await osd.start(addr)
            return osd

        for base in range(0, n_osds, BOOT_BATCH):
            batch = range(base, min(base + BOOT_BATCH, n_osds))
            osds.extend(await asyncio.gather(*(boot(i) for i in batch)))
            if log is not None and n_osds > BOOT_BATCH:
                log(f"  booted {len(osds)}/{n_osds} osds")
        return cls(mon, osds, faults=faults)

    @staticmethod
    def _tune_placement_for_scale(n_osds: int) -> None:
        """Big clusters must ride the fused placement path.

        The scalar per-PG CRUSH sweep costs ~0.5s per table rebuild on
        a 64-OSD map; during peering/recovery churn every daemon
        rebuilds per epoch, which saturates the event loop, delays
        heartbeats, triggers FALSE failure reports and feeds back into
        more epochs (observed as a 48-OSD bring-up wedged for minutes).
        Lowering the fused first-compile threshold (the same module
        knob ``bench.py --placement --smoke`` pins) makes the first
        post-pool-create rebuild pay one jit compile and every later
        epoch a ~ms vectorized launch.  An explicit operator override
        via CEPH_TPU_PLACEMENT_FUSED_MIN is respected.
        """
        import os
        if n_osds < 24 or "CEPH_TPU_PLACEMENT_FUSED_MIN" in os.environ:
            return
        from ..mon import pg_mapping
        pg_mapping.FUSED_MIN_LANES = min(pg_mapping.FUSED_MIN_LANES,
                                         192)

    @staticmethod
    def scaled_osd_config(n_osds: int) -> dict:
        """Defaults that keep a big cluster's control plane cheap:
        the heartbeat interval backs off with size (the capped fanout
        bounds per-OSD cost, this bounds aggregate message rate) while
        the grace scales with it so detection stays reliable."""
        if n_osds <= 16:
            return {"osd_heartbeat_interval": 0.5,
                    "osd_heartbeat_grace": 3.0}
        interval = 1.0 if n_osds <= 128 else 2.0
        return {"osd_heartbeat_interval": interval,
                "osd_heartbeat_grace": 6 * interval}

    @property
    def addr(self):
        return self.mon.addr

    async def stop(self) -> None:
        for o in self.osds:
            await o.stop()
        await self.mon.stop()

    # -- fault actions (the chaos machinery, shared) -------------------------
    async def kill_osd(self, index: int) -> dict:
        """Stop an OSD, keeping what a revive needs."""
        osd = self.osds[index]
        token = osd.revive_token()
        await osd.stop()
        return token

    async def revive_osd(self, index: int, token: dict) -> None:
        osd = OSD(uuid=token["uuid"], whoami=token["whoami"],
                  store=token["store"], host=token["host"],
                  config=token["config"], fault_injector=self.faults)
        await osd.start(self.mon.addr)
        self.osds[index] = osd

    async def wait_down(self, osd_id: int, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self.mon.osd_is_up(osd_id):
                return True
            await asyncio.sleep(0.2)
        return False

    async def wait_up(self, osd_id: int, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.mon.osd_is_up(osd_id):
                return True
            await asyncio.sleep(0.2)
        return False

    async def wait_clean(self, timeout: float = 30.0) -> bool:
        """Best-effort wait until no primary has pending recovery."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not any(osd.has_pending_recovery()
                       for osd in self.osds):
                return True
            await asyncio.sleep(0.2)
        return False

    # -- observability -------------------------------------------------------
    def perf_counters(self, which: str) -> dict:
        """One counter set summed across live OSDs; numeric values
        only (histogram/avg dict entries are skipped — use
        ``perf_dump`` for the full structures)."""
        out: dict[str, int | float] = {}
        for osd in self.osds:
            # a killed-but-not-yet-revived OSD still sits in the list;
            # counting its frozen lifetime counters makes phase deltas
            # spanning the revive (which swaps in a fresh instance, at
            # zero) go negative
            if osd.is_stopped():
                continue
            pc = osd.perf.get(which)
            if pc is None:
                continue
            for key, val in pc.dump().items():
                if isinstance(val, (int, float)):
                    out[key] = out.get(key, 0) + val
        return out

    def scheduler_counters(self) -> dict:
        """The dmClock sets rolled up for QoS reporting: dispatch and
        enqueue totals summed, queue-depth gauges reported as the MAX
        across OSDs (a sum of instantaneous depths means nothing)."""
        out: dict[str, float] = {}
        for osd in self.osds:
            if osd.is_stopped():
                continue
            pc = osd.perf.get("scheduler")
            if pc is None:
                continue
            for key, val in pc.dump().items():
                if not isinstance(val, (int, float)):
                    continue
                if key.startswith("depth"):
                    out[key] = max(out.get(key, 0), val)
                else:
                    out[key] = out.get(key, 0) + val
        return out

    def pg_states(self) -> dict[str, int]:
        states: dict[str, int] = {}
        for osd in self.osds:
            if osd.is_stopped():
                continue
            for state, n in osd.primary_pg_states().items():
                states[state] = states.get(state, 0) + n
        return states
