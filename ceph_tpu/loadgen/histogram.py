"""Log-bucketed latency histogram: tails without storing samples.

The HdrHistogram idea in miniature: bucket bounds grow geometrically
(default 2^(1/8) per bucket, i.e. 8 sub-buckets per octave), so any
reported percentile is within a bounded RELATIVE error of the true
sample — ``growth - 1`` (~9%) worst case — while memory stays O(log
range) no matter how many million ops are recorded.  Exact count,
sum, min and max ride along, so means and ops/s are exact.

Percentile values are the geometric midpoint of the selected bucket
(the unbiased point under the log layout); ``percentile_bounds``
returns the enclosing interval for callers (and tests) that need the
guarantee, not the estimate.
"""

from __future__ import annotations

import math

DEFAULT_GROWTH = 2 ** 0.125     # 8 buckets per octave, <=9.1% error
DEFAULT_MIN = 1e-5              # 10us: below client-op resolution

PERCENTILES = (50.0, 95.0, 99.0, 99.9)


class LatencyHistogram:
    __slots__ = ("growth", "min_value", "_log_g", "counts",
                 "n", "sum", "min", "max")

    def __init__(self, growth: float = DEFAULT_GROWTH,
                 min_value: float = DEFAULT_MIN) -> None:
        if growth <= 1.0:
            raise ValueError(f"growth must exceed 1.0, got {growth}")
        self.growth = float(growth)
        self.min_value = float(min_value)
        self._log_g = math.log(self.growth)
        self.counts: dict[int, int] = {}
        self.n = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    # -- recording ----------------------------------------------------------
    def _index(self, value: float) -> int:
        if value <= self.min_value:
            return 0
        return 1 + int(math.log(value / self.min_value) / self._log_g)

    def bucket_bounds(self, index: int) -> tuple[float, float]:
        """[lo, hi) covered by bucket `index` (bucket 0 = underflow)."""
        if index <= 0:
            return (0.0, self.min_value)
        return (self.min_value * self.growth ** (index - 1),
                self.min_value * self.growth ** index)

    def record(self, value: float) -> None:
        value = max(0.0, float(value))
        idx = self._index(value)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.n += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value

    def merge(self, other: "LatencyHistogram") -> None:
        if (other.growth != self.growth
                or other.min_value != self.min_value):
            raise ValueError("histogram layouts differ")
        for idx, c in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + c
        self.n += other.n
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    # -- reading ------------------------------------------------------------
    def _percentile_index(self, q: float) -> int:
        """Bucket holding the q-th percentile sample (nearest-rank)."""
        if self.n == 0:
            return 0
        rank = max(1, math.ceil(q / 100.0 * self.n))
        seen = 0
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if seen >= rank:
                return idx
        return max(self.counts)

    def percentile(self, q: float) -> float:
        """Point estimate: geometric midpoint of the rank's bucket,
        clamped to the exactly-tracked [min, max]."""
        if self.n == 0:
            return 0.0
        lo, hi = self.bucket_bounds(self._percentile_index(q))
        mid = math.sqrt(lo * hi) if lo > 0 else hi / 2.0
        return min(max(mid, self.min), self.max)

    def percentile_bounds(self, q: float) -> tuple[float, float]:
        """The interval GUARANTEED to contain the true percentile."""
        if self.n == 0:
            return (0.0, 0.0)
        return self.bucket_bounds(self._percentile_index(q))

    @property
    def mean(self) -> float:
        return self.sum / self.n if self.n else 0.0

    def summary(self) -> dict:
        """count/mean/min/max exact, percentiles log-bucketed."""
        out = {
            "count": self.n,
            "mean_s": round(self.mean, 6),
            "min_s": round(self.min, 6) if self.n else 0.0,
            "max_s": round(self.max, 6),
        }
        for q in PERCENTILES:
            key = f"p{q:g}".replace(".", "_")
            out[key + "_s"] = round(self.percentile(q), 6)
        return out

    def to_dict(self) -> dict:
        return {"growth": self.growth, "min_value": self.min_value,
                "counts": {str(k): v for k, v in self.counts.items()},
                "n": self.n, "sum": self.sum,
                "min": self.min if self.n else None, "max": self.max}

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHistogram":
        h = cls(growth=d["growth"], min_value=d["min_value"])
        h.counts = {int(k): int(v) for k, v in d["counts"].items()}
        h.n = int(d["n"])
        h.sum = float(d["sum"])
        h.min = math.inf if d.get("min") is None else float(d["min"])
        h.max = float(d["max"])
        return h
