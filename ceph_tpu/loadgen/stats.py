"""Process-wide workload perf counters (the client swarm's side).

One ``PerfCounters`` set shared by every swarm/driver in the process
and ADOPTED into each OSD's collection (like the integrity set), so a
plain ``perf dump`` shows the offered load — ops and bytes the clients
pushed, errors they saw — right next to what the daemons did with it.

Kept dependency-free (common.perf only): the OSD imports this at
construction time and must not drag the whole harness (or jax) in.
"""

from __future__ import annotations

from ..common.perf import PerfCounters

PERF = PerfCounters("workload")

# counter keys (all plain counters; the swarm holds latency in its own
# log-bucketed histograms, not here):
#   ops_read / ops_write / ops_rmw   completed ops per class
#   bytes_read / bytes_written      payload bytes moved
#   op_errors                       ops that returned an error
#   op_wedged                       ops that exceeded the op deadline
#   open_loop_stalls                open-loop dispatcher hit the
#                                   in-flight cap (offered load was
#                                   NOT met; reported, never hidden)


def snapshot() -> dict:
    """Point-in-time dump (for before/after deltas in reports)."""
    return dict(PERF.dump())


def delta(before: dict, after: dict) -> dict:
    """Numeric counter deltas between two snapshot() dumps."""
    out = {}
    for key, v in after.items():
        if isinstance(v, (int, float)):
            out[key] = v - before.get(key, 0)
    return out
