"""WorkloadSpec: a production-shaped op stream, deterministically.

The spec is pure data + pure functions: the same (spec, seed) always
yields the same working set (object names and sizes) and the same op
schedule (kind/object/offset sequence), so a loadgen run is
reproducible op-for-op and a report's deterministic half is
byte-identical across runs.  Nothing here touches the cluster.

Shapes covered (the mixes "Understanding System Characteristics of
Online Erasure Coding..." showed surface online-EC bottlenecks only
under concurrency):

* read/write/RMW mix — RMW is a partial overwrite at a non-zero
  offset, the EC read-modify-write amplification path;
* object sizes fixed / uniform / lognormal, pinned PER OBJECT so
  offsets stay valid no matter how ops interleave;
* key popularity uniform or Zipf (hot keys contend on their PGs);
* replicated or EC pools; open- or closed-loop issue with a target
  QPS (0 = unthrottled closed loop).
"""

from __future__ import annotations

import hashlib
import itertools
import math
import random
from dataclasses import asdict, dataclass, field
from typing import Iterator, NamedTuple

KINDS = ("read", "write", "rmw")


class Op(NamedTuple):
    kind: str       # read | write | rmw
    oid: str
    size: int       # bytes written (write/rmw) or 0 (read = full)
    off: int        # offset (rmw only; write is writefull at 0)


@dataclass
class WorkloadSpec:
    # -- cluster shape ------------------------------------------------------
    n_osds: int = 8
    pg_num: int = 64
    pool: str = "loadpool"
    pool_type: str = "erasure"          # erasure | replicated
    ec_k: int = 2
    ec_m: int = 1
    replica_size: int = 3

    # -- working set --------------------------------------------------------
    n_objects: int = 1000
    size_dist: str = "fixed"            # fixed | uniform | lognormal
    obj_size: int = 16 << 10            # fixed size / distribution mean
    size_min: int = 4 << 10
    size_max: int = 64 << 10

    # -- op stream ----------------------------------------------------------
    n_ops: int = 2000                   # steady-phase ops
    read_frac: float = 0.5
    write_frac: float = 0.35
    rmw_frac: float = 0.15
    rmw_bytes: int = 2048               # partial-overwrite span
    popularity: str = "zipf"            # zipf | uniform
    zipf_s: float = 1.1

    # -- issue discipline ---------------------------------------------------
    n_clients: int = 16
    mode: str = "closed"                # closed | open
    target_qps: float = 0.0             # 0 = unthrottled (closed only)

    # -- recovery interference ----------------------------------------------
    recovery_ops: int = 0               # 0 = skip the phase
    kill_osds: int = 1

    seed: int = 1
    name: str = "default"
    extra: dict = field(default_factory=dict)

    # -- validation ---------------------------------------------------------
    def validate(self) -> "WorkloadSpec":
        if self.pool_type not in ("erasure", "replicated"):
            raise ValueError(f"pool_type {self.pool_type!r}")
        if self.size_dist not in ("fixed", "uniform", "lognormal"):
            raise ValueError(f"size_dist {self.size_dist!r}")
        if self.popularity not in ("zipf", "uniform"):
            raise ValueError(f"popularity {self.popularity!r}")
        if self.mode not in ("closed", "open"):
            raise ValueError(f"mode {self.mode!r}")
        if self.mode == "open" and self.target_qps <= 0:
            raise ValueError("open-loop mode needs target_qps > 0")
        total = self.read_frac + self.write_frac + self.rmw_frac
        if total <= 0:
            raise ValueError("op mix fractions sum to zero")
        width = self.ec_k + self.ec_m
        if self.pool_type == "erasure" and self.n_osds < width:
            raise ValueError(
                f"{self.n_osds} OSDs cannot host k+m={width} shards")
        return self

    def to_dict(self) -> dict:
        return asdict(self)

    # -- deterministic working set ------------------------------------------
    def object_name(self, i: int) -> str:
        return f"lg-{i:06d}"

    def object_size(self, i: int) -> int:
        """Per-object size, stable across the whole run (offsets into
        an object must stay valid however ops interleave)."""
        if self.size_dist == "fixed":
            return self.obj_size
        rnd = random.Random(f"{self.seed}:size:{i}")
        if self.size_dist == "uniform":
            return rnd.randrange(self.size_min, self.size_max + 1)
        # lognormal around obj_size, clamped into [size_min, size_max]
        v = int(rnd.lognormvariate(math.log(self.obj_size), 0.5))
        return max(self.size_min, min(self.size_max, v))

    def _popularity_weights(self) -> list[float]:
        if self.popularity == "uniform":
            return [1.0] * self.n_objects
        # Zipf over a seeded PERMUTATION of object indices: hot keys
        # land on arbitrary PGs, not pg 0
        rnd = random.Random(f"{self.seed}:perm")
        order = list(range(self.n_objects))
        rnd.shuffle(order)
        weights = [0.0] * self.n_objects
        for rank, idx in enumerate(order):
            weights[idx] = 1.0 / (rank + 1) ** self.zipf_s
        return weights

    # -- deterministic op schedule ------------------------------------------
    def schedule(self, n_ops: int | None = None,
                 salt: str = "steady") -> list[Op]:
        """The op stream: same (spec, salt) -> same list, always."""
        n_ops = self.n_ops if n_ops is None else n_ops
        rnd = random.Random(f"{self.seed}:{salt}")
        weights = self._popularity_weights()
        cum = list(itertools.accumulate(weights))
        total = self.read_frac + self.write_frac + self.rmw_frac
        t_read = self.read_frac / total
        t_write = t_read + self.write_frac / total
        ops: list[Op] = []
        for _ in range(n_ops):
            idx = rnd.choices(range(self.n_objects), cum_weights=cum,
                              k=1)[0]
            oid = self.object_name(idx)
            size = self.object_size(idx)
            r = rnd.random()
            if r < t_read:
                ops.append(Op("read", oid, 0, 0))
            elif r < t_write:
                ops.append(Op("write", oid, size, 0))
            else:
                span = min(self.rmw_bytes, size)
                off = rnd.randrange(0, size - span + 1)
                ops.append(Op("rmw", oid, span, off))
        return ops

    def preload_ops(self) -> Iterator[Op]:
        """One writefull per object — the working set."""
        for i in range(self.n_objects):
            yield Op("write", self.object_name(i),
                     self.object_size(i), 0)

    def schedule_digest(self, ops: list[Op]) -> str:
        """Stable fingerprint of an op schedule (report provenance:
        two runs reporting the same digest replayed the same ops)."""
        h = hashlib.sha256()
        for op in ops:
            h.update(f"{op.kind}|{op.oid}|{op.size}|{op.off}\n"
                     .encode())
        return h.hexdigest()[:16]


_PAYLOAD_BASE: dict[int, bytes] = {}


def payload_for(spec: WorkloadSpec, size: int) -> bytes:
    """Deterministic payload bytes: one seeded base buffer per spec
    seed, sliced per request — a 10k-object working set must not cost
    10k distinct random buffers (content only matters for byte
    accounting and CRC exercise, not entropy)."""
    if size <= 0:
        return b""
    base = _PAYLOAD_BASE.get(spec.seed, b"")
    if len(base) < size:
        want = max(size, spec.size_max, spec.obj_size)
        rnd = random.Random(f"{spec.seed}:payload")
        base = rnd.getrandbits(8 * want).to_bytes(want, "little")
        _PAYLOAD_BASE[spec.seed] = base
    return base[:size]
