"""Closed-loop workload driver: bring-up -> load -> steady -> recovery.

``run_workload`` takes a ``WorkloadSpec``, boots a ``SimCluster``,
preloads the working set, drives the steady-state op mix through the
``ClientSwarm``, optionally kills OSDs mid-traffic (the
recovery-interference phase: client latency during backfill is THE
number online-EC papers show microbenches can't predict), and returns
a JSON-able report:

* per phase: ops/s, GiB/s, p50/p95/p99/p99.9 per op class, failures;
* interference: victim OSDs, detection time, p99 degradation ratios
  vs steady state, whether the cluster re-converged;
* QoS: per-class dmClock dispatch counts and queue depths from the
  OSDs' ``scheduler`` perf sets (client vs recovery reservation/limit
  behavior, observed rather than inferred);
* counter deltas: placement cache, integrity pipeline, EC batcher,
  and the process-wide ``workload`` set.

The deterministic half of the report (schedules, op/byte tallies) is
byte-identical for the same spec+seed — ``deterministic_view``
extracts it for comparison.
"""

from __future__ import annotations

import asyncio
import time

from ..client.rados import Rados
from ..common.config import ConfigProxy
from ..ops.crc32c_batch import PERF as INTEGRITY_PERF
from .cluster import SimCluster
from .spec import WorkloadSpec
from .stats import PERF as WORKLOAD_PERF, delta
from .swarm import ClientSwarm


def _noop_log(msg: str) -> None:
    pass


async def _create_pool(mon_addr, spec: WorkloadSpec) -> None:
    rados = await Rados(mon_addr, name="client.loadgen-admin").connect()
    try:
        if spec.pool_type == "erasure":
            profile = f"loadgen-k{spec.ec_k}m{spec.ec_m}"
            await rados.mon_command(
                "osd erasure-code-profile set",
                {"name": profile, "profile": {
                    "plugin": "tpu", "k": str(spec.ec_k),
                    "m": str(spec.ec_m),
                    "technique": "reed_sol_van"}})
            await rados.pool_create(
                spec.pool, pg_num=spec.pg_num, pool_type="erasure",
                erasure_code_profile=profile)
        else:
            await rados.pool_create(
                spec.pool, pg_num=spec.pg_num,
                pool_type="replicated", size=spec.replica_size,
                min_size=max(1, spec.replica_size - 1))
    finally:
        await rados.shutdown()


def _numeric(d: dict) -> dict:
    return {k: v for k, v in d.items() if isinstance(v, (int, float))}


async def run_workload(spec: WorkloadSpec, *,
                       conf: ConfigProxy | None = None,
                       log=_noop_log) -> dict:
    spec.validate()
    conf = conf or ConfigProxy()
    t_start = time.perf_counter()
    log(f"cluster: booting mon + {spec.n_osds} osds")
    cluster = await SimCluster.create(
        spec.n_osds, log=log,
        osd_config=spec.extra.get("osd_config"))
    report: dict = {"spec": spec.to_dict()}
    try:
        await _create_pool(cluster.addr, spec)
        bringup_s = time.perf_counter() - t_start
        log(f"cluster up in {bringup_s:.1f}s; pool '{spec.pool}' "
            f"({spec.pool_type}, pg_num={spec.pg_num})")

        swarm = ClientSwarm(spec, cluster.addr, conf=conf)
        await swarm.start()
        workload_before = WORKLOAD_PERF.dump()
        integrity_before = INTEGRITY_PERF.dump()
        placement_before = cluster.perf_counters("placement_cache")
        try:
            # -- load: materialize the working set ------------------------
            log(f"load: writing {spec.n_objects} objects")
            load = await swarm.preload()
            log(f"load: {load.ops} ops in {load.elapsed:.1f}s "
                f"({load.ops / max(load.elapsed, 1e-9):.0f} ops/s, "
                f"{load.failed} failed)")

            # -- steady: the production-shaped mix ------------------------
            steady_ops = spec.schedule(salt="steady")
            sched_before = cluster.scheduler_counters()
            log(f"steady: {len(steady_ops)} ops, mode={spec.mode}, "
                f"qps={spec.target_qps or 'unthrottled'}")
            steady = await swarm.run_phase(steady_ops, "steady")
            sched_steady = cluster.scheduler_counters()
            log(f"steady: {steady.ops} ops in {steady.elapsed:.1f}s "
                f"({steady.ops / max(steady.elapsed, 1e-9):.0f} ops/s,"
                f" {steady.failed} failed)")

            # -- recovery interference ------------------------------------
            interference: dict | None = None
            rec_phases: dict = {}
            rec_qos: dict = {}
            if spec.recovery_ops > 0 and spec.kill_osds > 0:
                interference, rec_phases, rec_qos = \
                    await _recovery_phase(cluster, swarm, spec, conf,
                                          log)
        finally:
            await swarm.shutdown()

        report["schedule"] = {
            "steady_ops": len(steady_ops),
            "steady_digest": spec.schedule_digest(steady_ops),
        }
        report["cluster"] = {
            "osds": spec.n_osds,
            "pool_type": spec.pool_type,
            "pg_num": spec.pg_num,
            "ec_k": spec.ec_k if spec.pool_type == "erasure" else None,
            "ec_m": spec.ec_m if spec.pool_type == "erasure" else None,
            "pg_states": cluster.pg_states(),
        }
        report["phases"] = {"load": load.to_dict(),
                            "steady": steady.to_dict()}
        for name, ph in rec_phases.items():
            report["phases"][name] = ph.to_dict()
        if interference is not None:
            report["interference"] = interference
        report["qos"] = {
            "steady": delta(sched_before, sched_steady),
            **rec_qos,
            "final": cluster.scheduler_counters(),
        }
        report["counters"] = {
            "workload_delta": delta(workload_before,
                                    WORKLOAD_PERF.dump()),
            "integrity_delta": delta(_numeric(integrity_before),
                                     _numeric(INTEGRITY_PERF.dump())),
            "placement_cache_delta": delta(
                placement_before,
                cluster.perf_counters("placement_cache")),
            "ec_batch": cluster.perf_counters("ec_batch"),
            "ec_degraded": cluster.perf_counters("ec_degraded"),
            "ec_pipeline": cluster.perf_counters("ec_pipeline"),
        }
        report["timing"] = {
            "bringup_s": round(bringup_s, 3),
            "total_s": round(time.perf_counter() - t_start, 3),
        }
        return report
    finally:
        await cluster.stop()


async def _recovery_phase(cluster: SimCluster, swarm: ClientSwarm,
                          spec: WorkloadSpec, conf: ConfigProxy,
                          log) -> tuple[dict, dict, dict]:
    """Kill OSDs under live traffic, measure the client's view twice:

    * ``degraded`` — victims down, reads reconstruct from survivors
      (the degraded-read stall regime);
    * ``backfill`` — victims revived, client ops contend with the
      recovery pushes catching them up (the client-vs-recovery
      reservation/limit regime the dmClock scheduler arbitrates).
    """
    n_kill = min(spec.kill_osds,
                 int(conf.get("loadgen_kill_osds")) or spec.kill_osds,
                 len(cluster.osds) - 1)
    victims = []
    t_kill = time.perf_counter()
    # deterministic victims: the highest-index OSDs (the chaos
    # --kill-last convention), which hold shards like any other
    for j in range(n_kill):
        idx = len(cluster.osds) - 1 - j
        victim_id = cluster.osds[idx].whoami
        token = await cluster.kill_osd(idx)
        victims.append({"index": idx, "osd": victim_id,
                        "token": token})
        log(f"recovery: killed osd.{victim_id}")
    settle = float(conf.get("loadgen_recovery_settle"))
    detected = True
    for v in victims:
        if not await cluster.wait_down(v["osd"], timeout=settle):
            detected = False
            log(f"recovery: osd.{v['osd']} NOT marked down in "
                f"{settle:.0f}s")
    down_detect_s = time.perf_counter() - t_kill

    deg_ops = spec.schedule(n_ops=spec.recovery_ops, salt="degraded")
    sched0 = cluster.scheduler_counters()
    log(f"degraded: driving {len(deg_ops)} ops with "
        f"{len(victims)} osd(s) down")
    degraded = await swarm.run_phase(deg_ops, "degraded")
    sched1 = cluster.scheduler_counters()
    log(f"degraded: {degraded.ops} ops in {degraded.elapsed:.1f}s "
        f"({degraded.failed} failed, {degraded.wedged} wedged)")

    revived = True
    for v in reversed(victims):
        await cluster.revive_osd(v["index"], v["token"])
        if not await cluster.wait_up(v["osd"], timeout=30.0):
            revived = False
    bf_ops = spec.schedule(n_ops=spec.recovery_ops, salt="backfill")
    log(f"backfill: driving {len(bf_ops)} ops while recovery "
        f"catches the revived osd(s) up")
    backfill = await swarm.run_phase(bf_ops, "backfill")
    sched2 = cluster.scheduler_counters()
    log(f"backfill: {backfill.ops} ops in {backfill.elapsed:.1f}s "
        f"({backfill.failed} failed)")
    clean = await cluster.wait_clean(timeout=30.0) if revived else False
    interference = {
        "victims": [v["osd"] for v in victims],
        "down_detected": detected,
        "down_detect_s": round(down_detect_s, 3),
        "revived": revived,
        "clean_after_revive": clean,
        "recovery_schedule_digest": spec.schedule_digest(deg_ops),
        "backfill_schedule_digest": spec.schedule_digest(bf_ops),
    }
    phases = {"degraded": degraded, "backfill": backfill}
    qos = {"degraded": delta(sched0, sched1),
           "backfill": delta(sched1, sched2)}
    return interference, phases, qos


def degradation_ratios(report: dict, phase: str = "degraded") -> dict:
    """p99 during an interference phase vs steady, per op class
    (>=1.0 means the kill made clients slower -- the macro number
    later perf PRs move)."""
    out: dict[str, float] = {}
    phases = report.get("phases", {})
    steady = phases.get("steady", {}).get("timing", {}) \
                   .get("latency", {})
    rec = phases.get(phase, {}).get("timing", {}) \
                .get("latency", {})
    for kind, lat in rec.items():
        base = steady.get(kind, {}).get("p99_s")
        if base and lat.get("p99_s"):
            out[kind] = round(lat["p99_s"] / base, 2)
    return out


def deterministic_view(report: dict) -> dict:
    """The seed-reproducible half of a report: spec, schedules, op and
    byte tallies — everything except wall-clock-dependent fields.
    Two runs with the same spec must agree on this byte-for-byte."""
    phases = {
        name: {k: v for k, v in ph.items() if k != "timing"}
        for name, ph in report.get("phases", {}).items()
    }
    view = {"spec": report.get("spec"),
            "schedule": report.get("schedule"),
            "phases": phases}
    interference = report.get("interference")
    if interference:
        view["interference"] = {
            "victims": interference.get("victims"),
            "recovery_schedule_digest":
                interference.get("recovery_schedule_digest"),
        }
    return view
