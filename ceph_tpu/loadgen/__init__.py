"""Cluster-scale closed-loop traffic harness.

Lazy exports (PEP 562, like ceph_tpu.ops): the OSD adopts
``loadgen.stats.PERF`` at construction time, and that import must not
drag the swarm -> librados -> osd import chain back in (cycle) nor
any heavy dependency.
"""

_EXPORTS = {
    "WorkloadSpec": ".spec",
    "Op": ".spec",
    "payload_for": ".spec",
    "LatencyHistogram": ".histogram",
    "SimCluster": ".cluster",
    "ClientSwarm": ".swarm",
    "PhaseResult": ".swarm",
    "run_workload": ".driver",
    "deterministic_view": ".driver",
    "degradation_ratios": ".driver",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib
    return getattr(importlib.import_module(mod, __name__), name)
