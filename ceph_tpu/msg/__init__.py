"""Async messenger: the host-side control/data plane transport.

The TPU-native split (SURVEY.md section 2.8): bulk chunk movement rides
XLA collectives over ICI inside a mesh; everything the reference sends as
messenger RPCs between daemons (maps, peering, heartbeats, rep/EC sub-ops
across failure domains) rides this asyncio messenger with v2-lite frames
(length-prefixed, crc32c-checksummed, HMAC-authenticated session setup --
the ProtocolV2 crc-mode analog, src/msg/async/ProtocolV2.h:19-56).
"""

from .message import Message  # noqa: F401
from .messenger import Messenger, Connection  # noqa: F401
