"""Typed wire codecs for the hot-path messages (MOSDOp discipline).

The reference gives every load-bearing message a hand-coded versioned
encoding (src/messages/MOSDOp.h, MOSDRepOp.h, MOSDPing.h) under the
denc ENCODE_START/FINISH envelope; rare control messages can afford a
generic path.  Same split here: the five messages that carry client
I/O, replication, and liveness get explicit field layouts with a
struct version (so fields can be added compatibly), and everything
else rides the generic tagged-value denc encoding (common/denc.py) --
either way the wire carries NO JSON (round-3/4 review: hot-path frames
paid json.dumps/loads per message).

Each codec encodes the message's *stable* fields with fixed layout and
carries any remaining keys in a generic-value `extras` dict, so a new
field never silently vanishes; promotion into the fixed layout is a
struct_v bump.
"""

from __future__ import annotations

from ..common.denc import Decoder, Encoder


def _split(data: dict, known: tuple) -> dict:
    """Keys outside the fixed layout -- plus fixed keys whose value is
    None, which the optional-field encoding cannot distinguish from
    absent; the generic extras dict carries them exactly."""
    return {k: v for k, v in data.items()
            if k not in known or v is None}


def _opt(out: dict, key: str, v) -> None:
    """Set only present fields so decode(encode(d)) == d exactly --
    handlers distinguish a missing key from a default value."""
    if v is not None:
        out[key] = v


# -- MOSDOp (client -> primary) ----------------------------------------------

_OP_FIELDS = ("pgid", "oid", "ops", "tid", "reqid")


def _enc_osd_op(enc: Encoder, d: dict) -> None:
    enc.start(1, 1)
    enc.optional(d.get("pgid"), Encoder.string)
    enc.optional(d.get("oid"), Encoder.string)
    enc.optional(d.get("tid"), Encoder.u64)
    reqid = d.get("reqid")
    enc.boolean(reqid is not None)
    if reqid is not None:
        enc.string(str(reqid[0]))
        enc.u64(int(reqid[1]))
    enc.optional(d.get("ops"), Encoder.value)
    enc.value(_split(d, _OP_FIELDS))
    enc.finish()


def _dec_osd_op(dec: Decoder) -> dict:
    dec.start(1)
    out = {}
    _opt(out, "pgid", dec.optional(Decoder.string))
    _opt(out, "oid", dec.optional(Decoder.string))
    _opt(out, "tid", dec.optional(Decoder.u64))
    if dec.boolean():
        out["reqid"] = [dec.string(), dec.u64()]
    _opt(out, "ops", dec.optional(Decoder.value))
    out.update(dec.value())
    dec.finish()
    return out


# -- MOSDOpReply (primary -> client) ------------------------------------------

_OPREPLY_FIELDS = ("tid", "epoch", "err", "results")


def _enc_osd_op_reply(enc: Encoder, d: dict) -> None:
    enc.start(1, 1)
    enc.optional(d.get("tid"), Encoder.u64)
    enc.optional(d.get("epoch"), Encoder.u64)
    enc.optional(d.get("err"), Encoder.string)
    enc.optional(d.get("results"), Encoder.value)
    enc.value(_split(d, _OPREPLY_FIELDS))
    enc.finish()


def _dec_osd_op_reply(dec: Decoder) -> dict:
    dec.start(1)
    out = {}
    _opt(out, "tid", dec.optional(Decoder.u64))
    _opt(out, "epoch", dec.optional(Decoder.u64))
    _opt(out, "err", dec.optional(Decoder.string))
    _opt(out, "results", dec.optional(Decoder.value))
    out.update(dec.value())
    dec.finish()
    return out


# -- MOSDRepOp / reply (primary <-> replica) ----------------------------------

# log_only rides the extras dict: its absent/False/True tri-state (and
# any future non-bool value) must round-trip exactly
_REPOP_FIELDS = ("pgid", "entry", "muts", "tid")


def _enc_rep_op(enc: Encoder, d: dict) -> None:
    enc.start(1, 1)
    enc.optional(d.get("pgid"), Encoder.string)
    enc.optional(d.get("tid"), Encoder.u64)
    enc.optional(d.get("entry"), Encoder.value)
    enc.optional(d.get("muts"), Encoder.value)
    enc.value(_split(d, _REPOP_FIELDS))
    enc.finish()


def _dec_rep_op(dec: Decoder) -> dict:
    dec.start(1)
    out = {}
    _opt(out, "pgid", dec.optional(Decoder.string))
    _opt(out, "tid", dec.optional(Decoder.u64))
    _opt(out, "entry", dec.optional(Decoder.value))
    _opt(out, "muts", dec.optional(Decoder.value))
    out.update(dec.value())
    dec.finish()
    return out


_REPREPLY_FIELDS = ("tid", "from_osd")


def _enc_rep_op_reply(enc: Encoder, d: dict) -> None:
    enc.start(1, 1)
    enc.optional(d.get("tid"), Encoder.u64)
    enc.optional(d.get("from_osd"), Encoder.i64)
    enc.value(_split(d, _REPREPLY_FIELDS))
    enc.finish()


def _dec_rep_op_reply(dec: Decoder) -> dict:
    dec.start(1)
    out = {}
    _opt(out, "tid", dec.optional(Decoder.u64))
    _opt(out, "from_osd", dec.optional(Decoder.i64))
    out.update(dec.value())
    dec.finish()
    return out


# -- MOSDPing / reply (liveness mesh) -----------------------------------------

_PING_FIELDS = ("from_osd", "stamp")


def _enc_osd_ping(enc: Encoder, d: dict) -> None:
    enc.start(1, 1)
    enc.optional(d.get("from_osd"), Encoder.i64)
    enc.optional(d.get("stamp"), Encoder.f64)
    enc.value(_split(d, _PING_FIELDS))
    enc.finish()


def _dec_osd_ping(dec: Decoder) -> dict:
    dec.start(1)
    out = {}
    _opt(out, "from_osd", dec.optional(Decoder.i64))
    _opt(out, "stamp", dec.optional(Decoder.f64))
    out.update(dec.value())
    dec.finish()
    return out


WIRE_CODECS = {
    "osd_op": (_enc_osd_op, _dec_osd_op),
    "osd_op_reply": (_enc_osd_op_reply, _dec_osd_op_reply),
    "rep_op": (_enc_rep_op, _dec_rep_op),
    "rep_op_reply": (_enc_rep_op_reply, _dec_rep_op_reply),
    "osd_ping": (_enc_osd_ping, _dec_osd_ping),
    # ping and its echo deliberately share one layout (MOSDPing
    # carries both directions upstream)
    # lint: disable=denc-symmetry -- shared ping layout
    "osd_ping_reply": (_enc_osd_ping, _dec_osd_ping),
}
