"""Wire message model and v2-lite frame codec.

Frame = magic | u32 meta_len | meta(denc) | segments | u32 crc32c.
The meta envelope is the repo's own versioned denc encoding
(common/denc.py), NOT json: hot-path types (osd_op, rep_op, ping --
msg/wire_types.py) get explicit MOSDOp-style field layouts, everything
else rides the generic tagged-value encoding, and a json escape hatch
remains only for payloads denc cannot express.  Raw binary segments
stay zero-copy -- the same meta/payload segment split ProtocolV2
frames use (4 segments + epilogue crcs, src/msg/async/frames_v2.cc).

meta envelope (denc, struct_v 1):
  string t | u64 seq | string from | u8 kind | blob payload |
  list<u32> seg_lens
where kind selects the payload codec: 0 generic value, 1 json
(escape hatch), 2 typed (wire_types.WIRE_CODECS[t]).
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Any

from ..common.denc import Decoder, DencError, Encoder
from ..native import crc32c

MAGIC = b"CTv3"
MAX_FRAME = 256 << 20

KIND_VALUE = 0
KIND_JSON = 1
KIND_TYPED = 2


@dataclass
class Message:
    type: str
    data: dict[str, Any] = field(default_factory=dict)
    segments: list[bytes] = field(default_factory=list)
    seq: int = 0
    from_name: str = ""

    def encode(self) -> bytes:
        from .wire_types import WIRE_CODECS
        payload = Encoder()
        codec = WIRE_CODECS.get(self.type)
        try:
            if codec is not None:
                kind = KIND_TYPED
                codec[0](payload, self.data)
            else:
                kind = KIND_VALUE
                payload.value(self.data)
        except (DencError, TypeError, OverflowError) as denc_err:
            # escape hatch: a payload the denc codecs (typed OR
            # generic) cannot express falls back to json -- best
            # effort, since json's data model is a subset; if json
            # can't carry it either, the original error surfaces
            try:
                blob = json.dumps(self.data).encode()
            except (TypeError, ValueError):
                raise denc_err
            kind = KIND_JSON
            payload = Encoder()
            payload.blob(blob)
        enc = Encoder()
        enc.start(1, 1)
        enc.string(self.type)
        enc.u64(self.seq)
        enc.string(self.from_name)
        enc.u8(kind)
        enc.blob(payload.bytes())
        enc.list([len(s) for s in self.segments], Encoder.u32)
        enc.finish()
        mb = enc.bytes()
        body = mb + b"".join(self.segments)
        crc = crc32c(body) & 0xFFFFFFFF
        return MAGIC + struct.pack("<I", len(mb)) + body + struct.pack(
            "<I", crc)

    @classmethod
    def decode(cls, buf: bytes) -> "Message":
        if buf[:4] != MAGIC:
            raise ValueError("bad magic")
        (meta_len,) = struct.unpack_from("<I", buf, 4)
        mb = buf[8:8 + meta_len]
        (crc,) = struct.unpack_from("<I", buf, len(buf) - 4)
        body = buf[8:len(buf) - 4]
        if (crc32c(body) & 0xFFFFFFFF) != crc:
            raise ValueError("frame crc mismatch")
        mtype, seq, from_name, data, seg_lens = _decode_meta(mb)
        segments = []
        off = 8 + meta_len
        for ln in seg_lens:
            segments.append(buf[off:off + ln])
            off += ln
        return cls(type=mtype, data=data, segments=segments,
                   seq=seq, from_name=from_name)


def _decode_meta(mb) -> tuple:
    from .wire_types import WIRE_CODECS
    dec = Decoder(mb)
    dec.start(1)
    mtype = dec.string()
    seq = dec.u64()
    from_name = dec.string()
    kind = dec.u8()
    payload = dec.blob()
    seg_lens = dec.list(Decoder.u32)
    dec.finish()
    if kind == KIND_TYPED:
        codec = WIRE_CODECS.get(mtype)
        if codec is None:
            raise ValueError(f"typed payload for unknown type {mtype}")
        data = codec[1](Decoder(payload))
    elif kind == KIND_VALUE:
        data = Decoder(payload).value()
    elif kind == KIND_JSON:
        data = json.loads(Decoder(payload).blob())
    else:
        raise ValueError(f"bad meta kind {kind}")
    return mtype, seq, from_name, data, seg_lens


COMP_MAGIC = b"CTvC"     # on-wire compressed frame (compression_onwire)
SEC_MAGIC = b"CTvE"      # AES-GCM encrypted frame (crypto_onwire secure mode)
COMPRESS_THRESHOLD = 1024
# a plain frame may carry meta and segments EACH up to MAX_FRAME; the
# wrapped paths must accept at least that (a tighter cap would reject
# on receive a frame the sender legally built -> teardown/replay loop)
MAX_WRAPPED = 2 * MAX_FRAME + 65536
OFFLOAD_THRESHOLD = 1 << 20     # executor offload for >1 MiB transforms


def _parse_plain(buf: bytes) -> bytes:
    if buf[:4] != MAGIC:
        raise ValueError("bad magic")
    return buf


def wrap_frame(buf: bytes, compressor=None, aead=None) -> bytes:
    """Apply the connection's negotiated on-wire transforms.

    compress-then-encrypt, as ProtocolV2 layers compression inside the
    secure session (compression_onwire.cc / crypto_onwire.cc); the
    compressed form is only used when it actually shrinks the frame.
    """
    if compressor is not None and len(buf) > COMPRESS_THRESHOLD:
        comp = compressor.compress(buf)
        if len(comp) < len(buf):
            buf = (COMP_MAGIC + struct.pack("<II", len(buf), len(comp))
                   + comp)
    if aead is not None:
        import os as _os
        nonce = _os.urandom(12)
        ct = aead.encrypt(nonce, buf, b"")
        buf = SEC_MAGIC + struct.pack("<I", len(ct)) + nonce + ct
    return buf


def unwrap_frame(buf: bytes, compressor=None) -> bytes:
    """Undo COMP wrapping of an in-memory frame (post-decryption)."""
    if buf[:4] == COMP_MAGIC:
        raw_len, comp_len = struct.unpack_from("<II", buf, 4)
        if raw_len > MAX_WRAPPED:
            raise ValueError("oversized compressed frame")
        if compressor is None:
            raise ValueError("compressed frame on a plain connection")
        try:
            # bounded: output capped at the declared raw_len so a
            # bomb frame fails before materializing, not after
            out = compressor.decompress(buf[12:12 + comp_len],
                                        max_length=raw_len)
        except Exception as e:
            # corrupt input must look like any other framing error so
            # the read loop's reconnect/teardown path handles it
            raise ValueError(f"frame decompress failed: {e}") from e
        if len(out) != raw_len:
            raise ValueError("compressed frame length mismatch")
        return _parse_plain(out)
    return _parse_plain(buf)


async def read_frame(reader, compressor=None, aead=None) -> bytes:
    """Read one full (plain) frame from an asyncio StreamReader,
    transparently unwrapping the connection's negotiated encryption
    and compression layers."""
    magic = await reader.readexactly(4)
    if aead is not None and magic != SEC_MAGIC:
        # a secure connection must never accept plaintext: an injected
        # cleartext frame would bypass the channel's authentication
        raise ValueError("plaintext frame on a secure connection")
    if magic == SEC_MAGIC:
        if aead is None:
            raise ValueError("encrypted frame on a plain connection")
        (ct_len,) = struct.unpack("<I", await reader.readexactly(4))
        if ct_len > MAX_WRAPPED:
            raise ValueError("oversized encrypted frame")
        nonce = await reader.readexactly(12)
        ct = await reader.readexactly(ct_len)
        try:
            if ct_len > OFFLOAD_THRESHOLD:
                # big decrypts off the event loop: heartbeats must not
                # stall behind a multi-MB AES pass
                import asyncio as _asyncio
                inner = await _asyncio.get_event_loop().run_in_executor(
                    None, aead.decrypt, nonce, ct, b"")
            else:
                inner = aead.decrypt(nonce, ct, b"")
        except ValueError:
            raise
        except Exception as e:
            raise ValueError(f"frame decrypt failed: {e}") from e
        return unwrap_frame(inner, compressor)
    if magic == COMP_MAGIC:
        lens = await reader.readexactly(8)
        raw_len, comp_len = struct.unpack("<II", lens)
        if max(raw_len, comp_len) > MAX_WRAPPED:
            raise ValueError("oversized compressed frame")
        comp = await reader.readexactly(comp_len)
        return unwrap_frame(magic + lens + comp, compressor)
    if magic != MAGIC:
        raise ValueError("bad magic")
    hdr = magic + await reader.readexactly(4)
    (meta_len,) = struct.unpack_from("<I", hdr, 4)
    if meta_len > MAX_FRAME:
        raise ValueError("oversized meta")
    mb = await reader.readexactly(meta_len)
    total_segs = sum(_meta_seg_lens(mb))
    if total_segs > MAX_FRAME:
        raise ValueError("oversized frame")
    rest = await reader.readexactly(total_segs + 4)
    return hdr + mb + rest


def _meta_seg_lens(mb: bytes) -> list[int]:
    """Just the segment lengths from a meta envelope (what the stream
    reader needs to size the rest of the frame)."""
    dec = Decoder(mb)
    dec.start(1)
    dec.string()        # t
    dec.u64()           # seq
    dec.string()        # from
    dec.u8()            # kind
    dec._take(dec.u32())    # skip payload without materializing it
    lens = dec.list(Decoder.u32)
    dec.finish()
    return lens
