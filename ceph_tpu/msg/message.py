"""Wire message model and v2-lite frame codec.

Frame = magic | u32 meta_len | meta(json) | segments | u32 crc32c, where
meta carries {t, seq, from, data, seg_lens}.  JSON meta + raw binary
segments keeps control fields debuggable while bulk chunk bytes stay
zero-copy -- the same meta/payload segment split ProtocolV2 frames use
(4 segments + epilogue crcs, src/msg/async/frames_v2.cc).
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Any

from ..native import crc32c

MAGIC = b"CTv2"
MAX_FRAME = 256 << 20


@dataclass
class Message:
    type: str
    data: dict[str, Any] = field(default_factory=dict)
    segments: list[bytes] = field(default_factory=list)
    seq: int = 0
    from_name: str = ""

    def encode(self) -> bytes:
        meta = {
            "t": self.type,
            "seq": self.seq,
            "from": self.from_name,
            "data": self.data,
            "segs": [len(s) for s in self.segments],
        }
        mb = json.dumps(meta, separators=(",", ":")).encode()
        body = mb + b"".join(self.segments)
        crc = crc32c(body) & 0xFFFFFFFF
        return MAGIC + struct.pack("<I", len(mb)) + body + struct.pack(
            "<I", crc)

    @classmethod
    def decode(cls, buf: bytes) -> "Message":
        if buf[:4] != MAGIC:
            raise ValueError("bad magic")
        (meta_len,) = struct.unpack_from("<I", buf, 4)
        mb = buf[8:8 + meta_len]
        meta = json.loads(mb)
        (crc,) = struct.unpack_from("<I", buf, len(buf) - 4)
        body = buf[8:len(buf) - 4]
        if (crc32c(body) & 0xFFFFFFFF) != crc:
            raise ValueError("frame crc mismatch")
        segments = []
        off = 8 + meta_len
        for ln in meta["segs"]:
            segments.append(buf[off:off + ln])
            off += ln
        return cls(type=meta["t"], data=meta["data"], segments=segments,
                   seq=meta["seq"], from_name=meta["from"])


async def read_frame(reader) -> bytes:
    """Read one full frame from an asyncio StreamReader."""
    hdr = await reader.readexactly(8)
    if hdr[:4] != MAGIC:
        raise ValueError("bad magic")
    (meta_len,) = struct.unpack_from("<I", hdr, 4)
    if meta_len > MAX_FRAME:
        raise ValueError("oversized meta")
    mb = await reader.readexactly(meta_len)
    meta = json.loads(mb)
    total_segs = sum(meta["segs"])
    if total_segs > MAX_FRAME:
        raise ValueError("oversized frame")
    rest = await reader.readexactly(total_segs + 4)
    return hdr + mb + rest
