"""Asyncio messenger with lossless-client reconnect semantics.

Responsibilities mirrored from the reference's AsyncMessenger
(src/msg/async/AsyncMessenger.h:74): bind/accept, connect-by-address with
connection caching, ordered per-connection delivery with sequence numbers,
resend of unacked messages after reconnect (lossless policy,
src/msg/Policy.h), dispatcher fan-out, and an HMAC-SHA256 session
handshake standing in for cephx (src/auth/cephx) in crc mode.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import os
import struct
import time
from collections import deque
from typing import Awaitable, Callable

from .message import Message, read_frame, wrap_frame

Dispatcher = Callable[["Connection", Message], Awaitable[None]]

HELLO_MAGIC = b"CTHL"
HELLO_ACCEPTS_TICKETS = 0x01     # server can validate cephx tickets
HELLO_REQUIRES_TICKET = 0x02     # server will NACK ticketless peers

# flow-control policy (src/msg/Policy.h throttler analog): receivers ack
# delivered seqs every ack_every messages or ack_bytes payload bytes --
# and on a short idle timer, so a sender whose window is smaller than
# the peer's batching cadence still gets unblocked -- and senders block
# in send() once the unacked window exceeds the messenger's
# max_unacked_msgs/max_unacked_bytes instead of growing without bound.
ACK_EVERY = 64
ACK_BYTES = 8 << 20
ACK_FLUSH_S = 0.2
ACK_TYPE = "__ack"

# per-peer sub-op coalescing (the PR-12 write pipeline): concurrent
# ops' sub-writes bound for the same peer inside one flush window ride
# ONE framed message instead of one send per shard -- one seq, one
# frame header, one syscall, one read-loop wakeup.  The receiver
# unpacks and dispatches the sub-messages in staging order, so
# per-peer FIFO (what keeps replica logs in version order) is exactly
# as strong as the unbatched path.
SUBOP_BATCH_TYPE = "__subop_batch"


class Connection:
    def __init__(self, messenger: "Messenger", peer_name: str,
                 reader, writer, *, outgoing: bool,
                 peer_addr: tuple[str, int] | None = None) -> None:
        self.messenger = messenger
        self.peer_name = peer_name
        self.reader = reader
        self.writer = writer
        self.outgoing = outgoing
        self.peer_addr = peer_addr
        self.out_seq = 0
        self.in_seq = 0
        self.unacked: deque[tuple[Message, int]] = deque()  # (msg, nbytes)
        self.unacked_bytes = 0
        self.acked_seq = 0           # peer-confirmed delivery watermark
        self._ack_pending_msgs = 0   # receive side: delivered since last ack
        self._ack_pending_bytes = 0
        self.closed = False
        self.generation = 0          # bumped per successful reconnect
        # negotiated on-wire transforms (ProtocolV2 compression_onwire
        # / crypto_onwire secure mode); set right after the handshake.
        # PER-DIRECTION AEAD keys: one shared key would let a recorded
        # client frame be reflected back to it as "authentic"
        self.compressor = None
        self.aead_tx = None
        self.aead_rx = None
        self._send_lock = asyncio.Lock()
        self._reconnect_lock = asyncio.Lock()
        self._window_open = asyncio.Event()
        self._window_open.set()
        self._read_task: asyncio.Task | None = None
        self._ack_task: asyncio.Task | None = None

    def _window_full(self) -> bool:
        m = self.messenger
        return (len(self.unacked) >= m.max_unacked_msgs
                or self.unacked_bytes >= m.max_unacked_bytes)

    def _trim_acked(self, seq: int) -> None:
        if seq <= self.acked_seq:
            return
        self.acked_seq = seq
        while self.unacked and self.unacked[0][0].seq <= seq:
            _, nbytes = self.unacked.popleft()
            self.unacked_bytes -= nbytes
        if not self._window_full():
            self._window_open.set()

    async def send(self, msg: Message) -> None:
        if msg.type == ACK_TYPE:
            raise ValueError(f"{ACK_TYPE} is a reserved control frame type")
        faults = self.messenger.faults
        if faults is not None:
            fd = faults.on_send(self.messenger.name, self.peer_name,
                                msg.type)
            if fd.drop:
                return           # vanished on the wire (chaos drop)
            if fd.delay > 0:
                await asyncio.sleep(fd.delay)
            for _ in range(fd.copies - 1):
                # duplicates take fresh seqs so the receiver's replay
                # dedup does NOT absorb them -- handler idempotency is
                # exactly what the duplication fault probes
                await self._send_one(Message(msg.type, dict(msg.data),
                                             segments=list(msg.segments)))
        await self._send_one(msg)

    async def _send_one(self, msg: Message) -> None:
        while True:
            # window wait OUTSIDE the lock: _reconnect needs _send_lock
            # for the writer swap+replay, and the acks that reopen the
            # window need the reconnected stream -- a sender parked
            # here while holding the lock would deadlock the pair.
            st = await self._send_locked(msg)
            if st == "sent":
                return
            if st == "reconnect":
                # outside the send lock: _reconnect takes it for the
                # writer swap + replay, so the replayed frames cannot
                # interleave with other senders' writes
                await self.messenger._reconnect(self)
                return          # msg is in unacked; the replay sent it
            self._window_open.clear()
            await self._window_open.wait()
            if self.closed:
                raise ConnectionError(f"{self.peer_name} closed")

    async def _send_locked(self, msg: Message) -> str:
        """One locked send attempt: "sent" | "reconnect" | "window"
        ("window" = flow-control window full, caller waits UNLOCKED
        and retries -- K queued senders re-check here so they cannot
        overshoot the window by K-1)."""
        async with self._send_lock:
            if self.closed:
                raise ConnectionError(f"{self.peer_name} closed")
            if self._window_full():
                return "window"
            self.out_seq += 1
            msg.seq = self.out_seq
            msg.from_name = self.messenger.name
            buf = msg.encode()
            self.unacked.append((msg, len(buf)))
            self.unacked_bytes += len(buf)
            from .message import OFFLOAD_THRESHOLD
            if (self.compressor or self.aead_tx) \
                    and len(buf) > OFFLOAD_THRESHOLD:
                # multi-MB compress/encrypt off the event loop so
                # heartbeat handling doesn't stall behind it; ordering
                # is preserved -- we still hold the send lock, and a
                # reconnect cannot swap the writer or renegotiate keys
                # under us because its swap+replay also requires the
                # send lock.
                wire = await asyncio.get_event_loop().run_in_executor(
                    None, wrap_frame, buf, self.compressor,
                    self.aead_tx)
                if self.closed:
                    raise ConnectionError(f"{self.peer_name} closed")
            else:
                wire = wrap_frame(buf, self.compressor, self.aead_tx)
            from ..common.throttle import injector as _fault
            if _fault.check("ms_inject_socket_failures"):
                # chaos: drop the transport mid-send; the lossless
                # reconnect+replay machinery must absorb it
                # (ms_inject_socket_failures, qa msgr-failures suites)
                self.writer.close()
            try:
                self.writer.write(wire)
                await self.writer.drain()
                return "sent"
            except (ConnectionError, OSError):
                if not self.outgoing:
                    await self.close()
                    raise
                return "reconnect"

    def _note_delivered(self, nbytes: int) -> None:
        """Receive side: count a delivery toward the ack cadence and
        confirm immediately once the cadence is hit (a lost ack is
        re-covered by the next one or the reconnect handshake)."""
        self._ack_pending_msgs += 1
        self._ack_pending_bytes += nbytes
        if (self._ack_pending_msgs >= self.messenger.ack_every
                or self._ack_pending_bytes >= self.messenger.ack_bytes):
            self._flush_ack()
        elif self._ack_task is None or self._ack_task.done():
            # idle flush: a sender with a window smaller than our
            # batching cadence must still see acks eventually
            self._ack_task = asyncio.ensure_future(self._ack_flusher())

    def _flush_ack(self) -> None:
        self._ack_pending_msgs = 0
        self._ack_pending_bytes = 0
        ack = Message(ACK_TYPE, {"seq": self.in_seq})
        ack.from_name = self.messenger.name
        try:
            self.writer.write(wrap_frame(ack.encode(), None,
                                         self.aead_tx))
        except (ConnectionError, OSError):
            pass

    async def _ack_flusher(self) -> None:
        try:
            await asyncio.sleep(ACK_FLUSH_S)
            if not self.closed and self._ack_pending_msgs:
                self._flush_ack()
        except asyncio.CancelledError:
            pass

    async def _resend_unacked(self) -> None:
        for msg, _ in list(self.unacked):
            self.writer.write(wrap_frame(msg.encode(), self.compressor,
                                         self.aead_tx))
        await self.writer.drain()

    async def close(self) -> None:
        self.closed = True
        self._window_open.set()      # wake throttled senders to error out
        if self._read_task:
            self._read_task.cancel()
        if self._ack_task:
            self._ack_task.cancel()
        try:
            self.writer.close()
        except Exception:
            pass


def pack_subop_batch(msgs: list[Message]) -> Message:
    """Fold staged sub-op messages into ONE framed flush: metas carry
    each sub-message's (type, data, segment count); the segment lists
    concatenate in order.  Seq/ack/replay accounting all happen on the
    outer frame -- a reconnect replays the whole flush, the receiver
    dedups it as one unit, and unpacking restores staging order."""
    metas = [{"t": m.type, "d": m.data, "n": len(m.segments)}
             for m in msgs]
    segments: list[bytes] = []
    for m in msgs:
        segments.extend(m.segments)
    return Message(SUBOP_BATCH_TYPE, {"metas": metas},
                   segments=segments)


def unpack_subop_batch(msg: Message) -> list[Message]:
    out: list[Message] = []
    off = 0
    for meta in msg.data.get("metas", []):
        n = int(meta.get("n", 0))
        sub = Message(meta["t"], meta["d"],
                      segments=list(msg.segments[off:off + n]))
        off += n
        sub.seq = msg.seq            # dedup identity is the frame's
        sub.from_name = msg.from_name
        out.append(sub)
    return out


class SubOpPipe:
    """Per-peer sub-op coalescing with a flush window.

    ``stage()`` parks an outbound message (synchronously -- staging
    order IS the wire order, which is what keeps replica logs applied
    in version order) on its peer's queue; ONE ship worker per peer
    drains that queue, coalescing everything staged since its last
    cycle into one ``pack_subop_batch`` frame.  The flush window is
    an event-loop pass (the codec batcher's Nagle-off discipline) --
    and under backpressure it widens NATURALLY: while a ship is in
    flight the queue keeps growing, and the next cycle carries the
    whole backlog in one frame.

    Per-peer workers are a liveness requirement, not an optimization:
    a single drain loop awaiting sends inline lets one dead peer's
    reconnect backoff head-of-line-block every other peer's commits
    -- observed at 64 OSDs as cluster-wide wedged ops the moment one
    OSD died.  A slow peer now stalls only its own queue, and a send
    failure fails exactly that peer's staged ``on_error`` hooks (the
    op layer sees the same per-send errors as the unbatched path).

    With a fault injector attached, messages ship INDIVIDUALLY: the
    injector's drop/delay/dup rules key on the logical message type,
    and hiding sub-ops inside a batch frame would blind the chaos
    harness to them (the kill-mid-pipeline tests depend on per-subop
    fault fidelity).
    """

    def __init__(self, messenger: Messenger, *,
                 flush_window: float = 0.002, perf=None) -> None:
        self.messenger = messenger
        self.flush_window = float(flush_window)
        self.perf = perf
        # peer -> deque of (addr, msg, on_error)
        self._peer_q: dict[str, deque] = {}
        self._peer_tasks: dict[str, asyncio.Task] = {}
        # peer -> a ship cycle is running (inline flush_now or the
        # worker task); the flag is the one-shipper-per-peer mutex
        # that keeps frames in staging order
        self._busy: dict[str, bool] = {}
        self._n_staged = 0
        self.closed = False

    def stage(self, addr: tuple[str, int], peer_name: str,
              msg: Message, on_error=None) -> None:
        """Park one sub-op send; the peer's ship worker flushes it.

        Shipping ALWAYS happens on the worker task, never inline in
        the staging caller: the op path stages while holding its PG
        lock, and an inline send to a dead peer would hold that lock
        across the reconnect backoff (the degraded-phase collapse the
        pipeline exists to prevent)."""
        if self.closed:
            raise ConnectionError("subop pipe closed")
        q = self._peer_q.setdefault(peer_name, deque())
        q.append((tuple(addr), msg, on_error))
        self._n_staged += 1
        if self._busy.get(peer_name):
            return               # the live ship cycle carries it
        t = self._peer_tasks.get(peer_name)
        if t is None or t.done():
            self._peer_tasks[peer_name] = asyncio.ensure_future(
                self.arm_flush_window(peer_name))

    async def arm_flush_window(self, peer: str) -> None:
        """The ship worker (one per peer, retires when the queue
        drains; ``stage`` re-arms).  One coalescing pass first:
        every already-runnable co-submitter stages during it."""
        if self._busy.get(peer):
            return
        try:
            if self.flush_window > 0:
                await asyncio.sleep(0)   # co-submitters stage here
            await self._ship_loop(peer)
        except asyncio.CancelledError:
            if not self._busy.get(peer):
                await self._ship_loop(peer)   # shutdown: ship now

    async def _ship_loop(self, peer: str) -> None:
        """Ship until the peer's queue drains.  Sole shipper: the
        _busy flag serializes cycles, so frames leave in staging
        order even when flush_now and the worker race."""
        q = self._peer_q.get(peer)
        if q is None:
            return
        self._busy[peer] = True
        try:
            while q:
                await self._ship_queued(peer, q)
        finally:
            self._busy[peer] = False

    async def _ship_queued(self, peer: str, q: deque) -> None:
        if not q:
            return
        entries = list(q)
        q.clear()
        self._n_staged -= len(entries)
        if self.perf is not None:
            self.perf.inc("flush_windows")
        addr = entries[0][0]
        msgs = [m for _, m, _ in entries]
        try:
            if len(msgs) == 1 or self.messenger.faults is not None:
                for a, m, _ in entries:
                    await self.messenger.send(a, peer, m)
            else:
                await self.messenger.send(addr, peer,
                                          pack_subop_batch(msgs))
                if self.perf is not None:
                    self.perf.inc("coalesced_subops", len(msgs))
        except (ConnectionError, OSError) as e:
            for _, _, on_error in entries:
                if on_error is not None:
                    on_error(e)

    async def close(self) -> None:
        """Ship anything parked, then refuse further staging -- a
        staged sub-op may never outlive the pipe (it would wedge the
        op awaiting its reply)."""
        self.closed = True
        for t in self._peer_tasks.values():
            t.cancel()
        self._peer_tasks.clear()
        for peer, q in list(self._peer_q.items()):
            if not self._busy.get(peer):
                await self._ship_queued(peer, q)


class Messenger:
    def __init__(self, name: str, secret: bytes | None = None, *,
                 max_unacked_msgs: int = 4096,
                 max_unacked_bytes: int = 64 << 20,
                 ack_every: int = ACK_EVERY,
                 ack_bytes: int = ACK_BYTES,
                 compression: str | None = None,
                 secure: bool = False,
                 faults=None) -> None:
        self.name = name
        self.secret = secret
        # deterministic message mangling (common/faults.py): consulted
        # on every app-level send and every delivered message; None in
        # production paths
        self.faults = faults
        # on-wire transforms this endpoint OFFERS/accepts; the server
        # picks during the handshake (ProtocolV2 negotiation)
        self.compression = compression
        self.secure = secure
        # secure mode needs a key source, but that can be the PSK OR a
        # cephx ticket/validator installed after construction; a
        # keyless endpoint that insists on secure simply refuses every
        # connection at negotiation time
        self.max_unacked_msgs = max_unacked_msgs
        self.max_unacked_bytes = max_unacked_bytes
        self.ack_every = ack_every
        self.ack_bytes = ack_bytes
        # incarnation distinguishes a restarted peer from a reconnecting
        # one (ProtocolV2's global_seq/connect_seq split): a new
        # incarnation resets the replay-dedup session, a reconnect of
        # the same incarnation resumes it
        self.incarnation = os.urandom(8).hex()
        # cephx ticket auth (composes with/replaces the static PSK,
        # src/auth/cephx/CephxProtocol.h): a CLIENT stores tickets per
        # target service in `tickets` ({"gen", "ticket", "session_key"
        # hex, "expires"}); connect() picks by the peer name's prefix
        # ("osd.3" -> tickets["osd"]) and proves the session key in
        # the handshake instead of the PSK.  A SERVER sets
        # `ticket_validator(gen, blob_hex) -> session_key bytes`
        # (raises to reject); the validated session key becomes the
        # connection secret for the proof, negotiation MAC, and
        # secure-mode AEAD keys, so a leaked PSK stops being forever
        # (round-3 review).  `require_ticket` makes the server NACK
        # peers that present no (or a bad) ticket.
        self.tickets: dict[str, dict] = {}
        self.ticket_validator = None
        self.require_ticket = False
        self.dispatchers: list[Dispatcher] = []
        # ms_fast_dispatch analog: a SYNCHRONOUS handler consulted
        # before the task-per-message dispatch path.  Returning True
        # consumes the message without spawning a task -- reply
        # messages that only resolve a tid waiter (the bulk of sub-op
        # traffic) skip a whole scheduling quantum each.  Fault
        # delays/duplicates still take the task path so chaos timing
        # semantics are unchanged.
        self.fast_dispatch = None
        # one connection per peer per DIRECTION: simultaneous cross-
        # connects between two daemons are legal and never race over a
        # shared slot (the reference arbitrates the same race with
        # ProtocolV2 global_seq; separate directions sidestep it)
        self.conns: dict[str, Connection] = {}       # outgoing, by peer
        self.conns_in: dict[str, Connection] = {}    # accepted, by peer
        # per-peer last delivered seq; survives reconnects so replayed
        # messages dedup (the lossless policy's session state)
        self._sessions: dict[str, int] = {}
        self._session_inst: dict[str, str] = {}      # peer -> incarnation
        self._connect_locks: dict[str, asyncio.Lock] = {}
        self._shutting_down = False
        self._server: asyncio.base_events.Server | None = None
        self.addr: tuple[str, int] | None = None
        self._accept_tasks: set[asyncio.Task] = set()

    # -- server -------------------------------------------------------------
    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._on_accept, host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    def add_dispatcher(self, fn: Dispatcher) -> None:
        self.dispatchers.append(fn)

    async def _on_accept(self, reader, writer) -> None:
        if self._shutting_down:
            writer.close()
            return
        try:
            peer_name, inst, nego, hs_nonce, hs_cnonce, hs_secret = \
                await self._handshake_server_read(reader, writer)
        except (asyncio.IncompleteReadError, ValueError, ConnectionError):
            writer.close()
            return
        if self._shutting_down:      # raced shutdown during handshake
            writer.close()
            return
        # close any stale conn from this peer BEFORE touching session
        # state: its read loop must not repopulate _sessions with an
        # old seq between our reset and the in_seq snapshot below
        old = self.conns_in.get(peer_name)
        if old is not None:
            await old.close()
        if self._session_inst.get(peer_name) != inst:
            # restarted peer: fresh session, no replay dedup state
            self._session_inst[peer_name] = inst
            self._sessions.pop(peer_name, None)
        last_seq = self._sessions.get(peer_name, 0)
        try:
            nego_blob = json.dumps(nego).encode()
            writer.write(b"ACK!" + struct.pack("<Q", last_seq)
                         + struct.pack("<I", len(nego_blob)) + nego_blob)
            await writer.drain()
        except (ConnectionError, OSError):
            writer.close()
            return
        conn = Connection(self, peer_name, reader, writer, outgoing=False)
        self._apply_negotiation(conn, nego, hs_nonce, hs_cnonce,
                                is_server=True, secret=hs_secret)
        conn.in_seq = last_seq
        self.conns_in[peer_name] = conn
        conn._read_task = asyncio.ensure_future(self._read_loop(conn))

    # -- handshake (HMAC challenge, cephx-lite) ------------------------------
    def _ticket_for(self, peer_name: str) -> dict | None:
        """The live ticket for the peer's service class, if any
        (expired tickets are dropped -- the owner refreshes)."""
        service = peer_name.split(".", 1)[0]
        t = self.tickets.get(service)
        if t is not None and t.get("expires", 0) < time.time():
            del self.tickets[service]
            return None
        return t

    def _session_keys(self, nonce: bytes, cnonce: bytes, salt: bytes,
                      secret: bytes | None = None):
        """Per-direction session keys from the full transcript: server
        nonce + CLIENT nonce + salt (a replayed server hello cannot
        force key reuse -- the client's nonce is fresh), with a
        direction label (c2s/s2c) so the two streams never share a key
        (cephx-style session key into AES-GCM, crypto_onwire.cc).
        The AEAD comes from cephx._aes: real AES-GCM when the
        optional `cryptography` wheel is present, the stdlib fallback
        otherwise (both ends of a connection share the environment in
        tests, so the negotiated mode always matches)."""
        from ..common.cephx import _aes
        secret = secret if secret is not None else self.secret
        base = nonce + cnonce + salt

        def key(label: bytes):
            return _aes(hmac.new(secret,
                                 b"ctv2-secure-" + label + base,
                                 hashlib.sha256).digest())
        return key(b"c2s"), key(b"s2c")

    def _nego_mac(self, nego: dict, nonce: bytes,
                  cnonce: bytes, secret: bytes | None = None) -> str:
        """Bind the negotiation to the shared secret: a MITM rewriting
        the plaintext nego blob (encryption downgrade) fails the MAC."""
        secret = secret if secret is not None else self.secret
        if secret is None:
            return ""
        blob = json.dumps({k: nego[k] for k in
                           ("compression", "secure", "salt")},
                          sort_keys=True).encode()
        return hmac.new(secret, b"nego" + nonce + cnonce + blob,
                        hashlib.sha256).hexdigest()

    def _negotiate(self, offered: dict,
                   secret: bytes | None = None) -> dict:
        """Server side: pick the on-wire transforms."""
        comp = ""
        if self.compression and self.compression in offered.get(
                "compress", []):
            comp = self.compression
        secure = bool(offered.get("secure")) and self.secure \
            and (secret if secret is not None
                 else self.secret) is not None
        return {"compression": comp, "secure": secure,
                "salt": os.urandom(16).hex()}

    async def _handshake_server_read(self, reader, writer):
        """Server side up to (not including) the ACK: returns
        (peer name, peer incarnation, negotiated transforms, nonce,
        cnonce, connection secret)."""
        nonce = os.urandom(16)
        # hello flags advertise ticket support so a ticket-holding
        # client talking to a PSK-only server falls back to the PSK
        # instead of proving a key the server can't derive
        flags = (HELLO_ACCEPTS_TICKETS
                 if self.ticket_validator is not None else 0) \
            | (HELLO_REQUIRES_TICKET if self.require_ticket else 0)
        writer.write(HELLO_MAGIC + struct.pack("<16sB", nonce, flags))
        await writer.drain()
        hdr = await reader.readexactly(4)
        if hdr != HELLO_MAGIC:
            raise ValueError("bad hello")
        (nlen,) = struct.unpack("<I", await reader.readexactly(4))
        payload = json.loads(await reader.readexactly(nlen))

        async def reject(why: str):
            writer.write(b"NACK")
            await writer.drain()
            raise ValueError(why)

        # cephx: a presented ticket, once validated against the
        # rotating service keys, carries the session key that becomes
        # THIS connection's secret (proof, nego MAC, AEAD) -- and its
        # sealed entity must MATCH the claimed peer name, or any
        # service-class ticket holder could impersonate any daemon
        secret = self.secret
        cephx = payload.get("cephx")
        if cephx is not None and self.ticket_validator is not None:
            try:
                info = self.ticket_validator(cephx["gen"],
                                             cephx["ticket"])
            except Exception as e:
                await reject(f"cephx ticket rejected: {e}")
            if info["entity"] != payload.get("name"):
                await reject(
                    f"ticket entity {info['entity']!r} does not match "
                    f"claimed name {payload.get('name')!r}")
            secret = info["session_key"]
        elif self.require_ticket:
            await reject("cephx ticket required")

        proof = bytes.fromhex(payload.get("proof", ""))
        if secret is not None:
            want = hmac.new(secret, nonce, hashlib.sha256).digest()
            if not hmac.compare_digest(proof, want):
                await reject("auth failure")
        nego = self._negotiate(payload, secret)
        if self.secure and not nego["secure"]:
            # the server's secure requirement binds BOTH directions: a
            # peer that won't (or can't) encrypt gets no session at all
            await reject("peer did not offer secure mode")
        cnonce = bytes.fromhex(payload.get("cnonce", "")) or b"\0" * 16
        nego["mac"] = self._nego_mac(nego, nonce, cnonce, secret)
        return payload["name"], payload.get("inst", ""), nego, \
            nonce, cnonce, secret

    def _apply_negotiation(self, conn: Connection, nego: dict,
                           nonce: bytes, cnonce: bytes,
                           is_server: bool,
                           secret: bytes | None = None) -> None:
        if conn.outgoing is is_server:
            raise ValueError("negotiation direction mismatch")
        secret = secret if secret is not None else self.secret
        # a RE-negotiation (reconnect) replaces the transforms wholesale:
        # keeping a stale compressor after the peer stopped offering it
        # would emit frames the peer can no longer parse
        conn.compressor = None
        conn.aead_tx = None
        conn.aead_rx = None
        if not is_server:
            # client: verify the server's pick against the transcript
            # MAC and refuse a downgrade of our secure requirement
            want = self._nego_mac(nego, nonce, cnonce, secret)
            if want and not hmac.compare_digest(
                    want, nego.get("mac", "")):
                raise ValueError("negotiation MAC mismatch (tampered?)")
            if self.secure and not nego.get("secure"):
                raise ValueError(
                    "peer refused secure mode (downgrade rejected)")
        if nego.get("compression"):
            from ..compressor import Compressor, CompressorError
            try:
                conn.compressor = Compressor.create(nego["compression"])
            except CompressorError as e:
                # normalize to the error type every negotiation-failure
                # path already handles (close, don't retry)
                raise ValueError(str(e)) from e
        if nego.get("secure"):
            c2s, s2c = self._session_keys(nonce, cnonce,
                                          bytes.fromhex(nego["salt"]),
                                          secret)
            if is_server:
                conn.aead_rx, conn.aead_tx = c2s, s2c
            else:
                conn.aead_tx, conn.aead_rx = c2s, s2c

    async def _handshake_client(self, reader, writer,
                                peer_name: str = ""):
        hdr = await reader.readexactly(21)
        if hdr[:4] != HELLO_MAGIC:
            raise ValueError("bad hello")
        nonce = hdr[4:20]
        flags = hdr[20]
        # a live ticket for the peer's service replaces the PSK: we
        # prove the ticket's session key, and the server recovers the
        # same key from the sealed ticket blob.  Only presented when
        # the server's hello says it can validate tickets (a PSK-only
        # server would otherwise fail our proof)
        secret = self.secret
        fields = {}
        ticket = (self._ticket_for(peer_name)
                  if peer_name and flags & HELLO_ACCEPTS_TICKETS
                  else None)
        if ticket is not None:
            secret = bytes.fromhex(ticket["session_key"])
            fields["cephx"] = {"gen": ticket["gen"],
                               "ticket": ticket["ticket"]}
        proof = b""
        if secret is not None:
            proof = hmac.new(secret, nonce, hashlib.sha256).digest()
        cnonce = os.urandom(16)
        payload = json.dumps({
            "name": self.name, "inst": self.incarnation,
            "proof": proof.hex(), "cnonce": cnonce.hex(),
            "compress": [self.compression] if self.compression else [],
            "secure": self.secure, **fields}).encode()
        writer.write(HELLO_MAGIC + struct.pack("<I", len(payload)) + payload)
        await writer.drain()
        ack = await reader.readexactly(4)
        if ack != b"ACK!":
            raise ConnectionError("auth rejected")
        (last_seq,) = struct.unpack("<Q", await reader.readexactly(8))
        (nego_len,) = struct.unpack("<I", await reader.readexactly(4))
        nego = json.loads(await reader.readexactly(nego_len))
        return last_seq, nego, nonce, cnonce, secret

    # -- client -------------------------------------------------------------
    async def connect(self, addr: tuple[str, int],
                      peer_name: str) -> Connection:
        # serialize per peer: N concurrent sends must share ONE
        # connection, not race N handshakes (the acceptor keeps a single
        # incoming conn per peer and would drop the losers mid-flight)
        lock = self._connect_locks.setdefault(peer_name, asyncio.Lock())
        async with lock:
            replay: list[Message] = []   # unacked msgs carried over
            conn = self.conns.get(peer_name)
            if conn is not None and not conn.closed:
                if conn.outgoing and conn.peer_addr is not None \
                        and tuple(conn.peer_addr) != tuple(addr):
                    # peer rebound to a new address: the cached conn
                    # points at a dead endpoint; carry its unacked
                    # messages over (lossless policy)
                    replay = [m for m, _ in conn.unacked]
                    await conn.close()
                else:
                    return conn
            elif conn is not None and conn.closed:
                replay = [m for m, _ in conn.unacked]
            reader, writer = await asyncio.open_connection(
                addr[0], addr[1])
            last_seq, nego, hs_nonce, hs_cnonce, hs_secret = \
                await self._handshake_client(reader, writer, peer_name)
            conn = Connection(self, peer_name, reader, writer,
                              outgoing=True, peer_addr=addr)
            self._apply_negotiation(conn, nego, hs_nonce, hs_cnonce,
                                    is_server=False, secret=hs_secret)
            # continue the server's seq space: a same-incarnation
            # session survives connection churn, and starting below
            # last_seq would get every message deduped as a replay
            conn.out_seq = last_seq
            self.conns[peer_name] = conn
            conn._read_task = asyncio.ensure_future(self._read_loop(conn))
            for msg in replay:
                if msg.seq > last_seq:
                    await conn.send(msg)     # re-stamps seq past last_seq
            return conn

    async def _reconnect(self, conn: Connection) -> None:
        """Lossless policy: reopen and replay unacked in order.

        Serialized per connection — the send error path and the
        read-loop EOF path can both request a reconnect concurrently;
        the second requester finds the generation already advanced and
        returns without racing reader/writer swaps.
        """
        if conn.peer_addr is None:
            await conn.close()
            raise ConnectionError("incoming connection lost")
        gen = conn.generation
        async with conn._reconnect_lock:
            if conn.closed:
                raise ConnectionError(f"{conn.peer_name} closed")
            if conn.generation != gen:
                return               # someone else already reconnected
            for attempt in range(5):
                try:
                    reader, writer = await asyncio.open_connection(
                        conn.peer_addr[0], conn.peer_addr[1])
                    last_seq, nego, hs_nonce, hs_cnonce, hs_secret = \
                        await self._handshake_client(reader, writer,
                                                     conn.peer_name)
                    # swap + replay under the SEND lock: a sender mid-
                    # flight must not write a newer seq onto the fresh
                    # stream before the replay of older unacked frames
                    # (the receiver's dedup would then drop the older
                    # seq as a replay -> silent loss)
                    async with conn._send_lock:
                        self._apply_negotiation(conn, nego, hs_nonce,
                                                hs_cnonce,
                                                is_server=False,
                                                secret=hs_secret)
                        conn._trim_acked(last_seq)
                        conn.reader, conn.writer = reader, writer
                        # server->client stream restarts on new accept
                        conn.in_seq = 0
                        conn.generation += 1
                        if conn._read_task:
                            conn._read_task.cancel()
                        conn._read_task = asyncio.ensure_future(
                            self._read_loop(conn))
                        await conn._resend_unacked()
                    return
                except (ConnectionError, OSError):
                    await asyncio.sleep(0.05 * (2 ** attempt))
                except ValueError:
                    # negotiation failure (MAC mismatch, downgrade,
                    # unknown compressor): retrying cannot help; close
                    # so connect() replaces the conn instead of
                    # returning a zombie forever
                    break
            await conn.close()
            raise ConnectionError(f"reconnect to {conn.peer_name} failed")

    async def send(self, addr: tuple[str, int], peer_name: str,
                   msg: Message) -> None:
        conn = await self.connect(addr, peer_name)
        await conn.send(msg)

    # -- dispatch -----------------------------------------------------------
    async def _read_loop(self, conn: Connection) -> None:
        try:
            while not conn.closed:
                buf = await read_frame(conn.reader, conn.compressor,
                                       conn.aead_rx)
                msg = Message.decode(buf)
                if msg.type == ACK_TYPE:   # control frame, outside seq space
                    conn._trim_acked(int(msg.data.get("seq", 0)))
                    continue
                if msg.seq <= conn.in_seq:
                    continue  # duplicate after resend
                conn.in_seq = msg.seq
                if not conn.outgoing:
                    self._sessions[conn.peer_name] = msg.seq
                conn._note_delivered(len(buf))
                if msg.type == SUBOP_BATCH_TYPE:
                    # one framed flush -> the staged sub-ops, delivered
                    # in staging order (per-peer FIFO preserved)
                    for sub in unpack_subop_batch(msg):
                        self._deliver(conn, sub)
                else:
                    self._deliver(conn, msg)
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            if conn.outgoing and not conn.closed:
                # lossless policy: try to re-establish and replay
                # unacked; on failure the conn is closed so connect()
                # replaces it instead of returning a cached corpse
                try:
                    t = asyncio.ensure_future(self._try_reconnect(conn))
                    self._accept_tasks.add(t)
                    t.add_done_callback(self._accept_tasks.discard)
                except RuntimeError:      # event loop shutting down
                    conn.closed = True
                    conn._window_open.set()
            else:
                conn.closed = True
                # wake any sender blocked on the flow-control window so
                # it raises instead of hanging on a dead connection
                conn._window_open.set()
                try:
                    conn.writer.close()
                except Exception:
                    pass
        except asyncio.CancelledError:
            pass

    def _deliver(self, conn: Connection, msg: Message) -> None:
        """Fault-inject and dispatch ONE logical message (seq/ack
        accounting already ran on its frame)."""
        copies, delay = 1, 0.0
        if self.faults is not None:
            # recv-side injection happens ABOVE the transport:
            # seq/ack accounting already ran, so a dropped
            # message is "lost in the daemon", not a wire error
            # the lossless replay would transparently heal
            fd = self.faults.on_recv(
                self.name, conn.peer_name or msg.from_name,
                msg.type)
            if fd.drop:
                return
            copies, delay = fd.copies, fd.delay
        if (self.fast_dispatch is not None and copies == 1
                and delay == 0.0 and self.fast_dispatch(conn, msg)):
            return
        # dispatch in a task: a handler that itself RPCs back to
        # this peer must not block the read loop its reply rides
        # on (the reference's DispatchQueue decoupling).  Task
        # creation order preserves ordering for handlers'
        # synchronous prefixes.
        for _ in range(copies):
            t = asyncio.ensure_future(
                self._dispatch_one(conn, msg, delay))
            self._accept_tasks.add(t)
            t.add_done_callback(self._accept_tasks.discard)

    async def _try_reconnect(self, conn: Connection) -> None:
        try:
            await self._reconnect(conn)
        except (ConnectionError, OSError):
            pass

    async def _dispatch_one(self, conn: Connection, msg: Message,
                            delay: float = 0.0) -> None:
        if delay > 0:
            await asyncio.sleep(delay)
        for d in list(self.dispatchers):
            try:
                await d(conn, msg)
            except (ConnectionError, OSError):
                pass

    async def shutdown(self) -> None:
        # stop accepting BEFORE closing connections: closing a conn
        # triggers the peer's instant reconnect, and a still-open
        # listener would accept it -- a ghost connection that survives
        # shutdown and keeps this daemon answering (e.g. heartbeats
        # from a "dead" OSD, defeating failure detection)
        self._shutting_down = True
        if self._server is not None:
            self._server.close()
        for t in list(self._accept_tasks):
            t.cancel()
        for conn in (list(self.conns.values())
                     + list(self.conns_in.values())):
            await conn.close()
        self.conns.clear()
        self.conns_in.clear()
        if self._server is not None:
            # 3.12 wait_closed blocks until every peer transport is
            # gone; peers shutting down concurrently make that a
            # deadlock, so bound it
            try:
                await asyncio.wait_for(self._server.wait_closed(), 1.0)
            except asyncio.TimeoutError:
                pass
