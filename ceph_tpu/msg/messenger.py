"""Asyncio messenger with lossless-client reconnect semantics.

Responsibilities mirrored from the reference's AsyncMessenger
(src/msg/async/AsyncMessenger.h:74): bind/accept, connect-by-address with
connection caching, ordered per-connection delivery with sequence numbers,
resend of unacked messages after reconnect (lossless policy,
src/msg/Policy.h), dispatcher fan-out, and an HMAC-SHA256 session
handshake standing in for cephx (src/auth/cephx) in crc mode.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import os
import struct
from collections import deque
from typing import Awaitable, Callable

from .message import Message, read_frame

Dispatcher = Callable[["Connection", Message], Awaitable[None]]

HELLO_MAGIC = b"CTHL"


class Connection:
    def __init__(self, messenger: "Messenger", peer_name: str,
                 reader, writer, *, outgoing: bool,
                 peer_addr: tuple[str, int] | None = None) -> None:
        self.messenger = messenger
        self.peer_name = peer_name
        self.reader = reader
        self.writer = writer
        self.outgoing = outgoing
        self.peer_addr = peer_addr
        self.out_seq = 0
        self.in_seq = 0
        self.unacked: deque[Message] = deque()
        self.closed = False
        self._send_lock = asyncio.Lock()
        self._read_task: asyncio.Task | None = None

    async def send(self, msg: Message) -> None:
        async with self._send_lock:
            self.out_seq += 1
            msg.seq = self.out_seq
            msg.from_name = self.messenger.name
            self.unacked.append(msg)
            if len(self.unacked) > 1024:
                self.unacked.popleft()
            try:
                self.writer.write(msg.encode())
                await self.writer.drain()
            except (ConnectionError, OSError):
                if self.outgoing:
                    await self.messenger._reconnect(self)
                else:
                    await self.close()
                    raise

    async def _resend_unacked(self) -> None:
        for msg in list(self.unacked):
            self.writer.write(msg.encode())
        await self.writer.drain()

    async def close(self) -> None:
        self.closed = True
        if self._read_task:
            self._read_task.cancel()
        try:
            self.writer.close()
        except Exception:
            pass


class Messenger:
    def __init__(self, name: str, secret: bytes | None = None) -> None:
        self.name = name
        self.secret = secret
        self.dispatchers: list[Dispatcher] = []
        self.conns: dict[str, Connection] = {}       # by peer name
        # per-peer last delivered seq; survives reconnects so replayed
        # messages dedup (the lossless policy's session state)
        self._sessions: dict[str, int] = {}
        self._server: asyncio.base_events.Server | None = None
        self.addr: tuple[str, int] | None = None
        self._accept_tasks: set[asyncio.Task] = set()

    # -- server -------------------------------------------------------------
    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._on_accept, host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        return self.addr

    def add_dispatcher(self, fn: Dispatcher) -> None:
        self.dispatchers.append(fn)

    async def _on_accept(self, reader, writer) -> None:
        try:
            peer_name = await self._handshake_server(reader, writer)
        except (asyncio.IncompleteReadError, ValueError, ConnectionError):
            writer.close()
            return
        conn = Connection(self, peer_name, reader, writer, outgoing=False)
        conn.in_seq = self._sessions.get(peer_name, 0)
        old = self.conns.get(peer_name)
        if old is not None and not old.outgoing:
            await old.close()
        self.conns[peer_name] = conn
        conn._read_task = asyncio.ensure_future(self._read_loop(conn))

    # -- handshake (HMAC challenge, cephx-lite) ------------------------------
    async def _handshake_server(self, reader, writer) -> str:
        nonce = os.urandom(16)
        writer.write(HELLO_MAGIC + struct.pack("<16s", nonce))
        await writer.drain()
        hdr = await reader.readexactly(4)
        if hdr != HELLO_MAGIC:
            raise ValueError("bad hello")
        (nlen,) = struct.unpack("<I", await reader.readexactly(4))
        payload = json.loads(await reader.readexactly(nlen))
        proof = bytes.fromhex(payload.get("proof", ""))
        if self.secret is not None:
            want = hmac.new(self.secret, nonce, hashlib.sha256).digest()
            if not hmac.compare_digest(proof, want):
                writer.write(b"NACK")
                await writer.drain()
                raise ValueError("auth failure")
        last_seq = self._sessions.get(payload["name"], 0)
        writer.write(b"ACK!" + struct.pack("<Q", last_seq))
        await writer.drain()
        return payload["name"]

    async def _handshake_client(self, reader, writer) -> None:
        hdr = await reader.readexactly(20)
        if hdr[:4] != HELLO_MAGIC:
            raise ValueError("bad hello")
        nonce = hdr[4:20]
        proof = b""
        if self.secret is not None:
            proof = hmac.new(self.secret, nonce, hashlib.sha256).digest()
        payload = json.dumps({"name": self.name,
                              "proof": proof.hex()}).encode()
        writer.write(HELLO_MAGIC + struct.pack("<I", len(payload)) + payload)
        await writer.drain()
        ack = await reader.readexactly(4)
        if ack != b"ACK!":
            raise ConnectionError("auth rejected")
        (last_seq,) = struct.unpack("<Q", await reader.readexactly(8))
        return last_seq

    # -- client -------------------------------------------------------------
    async def connect(self, addr: tuple[str, int],
                      peer_name: str) -> Connection:
        conn = self.conns.get(peer_name)
        if conn is not None and not conn.closed:
            return conn
        reader, writer = await asyncio.open_connection(addr[0], addr[1])
        await self._handshake_client(reader, writer)
        conn = Connection(self, peer_name, reader, writer, outgoing=True,
                          peer_addr=addr)
        self.conns[peer_name] = conn
        conn._read_task = asyncio.ensure_future(self._read_loop(conn))
        return conn

    async def _reconnect(self, conn: Connection) -> None:
        """Lossless policy: reopen and replay unacked in order."""
        if conn.peer_addr is None:
            await conn.close()
            raise ConnectionError("incoming connection lost")
        for attempt in range(5):
            try:
                reader, writer = await asyncio.open_connection(
                    conn.peer_addr[0], conn.peer_addr[1])
                last_seq = await self._handshake_client(reader, writer)
                while conn.unacked and conn.unacked[0].seq <= last_seq:
                    conn.unacked.popleft()
                conn.reader, conn.writer = reader, writer
                if conn._read_task:
                    conn._read_task.cancel()
                conn._read_task = asyncio.ensure_future(self._read_loop(conn))
                await conn._resend_unacked()
                return
            except (ConnectionError, OSError):
                await asyncio.sleep(0.05 * (2 ** attempt))
        await conn.close()
        raise ConnectionError(f"reconnect to {conn.peer_name} failed")

    async def send(self, addr: tuple[str, int], peer_name: str,
                   msg: Message) -> None:
        conn = await self.connect(addr, peer_name)
        await conn.send(msg)

    # -- dispatch -----------------------------------------------------------
    async def _read_loop(self, conn: Connection) -> None:
        try:
            while not conn.closed:
                buf = await read_frame(conn.reader)
                msg = Message.decode(buf)
                if msg.seq <= conn.in_seq:
                    continue  # duplicate after resend
                conn.in_seq = msg.seq
                if not conn.outgoing:
                    self._sessions[conn.peer_name] = msg.seq
                for d in self.dispatchers:
                    await d(conn, msg)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError, ValueError):
            pass

    async def shutdown(self) -> None:
        for conn in list(self.conns.values()):
            await conn.close()
        self.conns.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
