"""Erasure-code layer: interface, base class, plugin registry, plugins.

Mirrors the reference's plugin architecture (src/erasure-code/
ErasureCodeInterface.h:170, ErasureCodePlugin.cc:86) so that the benchmark
harness and the OSD ECBackend select codecs purely by profile name, while the
actual math runs as TPU kernels (ceph_tpu.ops).
"""

from .interface import ErasureCodeInterface, ErasureCodeProfile  # noqa: F401
from .base import ErasureCode, SIMD_ALIGN  # noqa: F401
from .registry import ErasureCodePluginRegistry, instance as registry  # noqa: F401
