"""Abstract erasure-code codec contract.

Python rendering of the reference's ErasureCodeInterface
(src/erasure-code/ErasureCodeInterface.h:170-470): systematic codes split an
object into k data chunks + m coding chunks; chunk i of a stripe lives on
shard i; array codes may subdivide chunks into sub-chunks.  Buffers are
``bytes``/``numpy.uint8`` arrays rather than bufferlists; chunk maps are
``dict[int, np.ndarray]``.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Mapping

import numpy as np

# profile: str -> str mapping, same shape as ErasureCodeProfile
ErasureCodeProfile = dict


class ErasureCodeInterface(ABC):
    """Codec contract.  All chunk indices are *shard* ids in [0, k+m)."""

    @abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Initialize from a profile; raises ValueError on bad profiles.

        Implementations must record the profile so get_profile() echoes it
        (the registry verifies the echo, as ErasureCodePlugin.cc:99 does).
        """

    @abstractmethod
    def get_profile(self) -> ErasureCodeProfile:
        ...

    @abstractmethod
    def get_chunk_count(self) -> int:
        """k + m."""

    @abstractmethod
    def get_data_chunk_count(self) -> int:
        """k."""

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """Array codes (Clay) override; 1 otherwise."""
        return 1

    @abstractmethod
    def get_chunk_size(self, stripe_width: int) -> int:
        """Chunk size for an object of ``stripe_width`` bytes (incl. padding)."""

    @abstractmethod
    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int],
    ) -> dict[int, list[tuple[int, int]]]:
        """Chunks (and sub-chunk ranges) to retrieve to read want_to_read.

        Returns {shard: [(offset, count), ...]} in sub-chunk units.
        Raises IOError if decoding is impossible.
        """

    @abstractmethod
    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: Mapping[int, int],
    ) -> set[int]:
        """Like minimum_to_decode but given per-chunk retrieval costs."""

    @abstractmethod
    def encode(
        self, want_to_encode: set[int], data: bytes,
    ) -> dict[int, np.ndarray]:
        """Split+pad ``data`` into k chunks, compute m parity chunks, return
        the requested subset."""

    @abstractmethod
    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        """Compute parity in place over prepared, equal-size chunks."""

    @abstractmethod
    def decode(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        chunk_size: int = 0,
    ) -> dict[int, np.ndarray]:
        """Reconstruct the requested chunks from the available ones."""

    @abstractmethod
    def decode_chunks(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        ...

    @abstractmethod
    def get_chunk_mapping(self) -> list[int]:
        """Pseudo-layout remap (LRC "mapping" profiles); [] = identity."""

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        """Reconstruct and concatenate the data chunks in order."""
        k = self.get_data_chunk_count()
        want = set(range(k))
        decoded = self.decode(want, chunks)
        return b"".join(bytes(decoded[i]) for i in range(k))
