"""Flat linear codec at sub-chunk granularity: the shared spine of the
recovery-bandwidth-optimal plugin family (lrc, pmsr).

Both codes are, at bottom, systematic GF(2^8) linear codes whose
structure lives in ONE generator matrix -- just not at whole-chunk
granularity: each of the n = k + m chunks is ``alpha`` sub-chunks, and
the generator maps the k*alpha data sub-chunks to all n*alpha stored
sub-chunks (identity on top: systematic).  LRC is the alpha=1 case
whose parity rows are the layered local/global combinations;
product-matrix MSR is the alpha=k-1 case whose sub-chunk structure is
what makes beta-sized repair fragments possible.

Putting the family on one flat generator buys three things:

  * ONE repair-matrix builder for every pattern: a lost chunk's rows
    re-expressed over the rows actually read (``gf.gf_solve_rows``) --
    the local-group XOR repair and the global multi-failure decode are
    the same call with different sources, so local-repair bytes are
    byte-identical to global-decode bytes by construction, not by a
    parallel implementation agreeing;
  * the batched data plane for free: ``encode_batch``/``decode_batch``
    reshape (B, chunks, L) to (B, sub-chunks, L/alpha) and ride the
    SAME scheduled/dense GF(2) kernel family as the tpu plugin
    (ops/gf2kernels -> ops/xor_schedule), padding buckets, cost model
    and first-use parity gates included -- LRC local parities and MSR
    repair matrices are exactly the sparse matrices greedy CSE
    minimizes best, so their schedules are warmed at build time;
  * a stable launch-compatibility story: the generator bytes are the
    ``CodecBatcher`` grouping signature and the (sources, lost) tuple
    is the decode grouping key, so concurrent repairs with the same
    pattern coalesce into one launch across PGs.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Mapping

import numpy as np

from ..gf import gf_matmul, gf_solve_rows
from .base import ErasureCode, SIMD_ALIGN


class LinearSubchunkCodec(ErasureCode):
    """Systematic (n*alpha, k*alpha) GF(2^8) code over sub-chunk rows.

    Subclasses set ``self.k``/``self.m``/``self.alpha`` and build
    ``self.generator`` (identity on the first k*alpha rows, ordered
    position-major: chunk p's sub-chunks are rows p*alpha..(p+1)*alpha)
    in their ``init``, then call ``finish_setup``.  Positions are shard
    ids; codes with a chunk remapping (LRC ``mapping`` profiles) order
    generator columns by LOGICAL data chunk and rows by position.
    """

    #: the CodecBatcher may coalesce this codec's launches even with a
    #: chunk remapping: the batched drivers place chunks by
    #: ``chunk_index`` (see StripeInfo.encode_async)
    batch_chunk_mapping_ok = True
    #: the MeshCodec flat dialect: launches use ``parity_matrix`` /
    #: ``decode_flat_matrix`` reshaped to sub-chunk rows
    mesh_flat_ok = True

    def __init__(self) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.alpha = 1
        self.generator: np.ndarray | None = None
        self._repair_cache: OrderedDict[tuple, np.ndarray] = \
            OrderedDict()

    # -- geometry -----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_sub_chunk_count(self) -> int:
        return self.alpha

    def get_alignment(self) -> int:
        # chunks must split into alpha equal sub-chunks; keep the SIMD
        # alignment when alpha already divides it
        if SIMD_ALIGN % self.alpha == 0:
            return SIMD_ALIGN
        return SIMD_ALIGN * self.alpha

    def finish_setup(self) -> None:
        """Validate the generator and warm the encode schedule."""
        ka = self.k * self.alpha
        na = (self.k + self.m) * self.alpha
        g = np.ascontiguousarray(self.generator, np.uint8)
        assert g.shape == (na, ka), (g.shape, na, ka)
        self.generator = g
        # the batcher groups launches by these bytes (codec_signature)
        self.encode_matrix = g
        if not np.array_equal(g[self._data_rows()],
                              np.eye(ka, dtype=np.uint8)):
            raise ValueError("generator is not systematic")
        from ..ops.xor_schedule import warm_gf8_schedule
        warm_gf8_schedule(self.parity_matrix)

    def _data_rows(self) -> list[int]:
        """Generator row indices of the data sub-chunks, in logical
        chunk order (mapped codes place data chunk i at position
        chunk_index(i))."""
        rows = []
        for i in range(self.k):
            p = self.chunk_index(i)
            rows.extend(range(p * self.alpha, (p + 1) * self.alpha))
        return rows

    @property
    def coding_positions(self) -> list[int]:
        """Positions hosting coding chunks, ascending (the order the
        batched encode emits parity rows in)."""
        dpos = {self.chunk_index(i) for i in range(self.k)}
        return [p for p in range(self.k + self.m) if p not in dpos]

    @property
    def parity_matrix(self) -> np.ndarray:
        """(m*alpha, k*alpha) rows of the coding positions."""
        rows = []
        for p in self.coding_positions:
            rows.extend(range(p * self.alpha, (p + 1) * self.alpha))
        return np.ascontiguousarray(self.generator[rows])

    def position_rows(self, positions) -> np.ndarray:
        rows = []
        for p in positions:
            rows.extend(range(p * self.alpha, (p + 1) * self.alpha))
        return np.ascontiguousarray(self.generator[rows])

    # -- sub-chunk reshapes --------------------------------------------------
    def _subrows(self, chunks: np.ndarray) -> np.ndarray:
        """(c, L) chunk rows -> (c*alpha, L/alpha) sub-chunk rows."""
        c, lane = chunks.shape
        assert lane % self.alpha == 0, (lane, self.alpha)
        return chunks.reshape(c * self.alpha, lane // self.alpha)

    def _unsubrows(self, sub: np.ndarray, c: int) -> np.ndarray:
        return sub.reshape(c, -1)

    # -- host encode/decode --------------------------------------------------
    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        data = np.stack([chunks[self.chunk_index(i)]
                         for i in range(self.k)])
        parity = gf_matmul(self.parity_matrix, self._subrows(data))
        out = self._unsubrows(parity, self.m)
        for r, p in enumerate(self.coding_positions):
            chunks[p][:] = out[r]

    def repair_matrix(self, src: tuple[int, ...],
                      lost: tuple[int, ...]) -> np.ndarray:
        """The (len(lost)*alpha, len(src)*alpha) GF(2^8) matrix writing
        the lost chunks' sub-rows over the source chunks' sub-rows.
        Cached per (sources, lost) pattern with its XOR schedule warmed
        at build time, so repeated repairs ride the scheduled kernels
        without compiling on the read path.  Raises IOError when the
        pattern is not recoverable from these sources."""
        key = (src, lost)
        entry = self._repair_cache.get(key)
        if entry is not None:
            self._repair_cache.move_to_end(key)
            return entry
        try:
            matrix = gf_solve_rows(self.position_rows(src),
                                   self.position_rows(lost))
        except ValueError as e:
            raise IOError(
                f"cannot repair chunks {list(lost)} from "
                f"{list(src)}: {e}") from e
        from ..ops.xor_schedule import warm_gf8_schedule
        warm_gf8_schedule(matrix)
        self._repair_cache[key] = matrix
        while len(self._repair_cache) > 128:
            self._repair_cache.popitem(last=False)
        return matrix

    def decode_chunks(self, want_to_read: set[int],
                      chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        available = set(chunks)
        lost = tuple(sorted(set(want_to_read) - available))
        if not lost:
            return
        src = self._decode_sources(lost, available)
        srcs = np.stack([np.asarray(chunks[p], dtype=np.uint8)
                         for p in src])
        matrix = self.repair_matrix(src, lost)
        rec = self._unsubrows(
            gf_matmul(matrix, self._subrows(srcs)), len(lost))
        for i, p in enumerate(lost):
            decoded[p][:] = rec[i]

    def _decode_sources(self, lost: tuple[int, ...],
                        available: set[int]) -> tuple[int, ...]:
        """The chunks a decode of ``lost`` reads, ascending.  The MDS
        default reads the first k survivors; layered subclasses
        override with their locality plan."""
        return tuple(sorted(available)[:self.k])

    # -- batched entry points (CodecBatcher / MeshCodec flat dialect) --------
    # The launches ride the same scheduled/dense GF(2) kernel family as
    # the tpu plugin: gf_matmul_batch_device routes each (matrix,
    # shape) through the xor_schedule cost model with a first-use
    # byte-parity gate against the host oracle and transparent dense
    # fallback.

    def _batch_matmul(self, matrix: np.ndarray, arr: np.ndarray,
                      out_chunks: int, out_np: bool):
        from ..ops.gf2kernels import gf_matmul_batch_device
        b, c, lane = arr.shape
        sub = arr.reshape(b, c * self.alpha, lane // self.alpha)
        out = gf_matmul_batch_device(matrix, sub, out_np=out_np)
        return out.reshape(b, out_chunks, lane)

    def encode_batch(self, data: np.ndarray, out_np: bool = False):
        """(B, k, L) data chunks (logical order) -> (B, m, L) coding
        chunks in ``coding_positions`` order, one launch."""
        return self._batch_matmul(self.parity_matrix, data, self.m,
                                  out_np)

    @staticmethod
    def pack_decode_extra(src, lost) -> tuple[int, ...]:
        """The (sources, lost) pattern as the batcher's int-tuple
        ``extra``: (n_src, *src, *lost)."""
        src = tuple(int(s) for s in src)
        lost = tuple(int(e) for e in lost)
        return (len(src),) + src + lost

    @staticmethod
    def unpack_decode_extra(extra) -> tuple[tuple, tuple]:
        extra = tuple(int(e) for e in extra)
        n_src = extra[0]
        return extra[1:1 + n_src], extra[1 + n_src:]

    def decode_signature(self, extra) -> str:
        """DecodeTableCache-style grouping key: same (sources, lost)
        pattern = same repair matrix = shareable launch."""
        src, lost = self.unpack_decode_extra(extra)
        return "".join(f"+{s}" for s in src) + "".join(
            f"-{e}" for e in lost)

    def decode_plan(self, want: set[int],
                    have: set[int]) -> tuple[tuple, tuple] | None:
        """(source positions, lost positions) for the batched decode
        drivers, or None when per-stripe host decode must serve.  The
        sources follow the codec's own selection (locality for LRC),
        restricted to what the caller actually holds."""
        lost = tuple(sorted(set(want) - set(have)))
        if not lost:
            return None
        try:
            src = self._decode_sources(lost, set(have))
        except (IOError, OSError, ValueError):
            return None
        if not set(src) <= set(have):
            return None
        return src, lost

    def decode_batch(self, erasures, survivors: np.ndarray,
                     out_np: bool = False):
        """Batched repair: ``erasures`` is the packed (n_src, *src,
        *lost) extra; ``survivors`` is (B, len(src), L) in src order.
        Returns (B, len(lost), L)."""
        src, lost = self.unpack_decode_extra(erasures)
        matrix = self.repair_matrix(src, lost)
        return self._batch_matmul(matrix, survivors, len(lost),
                                  out_np)

    def decode_flat_matrix(self, erasures) -> np.ndarray:
        """The repair matrix for a packed extra (the MeshCodec flat
        dialect hook -- the SAME cached matrix decode_batch uses)."""
        src, lost = self.unpack_decode_extra(erasures)
        return self.repair_matrix(src, lost)

    # -- repair planning ------------------------------------------------------
    def minimum_to_repair(self, lost: int, available: set[int]
                          ) -> dict[int, list[tuple[int, int]]] | None:
        """Sub-chunk read/compute spec to rebuild one lost chunk, or
        None when plain minimum_to_decode should serve.  Regenerating
        subclasses return the helper set with beta-sized fragment
        counts; the default (and layered codes, whose savings come
        from READING fewer chunks, not computing fragments) defers to
        minimum_to_decode."""
        return None
