"""Erasure-code plugin registry.

Python rendering of ErasureCodePluginRegistry (src/erasure-code/
ErasureCodePlugin.cc): plugins are named factories resolved at first use;
loading is by module import (the dlopen analog) from the builtin plugin
package or an explicit plugin directory; a version handshake and the
profile-echo check (:99-113) are preserved.
"""

from __future__ import annotations

import importlib
import importlib.util
import sys
import threading
from pathlib import Path
from typing import Callable

from .interface import ErasureCodeInterface, ErasureCodeProfile

# version handshake analog of PLUGIN_VERSION vs CEPH_GIT_NICE_VER
PLUGIN_API_VERSION = 1

# module attribute every plugin module must expose (entry-point analog of
# __erasure_code_init, ErasureCodePlugin.h:24-27)
ENTRY_POINT = "__erasure_code_init__"

DEFAULT_PLUGIN_PACKAGE = "ceph_tpu.ec.plugins"


class ErasureCodePlugin:
    """A named factory.  Subclass or instantiate with a factory callable."""

    def __init__(self, factory: Callable[[ErasureCodeProfile],
                                         ErasureCodeInterface],
                 api_version: int = PLUGIN_API_VERSION) -> None:
        self.api_version = api_version
        self._factory = factory

    def factory(self, profile: ErasureCodeProfile) -> ErasureCodeInterface:
        codec = self._factory(profile)
        codec.init(profile)
        return codec


class ErasureCodePluginRegistry:
    def __init__(self) -> None:
        # reentrant: load() holds it while the plugin entry point calls add()
        self._lock = threading.RLock()
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self.disable_dlclose = False  # parity knob; unused

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise ValueError(f"plugin {name} already registered")
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        return self._plugins.get(name)

    def remove(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    def load(self, plugin_name: str, directory: str | None = None) -> ErasureCodePlugin:
        """Import the plugin module and run its entry point.

        A module is looked up as ``<directory>/ec_<name>.py`` when a
        directory is given (the libec_<name>.so analog), else as
        ``ceph_tpu.ec.plugins.<name>``.
        """
        with self._lock:
            if plugin_name in self._plugins:
                return self._plugins[plugin_name]
            if directory:
                path = Path(directory) / f"ec_{plugin_name}.py"
                if not path.exists():
                    raise FileNotFoundError(
                        f"load dlopen({path}): file not found")
                spec = importlib.util.spec_from_file_location(
                    f"ceph_tpu_ec_plugin_{plugin_name}", path)
                module = importlib.util.module_from_spec(spec)
                sys.modules[spec.name] = module
                spec.loader.exec_module(module)
            else:
                try:
                    module = importlib.import_module(
                        f"{DEFAULT_PLUGIN_PACKAGE}.{plugin_name}")
                except ImportError as e:
                    raise FileNotFoundError(
                        f"load dlopen(ec_{plugin_name}): {e}") from e
            entry = getattr(module, ENTRY_POINT, None)
            if entry is None:
                raise ImportError(
                    f"erasure-code plugin {plugin_name}: missing entry point "
                    f"{ENTRY_POINT}")
            # the entry point registers itself (possibly under several names)
            entry(self, plugin_name)
            plugin = self._plugins.get(plugin_name)
            if plugin is None:
                raise ImportError(
                    f"erasure-code plugin {plugin_name}: entry point did not "
                    f"register the plugin")
            if plugin.api_version != PLUGIN_API_VERSION:
                del self._plugins[plugin_name]
                raise ImportError(
                    f"erasure-code plugin {plugin_name}: api version "
                    f"{plugin.api_version} != {PLUGIN_API_VERSION}")
            return plugin

    def factory(
        self,
        plugin_name: str,
        profile: ErasureCodeProfile,
        directory: str | None = None,
    ) -> ErasureCodeInterface:
        """Load (if needed) and instantiate a codec; verify profile echo."""
        plugin = self._plugins.get(plugin_name)
        if plugin is None:
            plugin = self.load(plugin_name, directory)
        codec = plugin.factory(profile)
        echoed = codec.get_profile()
        for key, val in profile.items():
            if key not in echoed:
                raise ValueError(
                    f"plugin {plugin_name} profile lost key {key}={val}")
        return codec

    def preload(self, plugins: list[str], directory: str | None = None) -> None:
        """global_init_preload_erasure_code analog (global_init.cc:593)."""
        for name in plugins:
            self.load(name, directory)


_instance: ErasureCodePluginRegistry | None = None
_instance_lock = threading.Lock()


def instance() -> ErasureCodePluginRegistry:
    global _instance
    if _instance is None:
        with _instance_lock:
            if _instance is None:
                _instance = ErasureCodePluginRegistry()
    return _instance
