"""Reed-Solomon matrix codec shared by the isa/jerasure/tpu plugins.

The codec owns the generator matrix and the decode-matrix LRU cache (the
analog of ErasureCodeIsaTableCache, reference src/erasure-code/isa/
ErasureCodeIsaTableCache.cc); the byte crunching is delegated to a backend:

  * ``NumpyBackend`` -- host reference path (and parity oracle),
  * ``ceph_tpu.ops.jax_backend.JaxBackend`` -- batched MXU bit-matmul path.

Both produce byte-identical chunks.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Mapping

import numpy as np

from ..gf import gf_matmul, build_decode_matrix, erasure_signature
from ..gf.matrices import decode_index_for
from .base import ErasureCode


class NumpyBackend:
    """Plain host GF(2^8) matmul backend."""

    name = "numpy"

    def matmul(self, matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
        """(r,k) GF coeff matrix x (k,n) byte rows -> (r,n) byte rows."""
        return gf_matmul(matrix, data)


class DecodeTableCache:
    """LRU of decode matrices keyed by erasure signature."""

    def __init__(self, maxsize: int = 256) -> None:
        self.maxsize = maxsize
        self._lru: OrderedDict[str, tuple[np.ndarray, list[int]]] = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, signature: str):
        entry = self._lru.get(signature)
        if entry is not None:
            self.hits += 1
            self._lru.move_to_end(signature)
        else:
            self.misses += 1
        return entry

    def put(self, signature: str, matrix: np.ndarray,
            decode_index: list[int]) -> None:
        self._lru[signature] = (matrix, decode_index)
        self._lru.move_to_end(signature)
        while len(self._lru) > self.maxsize:
            self._lru.popitem(last=False)


class RSMatrixCodec(ErasureCode):
    """Systematic (k+m, k) matrix code over GF(2^8).

    Subclasses set self.k, self.m, and build self.encode_matrix in
    prepare(); encode/decode flow through the backend.
    """

    def __init__(self, backend=None) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.encode_matrix: np.ndarray | None = None
        self.backend = backend or NumpyBackend()
        self.tcache = DecodeTableCache()

    # -- interface ----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = np.stack([chunks[self.chunk_index(i)] for i in range(k)])
        parity = self.backend.matmul(self.encode_matrix[k:], data)
        for r in range(m):
            chunks[self.chunk_index(k + r)][:] = parity[r]

    def _build_decode_matrix(self, erasures: list[int]):
        """Decode-matrix construction hook: wider-field codecs (the
        jerasure w=16/32 word techniques) override the FIELD while the
        driver above stays shared."""
        return build_decode_matrix(self.encode_matrix, self.k, erasures)

    def decode_chunks(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        erasures = [i for i in range(k + m) if i not in chunks]
        if len(erasures) > m:
            raise IOError(
                f"{len(erasures)} erasures exceed m={m}")
        if not erasures:
            return
        signature = erasure_signature(
            decode_index_for(k, set(erasures)), erasures)
        entry = self.tcache.get(signature)
        if entry is None:
            matrix, decode_index = self._build_decode_matrix(erasures)
            self.tcache.put(signature, matrix, decode_index)
        else:
            matrix, decode_index = entry
        sources = np.stack([decoded[i] for i in decode_index])
        recovered = self.backend.matmul(matrix, sources)
        for p, e in enumerate(erasures):
            decoded[e][:] = recovered[p]
