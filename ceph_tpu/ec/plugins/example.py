"""Trivial XOR example plugin: k=2, m=1.

Analog of the reference's in-tree example/teaching plugin
(src/test/erasure-code/ErasureCodeExample.h): parity = XOR of the two data
chunks; any single lost chunk is recoverable.  Used by registry tests.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from ..base import ErasureCode
from ..registry import ErasureCodePlugin


class ErasureCodeExample(ErasureCode):
    k = 2
    m = 1

    def init(self, profile) -> None:
        super().init(profile)

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_size(self, stripe_width: int) -> int:
        alignment = self.get_alignment()
        chunk_size = (stripe_width + self.k - 1) // self.k
        modulo = chunk_size % alignment
        if modulo:
            chunk_size += alignment - modulo
        return chunk_size

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: Mapping[int, int],
    ) -> set[int]:
        # prefer the cheapest 2 of the 3 chunks
        if want_to_read <= set(available):
            candidates = sorted(available, key=lambda i: (available[i], i))
            return set(candidates[:self.k])
        return self._minimum_to_decode(want_to_read, set(available))

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        chunks[2][:] = chunks[0] ^ chunks[1]

    def decode_chunks(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        missing = [i for i in range(3) if i not in chunks]
        if len(missing) > 1:
            raise IOError("example XOR code cannot recover >1 chunk")
        for i in missing:
            others = [j for j in range(3) if j != i]
            decoded[i][:] = decoded[others[0]] ^ decoded[others[1]]


def _factory(profile):
    return ErasureCodeExample()


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
