"""jerasure-semantics Reed-Solomon plugin (w=8 techniques).

Mirrors the reference's jerasure plugin techniques that operate byte-wise in
GF(2^8) (src/erasure-code/jerasure/ErasureCodeJerasure.cc):

  * reed_sol_van  -- systematized extended-Vandermonde matrix
    (reed_sol_vandermonde_coding_matrix, ErasureCodeJerasure.cc:203)
  * reed_sol_r6_op -- RAID6 rows [1,1,..], [1,2,4,..] with m forced to 2

Bit-matrix techniques (cauchy_orig/cauchy_good/liberation/blaum_roth/
liber8tion) pack w sub-packets per element and are scheduled for a later
round.  Chunk sizing follows ErasureCodeJerasure::get_chunk_size
(:80-104): stripe padded to a multiple of k*w*sizeof(int) then divided.
"""

from __future__ import annotations

import numpy as np

from ..rs_codec import RSMatrixCodec
from ..registry import ErasureCodePlugin
from ...gf import gen_jerasure_rs_vandermonde, gf_pow

LARGEST_VECTOR_WORDSIZE = 16

DEFAULT_K = "2"
DEFAULT_M = "1"
DEFAULT_W = "8"


class ErasureCodeJerasure(RSMatrixCodec):
    technique = "reed_sol_van"
    DEFAULT_K = DEFAULT_K
    DEFAULT_M = DEFAULT_M

    def __init__(self, backend=None) -> None:
        super().__init__(backend=backend)
        self.w = 8
        self.per_chunk_alignment = False

    def get_alignment(self) -> int:
        if self.per_chunk_alignment:
            return self.w * LARGEST_VECTOR_WORDSIZE
        alignment = self.k * self.w * 4  # sizeof(int)
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def get_chunk_size(self, stripe_width: int) -> int:
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = (stripe_width + self.k - 1) // self.k
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = stripe_width % alignment
        padded = stripe_width + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    def parse_base(self, profile) -> None:
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, DEFAULT_W)
        self.sanity_check_k_m(self.k, self.m)
        if self.w not in (8, 16, 32):
            # reference resets to default with a notice (:154-160)
            self.w = 8
        if self.w != 8:
            raise NotImplementedError(
                "jerasure w=16/32 (GF(2^16)/GF(2^32) words) not yet built")
        self.per_chunk_alignment = (
            str(profile.get("jerasure-per-chunk-alignment", "false")).lower()
            in ("true", "1", "yes"))

    def init(self, profile) -> None:
        self.parse(profile)
        self.parse_base(profile)
        self.prepare()
        super().init(profile)


class ErasureCodeJerasureReedSolomonVandermonde(ErasureCodeJerasure):
    technique = "reed_sol_van"
    DEFAULT_K = "7"
    DEFAULT_M = "3"

    def prepare(self) -> None:
        coding = gen_jerasure_rs_vandermonde(self.k, self.m)
        self.encode_matrix = np.concatenate(
            [np.eye(self.k, dtype=np.uint8), coding], axis=0)


class ErasureCodeJerasureReedSolomonRAID6(ErasureCodeJerasure):
    technique = "reed_sol_r6_op"
    DEFAULT_K = "7"
    DEFAULT_M = "2"

    def parse_base(self, profile) -> None:
        super().parse_base(profile)
        # RAID6 technique pins m=2 (ErasureCodeJerasure.h:111-128)
        self.m = 2

    def prepare(self) -> None:
        k = self.k
        coding = np.zeros((2, k), dtype=np.uint8)
        coding[0, :] = 1
        for j in range(k):
            coding[1, j] = gf_pow(2, j)
        self.encode_matrix = np.concatenate(
            [np.eye(k, dtype=np.uint8), coding], axis=0)


TECHNIQUES = {
    "reed_sol_van": ErasureCodeJerasureReedSolomonVandermonde,
    "reed_sol_r6_op": ErasureCodeJerasureReedSolomonRAID6,
}


def _factory(profile):
    technique = profile.get("technique", "reed_sol_van")
    cls = TECHNIQUES.get(technique)
    if cls is None:
        raise ValueError(
            f"jerasure: technique {technique} not supported "
            f"(have {sorted(TECHNIQUES)})")
    return cls()


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
