"""jerasure-semantics plugin: RS word techniques + bitmatrix techniques.

Mirrors the reference's jerasure plugin techniques
(src/erasure-code/jerasure/ErasureCodeJerasure.cc):

  * reed_sol_van  -- systematized extended-Vandermonde matrix
    (reed_sol_vandermonde_coding_matrix, ErasureCodeJerasure.cc:203)
  * reed_sol_r6_op -- RAID6 rows [1,1,..], [1,2,4,..] with m forced to 2
  * cauchy_orig   -- 1/(i ^ (m+j)) GF(2^w) Cauchy matrix expanded to a
    GF(2) bitmatrix (ErasureCodeJerasure.h:174, cauchy.c)
  * cauchy_good   -- same with the ones-minimizing matrix improvement
    (ErasureCodeJerasure.h:183)
  * liberation    -- minimal-density RAID-6 bitmatrix, w prime
    (ErasureCodeJerasure.h:192, liberation.c)
  * blaum_roth    -- RAID-6 over F2[x]/M_{w+1}(x), w+1 prime
    (ErasureCodeJerasure.h:229)

Bitmatrix techniques process chunks as regions of w packets of
``packetsize`` bytes; their whole data path is XOR (see
ec/bitmatrix_codec.py).  Chunk sizing follows
ErasureCodeJerasure::get_chunk_size (:80-104).
"""

from __future__ import annotations

import numpy as np

from ..bitmatrix_codec import BitMatrixCodec
from ..rs_codec import RSMatrixCodec
from ..registry import ErasureCodePlugin
from ...gf import gen_jerasure_rs_vandermonde, gf_pow
from ...gf.gf2w import (
    blaum_roth_coding_bitmatrix, cauchy_improve_coding_matrix,
    cauchy_original_coding_matrix, liberation_coding_bitmatrix,
    matrix_to_bitmatrix,
)

LARGEST_VECTOR_WORDSIZE = 16

DEFAULT_K = "2"
DEFAULT_M = "1"
DEFAULT_W = "8"


class GF2WBackend:
    """Word-region matmul backend for the w=16/32 word techniques
    (galois_w16/w32_region_mult semantics, ec/gf2w_region.py).  The
    TPU bit-matmul path is GF(2^8); wide-word codecs run here."""

    def __init__(self, w: int) -> None:
        self.w = w
        self.name = f"gf2w{w}"

    def matmul(self, matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
        from ..gf2w_region import gf2w_matmul
        return gf2w_matmul(matrix, data, self.w)


class ErasureCodeJerasure(RSMatrixCodec):
    technique = "reed_sol_van"
    DEFAULT_K = DEFAULT_K
    DEFAULT_M = DEFAULT_M

    def __init__(self, backend=None) -> None:
        super().__init__(backend=backend)
        self.w = 8
        self.per_chunk_alignment = False

    def _use_gf2w(self) -> bool:
        return self.w in (16, 32)

    def _build_decode_matrix(self, erasures):
        if self._use_gf2w():
            from ..gf2w_region import build_decode_matrix_w
            return build_decode_matrix_w(self.encode_matrix, self.k,
                                         erasures, self.w)
        return super()._build_decode_matrix(erasures)

    def get_alignment(self) -> int:
        if self.per_chunk_alignment:
            return self.w * LARGEST_VECTOR_WORDSIZE
        alignment = self.k * self.w * 4  # sizeof(int)
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def get_chunk_size(self, stripe_width: int) -> int:
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = (stripe_width + self.k - 1) // self.k
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = stripe_width % alignment
        padded = stripe_width + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k

    def parse_base(self, profile) -> None:
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, DEFAULT_W)
        self.sanity_check_k_m(self.k, self.m)
        if self.w not in (8, 16, 32):
            # reference resets to default with a notice (:154-160)
            self.w = 8
        if self._use_gf2w():
            # wide words: GF(2^w) region backend (the injected GF(2^8)
            # bit-matmul backend cannot serve these fields)
            self.backend = GF2WBackend(self.w)
        self.per_chunk_alignment = (
            str(profile.get("jerasure-per-chunk-alignment", "false")).lower()
            in ("true", "1", "yes"))

    def init(self, profile) -> None:
        self.parse(profile)
        self.parse_base(profile)
        self.prepare()
        super().init(profile)


class ErasureCodeJerasureReedSolomonVandermonde(ErasureCodeJerasure):
    technique = "reed_sol_van"
    DEFAULT_K = "7"
    DEFAULT_M = "3"

    def prepare(self) -> None:
        if self._use_gf2w():
            from ..gf2w_region import gen_rs_vandermonde_w, _DTYPE
            coding = gen_rs_vandermonde_w(self.k, self.m, self.w)
            ident = np.eye(self.k, dtype=_DTYPE[self.w])
            self.encode_matrix = np.concatenate([ident, coding], axis=0)
            return
        coding = gen_jerasure_rs_vandermonde(self.k, self.m)
        self.encode_matrix = np.concatenate(
            [np.eye(self.k, dtype=np.uint8), coding], axis=0)


class ErasureCodeJerasureReedSolomonRAID6(ErasureCodeJerasure):
    technique = "reed_sol_r6_op"
    DEFAULT_K = "7"
    DEFAULT_M = "2"

    def parse_base(self, profile) -> None:
        super().parse_base(profile)
        # RAID6 technique pins m=2 (ErasureCodeJerasure.h:111-128)
        self.m = 2

    def prepare(self) -> None:
        if self._use_gf2w():
            from ..gf2w_region import gen_raid6_w, _DTYPE
            coding = gen_raid6_w(self.k, self.w)
            ident = np.eye(self.k, dtype=_DTYPE[self.w])
            self.encode_matrix = np.concatenate([ident, coding], axis=0)
            return
        k = self.k
        coding = np.zeros((2, k), dtype=np.uint8)
        coding[0, :] = 1
        for j in range(k):
            coding[1, j] = gf_pow(2, j)
        self.encode_matrix = np.concatenate(
            [np.eye(k, dtype=np.uint8), coding], axis=0)


DEFAULT_PACKETSIZE = "2048"


class ErasureCodeJerasureBitMatrix(BitMatrixCodec):
    """Shared profile handling for the bitmatrix techniques."""

    technique = ""
    DEFAULT_K = "2"
    DEFAULT_M = "2"
    DEFAULT_W = "8"

    def parse_base(self, profile) -> None:
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, self.DEFAULT_W)
        self.packetsize = self.to_int("packetsize", profile,
                                      DEFAULT_PACKETSIZE)
        if self.packetsize <= 0:
            raise ValueError(
                f"packetsize={self.packetsize} must be positive")
        if self.w <= 0:
            raise ValueError(f"w={self.w} must be positive")
        self.sanity_check_k_m(self.k, self.m)

    def init(self, profile) -> None:
        self.parse(profile)
        self.parse_base(profile)
        self.prepare()
        super().init(profile)


class ErasureCodeJerasureCauchyOrig(ErasureCodeJerasureBitMatrix):
    technique = "cauchy_orig"
    DEFAULT_K = "7"
    DEFAULT_M = "3"

    def _coding_matrix(self):
        return cauchy_original_coding_matrix(self.k, self.m, self.w)

    def prepare(self) -> None:
        self.bitmatrix = matrix_to_bitmatrix(
            self._coding_matrix(), self.k, self.m, self.w)


class ErasureCodeJerasureCauchyGood(ErasureCodeJerasureCauchyOrig):
    technique = "cauchy_good"

    def _coding_matrix(self):
        return cauchy_improve_coding_matrix(
            cauchy_original_coding_matrix(self.k, self.m, self.w),
            self.k, self.m, self.w)


class ErasureCodeJerasureLiberation(ErasureCodeJerasureBitMatrix):
    technique = "liberation"
    DEFAULT_K = "2"
    DEFAULT_M = "2"
    DEFAULT_W = "7"

    def parse_base(self, profile) -> None:
        super().parse_base(profile)
        self.m = 2                  # RAID-6 family (ErasureCodeJerasure.h)

    def prepare(self) -> None:
        self.bitmatrix = liberation_coding_bitmatrix(self.k, self.w)


class ErasureCodeJerasureBlaumRoth(ErasureCodeJerasureLiberation):
    technique = "blaum_roth"
    DEFAULT_W = "6"

    def prepare(self) -> None:
        self.bitmatrix = blaum_roth_coding_bitmatrix(self.k, self.w)


TECHNIQUES = {
    "reed_sol_van": ErasureCodeJerasureReedSolomonVandermonde,
    "reed_sol_r6_op": ErasureCodeJerasureReedSolomonRAID6,
    "cauchy_orig": ErasureCodeJerasureCauchyOrig,
    "cauchy_good": ErasureCodeJerasureCauchyGood,
    "liberation": ErasureCodeJerasureLiberation,
    "blaum_roth": ErasureCodeJerasureBlaumRoth,
}


def _factory(profile):
    technique = profile.get("technique", "reed_sol_van")
    cls = TECHNIQUES.get(technique)
    if cls is None:
        raise ValueError(
            f"jerasure: technique {technique} not supported "
            f"(have {sorted(TECHNIQUES)})")
    return cls()


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
