"""Product-matrix MSR (minimum-storage regenerating) plugin.

The Rashmi-Shah-Kumar product-matrix construction ("Optimal
Exact-Regenerating Codes... via a Product-Matrix Construction"; the
execution blueprint is "Fast Product-Matrix Regenerating Codes",
PAPERS.md) at the MSR point, rendered over GF(2^8):

  * parameters [n = k + m, k, d = 2(k-1)] with sub-packetization
    alpha = k - 1 and per-helper repair bandwidth beta = 1 sub-chunk;
  * the message is two symmetric (alpha x alpha) matrices S1, S2
    (k*alpha free symbols = exactly the data), node i stores
    psi_i @ [S1; S2] where psi_i = [phi_i, lambda_i * phi_i] is row i
    of a Vandermonde encoding matrix -- any d rows of Psi and any
    alpha rows of Phi are nonsingular and the lambda_i are distinct,
    which is all the construction needs;
  * REPAIR of one lost chunk f: each of d helpers ships ONE computed
    sub-chunk (its alpha stored sub-chunks combined by phi_f -- a
    beta-sized fragment, NOT a stored range), and the collector solves
    the d x d system Psi_H u = fragments to rebuild the chunk.  Total
    repair traffic: d/alpha = 2 chunks' worth of bytes instead of the
    k full chunks RS repair reads.

The whole construction is linearized into the flat systematic
generator of ec/linear_codec.py (solve the first k nodes' stored
symbols for the message -- the standard systematic remap), so
encode/decode ride the batched scheduled/dense GF(2) kernel family
unchanged, MDS decode from any k chunks is the generic repair-matrix
build, and only the fragment algebra (phi_f combination, Psi_H^{-1}
aggregation) is MSR-specific.  Fragment and aggregate matrices are
LRU-cached with their XOR schedules warmed at build time.

Profile: ``plugin=pmsr k=K m=M [d=D]`` with k >= 3, m >= k-1 and
d = 2(k-1) (the product-matrix admissibility conditions; defaults to
d = 2(k-1), which equals k+m-1 -- every surviving node helps -- at the
canonical m = k-1 shape).
"""

from __future__ import annotations

import numpy as np

from ...gf.gf8 import (GF_EXP, GF_MUL_TABLE, gf_invert_matrix, gf_mul,
                       gf_pow)
from ..linear_codec import LinearSubchunkCodec
from ..registry import ErasureCodePlugin


def _pm_vandermonde(n: int, d: int) -> np.ndarray:
    """Psi: (n, d) Vandermonde rows psi_i = [1, x_i, ..., x_i^(d-1)]
    with x_i = g^i (g a field generator), so any d rows are
    nonsingular, any alpha = d/2 leading columns' rows are nonsingular
    (Phi), and lambda_i = x_i^alpha are pairwise distinct for
    n <= 255 / gcd(alpha, 255) (asserted by the caller)."""
    psi = np.zeros((n, d), dtype=np.uint8)
    for i in range(n):
        x = int(GF_EXP[i % 255]) if i else 1
        p = 1
        for j in range(d):
            psi[i, j] = p
            p = gf_mul(p, x)
    return psi


class ErasureCodePmsr(LinearSubchunkCodec):
    def __init__(self) -> None:
        super().__init__()
        self.d = 0
        self.phi: np.ndarray | None = None        # (n, alpha)
        self.lambdas: np.ndarray | None = None    # (n,)
        self.psi: np.ndarray | None = None        # (n, d)

    # -- profile ------------------------------------------------------------
    def _parse(self, profile) -> None:
        k = self.to_int("k", profile, "0")
        m = self.to_int("m", profile, "0")
        if k < 3:
            raise ValueError(
                f"pmsr: k={k} must be >= 3: the product-matrix MSR "
                f"sub-packetization is alpha=k-1 and alpha >= 2 is "
                f"what makes beta-sized repair fragments smaller than "
                f"chunks (EINVAL)")
        if m < k - 1:
            raise ValueError(
                f"pmsr: m={m} must be >= k-1={k - 1}: repair needs "
                f"d=2(k-1) helpers among the n-1={k + m - 1} "
                f"survivors (EINVAL)")
        d_default = 2 * (k - 1)
        d = self.to_int("d", profile, str(d_default))
        if d != d_default:
            raise ValueError(
                f"pmsr: d={d} is not admissible: the product-matrix "
                f"MSR construction exists exactly at d=2(k-1)"
                f"={d_default} (EINVAL)")
        self.k, self.m, self.d = k, m, d
        self.alpha = k - 1
        n = k + m
        # lambda_i = x_i^alpha distinct needs n below the power-map
        # period
        import math
        period = 255 // math.gcd(self.alpha, 255)
        if n > period:
            raise ValueError(
                f"pmsr: k+m={n} exceeds {period}, the largest width "
                f"with distinct repair multipliers over GF(2^8) for "
                f"alpha={self.alpha} (EINVAL)")

    def _build(self) -> None:
        k, m, d, a = self.k, self.m, self.d, self.alpha
        n = k + m
        psi = _pm_vandermonde(n, d)
        self.psi = psi
        self.phi = np.ascontiguousarray(psi[:, :a])
        self.lambdas = np.array(
            [gf_pow(int(GF_EXP[i % 255]) if i else 1, a)
             for i in range(n)], dtype=np.uint8)
        assert len(set(self.lambdas.tolist())) == n, \
            "repair multipliers not distinct"
        # the message -> stored-symbol map G: theta (the k*alpha free
        # entries of the symmetric S1, S2) -> the n*alpha stored
        # sub-symbols; stored_{i,a} = sum_b phi_i[b]*S1[b,a]
        #                           + lambda_i * sum_b phi_i[b]*S2[b,a]
        half = a * (a + 1) // 2
        nfree = 2 * half
        assert nfree == k * a, (nfree, k * a)
        pidx = {}
        t = 0
        for p in range(a):
            for q in range(p, a):
                pidx[(p, q)] = t
                t += 1
        g = np.zeros((n * a, nfree), dtype=np.uint8)
        for i in range(n):
            lam = int(self.lambdas[i])
            for col in range(a):
                row = g[i * a + col]
                for b in range(a):
                    key = pidx[(min(b, col), max(b, col))]
                    c = int(self.phi[i, b])
                    row[key] ^= c                       # S1 term
                    row[half + key] ^= gf_mul(lam, c)   # S2 term
        # systematic remap: choose theta so the first k nodes store the
        # raw data (invert the data-node block; nonsingular by the
        # product-matrix data-reconstruction property)
        inv = gf_invert_matrix(g[:k * a])
        gen = np.zeros((n * a, k * a), dtype=np.uint8)
        for r in range(n * a):
            row = np.zeros(k * a, dtype=np.uint8)
            for j in range(nfree):
                c = int(g[r, j])
                if c:
                    row ^= GF_MUL_TABLE[c][inv[j]]
            gen[r] = row
        self.generator = gen

    def init(self, profile) -> None:
        self._parse(profile)
        self.parse(profile)
        self._build()
        self.finish_setup()
        super().init(profile)

    # -- fragment repair algebra ---------------------------------------------
    def fragment_row(self, lost: int) -> np.ndarray:
        """(1, alpha) coefficients every helper applies to its own
        sub-chunks to produce its beta=1 repair fragment: phi_f."""
        return np.ascontiguousarray(self.phi[lost][None, :])

    def aggregate_matrix(self, lost: int,
                         helpers: tuple[int, ...]) -> np.ndarray:
        """(alpha, d) matrix mapping the d helper fragments (in helper
        order) to the lost chunk's alpha sub-chunks:
        [I | lambda_f I] @ Psi_H^{-1}.  Cached (the shared repair LRU)
        with its XOR schedule warmed."""
        key = ("agg", lost, helpers)
        entry = self._repair_cache.get(key)
        if entry is not None:
            self._repair_cache.move_to_end(key)
            return entry
        if len(helpers) != self.d:
            raise IOError(
                f"pmsr: repair of chunk {lost} needs exactly d="
                f"{self.d} helpers, got {len(helpers)}")
        inv = gf_invert_matrix(self.psi[list(helpers)])
        a = self.alpha
        lam = int(self.lambdas[lost])
        agg = inv[:a] ^ GF_MUL_TABLE[lam][inv[a:]]
        agg = np.ascontiguousarray(agg)
        from ...ops.xor_schedule import warm_gf8_schedule
        warm_gf8_schedule(agg)
        self._repair_cache[key] = agg
        while len(self._repair_cache) > 128:
            self._repair_cache.popitem(last=False)
        return agg

    def fragment_for(self, lost: int, chunk: np.ndarray) -> np.ndarray:
        """A helper's beta-sized fragment for repairing ``lost``: its
        own chunk's alpha sub-chunks combined by phi_f, stripe by
        stripe.  ``chunk`` is the helper's whole shard buffer (one
        chunk of chunk_size bytes per stripe); returns
        len(chunk)/alpha bytes, per-stripe fragments concatenated."""
        from ...gf import gf_matmul
        a = self.alpha
        buf = np.ascontiguousarray(chunk, np.uint8)
        cs = self._fragment_chunk_size(buf.size)
        sc = cs // a
        stacked = buf.reshape(-1, a, sc)                  # (nc, a, sc)
        flat = stacked.transpose(1, 0, 2).reshape(a, -1)  # (a, nc*sc)
        frag = gf_matmul(self.fragment_row(lost), flat)   # (1, nc*sc)
        return np.ascontiguousarray(frag.reshape(-1))

    def _fragment_chunk_size(self, shard_len: int) -> int:
        """Per-stripe chunk size within a shard buffer: the sub-chunk
        split is per CHUNK, so multi-stripe shards must reshape at the
        real stripe granularity.  The backend snapshots it via
        ``set_fragment_chunk_size`` at pool attach; a buffer it does
        not divide (bare codec tests) is treated as a single chunk."""
        cs = getattr(self, "_frag_cs", 0)
        if cs and shard_len % cs == 0:
            return cs
        assert shard_len % self.alpha == 0, (shard_len, self.alpha)
        return shard_len

    def set_fragment_chunk_size(self, chunk_size: int) -> None:
        assert chunk_size % self.alpha == 0, (chunk_size, self.alpha)
        self._frag_cs = int(chunk_size)

    def aggregate_fragments(self, lost: int,
                            frags: dict[int, np.ndarray]) -> np.ndarray:
        """Rebuild the lost chunk from beta-sized helper fragments
        keyed by helper position.  Byte-identical to the full decode
        of the same chunk (pinned by tests): both equal the stored
        generator rows applied to the data."""
        from ...gf import gf_matmul
        helpers = tuple(sorted(frags))
        agg = self.aggregate_matrix(lost, helpers)
        a = self.alpha
        flen = {len(np.asarray(f).reshape(-1)) for f in frags.values()}
        assert len(flen) == 1, flen
        flen = flen.pop()
        sc = self._fragment_chunk_size(flen * a) // a
        stacked = np.stack(
            [np.ascontiguousarray(np.asarray(frags[h], np.uint8)
                                  .reshape(-1)).reshape(-1, sc)
             for h in helpers])                    # (d, nc, sc)
        flat = stacked.reshape(len(helpers), -1)   # (d, nc*sc)
        rec = gf_matmul(agg, flat)                 # (a, nc*sc)
        out = rec.reshape(a, -1, sc).transpose(1, 0, 2)
        return np.ascontiguousarray(out.reshape(-1))

    # -- repair planning ------------------------------------------------------
    def minimum_to_repair(self, lost: int, available: set[int]
                          ) -> dict[int, list[tuple[int, int]]] | None:
        """The MSR helper set + fragment spec for a single lost chunk:
        d helpers each contributing one beta-sized computed sub-chunk
        ([(0, 1)] in sub-chunk units).  None when fewer than d
        survivors are reachable -- the caller falls back to the MDS
        k-chunk decode."""
        cands = sorted(set(available) - {lost})
        if len(cands) < self.d:
            return None
        helpers = cands[:self.d]
        return {h: [(0, 1)] for h in helpers}


def _factory(profile):
    return ErasureCodePmsr()


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
