"""Clay: Coupled-LAYer MSR regenerating code with sub-chunking.

Decision-level rendering of src/erasure-code/clay/ErasureCodeClay.cc
(Myna Vajha et al., FAST'18 construction):

  * geometry (parse, :188-302): q = d-k+1, nu shortens to q | (k+m+nu),
    t = (k+m+nu)/q, sub_chunk_no = q^t.  Nodes sit on a q x t grid;
    chunk x of column y is node y*q+x; sub-chunks are indexed by plane
    vectors z = (z_0..z_{t-1}) in [0,q)^t.
  * two scalar MDS codecs: ``mds`` (k+nu, m) decodes whole uncoupled
    planes; ``pft`` (2, 2) is the pairwise transform between coupled
    chunk bytes C and uncoupled U across a node pair (x,y,z) <->
    (z_y, y, z') -- positions (0,1)=coupled pair, (2,3)=uncoupled pair.
  * encode/decode (decode_layered, :650-715): planes are processed in
    increasing "intersection score" order; known nodes convert C->U,
    the mds codec decodes erased U planes, then U->C conversions
    recover the erased chunks.
  * single-failure repair (repair_one_lost_chunk, :469-647) reads only
    sub_chunk_no/q sub-chunks from each of d helpers instead of whole
    chunks -- the repair-bandwidth win sub-chunking exists for
    (minimum_to_repair / get_repair_subchunks, :332-400).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from ..base import ErasureCode
from ..registry import ErasureCodePlugin


def pow_int(a: int, x: int) -> int:
    return a ** x


class ErasureCodeClay(ErasureCode):
    def __init__(self) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 0
        self.mds = None
        self.pft = None

    # -- profile ------------------------------------------------------------
    def init(self, profile) -> None:
        from ..registry import instance as _registry
        self.parse(profile)
        self.k = self.to_int("k", profile, "4")
        self.m = self.to_int("m", profile, "2")
        self.sanity_check_k_m(self.k, self.m)
        self.d = self.to_int("d", profile, str(self.k + self.m - 1))
        if not self.k <= self.d <= self.k + self.m - 1:
            raise ValueError(
                f"clay: d={self.d} must be in [{self.k}, "
                f"{self.k + self.m - 1}]")
        scalar_mds = profile.get("scalar_mds", "jerasure")
        technique = profile.get("technique", "reed_sol_van")
        self.q = self.d - self.k + 1
        self.nu = (self.q - (self.k + self.m) % self.q) \
            if (self.k + self.m) % self.q else 0
        if self.k + self.m + self.nu > 254:
            raise ValueError("clay: k+m+nu > 254")
        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = pow_int(self.q, self.t)
        reg = _registry()
        self.mds = reg.factory(scalar_mds, {
            "k": str(self.k + self.nu), "m": str(self.m), "w": "8",
            "technique": technique})
        self.pft = reg.factory(scalar_mds, {
            "k": "2", "m": "2", "w": "8", "technique": technique})
        super().init(profile)

    # -- geometry -----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, stripe_width: int) -> int:
        # round_up_to(stripe, sub_chunk_no * k * pft_align) / k
        # (ErasureCodeClay.cc:90-96)
        align = self.sub_chunk_no * self.k * self.pft.get_chunk_size(1)
        padded = ((stripe_width + align - 1) // align) * align
        return padded // self.k

    def _plane_vector(self, z: int) -> list[int]:
        z_vec = [0] * self.t
        for i in range(self.t):
            z_vec[self.t - 1 - i] = z % self.q
            z = z // self.q
        return z_vec

    # -- pairwise transform plumbing ----------------------------------------
    def _pft_call(self, erased: set[int], known: dict[int, np.ndarray],
                  out: dict[int, np.ndarray]) -> None:
        """Run the (2,2) pairwise transform: positions 0,1 = coupled,
        2,3 = uncoupled; recover ``erased`` from ``known`` writing
        through the views in ``out``."""
        self.pft.decode_chunks(erased, known, out)

    # -- layered decode (decode_layered) ------------------------------------
    def _decode_layered(self, erased_chunks: set[int],
                        chunks: dict[int, np.ndarray]) -> None:
        q, t, nu = self.q, self.t, self.nu
        size = len(chunks[0])
        assert size % self.sub_chunk_no == 0
        sc = size // self.sub_chunk_no
        erased = set(erased_chunks)
        i = self.k + nu
        while len(erased) < self.m and i < q * t:
            erased.add(i)
            i += 1
        assert len(erased) == self.m
        U = {i: np.zeros(size, dtype=np.uint8) for i in range(q * t)}
        order = self._plane_order(erased)
        max_score = max(order.values(), default=0)
        for score in range(max_score + 1):
            for z in range(self.sub_chunk_no):
                if order[z] == score:
                    self._decode_erasures(erased, z, chunks, U, sc)
            for z in range(self.sub_chunk_no):
                if order[z] != score:
                    continue
                z_vec = self._plane_vector(z)
                for node_xy in erased:
                    x, y = node_xy % q, node_xy // q
                    node_sw = y * q + z_vec[y]
                    if z_vec[y] != x:
                        if node_sw not in erased:
                            self._recover_type1(chunks, U, x, y, z,
                                                z_vec, sc)
                        elif z_vec[y] < x:
                            self._coupled_from_uncoupled(
                                chunks, U, x, y, z, z_vec, sc)
                    else:
                        chunks[node_xy][z * sc:(z + 1) * sc] = \
                            U[node_xy][z * sc:(z + 1) * sc]

    def _plane_order(self, erased: set[int]) -> dict[int, int]:
        order = {}
        for z in range(self.sub_chunk_no):
            z_vec = self._plane_vector(z)
            order[z] = sum(1 for i in erased
                           if i % self.q == z_vec[i // self.q])
        return order

    def _decode_erasures(self, erased: set[int], z: int,
                         chunks: dict[int, np.ndarray],
                         U: dict[int, np.ndarray], sc: int) -> None:
        q, t = self.q, self.t
        z_vec = self._plane_vector(z)
        for x in range(q):
            for y in range(t):
                node_xy = q * y + x
                node_sw = q * y + z_vec[y]
                if node_xy in erased:
                    continue
                if z_vec[y] < x:
                    self._uncoupled_from_coupled(chunks, U, x, y, z,
                                                 z_vec, sc)
                elif z_vec[y] == x:
                    U[node_xy][z * sc:(z + 1) * sc] = \
                        chunks[node_xy][z * sc:(z + 1) * sc]
                elif node_sw in erased:
                    self._uncoupled_from_coupled(chunks, U, x, y, z,
                                                 z_vec, sc)
        self._decode_uncoupled(erased, z, U, sc)

    def _decode_uncoupled(self, erased: set[int], z: int,
                          U: dict[int, np.ndarray], sc: int) -> None:
        known = {}
        out = {}
        for i in range(self.q * self.t):
            view = U[i][z * sc:(z + 1) * sc]
            out[i] = view
            if i not in erased:
                known[i] = view
        self.mds.decode_chunks(erased, known, out)

    # -- the four C<->U conversions (views write through) -------------------
    def _pair(self, x: int, y: int, z: int,
              z_vec: list[int], sc: int):
        q, t = self.q, self.t
        node_xy = y * q + x
        node_sw = y * q + z_vec[y]
        z_sw = z + (x - z_vec[y]) * pow_int(q, t - 1 - y)
        swap = z_vec[y] > x
        return node_xy, node_sw, z_sw, swap

    def _uncoupled_from_coupled(self, chunks, U, x, y, z, z_vec,
                                sc) -> None:
        node_xy, node_sw, z_sw, swap = self._pair(x, y, z, z_vec, sc)
        i0, i1, i2, i3 = (1, 0, 3, 2) if swap else (0, 1, 2, 3)
        known = {i0: chunks[node_xy][z * sc:(z + 1) * sc],
                 i1: chunks[node_sw][z_sw * sc:(z_sw + 1) * sc]}
        out = {i0: known[i0], i1: known[i1],
               i2: U[node_xy][z * sc:(z + 1) * sc],
               i3: U[node_sw][z_sw * sc:(z_sw + 1) * sc]}
        self._pft_call({2, 3}, known, out)

    def _coupled_from_uncoupled(self, chunks, U, x, y, z, z_vec,
                                sc) -> None:
        node_xy, node_sw, z_sw, swap = self._pair(x, y, z, z_vec, sc)
        assert z_vec[y] < x
        known = {2: U[node_xy][z * sc:(z + 1) * sc],
                 3: U[node_sw][z_sw * sc:(z_sw + 1) * sc]}
        out = {0: chunks[node_xy][z * sc:(z + 1) * sc],
               1: chunks[node_sw][z_sw * sc:(z_sw + 1) * sc],
               2: known[2], 3: known[3]}
        self._pft_call({0, 1}, known, out)

    def _recover_type1(self, chunks, U, x, y, z, z_vec, sc) -> None:
        """node_xy erased, its pair node_sw known: C_xy from
        (C_sw, U_xy) via the pft (recover_type1_erasure)."""
        node_xy, node_sw, z_sw, swap = self._pair(x, y, z, z_vec, sc)
        i0, i1, i2, i3 = (1, 0, 3, 2) if swap else (0, 1, 2, 3)
        known = {i1: chunks[node_sw][z_sw * sc:(z_sw + 1) * sc],
                 i2: U[node_xy][z * sc:(z + 1) * sc]}
        out = {i0: chunks[node_xy][z * sc:(z + 1) * sc],
               i1: known[i1], i2: known[i2],
               i3: np.zeros(sc, dtype=np.uint8)}
        self._pft_call({i0, i3}, known, out)

    # -- interface: encode/decode -------------------------------------------
    def _grid_chunks(self, encoded: dict[int, np.ndarray],
                     size: int) -> dict[int, np.ndarray]:
        """Map interface chunk ids (0..k+m) onto grid node ids
        (0..q*t), inserting zeroed shortened nodes k..k+nu."""
        grid: dict[int, np.ndarray] = {}
        for i in range(self.k):
            grid[i] = encoded[i]
        for i in range(self.k, self.k + self.nu):
            grid[i] = np.zeros(size, dtype=np.uint8)
        for i in range(self.k, self.k + self.m):
            grid[i + self.nu] = encoded[i]
        return grid

    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        size = len(chunks[0])
        grid = self._grid_chunks(chunks, size)
        parity = {i + self.nu for i in range(self.k, self.k + self.m)}
        self._decode_layered(parity, grid)

    def decode_chunks(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        size = len(next(iter(decoded.values())))
        grid = self._grid_chunks(decoded, size)
        erased = set()
        for i in range(self.k + self.m):
            if i not in chunks:
                erased.add(i if i < self.k else i + self.nu)
        if not erased:
            return
        if len(erased) > self.m:
            raise IOError(
                f"clay: {len(erased)} erasures exceed m={self.m}")
        self._decode_layered(erased, grid)

    # -- repair-optimal single-failure path ---------------------------------
    def is_repair(self, want_to_read: set[int],
                  available: set[int]) -> bool:
        """Single lost chunk whose whole y-column (its local group) is
        available, with >= d helpers total (ErasureCodeClay::is_repair)."""
        if len(want_to_read) != 1:
            return False
        if set(want_to_read) <= set(available):
            return False
        lost = next(iter(want_to_read))
        lost_node = lost if lost < self.k else lost + self.nu
        for x in range(self.q):
            node = (lost_node // self.q) * self.q + x
            if self.k <= node < self.k + self.nu:
                continue                   # shortened node: always zero
            iface = node if node < self.k else node - self.nu
            if iface != lost and iface not in available:
                return False
        return len(available) >= self.d

    def get_repair_subchunks(self, lost_node: int) -> list[tuple[int, int]]:
        q, t = self.q, self.t
        y_lost, x_lost = lost_node // q, lost_node % q
        seq_sc = pow_int(q, t - 1 - y_lost)
        num_seq = pow_int(q, y_lost)
        out = []
        index = x_lost * seq_sc
        for _ in range(num_seq):
            out.append((index, seq_sc))
            index += q * seq_sc
        return out

    def minimum_to_decode(self, want_to_read, available):
        want = set(want_to_read)
        avail = set(available)
        if self.is_repair(want, avail):
            lost = next(iter(want))
            lost_node = lost if lost < self.k else lost + self.nu
            sub = self.get_repair_subchunks(lost_node)
            minimum: dict[int, list] = {}
            for j in range(self.q):
                rep = (lost_node // self.q) * self.q + j
                if j == lost_node % self.q:
                    continue
                if rep < self.k:
                    minimum[rep] = list(sub)
                elif rep >= self.k + self.nu:
                    minimum[rep - self.nu] = list(sub)
            for chunk in sorted(avail):
                if len(minimum) >= self.d:
                    break
                minimum.setdefault(chunk, list(sub))
            return minimum
        return super().minimum_to_decode(want, avail)

    def decode(self, want_to_read, chunks, chunk_size: int = 0):
        avail = set(chunks)
        if self.is_repair(set(want_to_read), avail) and chunk_size \
                and len(next(iter(chunks.values()))) < chunk_size:
            return self.repair(set(want_to_read), chunks)
        return self._decode(set(want_to_read), chunks)

    def repair(self, want_to_read: set[int],
               chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        """Repair ONE lost chunk from d helpers' repair sub-chunks.

        ``chunks`` holds each helper's CONCATENATED repair sub-chunks
        (the ranges minimum_to_decode returned), len = chunk_size / q.
        """
        assert len(want_to_read) == 1 and len(chunks) == self.d
        q, t, nu = self.q, self.t, self.nu
        lost = next(iter(want_to_read))
        lost_node = lost if lost < self.k else lost + nu
        repair_blocksize = len(next(iter(chunks.values())))
        repair_subchunks = self.sub_chunk_no // q
        sc = repair_blocksize // repair_subchunks
        chunk_size = self.sub_chunk_no * sc

        helper = {}
        aloof = set()
        for i in range(self.k + self.m):
            node = i if i < self.k else i + nu
            if i in chunks:
                helper[node] = np.asarray(chunks[i], dtype=np.uint8)
            elif i != lost:
                aloof.add(node)
        for i in range(self.k, self.k + nu):
            helper[i] = np.zeros(repair_blocksize, dtype=np.uint8)
        recovered = np.zeros(chunk_size, dtype=np.uint8)

        sub_ind = self.get_repair_subchunks(lost_node)
        plane_to_ind = {}
        ordered: dict[int, set[int]] = {}
        ind = 0
        for index, count in sub_ind:
            for z in range(index, index + count):
                z_vec = self._plane_vector(z)
                score = (1 if lost_node % q == z_vec[lost_node // q]
                         else 0)
                score += sum(1 for nd in aloof
                             if nd % q == z_vec[nd // q])
                assert score > 0
                ordered.setdefault(score, set()).add(z)
                plane_to_ind[z] = ind
                ind += 1

        U = {i: np.zeros(chunk_size, dtype=np.uint8)
             for i in range(q * t)}
        erasures = {lost_node - lost_node % q + i for i in range(q)}
        erasures |= aloof

        for score in sorted(ordered):
            for z in ordered[score]:
                z_vec = self._plane_vector(z)
                for y in range(t):
                    for x in range(q):
                        node_xy = y * q + x
                        if node_xy in erasures:
                            continue
                        z_sw = z + (x - z_vec[y]) * pow_int(q, t - 1 - y)
                        node_sw = y * q + z_vec[y]
                        i0, i1, i2, i3 = ((1, 0, 3, 2)
                                          if z_vec[y] > x
                                          else (0, 1, 2, 3))
                        hxy = helper[node_xy][
                            plane_to_ind[z] * sc:
                            (plane_to_ind[z] + 1) * sc]
                        if node_sw in aloof:
                            known = {i0: hxy,
                                     i3: U[node_sw][z_sw * sc:
                                                    (z_sw + 1) * sc]}
                            out = {i0: known[i0],
                                   i1: np.zeros(sc, np.uint8),
                                   i2: U[node_xy][z * sc:(z + 1) * sc],
                                   i3: known[i3]}
                            self._pft_call({i2}, known, out)
                        elif z_vec[y] != x:
                            known = {i0: hxy,
                                     i1: helper[node_sw][
                                         plane_to_ind[z_sw] * sc:
                                         (plane_to_ind[z_sw] + 1) * sc]}
                            out = {i0: known[i0], i1: known[i1],
                                   i2: U[node_xy][z * sc:(z + 1) * sc],
                                   i3: np.zeros(sc, np.uint8)}
                            self._pft_call({i2}, known, out)
                        else:
                            U[node_xy][z * sc:(z + 1) * sc] = hxy
                self._decode_uncoupled(erasures, z, U, sc)
                for node in erasures:
                    x, y = node % q, node // q
                    node_sw = y * q + z_vec[y]
                    z_sw = z + (x - z_vec[y]) * pow_int(q, t - 1 - y)
                    i0, i1, i2, i3 = ((1, 0, 3, 2) if z_vec[y] > x
                                      else (0, 1, 2, 3))
                    if node in aloof:
                        continue
                    if x == z_vec[y]:     # hole-dot pair
                        recovered[z * sc:(z + 1) * sc] = \
                            U[node][z * sc:(z + 1) * sc]
                    else:
                        assert node_sw == lost_node
                        known = {i0: helper[node][
                            plane_to_ind[z] * sc:
                            (plane_to_ind[z] + 1) * sc],
                            i2: U[node][z * sc:(z + 1) * sc]}
                        out = {i0: known[i0],
                               i1: recovered[z_sw * sc:(z_sw + 1) * sc],
                               i2: known[i2],
                               i3: np.zeros(sc, np.uint8)}
                        self._pft_call({i1}, known, out)
        return {lost: recovered}


def _factory(profile):
    return ErasureCodeClay()


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
