"""The `tpu` erasure-code plugin: ISA-semantics RS/Cauchy on the MXU.

Registers behind the same registry/interface boundary as every other
plugin, so the benchmark harness and the OSD EC backend pick it up by
profile name alone (the reference selects plugins the same way:
src/test/erasure-code/ceph_erasure_code_benchmark.cc:170).  Parity bytes
are identical to the `isa` plugin (same generator matrices, same GF(2^8)
field); only the execution engine differs: stripes are batched into one
MXU bit-matmul launch (see ceph_tpu/ops/gf2kernels.py).
"""

from __future__ import annotations

import numpy as np

from .isa import ErasureCodeIsa, K_VANDERMONDE
from ..registry import ErasureCodePlugin
from ...ops.jax_backend import JaxBackend


class ErasureCodeTpu(ErasureCodeIsa):
    def __init__(self, technique: str = K_VANDERMONDE) -> None:
        super().__init__(technique=technique, backend=JaxBackend())

    # -- batched entry points (OSD CodecBatcher / bench fast path) ----------
    def encode_batch(self, data: np.ndarray, out_np: bool = False):
        """(B, k, L) data chunks -> (B, m, L) parity chunks, one launch."""
        return self.backend.matmul_batch(
            self.encode_matrix[self.k:], data, out_np=out_np)

    def encode_batch_crc(self, data: np.ndarray):
        """encode_batch plus device-fused integrity: returns
        ((B, m, L) parity, (B, k+m) uint32 chunk CRCs) from one device
        round trip -- the CodecBatcher consumes this so shard CRCs are
        never a host re-hash of bytes the accelerator already held."""
        return self.backend.matmul_batch_crc(
            self.encode_matrix[self.k:], data)

    def decode_signature(self, erasures) -> str:
        """DecodeTableCache key for an erasure pattern.  Also the
        grouping key the per-OSD CodecBatcher uses to decide which
        reconstruction submissions may share a decode_batch launch
        (same signature = same decode matrix = same math)."""
        from ...gf import erasure_signature
        from ...gf.matrices import decode_index_for
        return erasure_signature(
            decode_index_for(self.k, set(erasures)), list(erasures))

    def decode_batch(self, erasures: list[int], chunks: np.ndarray,
                     out_np: bool = False):
        """Recover ``erasures`` for a batch.

        ``chunks`` is (B, k, L): for every stripe, the k surviving chunks in
        decode_index order (first k surviving shard ids ascending).
        """
        matrix = self.decode_matrix_for(erasures)
        return self.backend.matmul_batch(matrix, chunks, out_np=out_np)

    def decode_matrix_for(self, erasures) -> np.ndarray:
        """The decode matrix an erasure pattern selects, through the
        DecodeTableCache.  Shared by ``decode_batch`` and the sharded
        MeshCodec decode path, so both launch engines compute with the
        identical matrix (byte parity by construction)."""
        from ...gf import build_decode_matrix
        signature = self.decode_signature(erasures)
        entry = self.tcache.get(signature)
        if entry is None:
            matrix, decode_index = build_decode_matrix(
                self.encode_matrix, self.k, list(erasures))
            self.tcache.put(signature, matrix, decode_index)
        else:
            matrix, decode_index = entry
        return matrix


def _factory(profile):
    return ErasureCodeTpu(profile.get("technique", K_VANDERMONDE))


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
