"""LRC (locally repairable / layered) erasure-code plugin.

Semantics follow the reference's lrc plugin
(src/erasure-code/lrc/ErasureCodeLrc.h:47-134, ErasureCodeLrc.cc
parse_kml/layers_parse/_minimum_to_decode): the code is a stack of
layers, each a systematic RS sub-codec over a subset of the chunk
positions.  A ``k/m/l`` profile generates the canonical layered layout:

  local_group_count = (k + m) / l          # (k+m) % l == 0 required
  per group: k/lgc data chunks, m/lgc global parities, 1 local parity

The global layer computes the m global parities from all k data chunks;
each local layer computes its group's local parity over the group's l
chunks (data + global parities).  A single lost chunk is repaired from
its local group's other l chunks only -- ``minimum_to_decode`` returns
l shards, not k -- which is the whole point of the code: repair reads
stay inside a failure domain (here: inside a mesh sub-axis, see
ceph_tpu/parallel/sharded_ec.py lrc_local_repair).

Execution rides the flat linear spine (ec/linear_codec.py): the layer
stack composes into ONE systematic generator over the data chunks, so
local repair and global decode are the same ``gf_solve_rows`` repair-
matrix build over different source sets (byte-identical outputs by
construction), encode/decode coalesce through the CodecBatcher's
padding buckets onto the scheduled/dense GF(2) kernel family, and the
local-parity rows -- all-ones XOR combinations, the sparsest matrices
the greedy-CSE compiler sees -- have their schedules warmed at build
time.

Arbitrary layerings are accepted via ``mapping`` + ``layers`` profile
keys (layers as JSON ``[[mapping, profile], ...]``), mirroring
ErasureCodeLrc::layers_parse.
"""

from __future__ import annotations

import json

import numpy as np

from ...gf.gf8 import GF_MUL_TABLE
from ...gf.matrices import gen_rs_matrix, gen_cauchy1_matrix
from ..linear_codec import LinearSubchunkCodec
from ..registry import ErasureCodePlugin

DEFAULT_KML = -1


class _Layer:
    """One layer: a systematic RS code over a subset of positions."""

    def __init__(self, mapping: str, technique: str = "reed_sol_van"):
        self.mapping = mapping
        self.data_pos = [i for i, c in enumerate(mapping) if c == "D"]
        self.coding_pos = [i for i, c in enumerate(mapping) if c == "c"]
        self.positions = self.data_pos + self.coding_pos
        self.k = len(self.data_pos)
        self.m = len(self.coding_pos)
        if self.k < 1 or self.m < 1:
            raise ValueError(f"layer {mapping!r} needs >=1 D and >=1 c")
        gen = (gen_cauchy1_matrix if technique == "cauchy"
               else gen_rs_matrix)
        self.matrix = gen(self.k + self.m, self.k)


class ErasureCodeLrc(LinearSubchunkCodec):
    def __init__(self) -> None:
        super().__init__()
        self.l = 0
        self.m_global = 0          # the profile's m (global parities)
        self.mapping = ""
        self.layers: list[_Layer] = []
        self.chunk_count_ = 0

    # -- profile ------------------------------------------------------------
    def _parse_kml(self, profile) -> None:
        k = self.to_int("k", profile, str(DEFAULT_KML))
        m = self.to_int("m", profile, str(DEFAULT_KML))
        l = self.to_int("l", profile, str(DEFAULT_KML))
        present = [v != DEFAULT_KML for v in (k, m, l)]
        if not any(present):
            return
        if not all(present):
            raise ValueError(
                "lrc: all of k, m, l must be set or none (EINVAL)")
        for key in ("mapping", "layers"):
            if profile.get(key):
                raise ValueError(
                    f"lrc: {key} cannot be set when k/m/l are set "
                    f"(EINVAL)")
        self.sanity_check_k_m(k, m)
        if l < 1:
            raise ValueError(
                f"lrc: l={l} must be >= 1: each local group needs at "
                f"least one chunk beside its local parity (EINVAL)")
        if (k + m) % l:
            raise ValueError(
                f"lrc: k+m={k + m} must be a multiple of l={l} "
                f"(EINVAL)")
        lgc = (k + m) // l
        if k % lgc:
            raise ValueError(
                f"lrc: k={k} must be a multiple of (k+m)/l={lgc} "
                f"(EINVAL)")
        if m % lgc:
            raise ValueError(
                f"lrc: m={m} must be a multiple of (k+m)/l={lgc} "
                f"(EINVAL)")
        self.k, self.m_global, self.l = k, m, l
        kg, mg = k // lgc, m // lgc
        # mapping: per group D*kg + _*mg (global parities) + _ (local)
        profile["mapping"] = ("D" * kg + "_" * mg + "_") * lgc
        layers = [["".join(("D" * kg + "c" * mg + "_")
                           for _ in range(lgc)), ""]]
        for i in range(lgc):
            row = []
            for j in range(lgc):
                row.append("D" * (kg + mg) + "c" if i == j
                           else "_" * (kg + mg + 1))
            layers.append(["".join(row), ""])
        profile["layers"] = json.dumps(layers)

    def _parse_layers(self, profile) -> None:
        raw = profile.get("layers", "")
        if not raw:
            raise ValueError("lrc: profile needs layers or k/m/l")
        try:
            spec = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(f"lrc: layers is not valid JSON: {e}")
        mapping = profile.get("mapping", "")
        if not mapping:
            raise ValueError("lrc: mapping is required with layers")
        self.mapping = mapping
        self.chunk_count_ = len(mapping)
        self.layers = []
        for entry in spec:
            lmap = entry[0] if isinstance(entry, list) else str(entry)
            lprofile = (entry[1] if isinstance(entry, list)
                        and len(entry) > 1 else "")
            technique = "reed_sol_van"
            if isinstance(lprofile, dict):
                technique = lprofile.get("technique", technique)
            elif "cauchy" in str(lprofile):
                technique = "cauchy"
            if len(lmap) != len(mapping):
                raise ValueError(
                    f"lrc: layer {lmap!r} length != mapping length "
                    f"{len(mapping)}")
            self.layers.append(_Layer(lmap, technique))
        data_pos = [i for i, c in enumerate(mapping) if c == "D"]
        if self.k == 0:
            self.k = len(data_pos)
        # sanity: every non-data position is computed by some layer
        computed = set()
        for layer in self.layers:
            computed |= set(layer.coding_pos)
        uncovered = (set(range(self.chunk_count_)) - set(data_pos)
                     - computed)
        if uncovered:
            raise ValueError(
                f"lrc: positions {sorted(uncovered)} are neither data "
                f"nor computed by any layer")
        self.m = self.chunk_count_ - self.k

    def _build_generator(self) -> None:
        """Compose the layer stack into the flat systematic generator:
        each coding position's row over the data chunks, by GF(2^8)
        linearity of the layers (layer order matters: a layer may read
        positions an earlier layer computed, e.g. local parities over
        global parities in the canonical k/m/l layout)."""
        n, k = self.chunk_count_, self.k
        gen = np.zeros((n, k), dtype=np.uint8)
        defined = [False] * n
        for i in range(k):
            p = self.chunk_index(i)
            gen[p, i] = 1
            defined[p] = True
        for layer in self.layers:
            for dp in layer.data_pos:
                if not defined[dp]:
                    raise ValueError(
                        f"lrc: layer {layer.mapping!r} reads position "
                        f"{dp} before any layer computes it (reorder "
                        f"the layers)")
            for r, p in enumerate(layer.coding_pos):
                row = np.zeros(k, dtype=np.uint8)
                for j, dp in enumerate(layer.data_pos):
                    c = int(layer.matrix[layer.k + r, j])
                    if c:
                        row ^= GF_MUL_TABLE[c][gen[dp]]
                gen[p] = row
                defined[p] = True
        self.generator = gen

    def init(self, profile) -> None:
        self._parse_kml(profile)
        self._parse_layers(profile)
        self.parse(profile)        # builds chunk_mapping from mapping
        self.alpha = 1
        self._build_generator()
        self.finish_setup()
        super().init(profile)

    # -- interface ----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.chunk_count_

    def get_data_chunk_count(self) -> int:
        return self.k

    # -- locality-aware minimum_to_decode -----------------------------------
    def _repair_plan(self, want_to_read: set[int],
                     available: set[int]) -> tuple[set[int], list[int]]:
        """Greedy layered-repair closure.

        Returns (chunks to read, layer application order).  Prefers the
        layer that recovers a missing chunk with the FEWEST reads (the
        local group before the global layer), mirroring
        ErasureCodeLrc::_minimum_to_decode's locality preference.
        """
        wanted_missing = set(want_to_read) - set(available)
        if not wanted_missing:
            return set(want_to_read), []
        virtual_avail = set(available)
        reads: set[int] = set()
        order: list[int] = []

        def apply_layer(li: int) -> None:
            layer = self.layers[li]
            mine = set(layer.positions)
            have = virtual_avail & mine
            # the sub-decode reads the first k surviving chunks in the
            # layer's position order
            pos_index = {p: i for i, p in enumerate(layer.positions)}
            erasures = {pos_index[p] for p in mine - have}
            surviving = [p for p in layer.positions
                         if pos_index[p] not in erasures][:layer.k]
            reads.update(p for p in surviving if p in available)
            virtual_avail.update(mine - have)
            order.append(li)

        def feasible(li: int, need: set[int]) -> bool:
            layer = self.layers[li]
            mine = set(layer.positions)
            if not (need & mine):
                return False
            have = virtual_avail & mine
            return len(mine - have) <= layer.m and len(have) >= layer.k

        # smallest layer first = locality preference (a local group
        # beats the global layer when both can repair)
        by_size = sorted(range(len(self.layers)),
                         key=lambda i: len(self.layers[i].positions))
        for _ in range(len(self.layers) * (self.chunk_count_ + 1)):
            still = wanted_missing - virtual_avail
            if not still:
                break
            li = next((i for i in by_size if feasible(i, still)), None)
            if li is None:
                # no layer reaches a WANTED chunk directly: repairing
                # some other missing chunk may unlock one (e.g. a local
                # group fixing its loss lowers the global layer's
                # erasure count)
                other = set(range(self.chunk_count_)) - virtual_avail
                li = next((i for i in by_size if feasible(i, other)),
                          None)
            if li is None:
                raise IOError(
                    f"lrc: cannot repair {sorted(still)} from "
                    f"{sorted(available)}")
            apply_layer(li)
        wanted_reads = {p for p in want_to_read if p in available}
        return reads | wanted_reads, order

    def _minimum_to_decode(self, want_to_read: set[int],
                           available_chunks: set[int]) -> set[int]:
        reads, _ = self._repair_plan(want_to_read, available_chunks)
        return reads

    def _decode_sources(self, lost: tuple[int, ...],
                        available: set[int]) -> tuple[int, ...]:
        """The layered plan's read set: the local group for a single
        loss, the global closure otherwise.  The flat repair matrix
        over these sources reproduces the layer-by-layer recovery
        byte-for-byte (both compute the unique combination of the
        sources that equals the lost rows)."""
        reads, _ = self._repair_plan(set(lost), set(available))
        return tuple(sorted(reads))

    def get_alignment(self) -> int:
        return 32


def _factory(profile):
    return ErasureCodeLrc()


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
