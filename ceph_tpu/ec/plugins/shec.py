"""SHEC: Shingled Erasure Code (space-efficiency vs recovery-I/O knob).

Decision-level rendering of src/erasure-code/shec/ErasureCodeShec.cc:

  * matrix (shec_reedsolomon_coding_matrix, :465-533): start from the
    jerasure Vandermonde coding matrix, then zero a cyclic window of
    each parity row so parity rr covers only its "shingle"; the
    multiple-technique variant splits (m, c) into (m1, c1)+(m2, c2)
    minimizing recovery efficiency r_e1 (:424-460).
  * decode (shec_make_decoding_matrix, :535-763): exhaustive search
    over parity subsets for the SMALLEST square system (dup rows =
    dup columns, determinant != 0) that recovers the wanted erased
    data chunks -- this is what makes single-failure recovery read
    fewer than k chunks, SHEC's selling point.
  * minimum_to_decode returns exactly the rows of that system.

k+m may exceed what MDS codes allow to recover: SHEC trades
recoverability of some multi-erasure patterns for locality (the test
suite asserts both directions).
"""

from __future__ import annotations

from itertools import combinations
from typing import Mapping

import numpy as np

from ..base import ErasureCode
from ..registry import ErasureCodePlugin
from ...gf import gen_jerasure_rs_vandermonde, gf_matmul
from ...gf.gf8 import gf_invert_matrix

LARGEST_VECTOR_WORDSIZE = 16


class ErasureCodeShec(ErasureCode):
    technique = "multiple"

    def __init__(self) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.c = 0
        self.w = 8
        self.matrix: np.ndarray | None = None    # (m, k) coding rows

    # -- profile ------------------------------------------------------------
    def init(self, profile) -> None:
        self.parse(profile)
        self.k = self.to_int("k", profile, "4")
        self.m = self.to_int("m", profile, "3")
        self.c = self.to_int("c", profile, "2")
        self.w = self.to_int("w", profile, "8")
        if self.w != 8:
            # the SHEC coding matrix and all encode/decode math here
            # are GF(2^8); accepting w=16/32 would produce chunks that
            # are self-consistent but NOT the reference's w=16/32
            # encodings, and without the larger field's recoverability
            # -- refuse loudly instead (round-3 advisor finding)
            raise ValueError(
                f"shec: w={self.w} unsupported (GF(2^8) only; "
                f"use jerasure for w=16/32 word techniques)")
        if not 1 <= self.c <= self.m:
            raise ValueError(f"shec: need 1 <= c={self.c} <= m={self.m}")
        if self.k < 1 or self.m < 1:
            raise ValueError("shec: k and m must be >= 1")
        self.matrix = self._coding_matrix(
            single=self.technique == "single")
        super().init(profile)

    def _shingle_windows(self, m1: int, m2: int, c1: int,
                         c2: int) -> list[tuple[int, int]]:
        """Per-parity (start, end) of the ZEROED window (cyclic)."""
        out = []
        for rr in range(m1):
            end = ((rr * self.k) // m1) % self.k
            start = (((rr + c1) * self.k) // m1) % self.k
            out.append((start, end))
        for rr in range(m2):
            end = ((rr * self.k) // m2) % self.k
            start = (((rr + c2) * self.k) // m2) % self.k
            out.append((start, end))
        return out

    def _recovery_efficiency1(self, m1: int, m2: int, c1: int,
                              c2: int) -> float:
        """shec_calc_recovery_efficiency1: total shingle width."""
        if m1 < c1 or m2 < c2:
            return -1
        if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
            return -1
        r_e1 = 0
        for rr in range(m1):
            r_e1 += ((rr + c1) * self.k) // m1 - (rr * self.k) // m1
        for rr in range(m2):
            r_e1 += ((rr + c2) * self.k) // m2 - (rr * self.k) // m2
        return r_e1

    def _coding_matrix(self, single: bool) -> np.ndarray:
        k, m, c = self.k, self.m, self.c
        if single:
            m1, c1 = 0, 0
        else:
            best, m1, c1 = None, 0, 0
            for c1_try in range(c // 2 + 1):
                for m1_try in range(m + 1):
                    c2 = c - c1_try
                    m2 = m - m1_try
                    if m1_try < c1_try or m2 < c2:
                        continue
                    if (m1_try == 0) != (c1_try == 0):
                        continue
                    if (m2 == 0) != (c2 == 0):
                        continue
                    r = self._recovery_efficiency1(m1_try, m2, c1_try, c2)
                    if r >= 0 and (best is None or r < best):
                        best, m1, c1 = r, m1_try, c1_try
        m2, c2 = m - m1, c - c1
        matrix = gen_jerasure_rs_vandermonde(k, m).astype(np.uint8)
        for rr, (start, end) in enumerate(
                self._shingle_windows(m1, m2, c1, c2)):
            cc = start
            while cc != end:
                matrix[rr, cc] = 0
                cc = (cc + 1) % k
        return matrix

    # -- geometry -----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        alignment = self.k * self.w * 4
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def get_chunk_size(self, stripe_width: int) -> int:
        alignment = self.get_alignment()
        tail = stripe_width % alignment
        padded = stripe_width + (alignment - tail if tail else 0)
        return padded // self.k

    # -- decoding-system search (shec_make_decoding_matrix) ------------------
    def _search_decoding_system(self, want: set[int],
                                avails: set[int]):
        """Smallest square system recovering wanted erased data.

        Returns (dm_rows, dm_columns, inverse | None, minimum_set) or
        raises IOError when unrecoverable."""
        k, m = self.k, self.m
        want = set(want)
        # wanted-but-lost parity pulls in the data chunks it covers
        for i in range(m):
            if (k + i) in want and (k + i) not in avails:
                want |= {j for j in range(k) if self.matrix[i, j]}
        best = None          # (dup, ek, rows, cols)
        for ek in range(m + 1):
            if best is not None and best[1] <= ek and best[0] < k + 1:
                break
            for parities in combinations(range(m), ek):
                if any((k + p) not in avails for p in parities):
                    continue
                cols = {i for i in range(k)
                        if i in want and i not in avails}
                rows = set()
                for p in parities:
                    rows.add(k + p)
                    for j in range(k):
                        if self.matrix[p, j]:
                            cols.add(j)
                            if j in avails:
                                rows.add(j)
                if len(rows) != len(cols):
                    continue
                dup = len(rows)
                if best is not None and dup >= best[0]:
                    continue
                if dup == 0:
                    best = (0, ek, [], [])
                    break
                rs, cs = sorted(rows), sorted(cols)
                sub = np.zeros((dup, dup), dtype=np.uint8)
                for ri, r in enumerate(rs):
                    for ci, c2 in enumerate(cs):
                        sub[ri, ci] = (1 if r < k and r == c2 else
                                       0 if r < k else
                                       self.matrix[r - k, c2])
                try:
                    gf_invert_matrix(sub)
                except ValueError:
                    continue
                best = (dup, ek, rs, cs)
            if best is not None and best[0] == 0:
                break
        if best is None:
            raise IOError("shec: no recovery system for this pattern")
        dup, ek, rs, cs = best
        minimum = set(rs)
        for i in range(k):
            if i in want and i in avails:
                minimum.add(i)
        for i in range(m):
            if (k + i) in want and (k + i) in avails \
                    and (k + i) not in minimum:
                if any(self.matrix[i, j] and j not in want
                       for j in range(k)):
                    minimum.add(k + i)
        return dup, rs, cs, minimum

    def _minimum_to_decode(self, want_to_read: set[int],
                           available_chunks: set[int]) -> set[int]:
        _, _, _, minimum = self._search_decoding_system(
            set(want_to_read), set(available_chunks))
        return minimum

    def minimum_to_decode(self, want_to_read, available):
        minimum = self._minimum_to_decode(set(want_to_read),
                                          set(available))
        return {shard: [(0, 1)] for shard in sorted(minimum)}

    # minimum_to_decode_with_cost: inherited from ErasureCode -- the
    # cost-tier growth there calls back into this plugin's
    # _minimum_to_decode, so the decoding-system search still picks
    # the reads within the cheapest feasible candidate set.

    # -- data path -----------------------------------------------------------
    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = np.stack([chunks[self.chunk_index(i)] for i in range(k)])
        parity = gf_matmul(self.matrix, data)
        for r in range(m):
            chunks[self.chunk_index(k + r)][:] = parity[r]

    def decode_chunks(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        avails = set(chunks)
        erased = [i for i in want_to_read if i not in avails]
        if not erased:
            return
        # only the WANTED chunks are recovered -- recovering from a
        # minimal subset is the point of the shingle (the reference's
        # shec_matrix_decode takes explicit want/avails the same way)
        want = set(want_to_read)
        dup, rs, cs, _ = self._search_decoding_system(want, avails)
        if dup:
            sub = np.zeros((dup, dup), dtype=np.uint8)
            for ri, r in enumerate(rs):
                for ci, c2 in enumerate(cs):
                    sub[ri, ci] = (1 if r < k and r == c2 else
                                   0 if r < k else
                                   self.matrix[r - k, c2])
            inv = gf_invert_matrix(sub)
            src = np.stack([decoded[r] for r in rs])
            out = gf_matmul(inv, src)
            for ci, c2 in enumerate(cs):
                if c2 not in avails:
                    decoded[c2][:] = out[ci]
        # re-encode wanted erased parity: only its COVERED data chunks
        # matter (zero coefficients ignore the rest), and those were
        # pulled into the system by the search's want expansion
        for i in range(m):
            if (k + i) in erased:
                rowsrc = np.stack([decoded[j] for j in range(k)])
                decoded[k + i][:] = gf_matmul(
                    self.matrix[i:i + 1], rowsrc)[0]


class ErasureCodeShecSingle(ErasureCodeShec):
    technique = "single"


def _factory(profile):
    technique = profile.get("technique", "multiple")
    if technique == "single":
        return ErasureCodeShecSingle()
    if technique == "multiple":
        return ErasureCodeShec()
    raise ValueError(f"shec: unknown technique {technique}")


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
