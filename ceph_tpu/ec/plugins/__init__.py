"""Builtin erasure-code plugins (module per plugin, import = dlopen)."""
