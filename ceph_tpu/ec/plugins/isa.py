"""ISA-semantics Reed-Solomon plugin (host/numpy execution).

Byte-compatible with the reference's isa plugin
(src/erasure-code/isa/ErasureCodeIsa.cc): Vandermonde
(technique=reed_sol_van, the default) or Cauchy (technique=cauchy)
generator matrices over GF(2^8)/0x11d, chunk size ceil(stripe/k) rounded up
to EC_ISA_ADDRESS_ALIGNMENT (=32, ErasureCodeIsa.h:33), decode over the
first k surviving shards with an LRU decode-matrix cache.

The `tpu` plugin computes the same bytes on the MXU; this plugin is the
host-side oracle and small-op fallback.
"""

from __future__ import annotations

from ..rs_codec import RSMatrixCodec, NumpyBackend
from ..registry import ErasureCodePlugin
from ...gf import gen_rs_matrix, gen_cauchy1_matrix

EC_ISA_ADDRESS_ALIGNMENT = 32

K_VANDERMONDE = "reed_sol_van"
K_CAUCHY = "cauchy"

DEFAULT_K = "7"
DEFAULT_M = "3"


class ErasureCodeIsa(RSMatrixCodec):
    def __init__(self, technique: str = K_VANDERMONDE, backend=None) -> None:
        super().__init__(backend=backend)
        self.technique = technique

    def get_alignment(self) -> int:
        return EC_ISA_ADDRESS_ALIGNMENT

    def parse_km(self, profile) -> None:
        self.k = self.to_int("k", profile, DEFAULT_K)
        self.m = self.to_int("m", profile, DEFAULT_M)
        self.sanity_check_k_m(self.k, self.m)
        if self.technique == K_VANDERMONDE:
            # verified-safe envelope for the non-systematized Vandermonde
            # construction (ErasureCodeIsa.cc:345-377)
            if self.k > 32:
                raise ValueError(f"Vandermonde: k={self.k} must be <= 32")
            if self.m > 4:
                raise ValueError(
                    f"Vandermonde: m={self.m} must be < 5 for an MDS codec")
            if self.m == 4 and self.k > 21:
                raise ValueError(
                    f"Vandermonde: k={self.k} must be < 22 with m=4")

    def prepare(self) -> None:
        if self.technique == K_CAUCHY:
            self.encode_matrix = gen_cauchy1_matrix(self.k + self.m, self.k)
        else:
            self.encode_matrix = gen_rs_matrix(self.k + self.m, self.k)

    def init(self, profile) -> None:
        self.parse(profile)
        self.parse_km(profile)
        technique = profile.get("technique", self.technique)
        if technique not in (K_VANDERMONDE, K_CAUCHY):
            raise ValueError(f"isa: unknown technique {technique}")
        self.technique = technique
        self.prepare()
        super().init(profile)


def _factory(profile):
    return ErasureCodeIsa(profile.get("technique", K_VANDERMONDE))


def __erasure_code_init__(registry, name: str) -> None:
    registry.add(name, ErasureCodePlugin(_factory))
