"""Shared erasure-code implementation: profile parsing, chunk preparation.

Mirrors the reference's ErasureCode base class semantics
(src/erasure-code/ErasureCode.cc): in particular ``encode_prepare``'s
zero-pad + aligned chunking (:170-205) and the default minimum_to_decode
(:122-156), which the byte-parity contract depends on.
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

from .interface import ErasureCodeInterface, ErasureCodeProfile

# reference: ErasureCode.cc:42 (const unsigned ErasureCode::SIMD_ALIGN = 32)
SIMD_ALIGN = 32


class ErasureCode(ErasureCodeInterface):
    DEFAULT_RULE_ROOT = "default"
    DEFAULT_RULE_FAILURE_DOMAIN = "host"

    def __init__(self) -> None:
        self._profile: ErasureCodeProfile = {}
        self.chunk_mapping: list[int] = []
        self.rule_root = self.DEFAULT_RULE_ROOT
        self.rule_failure_domain = self.DEFAULT_RULE_FAILURE_DOMAIN
        self.rule_device_class = ""

    # -- profile helpers ----------------------------------------------------
    def to_int(self, name: str, profile: Mapping[str, str], default: str) -> int:
        v = profile.get(name, default)
        if v == "":
            v = default
        try:
            return int(v)
        except (TypeError, ValueError):
            raise ValueError(f"{name}={v!r} is not an integer")

    def to_string(self, name: str, profile: Mapping[str, str], default: str) -> str:
        return str(profile.get(name, default))

    def parse(self, profile: ErasureCodeProfile) -> None:
        self._to_mapping(profile)

    def _to_mapping(self, profile: ErasureCodeProfile) -> None:
        # "mapping" remaps pseudo-chunks: 'D' positions host data chunks in
        # order, the rest host coding chunks (ErasureCode.cc:283-302)
        mapping = profile.get("mapping")
        if mapping:
            data_pos = [i for i, c in enumerate(mapping) if c == "D"]
            coding_pos = [i for i, c in enumerate(mapping) if c != "D"]
            self.chunk_mapping = data_pos + coding_pos

    def init(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = self.to_string("crush-root", profile,
                                        self.DEFAULT_RULE_ROOT)
        self.rule_failure_domain = self.to_string(
            "crush-failure-domain", profile, self.DEFAULT_RULE_FAILURE_DOMAIN)
        self.rule_device_class = self.to_string(
            "crush-device-class", profile, "")
        self._profile = dict(profile)

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    @staticmethod
    def sanity_check_k_m(k: int, m: int) -> None:
        if k < 2:
            raise ValueError(f"k={k} must be >= 2")
        if m < 1:
            raise ValueError(f"m={m} must be >= 1")

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if len(self.chunk_mapping) > i else i

    def get_chunk_mapping(self) -> list[int]:
        return self.chunk_mapping

    # -- minimum_to_decode --------------------------------------------------
    def _minimum_to_decode(
        self, want_to_read: set[int], available_chunks: set[int],
    ) -> set[int]:
        if want_to_read <= available_chunks:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available_chunks) < k:
            raise IOError(
                f"cannot decode: {len(available_chunks)} < k={k} available")
        return set(sorted(available_chunks)[:k])

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int],
    ) -> dict[int, list[tuple[int, int]]]:
        minimum = self._minimum_to_decode(want_to_read, available)
        sub = [(0, self.get_sub_chunk_count())]
        return {shard: list(sub) for shard in sorted(minimum)}

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: Mapping[int, int],
    ) -> set[int]:
        """Cheapest feasible read set under per-chunk retrieval costs.

        Costs are grown tier by tier (cheapest first) and the FIRST
        feasible candidate set wins; ``_minimum_to_decode`` picks the
        actual reads WITHIN that set, so a subclass's selection policy
        (the LRC plugin's locality preference, SHEC's decoding-system
        search) composes with the cost ordering instead of being
        overridden by it.  The hedged read path feeds per-peer latency
        EWMAs in as costs: in-hand shards cost zero, straggling
        outstanding sub-reads carry a lateness penalty, so the plan it
        gets back routes around the slow source.  With uniform costs
        this degrades to the old behavior exactly.
        """
        want = set(want_to_read)
        order = sorted(available, key=lambda s: (available[s], s))
        cand: set[int] = set()
        i = 0
        while i < len(order):
            cost = available[order[i]]
            while i < len(order) and available[order[i]] == cost:
                cand.add(order[i])
                i += 1
            if i < len(order):      # more tiers left: probe this one
                try:
                    return self._minimum_to_decode(want, set(cand))
                except (IOError, OSError, ValueError):
                    continue
        # last tier = everything available; let its error propagate
        return self._minimum_to_decode(want, set(available))

    # -- encode/decode drivers ---------------------------------------------
    def get_chunk_size(self, stripe_width: int) -> int:
        # plugins with alignment constraints override; mirror of the common
        # ceil + align-up pattern (ErasureCodeIsa.cc:66-79)
        k = self.get_data_chunk_count()
        alignment = self.get_alignment()
        chunk_size = (stripe_width + k - 1) // k
        modulo = chunk_size % alignment
        if modulo:
            chunk_size += alignment - modulo
        return chunk_size

    def get_alignment(self) -> int:
        return SIMD_ALIGN

    def encode_prepare(self, raw: bytes) -> dict[int, np.ndarray]:
        """Slice ``raw`` into k zero-padded chunks + m zeroed parity chunks.

        Matches ErasureCode::encode_prepare (ErasureCode.cc:170-205): chunks
        k - padded_chunks .. k-1 are zero-filled beyond the data, parity
        buffers are allocated at blocksize.
        """
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        raw = np.frombuffer(raw, dtype=np.uint8) if not isinstance(
            raw, np.ndarray) else raw.view(np.uint8)
        blocksize = self.get_chunk_size(len(raw))
        padded_chunks = k - len(raw) // blocksize
        encoded: dict[int, np.ndarray] = {}
        for i in range(k - padded_chunks):
            encoded[self.chunk_index(i)] = np.array(
                raw[i * blocksize:(i + 1) * blocksize], dtype=np.uint8)
        if padded_chunks:
            remainder = len(raw) - (k - padded_chunks) * blocksize
            buf = np.zeros(blocksize, dtype=np.uint8)
            buf[:remainder] = raw[(k - padded_chunks) * blocksize:]
            encoded[self.chunk_index(k - padded_chunks)] = buf
            for i in range(k - padded_chunks + 1, k):
                encoded[self.chunk_index(i)] = np.zeros(blocksize, np.uint8)
        for i in range(k, k + m):
            encoded[self.chunk_index(i)] = np.zeros(blocksize, np.uint8)
        return encoded

    def encode(
        self, want_to_encode: set[int], data: bytes,
    ) -> dict[int, np.ndarray]:
        encoded = self.encode_prepare(data)
        self.encode_chunks(encoded)
        return {i: buf for i, buf in encoded.items() if i in want_to_encode}

    def _decode(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
    ) -> dict[int, np.ndarray]:
        have = set(chunks)
        if want_to_read <= have:
            return {i: np.asarray(chunks[i], dtype=np.uint8)
                    for i in want_to_read}
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        blocksize = len(next(iter(chunks.values())))
        decoded: dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i in chunks:
                decoded[i] = np.array(chunks[i], dtype=np.uint8)
            else:
                decoded[i] = np.zeros(blocksize, dtype=np.uint8)
        self.decode_chunks(want_to_read, chunks, decoded)
        return {i: decoded[i] for i in want_to_read}

    def decode(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        chunk_size: int = 0,
    ) -> dict[int, np.ndarray]:
        return self._decode(want_to_read, chunks)

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        """Reconstruct the object: data chunk i lives at shard
        chunk_index(i) for mapped codes (ErasureCode::decode_concat
        honours get_chunk_mapping the same way)."""
        k = self.get_data_chunk_count()
        want = {self.chunk_index(i) for i in range(k)}
        decoded = self.decode(want, chunks)
        return b"".join(bytes(decoded[self.chunk_index(i)])
                        for i in range(k))
