"""GF(2^16)/GF(2^32) word-region arithmetic for jerasure w=16/32.

The reference's jerasure word techniques at w=16/32 treat each chunk
as little-endian w-bit words and run the coding matrix over GF(2^w)
(jerasure_matrix_encode -> galois_w16/w32_region_mult, galois.c).
A region multiply by a CONSTANT c decomposes by byte: for data word
d = sum_i b_i << 8i,

    c * d  =  XOR_i  T_c,i[b_i]     with  T_c,i[x] = c * (x << 8i)

so the whole region is w/8 table lookups + XORs -- exactly the split
multiplication galois.c uses for w=32 (and a valid one for w=16),
rendered as numpy gathers.  Field polynomials match galois.c
(gf/gf2w.py PRIM_POLY), so the words are the reference's words.

Matrix construction and inversion run in plain ints via gf2w_mult;
the decode path mirrors gf/matrices.py build_decode_matrix over the
wider field.
"""

from __future__ import annotations

import functools

import numpy as np

from ..gf.gf2w import gf2w_inv, gf2w_mult

_DTYPE = {16: np.uint16, 32: np.uint32}


@functools.lru_cache(maxsize=4096)
def _mult_tables(c: int, w: int) -> tuple:
    """w/8 tables of 256 words: T_i[x] = c * (x << 8i) in GF(2^w)."""
    out = []
    for i in range(w // 8):
        t = np.zeros(256, dtype=_DTYPE[w])
        for x in range(256):
            t[x] = gf2w_mult(c, x << (8 * i), w)
        out.append(t)
    return tuple(out)


def region_mult(c: int, data: np.ndarray, w: int) -> np.ndarray:
    """Multiply a region of w-bit words by the constant ``c``."""
    words = data.view(_DTYPE[w])
    if c == 0:
        return np.zeros_like(words)
    if c == 1:
        return words.copy()
    tables = _mult_tables(c, w)
    out = tables[0][words & 0xFF]
    for i in range(1, w // 8):
        out ^= tables[i][(words >> (8 * i)) & 0xFF]
    return out


def gf2w_matmul(matrix: np.ndarray, data: np.ndarray,
                w: int) -> np.ndarray:
    """(r,k) GF(2^w) matrix x (k, n_bytes) byte rows -> (r, n_bytes).

    Rows are viewed as little-endian w-bit words (chunk sizes are
    w-aligned by get_alignment)."""
    r, k = matrix.shape
    rows = [region_mult_rows(matrix[i], data, w) for i in range(r)]
    return np.stack(rows).view(np.uint8).reshape(r, data.shape[1])


def region_mult_rows(coeffs, data: np.ndarray, w: int) -> np.ndarray:
    acc = None
    for j, c in enumerate(coeffs):
        prod = region_mult(int(c), data[j], w)
        acc = prod if acc is None else acc ^ prod
    return acc


def gf2w_invert_matrix(a: np.ndarray, w: int) -> np.ndarray:
    """Gauss-Jordan inverse over GF(2^w); raises ValueError if
    singular."""
    n = a.shape[0]
    m = [[int(v) for v in row] for row in a]
    inv = [[1 if i == j else 0 for j in range(n)] for i in range(n)]
    for col in range(n):
        piv = next((r for r in range(col, n) if m[r][col]), None)
        if piv is None:
            raise ValueError("singular matrix")
        if piv != col:
            m[col], m[piv] = m[piv], m[col]
            inv[col], inv[piv] = inv[piv], inv[col]
        pinv = gf2w_inv(m[col][col], w)
        m[col] = [gf2w_mult(v, pinv, w) for v in m[col]]
        inv[col] = [gf2w_mult(v, pinv, w) for v in inv[col]]
        for r in range(n):
            if r != col and m[r][col]:
                f = m[r][col]
                m[r] = [v ^ gf2w_mult(f, p, w)
                        for v, p in zip(m[r], m[col])]
                inv[r] = [v ^ gf2w_mult(f, p, w)
                          for v, p in zip(inv[r], inv[col])]
    return np.array(inv, dtype=_DTYPE[w])


def build_decode_matrix_w(encode_matrix: np.ndarray, k: int,
                          erasures: list[int],
                          w: int) -> tuple[np.ndarray, list[int]]:
    """build_decode_matrix over GF(2^w) (gf/matrices.py:131 widened)."""
    from ..gf.matrices import decode_index_for
    eset = set(erasures)
    decode_index = decode_index_for(k, eset)
    b = encode_matrix[decode_index, :k]
    d = gf2w_invert_matrix(b, w)
    c = np.zeros((len(erasures), k), dtype=_DTYPE[w])
    for p, e in enumerate(erasures):
        if e < k:
            c[p] = d[e]
        else:
            for i in range(k):
                s = 0
                for j in range(k):
                    s ^= gf2w_mult(int(d[j, i]),
                                   int(encode_matrix[e, j]), w)
                c[p, i] = s
    return c, decode_index


# -- generator matrices over GF(2^w) (jerasure constructions) ---------------

def gen_rs_vandermonde_w(k: int, m: int, w: int) -> np.ndarray:
    """reed_sol_van coding rows over GF(2^w): the jerasure
    distinguished Vandermonde (reed_sol.c) widened from the w=8
    rendering in gf/matrices.py."""
    rows, cols = k + m, k
    v = [[0] * cols for _ in range(rows)]
    v[0][0] = 1
    for i in range(1, rows - 1):
        p = 1
        for j in range(cols):
            v[i][j] = p
            p = gf2w_mult(p, i, w)
    v[rows - 1][cols - 1] = 1
    for i in range(1, cols):
        piv = i
        while piv < rows and v[piv][i] == 0:
            piv += 1
        if piv >= rows:
            raise ValueError("vandermonde systematization failed")
        if piv != i:
            v[i], v[piv] = v[piv], v[i]
        if v[i][i] != 1:
            inv = gf2w_inv(v[i][i], w)
            for r in range(rows):
                v[r][i] = gf2w_mult(v[r][i], inv, w)
        for j in range(cols):
            c = v[i][j]
            if j != i and c != 0:
                for r in range(rows):
                    v[r][j] ^= gf2w_mult(c, v[r][i], w)
    for j in range(cols):
        c = v[k][j]
        if c != 1:
            inv = gf2w_inv(c, w)
            for r in range(k, rows):
                v[r][j] = gf2w_mult(v[r][j], inv, w)
    for i in range(k + 1, rows):
        c = v[i][0]
        if c not in (0, 1):
            inv = gf2w_inv(c, w)
            v[i] = [gf2w_mult(x, inv, w) for x in v[i]]
    return np.array([row for row in v[k:]], dtype=_DTYPE[w])


def gen_raid6_w(k: int, w: int) -> np.ndarray:
    """reed_sol_r6_op rows over GF(2^w): [1,1,...] and [1,2,4,...]."""
    coding = np.zeros((2, k), dtype=_DTYPE[w])
    coding[0, :] = 1
    p = 1
    for j in range(k):
        coding[1, j] = p
        p = gf2w_mult(p, 2, w)
    return coding
