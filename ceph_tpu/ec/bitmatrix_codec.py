"""GF(2) bit-matrix codec: the jerasure bitmatrix-technique data path.

jerasure's cauchy/liberation/blaum_roth techniques encode by XORing
w-bit packet rows selected by a (m*w, k*w) GF(2) matrix
(jerasure_bitmatrix_encode): each chunk is a sequence of regions of
w * packetsize bytes; packet row c of region g of chunk j is plane
(j*w + c); coding plane r = XOR of the data planes with a 1 in
bitmatrix row r.  Decode inverts the (k*w)-square submatrix of
surviving generator rows over GF(2).

The XOR formulation is exactly the GF(2) bit-matmul the TPU kernel
family runs on the MXU (ops/gf2kernels.py) -- same math, different
plane granularity (w-bit packets instead of bit planes).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Mapping

import numpy as np

from ..gf.gf2w import gf2_invert
from ..ops.xor_schedule import scheduled_xor_matmul, warm_schedule
from .base import ErasureCode


class BitMatrixCodec(ErasureCode):
    """Systematic (k+m, k) code defined by a (m*w, k*w) GF(2) matrix.

    Subclasses set self.k/self.m/self.w/self.packetsize and build
    self.bitmatrix in prepare()."""

    def __init__(self) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.w = 8
        self.packetsize = 8
        self.bitmatrix: np.ndarray | None = None
        self._inv_cache: OrderedDict[tuple, np.ndarray] = OrderedDict()

    # -- geometry -----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        # chunk must hold whole regions of w*packetsize bytes
        # (ErasureCodeJerasure{Cauchy,Liberation}::get_alignment)
        return self.k * self.w * self.packetsize

    def get_chunk_size(self, stripe_width: int) -> int:
        alignment = self.get_alignment()
        tail = stripe_width % alignment
        padded = stripe_width + (alignment - tail if tail else 0)
        return padded // self.k

    # -- plane layout -------------------------------------------------------
    def _planes(self, chunks: np.ndarray) -> np.ndarray:
        """(n, csize) chunk rows -> (n*w, csize//w) packet planes."""
        n, csize = chunks.shape
        ps = self.packetsize
        regions = csize // (self.w * ps)
        # (n, regions, w, ps) -> (n, w, regions, ps) -> (n*w, regions*ps)
        return (chunks.reshape(n, regions, self.w, ps)
                .transpose(0, 2, 1, 3)
                .reshape(n * self.w, regions * ps))

    def _unplanes(self, planes: np.ndarray, n: int,
                  csize: int) -> np.ndarray:
        ps = self.packetsize
        regions = csize // (self.w * ps)
        return (planes.reshape(n, self.w, regions, ps)
                .transpose(0, 2, 1, 3)
                .reshape(n, csize))

    # -- encode/decode ------------------------------------------------------
    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = np.stack([chunks[self.chunk_index(i)] for i in range(k)])
        csize = data.shape[1]
        if csize % (self.w * self.packetsize):
            raise ValueError(
                f"chunk size {csize} not a multiple of w*packetsize="
                f"{self.w * self.packetsize}")
        planes = self._planes(data)
        # the CSE-minimized XOR schedule (ops/xor_schedule.py): the
        # encode matrix is hot for the codec's lifetime, so compile on
        # first use; byte-identical to the naive row-by-row XOR
        coding = scheduled_xor_matmul(self.bitmatrix, planes)
        out = self._unplanes(coding, m, csize)
        for r in range(m):
            chunks[self.chunk_index(k + r)][:] = out[r]

    def _generator_rows(self, chunk: int) -> np.ndarray:
        """The w generator rows (over the k*w data planes) of ``chunk``."""
        kw = self.k * self.w
        if chunk < self.k:
            rows = np.zeros((self.w, kw), dtype=np.uint8)
            for r in range(self.w):
                rows[r, chunk * self.w + r] = 1
            return rows
        return self.bitmatrix[(chunk - self.k) * self.w:
                              (chunk - self.k + 1) * self.w]

    def _repair_matrix(self, sel: tuple[int, ...],
                       erasures: tuple[int, ...]) -> np.ndarray:
        """ONE (len(erasures)*w, k*w) GF(2) matrix mapping the
        surviving planes directly to every missing chunk's planes:
        data erasure e contributes rows inv[e*w:(e+1)*w], coding
        erasure e contributes bitmatrix_rows(e) @ inv (mod 2) -- so
        repair is a single launch instead of one per lost chunk.
        Cached per (survivor set, erasure pattern) and its XOR
        schedule warmed at build time, so repeated repairs ride the
        scheduled kernel without paying a compile on the read path."""
        key = (",".join(map(str, sel)), ",".join(map(str, erasures)))
        entry = self._inv_cache.get(key)
        if entry is not None:
            self._inv_cache.move_to_end(key)   # LRU, not FIFO
            return entry
        w = self.w
        s = np.concatenate([self._generator_rows(c) for c in sel])
        inv = gf2_invert(s)               # raises if not decodable
        rows = []
        for e in erasures:
            if e < self.k:
                rows.append(inv[e * w:(e + 1) * w])
            else:
                gen = self.bitmatrix[(e - self.k) * w:
                                     (e - self.k + 1) * w]
                rows.append((gen.astype(np.uint32)
                             @ inv.astype(np.uint32)) & 1)
        repair = np.ascontiguousarray(
            np.concatenate(rows).astype(np.uint8))
        warm_schedule(repair)
        self._inv_cache[key] = repair
        while len(self._inv_cache) > 128:
            self._inv_cache.popitem(last=False)
        return repair

    def decode_chunks(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m = self.k, self.m
        erasures = [i for i in range(k + m) if i not in chunks]
        if not erasures:
            return
        if len(erasures) > m:
            raise IOError(f"{len(erasures)} erasures exceed m={m}")
        available = sorted(set(range(k + m)) - set(erasures))
        sel = available[:k]
        repair = self._repair_matrix(tuple(sel), tuple(erasures))
        csize = len(next(iter(decoded.values())))
        src = np.stack([decoded[c] for c in sel])
        # every missing chunk (data AND coding) from one launch; the
        # schedule was warmed when the repair matrix was built, so
        # the read path never compiles (allow_compile=False)
        planes = scheduled_xor_matmul(repair, self._planes(src),
                                      allow_compile=False)
        out = self._unplanes(planes, len(erasures), csize)
        for i, e in enumerate(erasures):
            decoded[e][:] = out[i]
