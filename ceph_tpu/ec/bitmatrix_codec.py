"""GF(2) bit-matrix codec: the jerasure bitmatrix-technique data path.

jerasure's cauchy/liberation/blaum_roth techniques encode by XORing
w-bit packet rows selected by a (m*w, k*w) GF(2) matrix
(jerasure_bitmatrix_encode): each chunk is a sequence of regions of
w * packetsize bytes; packet row c of region g of chunk j is plane
(j*w + c); coding plane r = XOR of the data planes with a 1 in
bitmatrix row r.  Decode inverts the (k*w)-square submatrix of
surviving generator rows over GF(2).

The XOR formulation is exactly the GF(2) bit-matmul the TPU kernel
family runs on the MXU (ops/gf2kernels.py) -- same math, different
plane granularity (w-bit packets instead of bit planes).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Mapping

import numpy as np

from ..gf.gf2w import gf2_invert, xor_matmul
from .base import ErasureCode


class BitMatrixCodec(ErasureCode):
    """Systematic (k+m, k) code defined by a (m*w, k*w) GF(2) matrix.

    Subclasses set self.k/self.m/self.w/self.packetsize and build
    self.bitmatrix in prepare()."""

    def __init__(self) -> None:
        super().__init__()
        self.k = 0
        self.m = 0
        self.w = 8
        self.packetsize = 8
        self.bitmatrix: np.ndarray | None = None
        self._inv_cache: OrderedDict[str, tuple] = OrderedDict()

    # -- geometry -----------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        # chunk must hold whole regions of w*packetsize bytes
        # (ErasureCodeJerasure{Cauchy,Liberation}::get_alignment)
        return self.k * self.w * self.packetsize

    def get_chunk_size(self, stripe_width: int) -> int:
        alignment = self.get_alignment()
        tail = stripe_width % alignment
        padded = stripe_width + (alignment - tail if tail else 0)
        return padded // self.k

    # -- plane layout -------------------------------------------------------
    def _planes(self, chunks: np.ndarray) -> np.ndarray:
        """(n, csize) chunk rows -> (n*w, csize//w) packet planes."""
        n, csize = chunks.shape
        ps = self.packetsize
        regions = csize // (self.w * ps)
        # (n, regions, w, ps) -> (n, w, regions, ps) -> (n*w, regions*ps)
        return (chunks.reshape(n, regions, self.w, ps)
                .transpose(0, 2, 1, 3)
                .reshape(n * self.w, regions * ps))

    def _unplanes(self, planes: np.ndarray, n: int,
                  csize: int) -> np.ndarray:
        ps = self.packetsize
        regions = csize // (self.w * ps)
        return (planes.reshape(n, self.w, regions, ps)
                .transpose(0, 2, 1, 3)
                .reshape(n, csize))

    # -- encode/decode ------------------------------------------------------
    def encode_chunks(self, chunks: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = np.stack([chunks[self.chunk_index(i)] for i in range(k)])
        csize = data.shape[1]
        if csize % (self.w * self.packetsize):
            raise ValueError(
                f"chunk size {csize} not a multiple of w*packetsize="
                f"{self.w * self.packetsize}")
        planes = self._planes(data)
        coding = xor_matmul(self.bitmatrix, planes)
        out = self._unplanes(coding, m, csize)
        for r in range(m):
            chunks[self.chunk_index(k + r)][:] = out[r]

    def _generator_rows(self, chunk: int) -> np.ndarray:
        """The w generator rows (over the k*w data planes) of ``chunk``."""
        kw = self.k * self.w
        if chunk < self.k:
            rows = np.zeros((self.w, kw), dtype=np.uint8)
            for r in range(self.w):
                rows[r, chunk * self.w + r] = 1
            return rows
        return self.bitmatrix[(chunk - self.k) * self.w:
                              (chunk - self.k + 1) * self.w]

    def decode_chunks(
        self, want_to_read: set[int], chunks: Mapping[int, np.ndarray],
        decoded: dict[int, np.ndarray],
    ) -> None:
        k, m, w = self.k, self.m, self.w
        erasures = [i for i in range(k + m) if i not in chunks]
        if not erasures:
            return
        if len(erasures) > m:
            raise IOError(f"{len(erasures)} erasures exceed m={m}")
        available = sorted(set(range(k + m)) - set(erasures))
        sel = available[:k]
        key = ",".join(map(str, sel))
        entry = self._inv_cache.get(key)
        if entry is None:
            s = np.concatenate([self._generator_rows(c) for c in sel])
            inv = gf2_invert(s)           # raises if not decodable
            self._inv_cache[key] = inv
            while len(self._inv_cache) > 128:
                self._inv_cache.popitem(last=False)
        else:
            inv = entry
            self._inv_cache.move_to_end(key)   # LRU, not FIFO
        csize = len(next(iter(decoded.values())))
        src = np.stack([decoded[c] for c in sel])
        data_planes = xor_matmul(inv, self._planes(src))
        data = self._unplanes(data_planes, k, csize)
        for e in erasures:
            if e < k:
                decoded[e][:] = data[e]
        coding_erased = [e for e in erasures if e >= k]
        if coding_erased:
            planes = self._planes(data)
            for e in coding_erased:
                rows = self.bitmatrix[(e - k) * w:(e - k + 1) * w]
                decoded[e][:] = self._unplanes(
                    xor_matmul(rows, planes), 1, csize)[0]
