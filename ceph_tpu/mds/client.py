"""CephFS client (libcephfs / src/client/Client.cc analog).

POSIX-ish surface: mkdir/rmdir/readdir/stat/open/read/write/truncate/
unlink/rename.  Metadata RPCs go to the active MDS (discovered from
the mds_map object, re-resolved on failure -- the FSMap subscription
analog); file DATA goes straight to the data pool through the striper
with the layout from the inode, never through the MDS.  File size is
write-back: the client tracks it per open file and flushes a setattr
on close/fsync (the Fw cap dirty-size flush)."""

from __future__ import annotations

import asyncio
import itertools
import json

from ..client.rados import Rados, RadosError
from ..client.striper import Layout, RadosStriper
from ..msg import Message
from .server import CAP_LEASE, DEFAULT_LAYOUT, MDSMAP_OID


class FsError(Exception):
    def __init__(self, errno_name: str, detail: str = "") -> None:
        super().__init__(f"{errno_name}{': ' + detail if detail else ''}")
        self.errno_name = errno_name


class FsFile:
    """An open file handle holding a capability.

    The cap ("r" or "w") is what makes cached state legal: a "w"
    holder may buffer its size and append position; when the MDS
    revokes (another client opened the file), the handle flushes and
    goes STALE -- the next write re-opens to re-acquire the cap and
    refresh the size, so two clients cannot clobber each other
    (Locker.cc cap revocation compressed to the Fr/Fw pair)."""

    def __init__(self, fs: "CephFS", path: str, dentry: dict,
                 append: bool = False, caps: str = "r",
                 snap_id: int | None = None,
                 snapc: dict | None = None) -> None:
        self.fs = fs
        self.path = path
        self.dentry = dentry
        self.ino = dentry["ino"]
        self.snap_id = snap_id          # frozen .snap view when set
        lay = dentry.get("layout") or DEFAULT_LAYOUT
        layout = Layout(stripe_unit=lay["su"], stripe_count=lay["sc"],
                        object_size=lay["os"])
        if snapc is not None:
            # a snapped realm: writes must stamp the realm's snapc so
            # the OSDs COW pre-snap data.  The snapc is per-file, so
            # the handle gets a PRIVATE ioctx (a shared one would leak
            # this context onto other files' writes) and bypasses the
            # shared write-back cache
            from ..client.rados import IoCtx
            dio = IoCtx(fs.rados, fs.data.pool_name, fs.data.pool_id)
            dio.set_snap_context(snapc["seq"], snapc["snaps"])
            self.striper = RadosStriper(dio, layout)
        elif snap_id is not None:
            self.striper = RadosStriper(fs.data, layout)
        else:
            self.striper = RadosStriper(fs._data_cache or fs.data,
                                        layout)
        self.size = dentry.get("size", 0)
        self.caps = caps
        self._stale = False
        self._append = append
        self._dirty = False
        self._closed = False
        fs._track_file(self)

    async def _reacquire(self, want: str) -> None:
        """Cap lost (revoked or lapsed): flush went out at revoke
        time; re-open to refresh size + regain the cap."""
        out = await self.fs._request({"op": "open", "path": self.path,
                                      "want": want})
        self.dentry = out["dentry"]
        self.size = self.dentry.get("size", 0)
        self.caps = out.get("caps", want)
        snapc = out.get("snapc")
        if snapc is not None:
            # the realm was snapped while we were revoked: subsequent
            # writes MUST stamp the new snapc or they overwrite data
            # the snapshot froze.  Rebuild the data path with it (a
            # private ioctx -- the shared one must not inherit it)
            from ..client.rados import IoCtx
            lay = self.dentry.get("layout") or DEFAULT_LAYOUT
            dio = IoCtx(self.fs.rados, self.fs.data.pool_name,
                        self.fs.data.pool_id)
            dio.set_snap_context(snapc["seq"], snapc["snaps"])
            self.striper = RadosStriper(dio, Layout(
                stripe_unit=lay["su"], stripe_count=lay["sc"],
                object_size=lay["os"]))
        self._stale = False
        self.fs._note_lease()

    async def write(self, data: bytes, offset: int | None = None) -> int:
        if self.snap_id is not None:
            raise FsError("EROFS", "snapshot view is read-only")
        if self._stale or "w" not in self.caps \
                or not self.fs._caps_fresh():
            await self._reacquire("w")
        # append mode: every write lands at EOF (O_APPEND); otherwise
        # an omitted offset means 0
        offset = self.size if self._append else (offset or 0)
        await self.striper.write(f"{self.ino:x}", data, offset)
        self.size = max(self.size, offset + len(data))
        self._dirty = True
        return len(data)

    async def read(self, length: int | None = None,
                   offset: int = 0) -> bytes:
        if self.snap_id is not None:
            # frozen view: data at the snap id, size from the frozen
            # dentry (the head's size xattr has moved on)
            return await self.striper.read(
                f"{self.ino:x}", length, offset, snap=self.snap_id,
                size_override=self.dentry.get("size", 0))
        if self._stale:
            await self._reacquire("r" if "w" not in self.caps else "w")
        return await self.striper.read(f"{self.ino:x}", length, offset)

    async def truncate(self, size: int) -> None:
        if self._stale or "w" not in self.caps \
                or not self.fs._caps_fresh():
            await self._reacquire("w")
        await self.striper.truncate(f"{self.ino:x}", size)
        self.size = size
        self._dirty = True

    async def fsync(self) -> None:
        if self.fs._data_cache is not None:
            # durability barrier: buffered data lands before the size
            # update is journaled (a crash can truncate, never corrupt)
            await self.fs._data_cache.flush()
        if self._dirty:
            await self.fs._request({"op": "setattr", "path": self.path,
                                    "attrs": {"size": self.size}})
            self._dirty = False

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self.snap_id is not None:
                self.fs._untrack_file(self)
                return                 # frozen view: nothing to flush
            await self.fsync()
            self.fs._untrack_file(self)
            try:
                await self.fs._send_to_mds(Message(
                    "cap_release", {"ino": self.ino}))
            except (ConnectionError, OSError):
                pass


class CephFS:
    """Mounted filesystem handle (ceph_mount analog)."""

    def __init__(self, mon_addr: tuple[str, int],
                 meta_pool: str = "cephfs_metadata",
                 data_pool: str = "cephfs_data",
                 name: str | None = None,
                 cache: bool = False) -> None:
        # write-back data cache (ObjectCacher): file writes ack from
        # cache; fsync/close/cap-revoke are the flush barriers
        self._cache_enabled = cache
        self._data_cache = None
        self.mon_addr = tuple(mon_addr)
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.rados = Rados(mon_addr, name=name)
        self.meta = None
        self.data = None
        self.mds_addr: tuple[str, int] | None = None
        self._tid = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        self._files: dict[int, list[FsFile]] = {}     # ino -> handles
        self._renew_task: asyncio.Task | None = None
        # local lease clock: caps are only trusted while a renewal (or
        # grant) succeeded within the lease -- after a connectivity
        # gap the MDS may have expired and re-granted them, so the
        # client must treat its own copies as stale
        self._lease_valid_until = 0.0

    async def mount(self) -> "CephFS":
        await self.rados.connect()
        self.meta = await self.rados.open_ioctx(self.meta_pool)
        self.data = await self.rados.open_ioctx(self.data_pool)
        self.rados.objecter.msgr.add_dispatcher(self._on_reply)
        if self._cache_enabled:
            from ..client.object_cacher import CachingIoCtx
            self._data_cache = CachingIoCtx(self.data)
        await self._find_mds()
        # session heartbeat for the MOUNT's lifetime, not just while
        # files are open: an MDS successor fences write-cap holders
        # that stay silent through its reconnect window, and a cap
        # release journaled by a dying active may be lost -- the
        # heartbeat is how an innocent client proves it's alive
        # (the reference's Client::renew_caps runs per-session too)
        if self._renew_task is None or self._renew_task.done():
            self._renew_task = asyncio.ensure_future(self._renew_loop())
        return self

    async def unmount(self) -> None:
        if self._renew_task:
            self._renew_task.cancel()
        if self._data_cache is not None:
            # the final flush failing means acked writes did NOT land:
            # surface it (the mount is still usable for a retry)
            await self._data_cache.cacher.close()
        await self.rados.shutdown()

    # -- capability bookkeeping ---------------------------------------------
    def _track_file(self, f: FsFile) -> None:
        self._files.setdefault(f.ino, []).append(f)

    def _untrack_file(self, f: FsFile) -> None:
        handles = self._files.get(f.ino, [])
        if f in handles:
            handles.remove(f)
        if not handles:
            self._files.pop(f.ino, None)

    async def _send_to_mds(self, msg: Message) -> None:
        await self.rados.objecter.msgr.send(self.mds_addr, "mds", msg)

    def _caps_fresh(self) -> bool:
        loop = asyncio.get_event_loop()
        return loop.time() < self._lease_valid_until

    def _note_lease(self) -> None:
        self._lease_valid_until = (asyncio.get_event_loop().time()
                                   + CAP_LEASE)

    async def _renew_loop(self) -> None:
        """Session heartbeat: keeps held caps alive AND tracks whether
        they are still trustworthy locally (an unacked lease means the
        MDS may have expired + re-granted them to someone else)."""
        try:
            while True:
                await asyncio.sleep(CAP_LEASE / 3)
                loop = asyncio.get_event_loop()
                fut = loop.create_future()
                self._renew_waiter = fut
                try:
                    await self._send_to_mds(
                        Message("session_renew", {}))
                    await asyncio.wait_for(fut, 2.0)
                    self._note_lease()
                except (ConnectionError, OSError,
                        asyncio.TimeoutError):
                    # the active may have MOVED (failover): rediscover
                    # and renew at the new address NOW -- the new
                    # active fences write-cap holders that stay silent
                    # past its reconnect window
                    try:
                        await self._find_mds()
                        fut2 = loop.create_future()
                        self._renew_waiter = fut2
                        await self._send_to_mds(
                            Message("session_renew", {}))
                        await asyncio.wait_for(fut2, 2.0)
                        self._note_lease()
                    except (ConnectionError, OSError, RadosError,
                            asyncio.TimeoutError):
                        pass           # lease clock keeps draining
                finally:
                    self._renew_waiter = None
        except asyncio.CancelledError:
            pass

    async def _on_cap_revoke(self, msg: Message) -> None:
        """The MDS wants our cap back: flush every dirty handle on the
        ino, mark them stale, release."""
        ino = msg.data["ino"]
        for f in list(self._files.get(ino, [])):
            try:
                await f.fsync()
            except (FsError, ConnectionError, OSError):
                pass
            f._stale = True
            f.caps = ""
        if self._data_cache is not None:
            # the cap is leaving us: another client may write next, so
            # our CLEAN extents are about to go stale (cap coherence)
            try:
                await self._data_cache.cacher.invalidate()
            except Exception:
                pass
        try:
            await self._send_to_mds(Message("cap_release",
                                            {"ino": ino}))
        except (ConnectionError, OSError):
            pass

    async def _find_mds(self, timeout: float = 30.0) -> None:
        """Resolve the active MDS from the mon's FSMap (MDSMonitor);
        the legacy mds_map omap object is the fallback so old
        single-daemon deployments still mount."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            try:
                fsmap = await self.rados.mon_command("fs dump", {})
                active = (fsmap or {}).get("active")
                if active and active.get("addr"):
                    self.mds_addr = tuple(active["addr"])
                    return
            except (RadosError, ConnectionError, OSError,
                    asyncio.TimeoutError, KeyError, TypeError):
                pass
            try:
                omap = await self.meta.get_omap(MDSMAP_OID)
                raw = omap.get("addr")
                if raw:
                    self.mds_addr = tuple(json.loads(raw))
                    return
            except RadosError:
                pass
            await asyncio.sleep(0.5)
        raise FsError("ETIMEDOUT", "no active mds")

    async def _on_reply(self, conn, msg: Message) -> None:
        if msg.type == "cap_revoke":
            await self._on_cap_revoke(msg)
            return
        if msg.type == "session_renew_ack":
            fut = getattr(self, "_renew_waiter", None)
            if fut is not None and not fut.done():
                fut.set_result(True)
            return
        if msg.type != "mds_reply":
            return
        fut = self._waiters.pop(msg.data.get("tid"), None)
        if fut is not None and not fut.done():
            fut.set_result(msg.data)

    async def _request(self, q: dict, timeout: float = 30.0) -> dict:
        """RPC to the active MDS; re-resolves on failure (the client's
        session reconnect to the new active after failover)."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        last: Exception | None = None
        # the reqid is STABLE across resends of this logical op (the
        # per-attempt tid is not): the MDS dedups a mutation whose
        # reply was lost instead of re-applying it (mkdir resent after
        # a failover must not surface EEXIST)
        reqid = f"{self.rados.objecter.msgr.name}:{next(self._tid)}"
        while loop.time() < deadline:
            tid = next(self._tid)
            fut = loop.create_future()
            self._waiters[tid] = fut
            try:
                await self.rados.objecter.msgr.send(
                    self.mds_addr, "mds", Message(
                        "mds_request",
                        {**q, "tid": tid, "reqid": reqid}))
                out = await asyncio.wait_for(fut, 5.0)
                if out.get("err") == "EAGAIN":       # standby answered
                    raise ConnectionError("mds not active")
                if "err" in out:
                    raise FsError(out["err"], out.get("detail", ""))
                return out
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                last = e
                self._waiters.pop(tid, None)
                await asyncio.sleep(0.5)
                try:
                    await self._find_mds(timeout=5.0)
                except FsError:
                    pass
        raise FsError("ETIMEDOUT", f"mds unreachable: {last}")

    # -- namespace ops ------------------------------------------------------
    async def mkdir(self, path: str, mode: int = 0o755) -> None:
        await self._request({"op": "mkdir", "path": path, "mode": mode})

    async def rmdir(self, path: str) -> None:
        await self._request({"op": "rmdir", "path": path})

    async def readdir(self, path: str = "/") -> dict[str, dict]:
        out = await self._request({"op": "readdir", "path": path})
        return out["entries"]

    async def ls(self, path: str = "/") -> list[str]:
        return sorted(await self.readdir(path))

    async def stat(self, path: str) -> dict:
        out = await self._request({"op": "stat", "path": path})
        return out["dentry"]

    async def exists(self, path: str) -> bool:
        try:
            await self.stat(path)
            return True
        except FsError as e:
            if e.errno_name == "ENOENT":
                return False
            raise

    async def unlink(self, path: str) -> None:
        await self._request({"op": "unlink", "path": path})

    async def rename(self, src: str, dst: str) -> None:
        await self._request({"op": "rename", "path": src, "dst": dst})

    # -- snapshots ----------------------------------------------------------
    async def mksnap(self, path: str, name: str) -> int:
        """Snapshot a directory subtree (mkdir <path>/.snap/<name>);
        read the frozen view back via '<path>/.snap/<name>/...'."""
        out = await self._request({"op": "mksnap", "path": path,
                                   "name": name})
        return out["snapid"]

    async def rmsnap(self, path: str, name: str) -> None:
        await self._request({"op": "rmsnap", "path": path,
                             "name": name})

    async def lssnap(self, path: str) -> dict:
        return (await self._request({"op": "lssnap",
                                     "path": path}))["snaps"]

    async def open(self, path: str, flags: str = "r",
                   mode: int = 0o644) -> FsFile:
        create = "w" in flags or "a" in flags or "+" in flags
        want = "w" if create else "r"
        out = await self._request({"op": "open", "path": path,
                                   "create": create, "mode": mode,
                                   "want": want})
        self._note_lease()
        f = FsFile(self, path, out["dentry"], append="a" in flags,
                   caps=out.get("caps", want),
                   snap_id=out.get("snapid"),
                   snapc=out.get("snapc"))
        if "w" in flags:        # 'w' and 'w+' both truncate (fopen(3))
            await f.truncate(0)
        return f

    # -- convenience --------------------------------------------------------
    async def write_file(self, path: str, data: bytes) -> None:
        f = await self.open(path, "w")
        try:
            await f.write(data, 0)
        finally:
            await f.close()

    async def read_file(self, path: str) -> bytes:
        f = await self.open(path, "r")
        try:
            return await f.read()
        finally:
            await f.close()

    async def walk(self, path: str = "/"):
        """Yield (dirpath, dirnames, filenames) depth-first."""
        entries = await self.readdir(path)
        dirs = [n for n, d in entries.items() if d["type"] == "dir"]
        files = [n for n, d in entries.items() if d["type"] == "file"]
        yield path, sorted(dirs), sorted(files)
        for d in sorted(dirs):
            sub = f"{path.rstrip('/')}/{d}"
            async for x in self.walk(sub):
                yield x
