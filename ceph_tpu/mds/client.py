"""CephFS client (libcephfs / src/client/Client.cc analog).

POSIX-ish surface: mkdir/rmdir/readdir/stat/open/read/write/truncate/
unlink/rename.  Metadata RPCs go to the active MDS (discovered from
the mds_map object, re-resolved on failure -- the FSMap subscription
analog); file DATA goes straight to the data pool through the striper
with the layout from the inode, never through the MDS.  File size is
write-back: the client tracks it per open file and flushes a setattr
on close/fsync (the Fw cap dirty-size flush)."""

from __future__ import annotations

import asyncio
import itertools
import json

from ..client.rados import Rados, RadosError
from ..client.striper import Layout, RadosStriper
from ..msg import Message
from .server import DEFAULT_LAYOUT, MDSMAP_OID


class FsError(Exception):
    def __init__(self, errno_name: str, detail: str = "") -> None:
        super().__init__(f"{errno_name}{': ' + detail if detail else ''}")
        self.errno_name = errno_name


class FsFile:
    """An open file handle."""

    def __init__(self, fs: "CephFS", path: str, dentry: dict,
                 append: bool = False) -> None:
        self.fs = fs
        self.path = path
        self.dentry = dentry
        self.ino = dentry["ino"]
        lay = dentry.get("layout") or DEFAULT_LAYOUT
        self.striper = RadosStriper(fs.data, Layout(
            stripe_unit=lay["su"], stripe_count=lay["sc"],
            object_size=lay["os"]))
        self.size = dentry.get("size", 0)
        self._append = append
        self._dirty = False
        self._closed = False

    async def write(self, data: bytes, offset: int | None = None) -> int:
        # append mode: every write lands at EOF (O_APPEND); otherwise
        # an omitted offset means 0
        offset = self.size if self._append else (offset or 0)
        await self.striper.write(f"{self.ino:x}", data, offset)
        self.size = max(self.size, offset + len(data))
        self._dirty = True
        return len(data)

    async def read(self, length: int | None = None,
                   offset: int = 0) -> bytes:
        return await self.striper.read(f"{self.ino:x}", length, offset)

    async def truncate(self, size: int) -> None:
        await self.striper.truncate(f"{self.ino:x}", size)
        self.size = size
        self._dirty = True

    async def fsync(self) -> None:
        if self._dirty:
            await self.fs._request({"op": "setattr", "path": self.path,
                                    "attrs": {"size": self.size}})
            self._dirty = False

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            await self.fsync()


class CephFS:
    """Mounted filesystem handle (ceph_mount analog)."""

    def __init__(self, mon_addr: tuple[str, int],
                 meta_pool: str = "cephfs_metadata",
                 data_pool: str = "cephfs_data",
                 name: str | None = None) -> None:
        self.mon_addr = tuple(mon_addr)
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.rados = Rados(mon_addr, name=name)
        self.meta = None
        self.data = None
        self.mds_addr: tuple[str, int] | None = None
        self._tid = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}

    async def mount(self) -> "CephFS":
        await self.rados.connect()
        self.meta = await self.rados.open_ioctx(self.meta_pool)
        self.data = await self.rados.open_ioctx(self.data_pool)
        self.rados.objecter.msgr.add_dispatcher(self._on_reply)
        await self._find_mds()
        return self

    async def unmount(self) -> None:
        await self.rados.shutdown()

    async def _find_mds(self, timeout: float = 30.0) -> None:
        """Resolve the active MDS address from mds_map (FSMap)."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            try:
                omap = await self.meta.get_omap(MDSMAP_OID)
                raw = omap.get("addr")
                if raw:
                    self.mds_addr = tuple(json.loads(raw))
                    return
            except RadosError:
                pass
            await asyncio.sleep(0.5)
        raise FsError("ETIMEDOUT", "no active mds")

    async def _on_reply(self, conn, msg: Message) -> None:
        if msg.type != "mds_reply":
            return
        fut = self._waiters.pop(msg.data.get("tid"), None)
        if fut is not None and not fut.done():
            fut.set_result(msg.data)

    async def _request(self, q: dict, timeout: float = 30.0) -> dict:
        """RPC to the active MDS; re-resolves on failure (the client's
        session reconnect to the new active after failover)."""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        last: Exception | None = None
        # the reqid is STABLE across resends of this logical op (the
        # per-attempt tid is not): the MDS dedups a mutation whose
        # reply was lost instead of re-applying it (mkdir resent after
        # a failover must not surface EEXIST)
        reqid = f"{self.rados.objecter.msgr.name}:{next(self._tid)}"
        while loop.time() < deadline:
            tid = next(self._tid)
            fut = loop.create_future()
            self._waiters[tid] = fut
            try:
                await self.rados.objecter.msgr.send(
                    self.mds_addr, "mds", Message(
                        "mds_request",
                        {**q, "tid": tid, "reqid": reqid}))
                out = await asyncio.wait_for(fut, 5.0)
                if out.get("err") == "EAGAIN":       # standby answered
                    raise ConnectionError("mds not active")
                if "err" in out:
                    raise FsError(out["err"], out.get("detail", ""))
                return out
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                last = e
                self._waiters.pop(tid, None)
                await asyncio.sleep(0.5)
                try:
                    await self._find_mds(timeout=5.0)
                except FsError:
                    pass
        raise FsError("ETIMEDOUT", f"mds unreachable: {last}")

    # -- namespace ops ------------------------------------------------------
    async def mkdir(self, path: str, mode: int = 0o755) -> None:
        await self._request({"op": "mkdir", "path": path, "mode": mode})

    async def rmdir(self, path: str) -> None:
        await self._request({"op": "rmdir", "path": path})

    async def readdir(self, path: str = "/") -> dict[str, dict]:
        out = await self._request({"op": "readdir", "path": path})
        return out["entries"]

    async def ls(self, path: str = "/") -> list[str]:
        return sorted(await self.readdir(path))

    async def stat(self, path: str) -> dict:
        out = await self._request({"op": "stat", "path": path})
        return out["dentry"]

    async def exists(self, path: str) -> bool:
        try:
            await self.stat(path)
            return True
        except FsError as e:
            if e.errno_name == "ENOENT":
                return False
            raise

    async def unlink(self, path: str) -> None:
        await self._request({"op": "unlink", "path": path})

    async def rename(self, src: str, dst: str) -> None:
        await self._request({"op": "rename", "path": src, "dst": dst})

    async def open(self, path: str, flags: str = "r",
                   mode: int = 0o644) -> FsFile:
        create = "w" in flags or "a" in flags or "+" in flags
        out = await self._request({"op": "open", "path": path,
                                   "create": create, "mode": mode})
        f = FsFile(self, path, out["dentry"], append="a" in flags)
        if "w" in flags:        # 'w' and 'w+' both truncate (fopen(3))
            await f.truncate(0)
        return f

    # -- convenience --------------------------------------------------------
    async def write_file(self, path: str, data: bytes) -> None:
        f = await self.open(path, "w")
        try:
            await f.write(data, 0)
        finally:
            await f.close()

    async def read_file(self, path: str) -> bytes:
        f = await self.open(path, "r")
        try:
            return await f.read()
        finally:
            await f.close()

    async def walk(self, path: str = "/"):
        """Yield (dirpath, dirnames, filenames) depth-first."""
        entries = await self.readdir(path)
        dirs = [n for n, d in entries.items() if d["type"] == "dir"]
        files = [n for n, d in entries.items() if d["type"] == "file"]
        yield path, sorted(dirs), sorted(files)
        for d in sorted(dirs):
            sub = f"{path.rstrip('/')}/{d}"
            async for x in self.walk(sub):
                yield x
