"""MDS daemon: filesystem metadata service over RADOS.

A compressed rendering of src/mds:

  * Dirfrag storage: one metadata-pool object per directory
    (``dir.<ino:016x>``), dentries in its omap with the inode EMBEDDED
    in the primary dentry -- exactly Ceph's on-disk choice
    (CDir/CDentry/CInode, src/mds/CDir.cc commit path).
  * Every mutation journals an event first (journal.py; MDLog::submit),
    then applies write-through to the dirfrag omap; replay re-applies
    the crash window idempotently.
  * Client RPC over the messenger mirrors Server::handle_client_request
    (src/mds/Server.cc:2520): path-resolve, mutate, reply with the
    dentry/inode.  File DATA never touches the MDS -- clients stripe
    it straight to the data pool (the layout rides in the inode), the
    defining CephFS data path split.
  * Mon-owned FSMap (src/mon/MDSMonitor.cc): every MDS beacons the
    monitor; the LEADER assigns the active rank and promotes a standby
    when the active's beacons lapse.  An MDS only activates when the
    FSMap names it -- the journal cls_lock remains as the WRITE FENCE
    (the blocklist analog: a deposed active whose lease lapsed cannot
    append), so membership is mon-decided and split-brain is
    lock-fenced.
  * Client capabilities with lease expiry (src/mds/Locker.cc
    compressed to two cap modes): "r" holders may read and cache, the
    single "w" holder may write data and buffer size updates.  A
    conflicting open REVOKES: holders flush dirty state and release;
    a dead client's caps lapse with its lease so revocation cannot
    hang.  Data-path fencing of a revoked-but-alive client across MDS
    failover (the OSD blocklist) is out of scope and noted here.
  * unlink purges file data through the striper after the journal
    commits (PurgeQueue analog).
"""

from __future__ import annotations

import asyncio
import json
import time

from ..client.rados import IoCtx, Rados, RadosError
from ..client.striper import Layout, RadosStriper
from ..msg import Message, Messenger
from .journal import Journal

ROOT_INO = 1
MDSMAP_OID = "mds_map"
INOTABLE_OID = "mds_inotable"
LOCK_NAME = "mds_active"
LOCK_DURATION = 6.0
LOCK_RENEW = 2.0
TRIM_EVERY = 64
BEACON_INTERVAL = 1.0
BEACON_GRACE = 8.0
CAP_LEASE = 8.0
RECONNECT_GRACE = 6.0      # failover window for cap holders to show
                           # up (> two client renewal periods, so a
                           # healthy client always makes the window)

DEFAULT_LAYOUT = {"su": 1 << 22, "sc": 1, "os": 1 << 22}


def dir_oid(ino: int) -> str:
    return f"dir.{ino:016x}"


def _now() -> float:
    return time.time()


class MDS:
    def __init__(self, name: str = "a",
                 meta_pool: str = "cephfs_metadata",
                 data_pool: str = "cephfs_data") -> None:
        self.name = name
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.msgr = Messenger(f"mds.{name}")
        self.rados: Rados | None = None
        self.meta: IoCtx | None = None
        self.data: IoCtx | None = None
        self.journal: Journal | None = None
        self.state = "standby"
        self.addr: tuple[str, int] | None = None
        self._tasks: list[asyncio.Task] = []
        self._next_ino = ROOT_INO + 1
        self._events_since_trim = 0
        self._lock = asyncio.Lock()       # one mutation at a time
        # reqid -> reply: lets a client safely RESEND a mutation whose
        # reply was lost (mkdir retried after an MDS death must not
        # surface EEXIST).  Rebuilt from the journal window on replay,
        # so dedup survives failover for as long as the pg-log-style
        # trim window (the reference replays its session table)
        self._completed: dict[str, dict] = {}
        self._stopped = False
        # sessions + capabilities (SessionMap/Locker compressed):
        # caps[ino][client] = {"mode": "r"|"w", "expires": t}
        self.sessions: dict[str, dict] = {}
        self.caps: dict[int, dict[str, dict]] = {}
        # a second concurrent revoker must get its OWN event; a single
        # slot would let one overwrite the other's and strand it for
        # the full lease (round-4 advisor finding)
        self._revoke_acks: dict[tuple[int, str],
                                list[asyncio.Event]] = {}
        # journaled write-cap holders (client -> {"iid", "inos"}):
        # replayed at failover so the new active can FENCE holders
        # that do not reconnect (the reference's reconnect phase +
        # session-table blocklist, mds/Server.cc reconnect)
        self._wcap_log: dict[str, dict] = {}
        self._reconnected: set[str] = set()
        self.mon_addr: tuple[str, int] | None = None
        self.msgr.add_dispatcher(self._dispatch)

    # -- lifecycle ----------------------------------------------------------
    async def start(self, mon_addr: tuple[str, int],
                    create_pools: bool = True) -> tuple[str, int]:
        self.mon_addr = tuple(mon_addr)
        self.rados = await Rados(mon_addr, name=f"mds.{self.name}"
                                 ).connect()
        pools = await self.rados.pool_list()
        if create_pools:
            for p in (self.meta_pool, self.data_pool):
                if p not in pools:
                    await self.rados.pool_create(p, pg_num=8)
        self.meta = await self.rados.open_ioctx(self.meta_pool)
        self.data = await self.rados.open_ioctx(self.data_pool)
        self.journal = Journal(self.meta)
        self.addr = await self.msgr.bind()
        t = asyncio.ensure_future(self._standby_loop())
        self._tasks.append(t)
        return self.addr

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        if self.state == "active":
            try:
                await self.meta.exec(MDSMAP_OID, "lock", "unlock",
                                     json.dumps({"name": LOCK_NAME,
                                                 "cookie": self.name}
                                                ).encode())
            except (RadosError, ConnectionError, OSError):
                pass
        await self.msgr.shutdown()
        if self.rados:
            await self.rados.shutdown()

    # -- beacons / FSMap-gated activation ------------------------------------
    async def _send_beacon(self) -> dict | None:
        """One MMDSBeacon to the mon; returns the ack (or None)."""
        q: asyncio.Queue = asyncio.Queue()

        async def d(conn, msg):
            if msg.type == "mds_beacon_ack":
                await q.put(msg.data)
        self.msgr.add_dispatcher(d)
        try:
            await self.msgr.send(self.mon_addr, "mon.0", Message(
                "mds_beacon", {"name": self.name,
                               "addr": list(self.addr),
                               "state": self.state}))
            return await asyncio.wait_for(q.get(), 3.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return None
        finally:
            self.msgr.dispatchers.remove(d)

    async def _standby_loop(self) -> None:
        """Beacon the mon; activate only when the FSMap names us.

        The mon owns MEMBERSHIP (who should be active); the journal
        cls_lock stays as the write FENCE -- a daemon the map deposed
        while its lease was still live simply waits the lease out."""
        try:
            while not self._stopped:
                ack = await self._send_beacon()
                if (ack is None or ack.get("you") != "active"):
                    await asyncio.sleep(BEACON_INTERVAL)
                    continue
                # the FSMap names us active: take the journal fence
                try:
                    await self.meta.exec(
                        MDSMAP_OID, "lock", "lock", json.dumps({
                            "name": LOCK_NAME, "type": "exclusive",
                            "cookie": self.name,
                            "duration": LOCK_DURATION,
                            "flags": 1}).encode())
                except RadosError:
                    await asyncio.sleep(1.0)
                    continue
                await self._become_active()
                loop = asyncio.get_event_loop()
                last_renew = loop.time()
                last_ack = loop.time()
                while not self._stopped:      # renewal + beacon loop
                    await asyncio.sleep(LOCK_RENEW)
                    ack = await self._send_beacon()
                    if ack is not None:
                        last_ack = loop.time()
                        if ack.get("you") == "standby":
                            # the mon deposed us (fsmap changed): stop
                            # serving NOW; the journal lease fences
                            # stale appends until it lapses
                            self.state = "standby"
                            break
                    elif loop.time() - last_ack > BEACON_GRACE:
                        # mon unreachable past the grace: the mon has
                        # (or will have) promoted a standby -- serving
                        # on while renewing the lock would block that
                        # standby forever.  Demote and stop renewing.
                        self.state = "standby"
                        break
                    try:
                        await self.meta.exec(
                            MDSMAP_OID, "lock", "lock", json.dumps({
                                "name": LOCK_NAME, "type": "exclusive",
                                "cookie": self.name,
                                "duration": LOCK_DURATION,
                                "flags": 1}).encode())
                        last_renew = asyncio.get_event_loop().time()
                    except (RadosError, ConnectionError, OSError) as e:
                        # losing the lock means a standby may be (or
                        # become) active: serving on is split-brain.
                        # EBUSY = someone else holds it: demote NOW;
                        # transient errors demote once the lease the
                        # peer waits out has certainly lapsed.
                        held_for = (asyncio.get_event_loop().time()
                                    - last_renew)
                        if (getattr(e, "errno_name", "") == "EBUSY"
                                or held_for > LOCK_DURATION):
                            self.state = "standby"
                            break
        except asyncio.CancelledError:
            pass

    async def _renew_lock(self) -> None:
        await self.meta.exec(MDSMAP_OID, "lock", "lock", json.dumps({
            "name": LOCK_NAME, "type": "exclusive",
            "cookie": self.name, "duration": LOCK_DURATION,
            "flags": 1}).encode())

    async def _become_active(self) -> None:
        await self.journal.load()
        n = 0
        loop = asyncio.get_event_loop()
        last_renew = loop.time()
        async for ev in self.journal.replay():   # crash-window replay
            await self._apply_event(ev, replay=True)
            if ev.get("reqid"):
                self._remember(ev["reqid"], ev.get("reply", {}))
            n += 1
            # a long replay must not outlive the activation lease, or
            # the standby wins the expired lock mid-replay (split-brain)
            if n % 16 == 0 and loop.time() - last_renew > LOCK_RENEW:
                await self._renew_lock()
                last_renew = loop.time()
        await self.journal.trim()
        await self._load_inotable()
        # ensure the root dirfrag exists
        try:
            await self.meta.stat(dir_oid(ROOT_INO))
        except RadosError:
            await self.meta.write_full(dir_oid(ROOT_INO), b"")
        await self.meta.set_omap(MDSMAP_OID, {
            "addr": json.dumps(list(self.addr)).encode(),
            "name": self.name.encode(),
            "epoch": str(int(_now())).encode()})
        # reconnect-or-fence BEFORE serving: stale write-cap holders
        # from the previous active must be blocklisted first, and the
        # survivors' custody re-journaled (replay trimmed the old
        # records away)
        await self._reconnect_and_fence()
        for client, ent in self._wcap_log.items():
            for ino in ent["inos"]:
                try:
                    await self.journal.append(
                        {"op": "cap_grant_w", "client": client,
                         "ino": ino, "iid": ent["iid"]})
                except RadosError:
                    pass
        self.state = "active"

    async def _load_inotable(self) -> None:
        try:
            omap = await self.meta.get_omap(INOTABLE_OID)
            self._next_ino = int(omap.get("next_ino",
                                          str(ROOT_INO + 1).encode()))
        except RadosError:
            self._next_ino = ROOT_INO + 1

    async def _alloc_ino(self) -> int:
        ino = self._next_ino
        self._next_ino += 1
        await self.meta.set_omap(INOTABLE_OID, {
            "next_ino": str(self._next_ino).encode()})
        return ino

    # -- dirfrag access -----------------------------------------------------
    async def _dentries(self, ino: int) -> dict[str, dict]:
        try:
            omap = await self.meta.get_omap(dir_oid(ino))
        except RadosError:
            return {}
        return {k: json.loads(v) for k, v in omap.items()}

    async def _lookup_dentry(self, ino: int, name: str) -> dict | None:
        d = await self._dentries(ino)
        return d.get(name)

    async def _resolve_inos(self, path: str) -> list[int]:
        """Directory-ino chain from root down to (and including) the
        path's directory components -- the ancestor set a rename must
        check against."""
        parts = [p for p in path.split("/") if p]
        chain = [ROOT_INO]
        ino = ROOT_INO
        for name in parts[:-1]:
            child = await self._lookup_dentry(ino, name)
            if child is None or child["type"] != "dir":
                raise FsOpError("ENOENT", path)
            ino = child["ino"]
            chain.append(ino)
        return chain

    async def _resolve(self, path: str,
                       want_parent: bool = False):
        """Walk the path from root. Returns (ino, dentry|None) or, with
        want_parent, (parent_ino, leaf_name, dentry|None)."""
        parts = [p for p in path.split("/") if p]
        ino = ROOT_INO
        dent = {"ino": ROOT_INO, "type": "dir", "mode": 0o755}
        for i, name in enumerate(parts):
            last = i == len(parts) - 1
            if dent["type"] != "dir":
                raise FsOpError("ENOTDIR", "/".join(parts[:i]))
            child = await self._lookup_dentry(ino, name)
            if last and want_parent:
                return ino, name, child
            if child is None:
                raise FsOpError("ENOENT", "/".join(parts[:i + 1]))
            ino, dent = child["ino"], child
        if want_parent:
            if not parts:
                raise FsOpError("EINVAL", "root has no parent")
            return None                    # unreachable
        return ino, dent

    # -- journal + apply ----------------------------------------------------
    async def _journal_and_apply(self, ev: dict,
                                 reqid: str | None = None,
                                 reply: dict | None = None) -> None:
        if reqid is not None:
            ev = {**ev, "reqid": reqid, "reply": reply or {}}
        await self.journal.append(ev)
        await self._apply_event(ev)
        if reqid is not None:
            self._remember(reqid, reply or {})
        self._events_since_trim += 1
        if self._events_since_trim >= TRIM_EVERY:
            # write-through: everything journaled is already applied
            self._events_since_trim = 0
            await self.journal.trim()
            # trim discarded the write-cap custody records; re-journal
            # them or a failover successor cannot fence pre-trim
            # holders
            for client, ent in list(self._wcap_log.items()):
                for ino in ent["inos"]:
                    await self.journal.append(
                        {"op": "cap_grant_w", "client": client,
                         "ino": ino, "iid": ent["iid"]})

    def _remember(self, reqid: str, reply: dict) -> None:
        self._completed[reqid] = reply
        while len(self._completed) > 4096:
            self._completed.pop(next(iter(self._completed)))

    async def _apply_event(self, ev: dict, replay: bool = False) -> None:
        op = ev["op"]
        if op in ("cap_grant_w", "cap_release_w"):
            # write-cap custody records: replayed so a failover
            # successor knows whom to reconnect-or-fence
            self._apply_wcap(op, ev["client"], ev["ino"], ev["iid"])
            return
        if op == "link":
            await self.meta.set_omap(dir_oid(ev["dir"]), {
                ev["name"]: json.dumps(ev["dentry"]).encode()})
            if ev["dentry"]["type"] == "dir" and ev.get("mkdir"):
                try:
                    await self.meta.stat(dir_oid(ev["dentry"]["ino"]))
                except RadosError:
                    await self.meta.write_full(
                        dir_oid(ev["dentry"]["ino"]), b"")
        elif op == "unlink":
            try:
                await self.meta.rm_omap_keys(dir_oid(ev["dir"]),
                                             [ev["name"]])
            except RadosError:
                pass
            if ev.get("rmdir_ino"):
                try:
                    await self.meta.remove(dir_oid(ev["rmdir_ino"]))
                except RadosError:
                    pass
            if ev.get("purge"):
                # purge rides the event so a crash between journal
                # commit and data removal re-purges on replay (the
                # reference's PurgeQueue is durable for the same reason)
                await self._purge_file(ev["purge"])
        elif op == "rename":
            # one event, two dirfrag updates: replay makes the pair
            # atomic-on-crash (EMetaBlob touching two dirs)
            await self.meta.set_omap(dir_oid(ev["dst_dir"]), {
                ev["dst_name"]: json.dumps(ev["dentry"]).encode()})
            if (ev["src_dir"], ev["src_name"]) != (ev["dst_dir"],
                                                  ev["dst_name"]):
                try:
                    await self.meta.rm_omap_keys(dir_oid(ev["src_dir"]),
                                                 [ev["src_name"]])
                except RadosError:
                    pass
            if ev.get("rmdir_ino"):       # dir replaced by the rename
                try:
                    await self.meta.remove(dir_oid(ev["rmdir_ino"]))
                except RadosError:
                    pass
            if ev.get("purge"):           # file replaced by the rename
                await self._purge_file(ev["purge"])
        elif op == "setattr":
            dent = await self._lookup_dentry(ev["dir"], ev["name"])
            if dent is not None and (replay is False
                                     or dent["ino"] == ev["ino"]):
                dent.update(ev["attrs"])
                await self.meta.set_omap(dir_oid(ev["dir"]), {
                    ev["name"]: json.dumps(dent).encode()})

    # -- purge (PurgeQueue) --------------------------------------------------
    async def _purge_file(self, dent: dict) -> None:
        lay = dent.get("layout", DEFAULT_LAYOUT)
        striper = RadosStriper(self.data, Layout(
            stripe_unit=lay["su"], stripe_count=lay["sc"],
            object_size=lay["os"]))
        try:
            await striper.remove(f"{dent['ino']:x}")
        except RadosError:
            pass

    # -- capabilities (Locker.cc compressed) ---------------------------------
    def _prune_caps(self, ino: int) -> dict[str, dict]:
        now = _now()
        holders = self.caps.get(ino, {})
        for client in [c for c, cap in holders.items()
                       if cap["expires"] < now]:
            holders.pop(client)
        if not holders:
            self.caps.pop(ino, None)
        return self.caps.get(ino, {})

    def _client_iid(self, client: str) -> str:
        """The client INSTANCE id ("name:incarnation") as it appears
        in the reqids its Objecter stamps on OSD ops -- the unit the
        OSDMap blocklist fences."""
        inst = self.msgr._session_inst.get(client)
        return f"{client}:{inst}" if inst else client

    async def _fence_client(self, client: str) -> bool:
        """Blocklist the client instance at the DATA path: a revoked-
        but-alive client that lost its lease can still have in-flight
        OSD writes; the OSDs must refuse them before the cap can be
        handed to someone else (OSDMonitor blocklist; closes the
        round-4 'caps don't fence the data path' gap).  Returns
        whether the fence actually landed -- a cap must NOT be
        re-granted on a failed fence."""
        iid = self._client_iid(client)
        for _ in range(3):
            try:
                await self.rados.mon_command(
                    "osd blocklist", {"id": iid, "duration": 600})
                return True
            except Exception:
                await asyncio.sleep(0.2)
        return False

    async def _revoke_cap(self, ino: int, client: str) -> None:
        """Ask ``client`` to flush + release its cap on ``ino``; waits
        for the release ack or the cap's lease expiry, whichever comes
        first (a dead client cannot wedge the grant).  A holder that
        NEVER acks is fenced at the OSDs before the cap is freed."""
        cap = self.caps.get(ino, {}).get(client)
        sess = self.sessions.get(client)
        if cap is None:
            return
        ev = asyncio.Event()
        self._revoke_acks.setdefault((ino, client), []).append(ev)
        deadline = _now() + max(0.1, cap["expires"] - _now())
        acked = False
        try:
            # RE-SEND the revoke while waiting: one lost message must
            # not escalate a healthy client into a 600s blocklist
            while _now() < deadline:
                sess = self.sessions.get(client)
                if sess is not None and sess.get("conn") is not None:
                    try:
                        await sess["conn"].send(Message(
                            "cap_revoke", {"ino": ino,
                                           "mode": cap["mode"]}))
                    except (ConnectionError, OSError):
                        pass
                try:
                    await asyncio.wait_for(
                        ev.wait(), min(1.0, max(0.05,
                                                deadline - _now())))
                    acked = True
                    break
                except asyncio.TimeoutError:
                    continue
            if not acked and cap["mode"] == "w":
                # lease lapsed with no release ack: the holder may be
                # wedged with dirty data in flight -- fence it.  If
                # the fence cannot land, the cap must not be freed
                # (the opener gets EAGAIN rather than a second writer)
                if not await self._fence_client(client):
                    raise FsOpError(
                        "EAGAIN", "cannot fence stale cap holder")
        finally:
            lst = self._revoke_acks.get((ino, client))
            if lst is not None:
                if ev in lst:
                    lst.remove(ev)
                if not lst:
                    self._revoke_acks.pop((ino, client), None)
        if self.caps.get(ino, {}).pop(client, None) is not None \
                and cap["mode"] == "w":
            await self._journal_wcap("cap_release_w", ino, client)

    async def _acquire_caps(self, ino: int, client: str,
                            want: str) -> str:
        """Grant ``want`` ("r" or "w") on ``ino`` to ``client``,
        revoking conflicting holders first: one writer XOR many
        readers (the Fr/Fw subset of the cap lattice).  Conflicts are
        RECOMPUTED after every awaited revoke: a second opener may
        have been granted while we waited, and granting on a stale
        snapshot would seat two writers (round-4 advisor finding)."""
        while True:
            holders = self._prune_caps(ino)
            if want == "w":
                conflicts = [c for c in holders if c != client]
            else:
                conflicts = [c for c, cap in holders.items()
                             if c != client and cap["mode"] == "w"]
            if not conflicts:
                break
            await self._revoke_cap(ino, conflicts[0])
        if want == "w":
            await self._journal_wcap("cap_grant_w", ino, client)
        self.caps.setdefault(ino, {})[client] = {
            "mode": want, "expires": _now() + CAP_LEASE}
        return want

    async def _journal_wcap(self, etype: str, ino: int,
                            client: str) -> None:
        """Durably record write-cap custody so a FAILOVER successor
        knows which client instances may still have writes in flight
        (the reference journals its session/cap tables)."""
        self._apply_wcap(etype, client, ino, self._client_iid(client))
        if self.journal is not None and self.state == "active":
            try:
                await self.journal.append(
                    {"op": etype, "client": client, "ino": ino,
                     "iid": self._client_iid(client)})
            except RadosError:
                pass

    def _apply_wcap(self, etype: str, client: str, ino: int,
                    iid: str) -> None:
        if etype == "cap_grant_w":
            ent = self._wcap_log.setdefault(
                client, {"iid": iid, "inos": set()})
            ent["iid"] = iid
            ent["inos"].add(ino)
        else:
            ent = self._wcap_log.get(client)
            if ent is not None:
                ent["inos"].discard(ino)
                if not ent["inos"]:
                    self._wcap_log.pop(client, None)

    async def _reconnect_and_fence(self) -> None:
        """Failover reconnect phase: write-cap holders replayed from
        the journal get a grace window to show up at the NEW active;
        the silent ones are blocklisted before we serve (a deposed
        client's delayed writes must not land on data someone else
        now holds the cap for).  Survivors get their caps RE-SEATED,
        so a later conflicting open revokes them like any holder."""
        if not self._wcap_log:
            return
        # only contacts DURING the window count: entries from a
        # previous tenure of this daemon (mds flap) must not spare a
        # holder that is in fact wedged
        self._reconnected.clear()
        deadline = _now() + RECONNECT_GRACE
        last_renew = _now()
        while _now() < deadline and \
                set(self._wcap_log) - self._reconnected:
            await asyncio.sleep(0.05)
            if _now() - last_renew > LOCK_RENEW:
                # the window must not outlive the journal fence or the
                # mon's beacon grace: a silent wait here would seat a
                # SECOND active (the split-brain the lock prevents)
                last_renew = _now()
                await self._renew_lock()
                await self._send_beacon()
        for client, ent in list(self._wcap_log.items()):
            if client in self._reconnected:
                # survivor: re-seat its write caps so the next
                # conflicting open goes through revoke, not a silent
                # double-grant
                for ino in ent["inos"]:
                    self.caps.setdefault(ino, {})[client] = {
                        "mode": "w", "expires": _now() + CAP_LEASE}
                continue
            try:
                await self.rados.mon_command(
                    "osd blocklist", {"id": ent["iid"],
                                      "duration": 600})
            except Exception:
                pass
            self._wcap_log.pop(client, None)

    def _renew_session(self, client: str) -> None:
        now = _now()
        for holders in self.caps.values():
            cap = holders.get(client)
            if cap is not None and cap["expires"] >= now:
                cap["expires"] = now + CAP_LEASE
        sess = self.sessions.get(client)
        if sess is not None:
            sess["renewed"] = now

    # -- client RPC ----------------------------------------------------------
    async def _dispatch(self, conn, msg: Message) -> None:
        client = msg.from_name
        self._reconnected.add(client)   # counts toward the failover
        #                                 reconnect window
        if msg.type == "cap_release":
            ino = msg.data["ino"]
            cap = self.caps.get(ino, {}).pop(client, None)
            for ev in self._revoke_acks.get((ino, client), []):
                ev.set()
            if cap is not None and cap["mode"] == "w":
                await self._journal_wcap("cap_release_w", ino, client)
            return
        if msg.type == "session_renew":
            self._renew_session(client)
            try:
                await conn.send(Message("session_renew_ack", {}))
            except (ConnectionError, OSError):
                pass
            return
        if msg.type != "mds_request":
            return
        self.sessions[client] = {"conn": conn, "renewed": _now()}
        try:
            if self.state != "active":
                out = {"err": "EAGAIN", "detail": "mds not active"}
            else:
                out = await self._handle(msg.data, client)
        except FsOpError as e:
            out = {"err": e.errno_name, "detail": e.detail}
        except (RadosError, asyncio.TimeoutError) as e:
            out = {"err": "EIO", "detail": str(e)}
        try:
            await conn.send(Message("mds_reply",
                                    {"tid": msg.data.get("tid"), **out}))
        except (ConnectionError, OSError):
            pass

    async def _handle(self, q: dict, client: str = "") -> dict:
        op = q["op"]
        path = q.get("path", "/")
        if op in ("mkdir", "create", "unlink", "rmdir", "rename",
                  "setattr"):
            async with self._lock:
                reqid = q.get("reqid")
                if reqid and reqid in self._completed:
                    # lost-reply resend: acknowledge, don't re-apply
                    return dict(self._completed[reqid])
                out = await self._handle_mutation(op, path, q)
                return out
        if op == "lookup" or op == "stat":
            if path.strip("/") == "":
                return {"dentry": {"ino": ROOT_INO, "type": "dir",
                                   "mode": 0o755}}
            _, dent = await self._resolve(path)
            return {"dentry": dent}
        if op == "readdir":
            if path.strip("/") == "":
                ino = ROOT_INO
            else:
                ino, dent = await self._resolve(path)
                if dent["type"] != "dir":
                    raise FsOpError("ENOTDIR", path)
            return {"entries": await self._dentries(ino)}
        if op == "open":
            want = q.get("want", "r")
            parent, name, dent = await self._resolve(path,
                                                     want_parent=True)
            if dent is None:
                if not q.get("create"):
                    raise FsOpError("ENOENT", path)
                async with self._lock:
                    out = await self._handle_mutation("create", path, q)
            else:
                if dent["type"] == "dir":
                    raise FsOpError("EISDIR", path)
                out = {"dentry": dent, "parent": parent, "name": name}
            # cap grant OUTSIDE the mutation lock: the revoked client's
            # flush is itself a locked mutation (setattr) and must be
            # able to land while we wait for its release
            granted = await self._acquire_caps(
                out["dentry"]["ino"], client, want)
            # re-read: the flush may have grown the size we hand out
            parent2, name2, dent2 = await self._resolve(
                path, want_parent=True)
            if dent2 is not None:
                out["dentry"] = dent2
            out["caps"] = granted
            out["lease_s"] = CAP_LEASE
            return out
        raise FsOpError("EOPNOTSUPP", op)

    async def _handle_mutation(self, op: str, path: str,
                               q: dict) -> dict:
        reqid = q.get("reqid")
        if op in ("mkdir", "create"):
            parent, name, existing = await self._resolve(
                path, want_parent=True)
            if existing is not None:
                if op == "create" and q.get("create") \
                        and existing["type"] == "file" \
                        and not q.get("excl"):
                    return {"dentry": existing, "parent": parent,
                            "name": name, "caps": "pAsLsXsFsrw"}
                raise FsOpError("EEXIST", path)
            ino = await self._alloc_ino()
            dent = {"ino": ino,
                    "type": "dir" if op == "mkdir" else "file",
                    "mode": q.get("mode",
                                  0o755 if op == "mkdir" else 0o644),
                    "size": 0, "mtime": _now(),
                    "ctime": _now()}
            if op == "create":
                dent["layout"] = q.get("layout", DEFAULT_LAYOUT)
            reply = {"dentry": dent, "parent": parent, "name": name,
                     "caps": "pAsLsXsFsrw"}
            await self._journal_and_apply({
                "op": "link", "dir": parent, "name": name,
                "dentry": dent, "mkdir": op == "mkdir"}, reqid, reply)
            return reply
        if op in ("unlink", "rmdir"):
            parent, name, dent = await self._resolve(path,
                                                     want_parent=True)
            if dent is None:
                raise FsOpError("ENOENT", path)
            if op == "rmdir":
                if dent["type"] != "dir":
                    raise FsOpError("ENOTDIR", path)
                if await self._dentries(dent["ino"]):
                    raise FsOpError("ENOTEMPTY", path)
            elif dent["type"] == "dir":
                raise FsOpError("EISDIR", path)
            await self._journal_and_apply({
                "op": "unlink", "dir": parent, "name": name,
                "rmdir_ino": dent["ino"] if op == "rmdir" else 0,
                "purge": dent if op == "unlink" else None},
                reqid, {})
            return {}
        if op == "rename":
            src_parent, src_name, dent = await self._resolve(
                path, want_parent=True)
            if dent is None:
                raise FsOpError("ENOENT", path)
            dst_parent, dst_name, dst_dent = await self._resolve(
                q["dst"], want_parent=True)
            if dst_dent is not None and dst_dent["ino"] == dent["ino"]:
                # rename onto itself: POSIX no-op (rename(2)); anything
                # else would purge the file's own data as "replaced"
                return {"dentry": dent}
            if dent["type"] == "dir":
                # a directory must not move into its own subtree: the
                # dirfrag would link to itself and the subtree would
                # drop out of the namespace forever
                if dent["ino"] in await self._resolve_inos(q["dst"]):
                    raise FsOpError("EINVAL",
                                    "cannot move a directory into "
                                    "its own subtree")
            if dst_dent is not None:
                if dst_dent["type"] == "dir":
                    if dent["type"] != "dir":
                        raise FsOpError("EISDIR", q["dst"])
                    if await self._dentries(dst_dent["ino"]):
                        raise FsOpError("ENOTEMPTY", q["dst"])
                elif dent["type"] == "dir":
                    raise FsOpError("ENOTDIR", q["dst"])
            replaced_dir = (dst_dent["ino"]
                            if dst_dent and dst_dent["type"] == "dir"
                            else 0)
            replaced_file = (dst_dent
                             if dst_dent and dst_dent["type"] == "file"
                             else None)
            await self._journal_and_apply({
                "op": "rename", "src_dir": src_parent,
                "src_name": src_name, "dst_dir": dst_parent,
                "dst_name": dst_name, "dentry": dent,
                "rmdir_ino": replaced_dir, "purge": replaced_file},
                reqid, {"dentry": dent})
            return {"dentry": dent}
        if op == "setattr":
            parent, name, dent = await self._resolve(path,
                                                     want_parent=True)
            if dent is None:
                raise FsOpError("ENOENT", path)
            attrs = {k: v for k, v in q.get("attrs", {}).items()
                     if k in ("size", "mode", "mtime")}
            attrs["ctime"] = _now()
            dent.update(attrs)
            await self._journal_and_apply({
                "op": "setattr", "dir": parent, "name": name,
                "ino": dent["ino"], "attrs": attrs},
                reqid, {"dentry": dent})
            return {"dentry": dent}
        raise FsOpError("EOPNOTSUPP", op)


class FsOpError(Exception):
    def __init__(self, errno_name: str, detail: str = "") -> None:
        super().__init__(f"{errno_name}: {detail}")
        self.errno_name = errno_name
        self.detail = detail
