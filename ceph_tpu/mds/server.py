"""MDS daemon: filesystem metadata service over RADOS.

A compressed rendering of src/mds:

  * Dirfrag storage: one metadata-pool object per directory
    (``dir.<ino:016x>``), dentries in its omap with the inode EMBEDDED
    in the primary dentry -- exactly Ceph's on-disk choice
    (CDir/CDentry/CInode, src/mds/CDir.cc commit path).
  * Every mutation journals an event first (journal.py; MDLog::submit),
    then applies write-through to the dirfrag omap; replay re-applies
    the crash window idempotently.
  * Client RPC over the messenger mirrors Server::handle_client_request
    (src/mds/Server.cc:2520): path-resolve, mutate, reply with the
    dentry/inode.  File DATA never touches the MDS -- clients stripe
    it straight to the data pool (the layout rides in the inode), the
    defining CephFS data path split.
  * Mon-owned FSMap (src/mon/MDSMonitor.cc): every MDS beacons the
    monitor; the LEADER assigns the active rank and promotes a standby
    when the active's beacons lapse.  An MDS only activates when the
    FSMap names it -- the journal cls_lock remains as the WRITE FENCE
    (the blocklist analog: a deposed active whose lease lapsed cannot
    append), so membership is mon-decided and split-brain is
    lock-fenced.
  * Client capabilities with lease expiry (src/mds/Locker.cc
    compressed to two cap modes): "r" holders may read and cache, the
    single "w" holder may write data and buffer size updates.  A
    conflicting open REVOKES: holders flush dirty state and release;
    a dead client's caps lapse with its lease so revocation cannot
    hang; a revoked-but-alive client that never acks is FENCED at the
    data path via the OSDMap blocklist, and failover runs a
    reconnect-or-fence window over journaled write-cap custody.
  * Directory snapshots (SnapServer/snaprealm compressed): a subtree
    freeze captured as a manifest + pool self-managed snap id;
    ".snap/<name>" paths resolve the frozen view; writers under a
    snapped realm stamp the realm snapc so OSDs COW; rmsnap feeds the
    OSD snap-trim machinery.
  * unlink purges file data through the striper after the journal
    commits (PurgeQueue analog).
"""

from __future__ import annotations

import asyncio
import json
import time

from ..client.rados import IoCtx, Rados, RadosError
from ..client.striper import Layout, RadosStriper
from ..msg import Message, Messenger
from .journal import Journal

ROOT_INO = 1
MDSMAP_OID = "mds_map"
SNAPDIRS_OID = "mds_snapdirs"
INOTABLE_OID = "mds_inotable"
LOCK_NAME = "mds_active"
LOCK_DURATION = 6.0
LOCK_RENEW = 2.0
TRIM_EVERY = 64
BEACON_INTERVAL = 1.0
BEACON_GRACE = 8.0
CAP_LEASE = 8.0
RECONNECT_GRACE = 6.0      # failover window for cap holders to show
                           # up (> two client renewal periods, so a
                           # healthy client always makes the window)

DEFAULT_LAYOUT = {"su": 1 << 22, "sc": 1, "os": 1 << 22}


def dir_oid(ino: int) -> str:
    return f"dir.{ino:016x}"


def _now() -> float:
    return time.time()


class MDS:
    def __init__(self, name: str = "a",
                 meta_pool: str = "cephfs_metadata",
                 data_pool: str = "cephfs_data",
                 cephx_key: str | None = None) -> None:
        self.name = name
        # cephx: the MDS's own entity key -- its embedded rados client
        # must hold OSD tickets when the cluster enforces them
        self.cephx_key = cephx_key
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.msgr = Messenger(f"mds.{name}")
        self.rados: Rados | None = None
        self.meta: IoCtx | None = None
        self.data: IoCtx | None = None
        self.journal: Journal | None = None
        self.state = "standby"
        self.addr: tuple[str, int] | None = None
        self._tasks: list[asyncio.Task] = []
        self._next_ino = ROOT_INO + 1
        self._events_since_trim = 0
        self._lock = asyncio.Lock()       # one mutation at a time
        # reqid -> reply: lets a client safely RESEND a mutation whose
        # reply was lost (mkdir retried after an MDS death must not
        # surface EEXIST).  Rebuilt from the journal window on replay,
        # so dedup survives failover for as long as the pg-log-style
        # trim window (the reference replays its session table)
        self._completed: dict[str, dict] = {}
        self._stopped = False
        # sessions + capabilities (SessionMap/Locker compressed):
        # caps[ino][client] = {"mode": "r"|"w", "expires": t}
        self.sessions: dict[str, dict] = {}
        self.caps: dict[int, dict[str, dict]] = {}
        # a second concurrent revoker must get its OWN event; a single
        # slot would let one overwrite the other's and strand it for
        # the full lease (round-4 advisor finding)
        self._revoke_acks: dict[tuple[int, str],
                                list[asyncio.Event]] = {}
        # journaled write-cap holders (client -> {"iid", "inos"}):
        # replayed at failover so the new active can FENCE holders
        # that do not reconnect (the reference's reconnect phase +
        # session-table blocklist, mds/Server.cc reconnect)
        self._wcap_log: dict[str, dict] = {}
        self._reconnected: set[str] = set()
        # dirs that have snapshots (ino set, persisted in SNAPDIRS_OID
        # omap): lets the open hot path skip realm-snapc computation
        # entirely when the filesystem has no snapshots
        self._snapped_dirs: set[int] = set()
        self._snap_ids: set[int] = set()
        # serializes mksnap's revoke->allocate->freeze sequence against
        # write-cap grants: an open racing that window would get a
        # snapc without the new id and overwrite frozen data
        self._snap_barrier = asyncio.Lock()
        self.mon_addr: tuple[str, int] | None = None
        self.msgr.add_dispatcher(self._dispatch)

    # -- lifecycle ----------------------------------------------------------
    async def start(self, mon_addr: tuple[str, int],
                    create_pools: bool = True) -> tuple[str, int]:
        self.mon_addr = tuple(mon_addr)
        self.rados = await Rados(mon_addr, name=f"mds.{self.name}"
                                 ).connect()
        if self.cephx_key:
            await self.rados.authenticate(f"mds.{self.name}",
                                          self.cephx_key)
        pools = await self.rados.pool_list()
        if create_pools:
            for p in (self.meta_pool, self.data_pool):
                if p not in pools:
                    await self.rados.pool_create(p, pg_num=8)
        self.meta = await self.rados.open_ioctx(self.meta_pool)
        self.data = await self.rados.open_ioctx(self.data_pool)
        self.journal = Journal(self.meta)
        self.addr = await self.msgr.bind()
        t = asyncio.ensure_future(self._standby_loop())
        self._tasks.append(t)
        return self.addr

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        if self.state == "active":
            try:
                await self.meta.exec(MDSMAP_OID, "lock", "unlock",
                                     json.dumps({"name": LOCK_NAME,
                                                 "cookie": self.name}
                                                ).encode())
            except (RadosError, ConnectionError, OSError):
                pass
        await self.msgr.shutdown()
        if self.rados:
            await self.rados.shutdown()

    # -- beacons / FSMap-gated activation ------------------------------------
    async def _send_beacon(self) -> dict | None:
        """One MMDSBeacon to the mon; returns the ack (or None)."""
        q: asyncio.Queue = asyncio.Queue()

        async def d(conn, msg):
            if msg.type == "mds_beacon_ack":
                await q.put(msg.data)
        self.msgr.add_dispatcher(d)
        try:
            await self.msgr.send(self.mon_addr, "mon.0", Message(
                "mds_beacon", {"name": self.name,
                               "addr": list(self.addr),
                               "state": self.state}))
            return await asyncio.wait_for(q.get(), 3.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            return None
        finally:
            self.msgr.dispatchers.remove(d)

    async def _standby_loop(self) -> None:
        """Beacon the mon; activate only when the FSMap names us.

        The mon owns MEMBERSHIP (who should be active); the journal
        cls_lock stays as the write FENCE -- a daemon the map deposed
        while its lease was still live simply waits the lease out."""
        try:
            while not self._stopped:
                ack = await self._send_beacon()
                if (ack is None or ack.get("you") != "active"):
                    await asyncio.sleep(BEACON_INTERVAL)
                    continue
                # the FSMap names us active: take the journal fence
                try:
                    await self.meta.exec(
                        MDSMAP_OID, "lock", "lock", json.dumps({
                            "name": LOCK_NAME, "type": "exclusive",
                            "cookie": self.name,
                            "duration": LOCK_DURATION,
                            "flags": 1}).encode())
                except RadosError:
                    await asyncio.sleep(1.0)
                    continue
                await self._become_active()
                loop = asyncio.get_event_loop()
                last_renew = loop.time()
                last_ack = loop.time()
                while not self._stopped:      # renewal + beacon loop
                    await asyncio.sleep(LOCK_RENEW)
                    ack = await self._send_beacon()
                    if ack is not None:
                        last_ack = loop.time()
                        if ack.get("you") == "standby":
                            # the mon deposed us (fsmap changed): stop
                            # serving NOW; the journal lease fences
                            # stale appends until it lapses
                            self.state = "standby"
                            break
                    elif loop.time() - last_ack > BEACON_GRACE:
                        # mon unreachable past the grace: the mon has
                        # (or will have) promoted a standby -- serving
                        # on while renewing the lock would block that
                        # standby forever.  Demote and stop renewing.
                        self.state = "standby"
                        break
                    try:
                        await self.meta.exec(
                            MDSMAP_OID, "lock", "lock", json.dumps({
                                "name": LOCK_NAME, "type": "exclusive",
                                "cookie": self.name,
                                "duration": LOCK_DURATION,
                                "flags": 1}).encode())
                        last_renew = asyncio.get_event_loop().time()
                    except (RadosError, ConnectionError, OSError) as e:
                        # losing the lock means a standby may be (or
                        # become) active: serving on is split-brain.
                        # EBUSY = someone else holds it: demote NOW;
                        # transient errors demote once the lease the
                        # peer waits out has certainly lapsed.
                        held_for = (asyncio.get_event_loop().time()
                                    - last_renew)
                        if (getattr(e, "errno_name", "") == "EBUSY"
                                or held_for > LOCK_DURATION):
                            self.state = "standby"
                            break
        except asyncio.CancelledError:
            pass

    async def _renew_lock(self) -> None:
        await self.meta.exec(MDSMAP_OID, "lock", "lock", json.dumps({
            "name": LOCK_NAME, "type": "exclusive",
            "cookie": self.name, "duration": LOCK_DURATION,
            "flags": 1}).encode())

    async def _become_active(self) -> None:
        await self.journal.load()
        n = 0
        loop = asyncio.get_event_loop()
        last_renew = loop.time()
        async for ev in self.journal.replay():   # crash-window replay
            await self._apply_event(ev, replay=True)
            if ev.get("reqid"):
                self._remember(ev["reqid"], ev.get("reply", {}))
            n += 1
            # a long replay must not outlive the activation lease, or
            # the standby wins the expired lock mid-replay (split-brain)
            if n % 16 == 0 and loop.time() - last_renew > LOCK_RENEW:
                await self._renew_lock()
                last_renew = loop.time()
        await self.journal.trim()
        await self._load_inotable()
        try:
            snapdirs = await self.meta.get_omap(SNAPDIRS_OID)
        except RadosError:
            snapdirs = {}
        self._snap_ids = {int(k) for k in snapdirs}
        self._snapped_dirs = {json.loads(v)["dir"]
                              for v in snapdirs.values()}
        # ensure the root dirfrag exists
        try:
            await self.meta.stat(dir_oid(ROOT_INO))
        except RadosError:
            await self.meta.write_full(dir_oid(ROOT_INO), b"")
        await self.meta.set_omap(MDSMAP_OID, {
            "addr": json.dumps(list(self.addr)).encode(),
            "name": self.name.encode(),
            "epoch": str(int(_now())).encode()})
        # reconnect-or-fence BEFORE serving: stale write-cap holders
        # from the previous active must be blocklisted first, and the
        # survivors' custody re-journaled (replay trimmed the old
        # records away)
        await self._reconnect_and_fence()
        for client, ent in self._wcap_log.items():
            for ino in ent["inos"]:
                try:
                    await self.journal.append(
                        {"op": "cap_grant_w", "client": client,
                         "ino": ino, "iid": ent["iid"]})
                except RadosError:
                    pass
        self.state = "active"

    async def _load_inotable(self) -> None:
        try:
            omap = await self.meta.get_omap(INOTABLE_OID)
            self._next_ino = int(omap.get("next_ino",
                                          str(ROOT_INO + 1).encode()))
        except RadosError:
            self._next_ino = ROOT_INO + 1

    async def _alloc_ino(self) -> int:
        ino = self._next_ino
        self._next_ino += 1
        await self.meta.set_omap(INOTABLE_OID, {
            "next_ino": str(self._next_ino).encode()})
        return ino

    # -- dirfrag access -----------------------------------------------------
    async def _dentries(self, ino: int) -> dict[str, dict]:
        try:
            omap = await self.meta.get_omap(dir_oid(ino))
        except RadosError:
            return {}
        # "snap:*" keys are the directory's snapshot table (snaprealm
        # sidecar), not dentries
        return {k: json.loads(v) for k, v in omap.items()
                if not k.startswith("snap:")}

    async def _lookup_dentry(self, ino: int, name: str) -> dict | None:
        d = await self._dentries(ino)
        return d.get(name)

    async def _resolve_inos(self, path: str) -> list[int]:
        """Directory-ino chain from root down to (and including) the
        path's directory components -- the ancestor set a rename must
        check against."""
        parts = [p for p in path.split("/") if p]
        chain = [ROOT_INO]
        ino = ROOT_INO
        for name in parts[:-1]:
            child = await self._lookup_dentry(ino, name)
            if child is None or child["type"] != "dir":
                raise FsOpError("ENOENT", path)
            ino = child["ino"]
            chain.append(ino)
        return chain

    # -- snapshots (SnapServer / snaprealms compressed) ----------------------
    #
    # A directory snapshot (mkdir .snap/<name> in the reference,
    # src/mds/SnapServer.h + doc/dev/cephfs-snapshots.rst) freezes the
    # SUBTREE: the namespace is captured as a manifest object written
    # at snap time (relpath -> dentry, sizes post cap-flush), and file
    # DATA rides the pool's self-managed snap machinery -- writers
    # under a snapped realm stamp a snapc that makes the OSDs COW, and
    # ".snap/<name>/..." reads resolve through the manifest and read
    # data objects at the snap id.  rmsnap releases the pool snap id,
    # which the existing OSD snap-trim reclaims.

    def _snap_manifest_oid(self, ino: int, sid: int) -> str:
        return f"snapmanifest.{ino:x}.{sid}"

    async def _snap_table(self, ino: int) -> dict[str, int]:
        try:
            omap = await self.meta.get_omap(dir_oid(ino))
        except RadosError:
            return {}
        return {k[len("snap:"):]: json.loads(v)["id"]
                for k, v in omap.items() if k.startswith("snap:")}

    async def _subtree_walk(self, ino: int,
                            prefix: str = "") -> dict[str, dict]:
        """relpath -> dentry for everything under a directory."""
        out: dict[str, dict] = {}
        for name, dent in (await self._dentries(ino)).items():
            rel = f"{prefix}{name}"
            out[rel] = dent
            if dent.get("type") == "dir":
                out.update(await self._subtree_walk(dent["ino"],
                                                    rel + "/"))
        return out

    async def _realm_snapc(self, path: str) -> dict | None:
        """The snap context writes under ``path`` must stamp.

        CONSERVATIVE: every live snapshot id in the filesystem (the
        in-memory registry loaded at activation).  Precise per-realm
        sets would need parent pointers the dirfrag schema does not
        keep; the superset only costs spurious COW clones on files
        outside the realm, which trim with the snap -- while being
        O(1) on the open hot path and correct across MDS restarts
        (it never depends on accumulated ioctx state)."""
        if not self._snap_ids:
            return None
        snaps = sorted(self._snap_ids, reverse=True)
        return {"seq": snaps[0], "snaps": snaps}

    async def _resolve_snap(self, parts: list[str], i: int):
        """Handle a '.snap' path component: parts[i] == '.snap' under
        the directory chain parts[:i].  Returns (dentry, sid) of the
        frozen view -- or raises."""
        dir_ino, dir_dent = await self._resolve(
            "/".join(parts[:i]) or "/")
        if dir_dent["type"] != "dir":
            raise FsOpError("ENOTDIR", "/".join(parts[:i]))
        table = await self._snap_table(dir_ino)
        if i + 1 >= len(parts):
            # ".snap" itself: a pseudo-dir listing snapshot names
            return ({"ino": dir_ino, "type": "snapdir",
                     "snaps": sorted(table)}, None)
        snapname = parts[i + 1]
        sid = table.get(snapname)
        if sid is None:
            raise FsOpError("ENOENT", f".snap/{snapname}")
        try:
            raw = await self.meta.read(
                self._snap_manifest_oid(dir_ino, sid))
        except RadosError:
            # table entry journaled but manifest gone/in-flight
            raise FsOpError("EAGAIN", f".snap/{snapname} not ready")
        manifest = json.loads(raw)["dentries"]
        rest = parts[i + 2:]
        if not rest:
            dent = {"ino": dir_ino, "type": "dir", "mode": 0o755}
        else:
            dent = manifest.get("/".join(rest))
            if dent is None:
                raise FsOpError("ENOENT", "/".join(parts))
        return ({**dent, "snapid": sid, "manifest_dir": dir_ino,
                 "_manifest": manifest}, sid)

    async def _resolve(self, path: str,
                       want_parent: bool = False):
        """Walk the path from root. Returns (ino, dentry|None) or, with
        want_parent, (parent_ino, leaf_name, dentry|None)."""
        parts = [p for p in path.split("/") if p]
        if ".snap" in parts:
            if want_parent:
                raise FsOpError("EROFS", "snapshots are read-only")
            dent, _sid = await self._resolve_snap(
                parts, parts.index(".snap"))
            dent = {k: v for k, v in dent.items() if k != "_manifest"}
            return dent["ino"], dent
        ino = ROOT_INO
        dent = {"ino": ROOT_INO, "type": "dir", "mode": 0o755}
        for i, name in enumerate(parts):
            last = i == len(parts) - 1
            if dent["type"] != "dir":
                raise FsOpError("ENOTDIR", "/".join(parts[:i]))
            child = await self._lookup_dentry(ino, name)
            if last and want_parent:
                return ino, name, child
            if child is None:
                raise FsOpError("ENOENT", "/".join(parts[:i + 1]))
            ino, dent = child["ino"], child
        if want_parent:
            if not parts:
                raise FsOpError("EINVAL", "root has no parent")
            return None                    # unreachable
        return ino, dent

    # -- journal + apply ----------------------------------------------------
    async def _journal_and_apply(self, ev: dict,
                                 reqid: str | None = None,
                                 reply: dict | None = None) -> None:
        if reqid is not None:
            ev = {**ev, "reqid": reqid, "reply": reply or {}}
        await self.journal.append(ev)
        await self._apply_event(ev)
        if reqid is not None:
            self._remember(reqid, reply or {})
        self._events_since_trim += 1
        if self._events_since_trim >= TRIM_EVERY:
            # write-through: everything journaled is already applied
            self._events_since_trim = 0
            await self.journal.trim()
            # trim discarded the write-cap custody records; re-journal
            # them or a failover successor cannot fence pre-trim
            # holders
            for client, ent in list(self._wcap_log.items()):
                for ino in ent["inos"]:
                    await self.journal.append(
                        {"op": "cap_grant_w", "client": client,
                         "ino": ino, "iid": ent["iid"]})

    def _remember(self, reqid: str, reply: dict) -> None:
        self._completed[reqid] = reply
        while len(self._completed) > 4096:
            self._completed.pop(next(iter(self._completed)))

    async def _apply_event(self, ev: dict, replay: bool = False) -> None:
        op = ev["op"]
        if op in ("cap_grant_w", "cap_release_w"):
            # write-cap custody records: replayed so a failover
            # successor knows whom to reconnect-or-fence
            self._apply_wcap(op, ev["client"], ev["ino"], ev["iid"])
            return
        if op == "mksnap":
            await self.meta.set_omap(dir_oid(ev["dir"]), {
                f"snap:{ev['name']}": json.dumps(
                    {"id": ev["sid"]}).encode()})
            await self.meta.set_omap(SNAPDIRS_OID, {
                str(ev["sid"]): json.dumps(
                    {"dir": ev["dir"],
                     "name": ev["name"]}).encode()})
            self._snap_ids.add(ev["sid"])
            self._snapped_dirs.add(ev["dir"])
            return
        if op == "rmsnap":
            try:
                await self.meta.rm_omap_keys(
                    dir_oid(ev["dir"]), [f"snap:{ev['name']}"])
                await self.meta.rm_omap_keys(SNAPDIRS_OID,
                                             [str(ev["sid"])])
            except RadosError:
                pass
            self._snap_ids.discard(ev["sid"])
            return
        if op == "link":
            await self.meta.set_omap(dir_oid(ev["dir"]), {
                ev["name"]: json.dumps(ev["dentry"]).encode()})
            if ev["dentry"]["type"] == "dir" and ev.get("mkdir"):
                try:
                    await self.meta.stat(dir_oid(ev["dentry"]["ino"]))
                except RadosError:
                    await self.meta.write_full(
                        dir_oid(ev["dentry"]["ino"]), b"")
        elif op == "unlink":
            try:
                await self.meta.rm_omap_keys(dir_oid(ev["dir"]),
                                             [ev["name"]])
            except RadosError:
                pass
            if ev.get("rmdir_ino"):
                try:
                    await self.meta.remove(dir_oid(ev["rmdir_ino"]))
                except RadosError:
                    pass
            if ev.get("purge"):
                # purge rides the event so a crash between journal
                # commit and data removal re-purges on replay (the
                # reference's PurgeQueue is durable for the same reason)
                await self._purge_file(ev["purge"])
        elif op == "rename":
            # one event, two dirfrag updates: replay makes the pair
            # atomic-on-crash (EMetaBlob touching two dirs)
            await self.meta.set_omap(dir_oid(ev["dst_dir"]), {
                ev["dst_name"]: json.dumps(ev["dentry"]).encode()})
            if (ev["src_dir"], ev["src_name"]) != (ev["dst_dir"],
                                                  ev["dst_name"]):
                try:
                    await self.meta.rm_omap_keys(dir_oid(ev["src_dir"]),
                                                 [ev["src_name"]])
                except RadosError:
                    pass
            if ev.get("rmdir_ino"):       # dir replaced by the rename
                try:
                    await self.meta.remove(dir_oid(ev["rmdir_ino"]))
                except RadosError:
                    pass
            if ev.get("purge"):           # file replaced by the rename
                await self._purge_file(ev["purge"])
        elif op == "setattr":
            dent = await self._lookup_dentry(ev["dir"], ev["name"])
            if dent is not None and (replay is False
                                     or dent["ino"] == ev["ino"]):
                dent.update(ev["attrs"])
                await self.meta.set_omap(dir_oid(ev["dir"]), {
                    ev["name"]: json.dumps(dent).encode()})

    # -- purge (PurgeQueue) --------------------------------------------------
    async def _purge_file(self, dent: dict,
                          path: str = "/") -> None:
        lay = dent.get("layout", DEFAULT_LAYOUT)
        dio = self.data
        snapc = await self._realm_snapc(path)
        if snapc is not None:
            # the remove must stamp the realm's snapc so the OSD COWs
            # the head into the snap clones instead of deleting the
            # only copy -- and it must not depend on whatever snapc
            # happens to be folded into self.data (an MDS restart
            # starts with a clean ioctx while the realm persists)
            dio = IoCtx(self.rados, self.data.pool_name,
                        self.data.pool_id)
            dio.set_snap_context(snapc["seq"], snapc["snaps"])
        striper = RadosStriper(dio, Layout(
            stripe_unit=lay["su"], stripe_count=lay["sc"],
            object_size=lay["os"]))
        try:
            await striper.remove(f"{dent['ino']:x}")
        except RadosError:
            pass

    # -- capabilities (Locker.cc compressed) ---------------------------------
    def _prune_caps(self, ino: int) -> dict[str, dict]:
        now = _now()
        holders = self.caps.get(ino, {})
        for client in [c for c, cap in holders.items()
                       if cap["expires"] < now]:
            holders.pop(client)
        if not holders:
            self.caps.pop(ino, None)
        return self.caps.get(ino, {})

    def _client_iid(self, client: str) -> str:
        """The client INSTANCE id ("name:incarnation") as it appears
        in the reqids its Objecter stamps on OSD ops -- the unit the
        OSDMap blocklist fences."""
        inst = self.msgr._session_inst.get(client)
        return f"{client}:{inst}" if inst else client

    async def _fence_client(self, client: str) -> bool:
        """Blocklist the client instance at the DATA path: a revoked-
        but-alive client that lost its lease can still have in-flight
        OSD writes; the OSDs must refuse them before the cap can be
        handed to someone else (OSDMonitor blocklist; closes the
        round-4 'caps don't fence the data path' gap).  Returns
        whether the fence actually landed -- a cap must NOT be
        re-granted on a failed fence."""
        iid = self._client_iid(client)
        for _ in range(3):
            try:
                await self.rados.mon_command(
                    "osd blocklist", {"id": iid, "duration": 600})
                return True
            except Exception:
                await asyncio.sleep(0.2)
        return False

    async def _revoke_cap(self, ino: int, client: str) -> None:
        """Ask ``client`` to flush + release its cap on ``ino``; waits
        for the release ack or the cap's lease expiry, whichever comes
        first (a dead client cannot wedge the grant).  A holder that
        NEVER acks is fenced at the OSDs before the cap is freed."""
        cap = self.caps.get(ino, {}).get(client)
        sess = self.sessions.get(client)
        if cap is None:
            return
        ev = asyncio.Event()
        self._revoke_acks.setdefault((ino, client), []).append(ev)
        deadline = _now() + max(0.1, cap["expires"] - _now())
        acked = False
        try:
            # RE-SEND the revoke while waiting: one lost message must
            # not escalate a healthy client into a 600s blocklist
            while _now() < deadline:
                sess = self.sessions.get(client)
                if sess is not None and sess.get("conn") is not None:
                    try:
                        await sess["conn"].send(Message(
                            "cap_revoke", {"ino": ino,
                                           "mode": cap["mode"]}))
                    except (ConnectionError, OSError):
                        pass
                try:
                    await asyncio.wait_for(
                        ev.wait(), min(1.0, max(0.05,
                                                deadline - _now())))
                    acked = True
                    break
                except asyncio.TimeoutError:
                    continue
            if not acked and cap["mode"] == "w":
                # lease lapsed with no release ack: the holder may be
                # wedged with dirty data in flight -- fence it.  If
                # the fence cannot land, the cap must not be freed
                # (the opener gets EAGAIN rather than a second writer)
                if not await self._fence_client(client):
                    raise FsOpError(
                        "EAGAIN", "cannot fence stale cap holder")
        finally:
            lst = self._revoke_acks.get((ino, client))
            if lst is not None:
                if ev in lst:
                    lst.remove(ev)
                if not lst:
                    self._revoke_acks.pop((ino, client), None)
        if self.caps.get(ino, {}).pop(client, None) is not None \
                and cap["mode"] == "w":
            await self._journal_wcap("cap_release_w", ino, client)

    async def _acquire_caps(self, ino: int, client: str,
                            want: str) -> str:
        """Grant ``want`` ("r" or "w") on ``ino`` to ``client``,
        revoking conflicting holders first: one writer XOR many
        readers (the Fr/Fw subset of the cap lattice).  Conflicts are
        RECOMPUTED after every awaited revoke: a second opener may
        have been granted while we waited, and granting on a stale
        snapshot would seat two writers (round-4 advisor finding)."""
        while True:
            holders = self._prune_caps(ino)
            if want == "w":
                conflicts = [c for c in holders if c != client]
            else:
                conflicts = [c for c, cap in holders.items()
                             if c != client and cap["mode"] == "w"]
            if not conflicts:
                break
            await self._revoke_cap(ino, conflicts[0])
        if want == "w":
            await self._journal_wcap("cap_grant_w", ino, client)
        self.caps.setdefault(ino, {})[client] = {
            "mode": want, "expires": _now() + CAP_LEASE}
        return want

    async def _journal_wcap(self, etype: str, ino: int,
                            client: str) -> None:
        """Durably record write-cap custody so a FAILOVER successor
        knows which client instances may still have writes in flight
        (the reference journals its session/cap tables)."""
        self._apply_wcap(etype, client, ino, self._client_iid(client))
        if self.journal is not None and self.state == "active":
            try:
                await self.journal.append(
                    {"op": etype, "client": client, "ino": ino,
                     "iid": self._client_iid(client)})
            except RadosError:
                pass

    def _apply_wcap(self, etype: str, client: str, ino: int,
                    iid: str) -> None:
        if etype == "cap_grant_w":
            ent = self._wcap_log.setdefault(
                client, {"iid": iid, "inos": set()})
            ent["iid"] = iid
            ent["inos"].add(ino)
        else:
            ent = self._wcap_log.get(client)
            if ent is not None:
                ent["inos"].discard(ino)
                if not ent["inos"]:
                    self._wcap_log.pop(client, None)

    async def _reconnect_and_fence(self) -> None:
        """Failover reconnect phase: write-cap holders replayed from
        the journal get a grace window to show up at the NEW active;
        the silent ones are blocklisted before we serve (a deposed
        client's delayed writes must not land on data someone else
        now holds the cap for).  Survivors get their caps RE-SEATED,
        so a later conflicting open revokes them like any holder."""
        if not self._wcap_log:
            return
        # only contacts DURING the window count: entries from a
        # previous tenure of this daemon (mds flap) must not spare a
        # holder that is in fact wedged
        self._reconnected.clear()
        deadline = _now() + RECONNECT_GRACE
        last_renew = _now()
        while _now() < deadline and \
                set(self._wcap_log) - self._reconnected:
            await asyncio.sleep(0.05)
            if _now() - last_renew > LOCK_RENEW:
                # the window must not outlive the journal fence or the
                # mon's beacon grace: a silent wait here would seat a
                # SECOND active (the split-brain the lock prevents)
                last_renew = _now()
                await self._renew_lock()
                await self._send_beacon()
        for client, ent in list(self._wcap_log.items()):
            if client in self._reconnected:
                # survivor: re-seat its write caps so the next
                # conflicting open goes through revoke, not a silent
                # double-grant
                for ino in ent["inos"]:
                    self.caps.setdefault(ino, {})[client] = {
                        "mode": "w", "expires": _now() + CAP_LEASE}
                continue
            try:
                await self.rados.mon_command(
                    "osd blocklist", {"id": ent["iid"],
                                      "duration": 600})
            except Exception:
                pass
            self._wcap_log.pop(client, None)

    def _renew_session(self, client: str) -> None:
        now = _now()
        for holders in self.caps.values():
            cap = holders.get(client)
            if cap is not None and cap["expires"] >= now:
                cap["expires"] = now + CAP_LEASE
        sess = self.sessions.get(client)
        if sess is not None:
            sess["renewed"] = now

    # -- client RPC ----------------------------------------------------------
    async def _dispatch(self, conn, msg: Message) -> None:
        client = msg.from_name
        self._reconnected.add(client)   # counts toward the failover
        #                                 reconnect window
        if msg.type == "cap_release":
            ino = msg.data["ino"]
            cap = self.caps.get(ino, {}).pop(client, None)
            for ev in self._revoke_acks.get((ino, client), []):
                ev.set()
            if cap is not None and cap["mode"] == "w":
                await self._journal_wcap("cap_release_w", ino, client)
            return
        if msg.type == "session_renew":
            self._renew_session(client)
            try:
                await conn.send(Message("session_renew_ack", {}))
            except (ConnectionError, OSError):
                pass
            return
        if msg.type != "mds_request":
            return
        self.sessions[client] = {"conn": conn, "renewed": _now()}
        try:
            if self.state != "active":
                out = {"err": "EAGAIN", "detail": "mds not active"}
            else:
                out = await self._handle(msg.data, client)
        except FsOpError as e:
            out = {"err": e.errno_name, "detail": e.detail}
        except (RadosError, asyncio.TimeoutError) as e:
            out = {"err": "EIO", "detail": str(e)}
        try:
            await conn.send(Message("mds_reply",
                                    {"tid": msg.data.get("tid"), **out}))
        except (ConnectionError, OSError):
            pass

    async def _handle(self, q: dict, client: str = "") -> dict:
        op = q["op"]
        path = q.get("path", "/")
        leaf = path.rstrip("/").rsplit("/", 1)[-1]
        if op in ("mkdir", "create", "open", "rename") and (
                leaf.startswith("snap:")
                or (op == "rename" and str(q.get("dst", ""))
                    .rstrip("/").rsplit("/", 1)[-1]
                    .startswith("snap:"))):
            # "snap:*" omap keys are the snaprealm table; a dentry with
            # that name would shadow it
            raise FsOpError("EINVAL", "'snap:' names are reserved")
        if op in ("mkdir", "create", "unlink", "rmdir", "rename",
                  "setattr"):
            async with self._lock:
                reqid = q.get("reqid")
                if reqid and reqid in self._completed:
                    # lost-reply resend: acknowledge, don't re-apply
                    return dict(self._completed[reqid])
                out = await self._handle_mutation(op, path, q)
                return out
        if op == "lookup" or op == "stat":
            if path.strip("/") == "":
                return {"dentry": {"ino": ROOT_INO, "type": "dir",
                                   "mode": 0o755}}
            _, dent = await self._resolve(path)
            return {"dentry": dent}
        if op == "readdir":
            parts = [p for p in path.split("/") if p]
            if ".snap" in parts:
                i = parts.index(".snap")
                dent, sid = await self._resolve_snap(parts, i)
                if dent.get("type") == "snapdir":
                    return {"entries": {
                        n: {"type": "dir", "ino": dent["ino"]}
                        for n in dent["snaps"]}}
                if dent.get("type") != "dir":
                    raise FsOpError("ENOTDIR", path)
                manifest = dent["_manifest"]
                rel = "/".join(parts[i + 2:])
                pref = rel + "/" if rel else ""
                return {"entries": {
                    k[len(pref):]: v for k, v in manifest.items()
                    if k.startswith(pref)
                    and "/" not in k[len(pref):]}}
            if path.strip("/") == "":
                ino = ROOT_INO
            else:
                ino, dent = await self._resolve(path)
                if dent["type"] != "dir":
                    raise FsOpError("ENOTDIR", path)
            return {"entries": await self._dentries(ino)}
        if op == "mksnap":
            # NOT under the mutation lock: revocation waits for the
            # holders' flushes, which are themselves locked mutations
            # (same reason open's cap grant sits outside the lock).
            # reqid dedup: a resend of a slow mksnap (revokes can take
            # a full lease) must ack, not re-execute into EEXIST
            reqid = q.get("reqid")
            if reqid and reqid in self._completed:
                return dict(self._completed[reqid])
            out = await self._handle_mksnap(path, q["name"])
            if reqid:
                self._remember(reqid, out)
            return out
        if op == "rmsnap":
            reqid = q.get("reqid")
            if reqid and reqid in self._completed:
                return dict(self._completed[reqid])
            out = await self._handle_rmsnap(path, q["name"])
            if reqid:
                self._remember(reqid, out)
            return out
        if op == "lssnap":
            ino, dent = await self._resolve(path)
            if dent["type"] != "dir":
                raise FsOpError("ENOTDIR", path)
            return {"snaps": await self._snap_table(ino)}
        if op == "open":
            want = q.get("want", "r")
            if ".snap" in path.split("/"):
                if want != "r":
                    raise FsOpError("EROFS", "snapshots are read-only")
                _ino, dent = await self._resolve(path)
                if dent.get("type") == "dir" \
                        or dent.get("type") == "snapdir":
                    raise FsOpError("EISDIR", path)
                return {"dentry": dent, "caps": "r",
                        "snapid": dent.get("snapid"),
                        "lease_s": CAP_LEASE}
            parent, name, dent = await self._resolve(path,
                                                     want_parent=True)
            if dent is None:
                if not q.get("create"):
                    raise FsOpError("ENOENT", path)
                async with self._lock:
                    out = await self._handle_mutation("create", path, q)
            else:
                if dent["type"] == "dir":
                    raise FsOpError("EISDIR", path)
                out = {"dentry": dent, "parent": parent, "name": name}
            # cap grant OUTSIDE the mutation lock: the revoked client's
            # flush is itself a locked mutation (setattr) and must be
            # able to land while we wait for its release.  Write
            # grants serialize with mksnap's freeze window (see
            # _snap_barrier) so the snapc handed out always includes
            # any snapshot being taken right now
            if want == "w":
                async with self._snap_barrier:
                    granted = await self._acquire_caps(
                        out["dentry"]["ino"], client, want)
                    snapc = await self._realm_snapc(path)
            else:
                granted = await self._acquire_caps(
                    out["dentry"]["ino"], client, want)
                snapc = await self._realm_snapc(path)
            # re-read: the flush may have grown the size we hand out
            parent2, name2, dent2 = await self._resolve(
                path, want_parent=True)
            if dent2 is not None:
                out["dentry"] = dent2
            out["caps"] = granted
            out["lease_s"] = CAP_LEASE
            if snapc is not None:
                # writes under a snapped realm must stamp this snapc
                # so the OSDs COW pre-snap data (snaprealm -> client
                # cap message carries the context in the reference)
                out["snapc"] = snapc
            return out
        raise FsOpError("EOPNOTSUPP", op)

    async def _handle_mksnap(self, path: str, name: str) -> dict:
        """mkdir .snap/<name>: freeze the subtree.  Write caps under
        it are revoked first (holders flush), the data pool allocates
        the snap id, and the post-flush namespace is captured as the
        manifest (SnapServer::prepare + the snaprealm split,
        compressed)."""
        ino, dent = await self._resolve(path)
        if dent["type"] != "dir":
            raise FsOpError("ENOTDIR", path)
        if name in await self._snap_table(ino):
            raise FsOpError("EEXIST", f".snap/{name}")
        # the barrier fences write-cap GRANTS for the whole
        # revoke->allocate->freeze sequence: an open slipping between
        # the revokes and the journaled table entry would write with a
        # snapc that lacks the new id, overwriting frozen data
        async with self._snap_barrier:
            subtree = await self._subtree_walk(ino)
            for rel, d in subtree.items():
                if d.get("type") != "dir":
                    holders = list(self.caps.get(d["ino"], {}))
                    for client in holders:
                        await self._revoke_cap(d["ino"], client)
            sid = await self.data.selfmanaged_snap_create()
            subtree = await self._subtree_walk(ino)  # post-flush sizes
            await self.meta.write_full(
                self._snap_manifest_oid(ino, sid),
                json.dumps({"dentries": subtree}).encode())
            async with self._lock:
                if name in await self._snap_table(ino):
                    # lost a race: release everything this attempt
                    # allocated (snap id, manifest) before failing
                    try:
                        await self.meta.remove(
                            self._snap_manifest_oid(ino, sid))
                    except RadosError:
                        pass
                    await self.data.selfmanaged_snap_remove(sid)
                    raise FsOpError("EEXIST", f".snap/{name}")
                await self._journal_and_apply(
                    {"op": "mksnap", "dir": ino,
                     "name": name, "sid": sid})
        return {"snapid": sid}

    async def _handle_rmsnap(self, path: str, name: str) -> dict:
        ino, dent = await self._resolve(path)
        table = await self._snap_table(ino)
        sid = table.get(name)
        if sid is None:
            raise FsOpError("ENOENT", f".snap/{name}")
        async with self._lock:
            await self._journal_and_apply({"op": "rmsnap", "dir": ino,
                                           "name": name, "sid": sid})
        try:
            await self.meta.remove(self._snap_manifest_oid(ino, sid))
        except RadosError:
            pass
        # release the pool snap id: the OSDs' snap-trim machinery
        # reclaims the clones (pg_pool_t removed_snaps path)
        await self.data.selfmanaged_snap_remove(sid)
        return {"snapid": sid}

    async def _handle_mutation(self, op: str, path: str,
                               q: dict) -> dict:
        reqid = q.get("reqid")
        if op in ("mkdir", "create"):
            parent, name, existing = await self._resolve(
                path, want_parent=True)
            if existing is not None:
                if op == "create" and q.get("create") \
                        and existing["type"] == "file" \
                        and not q.get("excl"):
                    return {"dentry": existing, "parent": parent,
                            "name": name, "caps": "pAsLsXsFsrw"}
                raise FsOpError("EEXIST", path)
            ino = await self._alloc_ino()
            dent = {"ino": ino,
                    "type": "dir" if op == "mkdir" else "file",
                    "mode": q.get("mode",
                                  0o755 if op == "mkdir" else 0o644),
                    "size": 0, "mtime": _now(),
                    "ctime": _now()}
            if op == "create":
                dent["layout"] = q.get("layout", DEFAULT_LAYOUT)
            reply = {"dentry": dent, "parent": parent, "name": name,
                     "caps": "pAsLsXsFsrw"}
            await self._journal_and_apply({
                "op": "link", "dir": parent, "name": name,
                "dentry": dent, "mkdir": op == "mkdir"}, reqid, reply)
            return reply
        if op in ("unlink", "rmdir"):
            parent, name, dent = await self._resolve(path,
                                                     want_parent=True)
            if dent is None:
                raise FsOpError("ENOENT", path)
            if op == "rmdir":
                if dent["type"] != "dir":
                    raise FsOpError("ENOTDIR", path)
                if await self._dentries(dent["ino"]):
                    raise FsOpError("ENOTEMPTY", path)
            elif dent["type"] == "dir":
                raise FsOpError("EISDIR", path)
            await self._journal_and_apply({
                "op": "unlink", "dir": parent, "name": name,
                "rmdir_ino": dent["ino"] if op == "rmdir" else 0,
                "purge": dent if op == "unlink" else None},
                reqid, {})
            return {}
        if op == "rename":
            src_parent, src_name, dent = await self._resolve(
                path, want_parent=True)
            if dent is None:
                raise FsOpError("ENOENT", path)
            dst_parent, dst_name, dst_dent = await self._resolve(
                q["dst"], want_parent=True)
            if dst_dent is not None and dst_dent["ino"] == dent["ino"]:
                # rename onto itself: POSIX no-op (rename(2)); anything
                # else would purge the file's own data as "replaced"
                return {"dentry": dent}
            if dent["type"] == "dir":
                # a directory must not move into its own subtree: the
                # dirfrag would link to itself and the subtree would
                # drop out of the namespace forever
                if dent["ino"] in await self._resolve_inos(q["dst"]):
                    raise FsOpError("EINVAL",
                                    "cannot move a directory into "
                                    "its own subtree")
            if dst_dent is not None:
                if dst_dent["type"] == "dir":
                    if dent["type"] != "dir":
                        raise FsOpError("EISDIR", q["dst"])
                    if await self._dentries(dst_dent["ino"]):
                        raise FsOpError("ENOTEMPTY", q["dst"])
                elif dent["type"] == "dir":
                    raise FsOpError("ENOTDIR", q["dst"])
            replaced_dir = (dst_dent["ino"]
                            if dst_dent and dst_dent["type"] == "dir"
                            else 0)
            replaced_file = (dst_dent
                             if dst_dent and dst_dent["type"] == "file"
                             else None)
            await self._journal_and_apply({
                "op": "rename", "src_dir": src_parent,
                "src_name": src_name, "dst_dir": dst_parent,
                "dst_name": dst_name, "dentry": dent,
                "rmdir_ino": replaced_dir, "purge": replaced_file},
                reqid, {"dentry": dent})
            return {"dentry": dent}
        if op == "setattr":
            parent, name, dent = await self._resolve(path,
                                                     want_parent=True)
            if dent is None:
                raise FsOpError("ENOENT", path)
            attrs = {k: v for k, v in q.get("attrs", {}).items()
                     if k in ("size", "mode", "mtime")}
            attrs["ctime"] = _now()
            dent.update(attrs)
            await self._journal_and_apply({
                "op": "setattr", "dir": parent, "name": name,
                "ino": dent["ino"], "attrs": attrs},
                reqid, {"dentry": dent})
            return {"dentry": dent}
        raise FsOpError("EOPNOTSUPP", op)


class FsOpError(Exception):
    def __init__(self, errno_name: str, detail: str = "") -> None:
        super().__init__(f"{errno_name}: {detail}")
        self.errno_name = errno_name
        self.detail = detail
