"""MDS metadata journal (MDLog/Journaler analog).

The reference journals every metadata mutation as an event appended
through the Journaler (src/osdc/Journaler.cc) before touching the
backing dirfrag objects, then trims segments once the dirty metadata
is flushed (src/mds/MDLog.cc).  Here the schema is:

    mds_journal_head      omap {write_seq, trim_seq}
    mds_journal.<seg>     JSON event lines, SEG_EVENTS per segment

Events carry absolute post-state (idempotent), so replay after a
crash -- re-applying every event in (trim_seq, write_seq] -- converges
regardless of where the crash hit.  The daemon is write-through (the
dir omap update follows the journal append immediately), so the
replay window is just the crash race, and trim advances cheaply.
"""

from __future__ import annotations

import json

from ..client.rados import RadosError

HEAD_OID = "mds_journal_head"
SEG_EVENTS = 128


def _seg_oid(seg: int) -> str:
    return f"mds_journal.{seg:08x}"


class Journal:
    def __init__(self, ioctx) -> None:
        self.ioctx = ioctx
        self.write_seq = 0
        self.trim_seq = 0

    async def load(self) -> None:
        try:
            omap = await self.ioctx.get_omap(HEAD_OID)
        except RadosError:
            omap = {}
        self.write_seq = int(omap.get("write_seq", b"0"))
        self.trim_seq = int(omap.get("trim_seq", b"0"))

    async def _save_head(self) -> None:
        await self.ioctx.set_omap(HEAD_OID, {
            "write_seq": str(self.write_seq).encode(),
            "trim_seq": str(self.trim_seq).encode()})

    async def append(self, event: dict) -> int:
        """Durably journal one event; returns its seq."""
        seq = self.write_seq + 1
        line = json.dumps({"seq": seq, **event}) + "\n"
        await self.ioctx.append(_seg_oid((seq - 1) // SEG_EVENTS),
                                line.encode())
        self.write_seq = seq
        await self._save_head()
        return seq

    async def replay(self):
        """Yield every event in (trim_seq, write_seq] in order."""
        if self.write_seq <= self.trim_seq:
            return
        first_seg = self.trim_seq // SEG_EVENTS
        last_seg = (self.write_seq - 1) // SEG_EVENTS
        for seg in range(first_seg, last_seg + 1):
            try:
                raw = await self.ioctx.read(_seg_oid(seg))
            except RadosError as e:
                if e.errno_name == "ENOENT":
                    continue
                raise
            for line in raw.decode().splitlines():
                if not line.strip():
                    continue
                ev = json.loads(line)
                if self.trim_seq < ev["seq"] <= self.write_seq:
                    yield ev

    async def trim(self, upto: int | None = None) -> None:
        """Advance trim_seq and drop wholly-trimmed segments."""
        upto = self.write_seq if upto is None else upto
        if upto <= self.trim_seq:
            return
        old_first = self.trim_seq // SEG_EVENTS
        self.trim_seq = upto
        await self._save_head()
        new_first = self.trim_seq // SEG_EVENTS
        for seg in range(old_first, new_first):
            try:
                await self.ioctx.remove(_seg_oid(seg))
            except RadosError:
                pass
