"""MDS + CephFS analog: metadata daemon, journal, POSIX-ish client.

Reference: src/mds (Server.cc client RPC, MDLog/Journaler metadata
journal, MDCache dirfrag storage), src/client (libcephfs).
"""

from .server import MDS
from .client import CephFS, FsError

__all__ = ["MDS", "CephFS", "FsError"]
