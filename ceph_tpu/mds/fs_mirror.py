"""cephfs-mirror analog: directory-tree replication between clusters.

The reference's cephfs-mirror (src/tools/cephfs_mirror) replays
configured directory trees from a primary filesystem to a secondary.
This renders the same shape over the CephFS client: a sync cycle
walks the source tree, copies files whose (size, mtime) changed,
creates missing directories, and prunes entries that vanished from
the source; FsMirrorDaemon loops cycles over every configured
directory (the PeerReplayer).

Like the reference's snapshot-diff mode this is eventually-consistent
per cycle; unlike rbd-mirror no point-in-time snapshots are taken
(dir snapshots are future work), so a cycle racing writers may copy a
torn file and repair it on the next cycle.
"""

from __future__ import annotations

import asyncio

from .client import CephFS, FsError

MIRROR_DIRS_OID = "cephfs_mirror_dirs"      # metadata-pool registry


async def fs_mirror_add(meta_ioctx, path: str) -> None:
    await meta_ioctx.set_omap(MIRROR_DIRS_OID, {path: b"enabled"})


async def fs_mirror_remove(meta_ioctx, path: str) -> None:
    from ..client.rados import RadosError
    try:
        await meta_ioctx.rm_omap_keys(MIRROR_DIRS_OID, [path])
    except RadosError as e:
        if e.errno_name != "ENOENT":
            raise


async def fs_mirror_dirs(meta_ioctx) -> list[str]:
    from ..client.rados import RadosError
    try:
        return sorted((await meta_ioctx.get_omap(MIRROR_DIRS_OID)))
    except RadosError as e:
        if e.errno_name == "ENOENT":
            return []
        raise


async def _ensure_dir(fs: CephFS, path: str) -> None:
    try:
        st = await fs.stat(path)
        if st["type"] == "dir":
            return
        await fs.unlink(path)          # file shadowing a dir: replace
    except FsError as e:
        if e.errno_name != "ENOENT":
            raise
    await fs.mkdir(path)


async def fs_mirror_sync(src: CephFS, dst: CephFS,
                         root: str) -> dict:
    """One cycle for one tree; returns {copied, removed, bytes}."""
    copied = removed = nbytes = 0
    await _ensure_dir(dst, root)
    async for dirpath, dirs, files in src.walk(root):
        src_entries = await src.readdir(dirpath)
        try:
            dst_entries = await dst.readdir(dirpath)
        except FsError as e:
            if e.errno_name != "ENOENT":
                raise
            await _ensure_dir(dst, dirpath)
            dst_entries = {}
        # prune entries gone from the source (dirs depth-first via
        # recursion would be costlier; a vanished dir prunes bottom-up
        # over successive cycles, which converges)
        for name, dent in dst_entries.items():
            if name not in src_entries:
                full = f"{dirpath.rstrip('/')}/{name}"
                try:
                    if dent["type"] == "dir":
                        await dst.rmdir(full)
                    else:
                        await dst.unlink(full)
                    removed += 1
                except FsError:
                    pass               # non-empty dir: next cycle
        for name in dirs:
            await _ensure_dir(dst, f"{dirpath.rstrip('/')}/{name}")
        for name in files:
            full = f"{dirpath.rstrip('/')}/{name}"
            sd = src_entries.get(name)
            if sd is None:
                continue      # deleted between walk and this listing
            dd = dst_entries.get(name)
            if dd is not None and dd["type"] == "file" \
                    and dd.get("size") == sd.get("size") \
                    and dd.get("mtime") == sd.get("mtime"):
                continue               # unchanged
            data = await src.read_file(full)
            f = await dst.open(full, "w")
            try:
                if data:
                    await f.write(data, 0)
            finally:
                await f.close()
            # carry the source mtime so the next cycle sees it as
            # unchanged (the reference preserves attrs the same way)
            await dst._request({"op": "setattr", "path": full,
                                "attrs": {"mtime": sd.get("mtime", 0),
                                          "size": len(data)}})
            copied += 1
            nbytes += len(data)
    return {"copied": copied, "removed": removed, "bytes": nbytes}


class FsMirrorDaemon:
    """PeerReplayer: primary fs -> secondary fs, all configured dirs."""

    def __init__(self, src: CephFS, dst: CephFS,
                 interval: float = 10.0) -> None:
        self.src = src
        self.dst = dst
        self.interval = interval
        self.stats: dict[str, dict] = {}
        self._task: asyncio.Task | None = None

    async def sync_all(self) -> dict:
        dirs = await fs_mirror_dirs(self.src.meta)
        for path in dirs:
            try:
                self.stats[path] = await fs_mirror_sync(
                    self.src, self.dst, path)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 -- per-dir isolation
                self.stats[path] = {
                    "error": f"{type(e).__name__}: {e}"}
        self.stats = {k: v for k, v in self.stats.items() if k in dirs}
        return dict(self.stats)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self.sync_all()
            except asyncio.CancelledError:
                return
            except Exception as e:  # noqa: BLE001 -- keep replaying
                self.stats["_daemon_error"] = {
                    "error": f"{type(e).__name__}: {e}"}
            try:
                await asyncio.sleep(self.interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
