"""Scalar CRUSH rule engine (host oracle + control plane path).

Decision-for-decision rendering of src/crush/mapper.c: straw2 draws via the
fixed-point log (crush_ln), firstn's retry_descent/retry_bucket/reject flow
(mapper.c:441-617), indep's breadth-first stable placement
(mapper.c:636-825), and crush_do_rule_no_retry's step machine
(mapper.c:826-1032).  The vectorized TPU mapper is validated against this
module lane by lane.
"""

from __future__ import annotations

from .hashes import crush_hash32_2, crush_hash32_3, crush_hash32_4
from .ln import crush_ln, S64_MIN
from .types import (
    Bucket,
    CrushMap,
    CRUSH_BUCKET_UNIFORM,
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    CRUSH_RULE_TAKE,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE,
)


class _WorkBucket:
    __slots__ = ("perm_x", "perm_n", "perm")

    def __init__(self, size: int) -> None:
        self.perm_x = 0
        self.perm_n = 0
        self.perm = [0] * size


class CrushWork:
    """Per-invocation scratch (uniform-bucket permutation state)."""

    def __init__(self, crush_map: CrushMap) -> None:
        self.work: dict[int, _WorkBucket] = {
            bid: _WorkBucket(b.size) for bid, b in crush_map.buckets.items()
        }


def _bucket_perm_choose(bucket: Bucket, work: _WorkBucket, x: int, r: int) -> int:
    pr = r % bucket.size
    if work.perm_x != (x & 0xFFFFFFFF) or work.perm_n == 0:
        work.perm_x = x & 0xFFFFFFFF
        if pr == 0:
            s = crush_hash32_3(x, bucket.id, 0) % bucket.size
            work.perm[0] = s
            work.perm_n = 0xFFFF  # magic: see cleanup branch
            return bucket.items[s]
        work.perm = list(range(bucket.size))
        work.perm_n = 0
    elif work.perm_n == 0xFFFF:
        # clean up after the r=0 fast path
        for i in range(1, bucket.size):
            work.perm[i] = i
        work.perm[work.perm[0]] = 0
        work.perm_n = 1
    while work.perm_n <= pr:
        p = work.perm_n
        if p < bucket.size - 1:
            i = crush_hash32_3(x, bucket.id, p) % (bucket.size - p)
            if i:
                work.perm[p + i], work.perm[p] = work.perm[p], work.perm[p + i]
        work.perm_n += 1
    return bucket.items[work.perm[pr]]


def _bucket_list_choose(bucket: Bucket, x: int, r: int) -> int:
    sums = bucket._list_sum_weights
    if sums is None:
        sums = []
        acc = 0
        for w in bucket.item_weights:
            acc += w
            sums.append(acc)
        # list buckets sum front-to-back in the reference builder; choice
        # walks back-to-front comparing against sum_weights[i]
        bucket._list_sum_weights = sums
    for i in range(bucket.size - 1, -1, -1):
        w = crush_hash32_4(x, bucket.items[i], r, bucket.id)
        w &= 0xFFFF
        w = (w * sums[i]) >> 16
        if w < bucket.item_weights[i]:
            return bucket.items[i]
    return bucket.items[0]


def _tree_height(n: int) -> int:
    h = 0
    while (n & 1) == 0:
        h += 1
        n >>= 1
    return h


def _bucket_tree_choose(bucket: Bucket, x: int, r: int) -> int:
    node_weights = bucket._tree_node_weights
    if node_weights is None:
        node_weights = _build_tree_weights(bucket)
        bucket._tree_node_weights = node_weights
    num_nodes = len(node_weights)
    n = num_nodes >> 1
    while not (n & 1):
        w = node_weights[n]
        t = (crush_hash32_4(x, n, r, bucket.id) * w) >> 32
        h = _tree_height(n)
        left = n - (1 << (h - 1))
        if t < node_weights[left]:
            n = left
        else:
            n = n + (1 << (h - 1))
    return bucket.items[n >> 1]


def _build_tree_weights(bucket: Bucket) -> list[int]:
    # leaves at odd indices 1,3,5,...; interior nodes accumulate children
    depth = 1
    while (1 << depth) < bucket.size * 2:
        depth += 1
    num_nodes = 1 << depth
    w = [0] * num_nodes
    for i, wt in enumerate(bucket.item_weights):
        node = i * 2 + 1
        w[node] = wt
        # propagate up
        d = 1
        while True:
            h = _tree_height(node) if node & 1 == 0 else 0
            parent = ((node >> (d)) | 1) << (d)
            if parent >= num_nodes:
                break
            w[parent] += wt
            if parent == num_nodes >> 1:
                break
            node2 = parent
            d = _tree_height(node2) + 1
            node = node2
    return w


def _bucket_straw_choose(bucket: Bucket, x: int, r: int) -> int:
    # legacy straw: requires precomputed straws; approximate with straw2
    # draws scaled by weights is NOT identical -- we compute the original
    # scheme only when straws are provided
    high = 0
    high_draw = -1
    straws = getattr(bucket, "straws", None)
    if straws is None:
        # fall back to straw2 semantics (modern maps don't use straw)
        return _bucket_straw2_choose(bucket, x, r)
    for i in range(bucket.size):
        draw = crush_hash32_3(x, bucket.items[i], r) & 0xFFFF
        draw *= straws[i]
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _div64_s64(a: int, b: int) -> int:
    """C99 signed division (truncation toward zero)."""
    q = abs(a) // abs(b)
    if (a < 0) != (b < 0):
        q = -q
    return q


def _generate_exponential_distribution(hash_type: int, x: int, y: int, z: int,
                                       weight: int) -> int:
    u = crush_hash32_3(x, y, z) & 0xFFFF
    ln = crush_ln(u) - 0x1000000000000
    return _div64_s64(ln, weight)


def _choose_arg_weights(bucket: Bucket, arg: dict | None,
                        position: int) -> list[int]:
    """mapper.c:289 get_choose_arg_weights: the per-position weight
    set (balancer override) or the bucket's own weights."""
    if not arg or not arg.get("weight_set"):
        return bucket.item_weights
    ws = arg["weight_set"]
    return ws[min(position, len(ws) - 1)]


def _bucket_straw2_choose(bucket: Bucket, x: int, r: int,
                          arg: dict | None = None,
                          position: int = 0) -> int:
    weights = _choose_arg_weights(bucket, arg, position)
    ids = (arg.get("ids") if arg else None) or bucket.items
    high = 0
    high_draw = 0
    for i in range(bucket.size):
        w = weights[i]
        if w:
            draw = _generate_exponential_distribution(
                bucket.hash, x, ids[i], r, w)
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high = i
            high_draw = draw
    return bucket.items[high]


def _crush_bucket_choose(bucket: Bucket, work: _WorkBucket, x: int, r: int,
                         arg: dict | None = None,
                         position: int = 0) -> int:
    if bucket.size == 0:
        raise AssertionError("empty bucket")
    if bucket.alg == CRUSH_BUCKET_UNIFORM:
        return _bucket_perm_choose(bucket, work, x, r)
    if bucket.alg == CRUSH_BUCKET_LIST:
        return _bucket_list_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_TREE:
        return _bucket_tree_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW:
        return _bucket_straw_choose(bucket, x, r)
    if bucket.alg == CRUSH_BUCKET_STRAW2:
        return _bucket_straw2_choose(bucket, x, r, arg, position)
    return bucket.items[0]


def _is_out(crush_map: CrushMap, weights: list[int], item: int, x: int) -> bool:
    if item >= len(weights):
        return True
    w = weights[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (crush_hash32_2(x, item) & 0xFFFF) >= w


def _choose_firstn(
    crush_map: CrushMap, work: CrushWork, bucket: Bucket,
    weights: list[int], x: int, numrep: int, choose_type: int,
    out: list[int], outpos: int, out_size: int,
    tries: int, recurse_tries: int, local_retries: int,
    local_fallback_retries: int, recurse_to_leaf: bool,
    vary_r: int, stable: int, out2: list[int] | None, parent_r: int,
    choose_args: dict | None = None,
) -> int:
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        item = 0
        while True:  # retry_descent
            retry_descent = False
            in_bucket = bucket
            flocal = 0
            while True:  # retry_bucket
                retry_bucket = False
                collide = False
                r = rep + parent_r + ftotal
                if in_bucket.size == 0:
                    reject = True
                else:
                    if (local_fallback_retries > 0
                            and flocal >= (in_bucket.size >> 1)
                            and flocal > local_fallback_retries):
                        item = _bucket_perm_choose(
                            in_bucket, work.work[in_bucket.id], x, r)
                    else:
                        item = _crush_bucket_choose(
                            in_bucket, work.work[in_bucket.id], x, r,
                            choose_args.get(in_bucket.id)
                            if choose_args else None, outpos)
                    if item >= crush_map.max_devices:
                        skip_rep = True
                        break
                    itemtype = crush_map.item_type(item)
                    if itemtype != choose_type:
                        if item >= 0 or item not in crush_map.buckets:
                            skip_rep = True
                            break
                        in_bucket = crush_map.buckets[item]
                        retry_bucket = True
                        continue
                    for i in range(outpos):
                        if out[i] == item:
                            collide = True
                            break
                    reject = False
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = r >> (vary_r - 1) if vary_r else 0
                            if _choose_firstn(
                                crush_map, work, crush_map.buckets[item],
                                weights, x, 1 if stable else outpos + 1, 0,
                                out2, outpos, count,
                                recurse_tries, 0, local_retries,
                                local_fallback_retries, False,
                                vary_r, stable, None, sub_r,
                                choose_args,
                            ) <= outpos:
                                reject = True
                        else:
                            out2[outpos] = item
                    if not reject and not collide:
                        if itemtype == 0:
                            reject = _is_out(crush_map, weights, item, x)
                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0
                          and flocal <= in_bucket.size + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True
                if not retry_bucket:
                    break
            if not retry_descent:
                break
        if skip_rep:
            rep += 1
            continue
        out[outpos] = item
        outpos += 1
        count -= 1
        rep += 1
    return outpos


def _choose_indep(
    crush_map: CrushMap, work: CrushWork, bucket: Bucket,
    weights: list[int], x: int, left: int, numrep: int, choose_type: int,
    out: list[int], outpos: int, tries: int, recurse_tries: int,
    recurse_to_leaf: bool, out2: list[int] | None, parent_r: int,
    choose_args: dict | None = None,
) -> None:
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[rep] = CRUSH_ITEM_UNDEF
    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != CRUSH_ITEM_UNDEF:
                continue
            in_bucket = bucket
            while True:
                r = rep + parent_r
                if (in_bucket.alg == CRUSH_BUCKET_UNIFORM
                        and in_bucket.size % numrep == 0):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_bucket.size == 0:
                    break
                item = _crush_bucket_choose(
                    in_bucket, work.work[in_bucket.id], x, r,
                    choose_args.get(in_bucket.id)
                    if choose_args else None, outpos)
                if item >= crush_map.max_devices:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                itemtype = crush_map.item_type(item)
                if itemtype != choose_type:
                    if item >= 0 or item not in crush_map.buckets:
                        out[rep] = CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[rep] = CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_bucket = crush_map.buckets[item]
                    continue
                collide = False
                for i in range(outpos, endpos):
                    if out[i] == item:
                        collide = True
                        break
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        _choose_indep(
                            crush_map, work, crush_map.buckets[item],
                            weights, x, 1, numrep, 0,
                            out2, rep, recurse_tries, 0, False, None, r,
                            choose_args)
                        if out2 is not None and out2[rep] == CRUSH_ITEM_NONE:
                            break
                    elif out2 is not None:
                        out2[rep] = item
                if itemtype == 0 and _is_out(crush_map, weights, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] == CRUSH_ITEM_UNDEF:
            out[rep] = CRUSH_ITEM_NONE
        if out2 is not None and out2[rep] == CRUSH_ITEM_UNDEF:
            out2[rep] = CRUSH_ITEM_NONE


def crush_do_rule(
    crush_map: CrushMap, ruleno: int, x: int, result_max: int,
    weights: list[int], choose_args: dict | None = None,
) -> list[int]:
    """Run a rule; returns the mapped item vector (may contain NONE holes).

    ``choose_args`` (bucket id -> {"weight_set", "ids"}) overrides
    straw2 draw weights per output position -- the balancer's
    crush-compat weight-set mechanism (mapper.c crush_do_rule's
    choose_args parameter).  Defaults to the map's own choose_args."""
    if choose_args is None:
        choose_args = getattr(crush_map, "choose_args", None) or None
    rule = crush_map.rules.get(ruleno)
    if rule is None:
        return []
    t = crush_map.tunables
    work = CrushWork(crush_map)
    # "the original choose_total_tries value counted retries, not tries" --
    # add one (mapper.c:851-855)
    choose_tries = t.choose_total_tries + 1
    choose_leaf_tries = 0
    choose_local_retries = t.choose_local_tries
    choose_local_fallback_retries = t.choose_local_fallback_tries
    vary_r = t.chooseleaf_vary_r
    stable = t.chooseleaf_stable

    w: list[int] = []
    result: list[int] = []
    for step in rule.steps:
        if step.op == CRUSH_RULE_TAKE:
            if (0 <= step.arg1 < crush_map.max_devices
                    or step.arg1 in crush_map.buckets):
                w = [step.arg1]
        elif step.op == CRUSH_RULE_SET_CHOOSE_TRIES:
            if step.arg1 > 0:
                choose_tries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            if step.arg1 > 0:
                choose_leaf_tries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
            if step.arg1 >= 0:
                choose_local_retries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if step.arg1 >= 0:
                choose_local_fallback_retries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
            if step.arg1 >= 0:
                vary_r = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
            if step.arg1 >= 0:
                stable = step.arg1
        elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
                         CRUSH_RULE_CHOOSELEAF_FIRSTN,
                         CRUSH_RULE_CHOOSELEAF_INDEP):
            if not w:
                continue
            firstn = step.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                 CRUSH_RULE_CHOOSELEAF_FIRSTN)
            recurse_to_leaf = step.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                          CRUSH_RULE_CHOOSELEAF_INDEP)
            o = [0] * result_max
            c = [0] * result_max
            osize = 0
            for wi in w:
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                if wi >= 0 or wi not in crush_map.buckets:
                    continue
                bucket = crush_map.buckets[wi]
                # the reference passes o+osize / c+osize as segment bases:
                # collision scans and outpos are relative to this TAKE block
                seg = [0] * (result_max - osize)
                cseg = [0] * (result_max - osize)
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t.chooseleaf_descend_once:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    n = _choose_firstn(
                        crush_map, work, bucket, weights, x, numrep,
                        step.arg2,
                        seg, 0, result_max - osize,
                        choose_tries, recurse_tries,
                        choose_local_retries, choose_local_fallback_retries,
                        recurse_to_leaf, vary_r, stable, cseg, 0,
                        choose_args)
                    o[osize:osize + n] = seg[:n]
                    c[osize:osize + n] = cseg[:n]
                    osize += n
                else:
                    out_size = min(numrep, result_max - osize)
                    _choose_indep(
                        crush_map, work, bucket, weights, x, out_size,
                        numrep, step.arg2, seg, 0, choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, cseg, 0, choose_args)
                    o[osize:osize + out_size] = seg[:out_size]
                    c[osize:osize + out_size] = cseg[:out_size]
                    osize += out_size
            if recurse_to_leaf:
                o[:osize] = c[:osize]
            w = o[:osize]
        elif step.op == CRUSH_RULE_EMIT:
            for item in w:
                if len(result) < result_max:
                    result.append(item)
            w = []
    return result
