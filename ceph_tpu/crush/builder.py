"""CRUSH map construction helpers (CrushWrapper-builder analog).

Covers what pool creation needs: flat and two-level straw2 hierarchies and
the standard replicated / erasure rules (the same step sequences
CrushWrapper::add_simple_rule emits, including the erasure rules'
set_chooseleaf_tries 5 / set_choose_tries 100 preamble).
"""

from __future__ import annotations

from .types import (
    Bucket,
    CrushMap,
    Rule,
    RuleStep,
    CRUSH_BUCKET_STRAW2,
    CRUSH_RULE_TAKE,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TYPE_REPLICATED,
    CRUSH_RULE_TYPE_ERASURE,
)

ROOT_ID = -1


def build_flat_map(n_osds: int, weights=None,
                   alg: int = CRUSH_BUCKET_STRAW2) -> CrushMap:
    """One root bucket holding all OSDs directly."""
    m = CrushMap()
    weights = weights or [0x10000] * n_osds
    root = Bucket(id=ROOT_ID, type=10, alg=alg,
                  items=list(range(n_osds)), item_weights=list(weights))
    m.add_bucket(root, "default")
    m.add_rule(replicated_rule(0, ROOT_ID, choose_type=0, leaf=False))
    return m


def build_two_level_map(n_hosts: int, osds_per_host: int,
                        host_weights=None,
                        alg: int = CRUSH_BUCKET_STRAW2) -> CrushMap:
    """root -> hosts -> osds; osd ids are dense [0, n_hosts*osds_per_host)."""
    m = CrushMap()
    host_ids = []
    for h in range(n_hosts):
        hid = -(2 + h)
        osds = [h * osds_per_host + i for i in range(osds_per_host)]
        host = Bucket(id=hid, type=1, alg=alg, items=osds,
                      item_weights=[0x10000] * osds_per_host)
        m.add_bucket(host, f"host{h}")
        host_ids.append(hid)
    hw = host_weights or [0x10000 * osds_per_host] * n_hosts
    root = Bucket(id=ROOT_ID, type=10, alg=alg, items=host_ids,
                  item_weights=list(hw))
    m.add_bucket(root, "default")
    m.add_rule(replicated_rule(0, ROOT_ID, choose_type=1, leaf=True))
    m.add_rule(erasure_rule(1, ROOT_ID, choose_type=1, leaf=True))
    return m


def build_hierarchy(fanouts: list[int], type_ids: list[int] | None = None,
                    weights=None,
                    alg: int = CRUSH_BUCKET_STRAW2) -> CrushMap:
    """Uniform tree of arbitrary depth: ``fanouts[l]`` children per
    bucket at level l; the last fanout counts OSDs per leaf bucket.
    E.g. [4, 5, 10] = root -> 4 racks -> 5 hosts each -> 10 osds each
    (1000-OSD depth-4 node path root/rack/host/osd).

    ``weights`` optionally gives per-osd 16.16 weights; bucket weights
    sum their children (as CrushWrapper keeps them)."""
    m = CrushMap()
    depth = len(fanouts)
    type_ids = type_ids or list(range(depth, 0, -1))
    n_osds = 1
    for f in fanouts:
        n_osds *= f
    weights = weights or [0x10000] * n_osds
    next_id = [ROOT_ID]

    def build(level: int, osd_base: int) -> tuple[int, int]:
        """Returns (bucket_id_or_osd, weight)."""
        span = 1
        for f in fanouts[level:]:
            span *= f
        bid = next_id[0]
        next_id[0] -= 1
        items, iw = [], []
        for c in range(fanouts[level]):
            if level == depth - 1:
                osd = osd_base + c
                items.append(osd)
                iw.append(weights[osd])
            else:
                sub, subw = build(level + 1,
                                  osd_base + c * (span // fanouts[level]))
                items.append(sub)
                iw.append(subw)
        b = Bucket(id=bid, type=type_ids[level], alg=alg,
                   items=items, item_weights=iw)
        m.add_bucket(b, f"b{level}.{bid}")
        return bid, sum(iw)

    build(0, 0)
    leaf_type = type_ids[-1]
    m.add_rule(replicated_rule(0, ROOT_ID, choose_type=leaf_type,
                               leaf=True))
    m.add_rule(erasure_rule(1, ROOT_ID, choose_type=leaf_type,
                            leaf=True))
    return m


def replicated_rule(rule_id: int, root: int, choose_type: int,
                    leaf: bool) -> Rule:
    op = CRUSH_RULE_CHOOSELEAF_FIRSTN if leaf else CRUSH_RULE_CHOOSE_FIRSTN
    return Rule(rule_id=rule_id, type=CRUSH_RULE_TYPE_REPLICATED, steps=[
        RuleStep(CRUSH_RULE_TAKE, root),
        RuleStep(op, 0, choose_type),
        RuleStep(CRUSH_RULE_EMIT),
    ])


def erasure_rule(rule_id: int, root: int, choose_type: int,
                 leaf: bool) -> Rule:
    op = CRUSH_RULE_CHOOSELEAF_INDEP if leaf else CRUSH_RULE_CHOOSE_INDEP
    return Rule(rule_id=rule_id, type=CRUSH_RULE_TYPE_ERASURE, steps=[
        RuleStep(CRUSH_RULE_SET_CHOOSELEAF_TRIES, 5),
        RuleStep(CRUSH_RULE_SET_CHOOSE_TRIES, 100),
        RuleStep(CRUSH_RULE_TAKE, root),
        RuleStep(op, 0, choose_type),
        RuleStep(CRUSH_RULE_EMIT),
    ])
