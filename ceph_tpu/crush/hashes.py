"""rjenkins1 32-bit mix hashes (scalar + numpy-vectorized).

Semantics of src/crush/hash.c:12-117 and the string hash of
src/common/ceph_hash.cc (ceph_str_hash_rjenkins), reimplemented over
explicit uint32 wraparound.  These drive every placement decision, so they
must match bit-for-bit; tests pin golden values.
"""

from __future__ import annotations

import numpy as np

CRUSH_HASH_SEED = 1315423911
_M = 0xFFFFFFFF


def _mix(a: int, b: int, c: int) -> tuple[int, int, int]:
    a = (a - b) & _M; a = (a - c) & _M; a ^= c >> 13
    b = (b - c) & _M; b = (b - a) & _M; b = (b ^ (a << 8)) & _M
    c = (c - a) & _M; c = (c - b) & _M; c ^= b >> 13
    a = (a - b) & _M; a = (a - c) & _M; a ^= c >> 12
    b = (b - c) & _M; b = (b - a) & _M; b = (b ^ (a << 16)) & _M
    c = (c - a) & _M; c = (c - b) & _M; c ^= b >> 5
    a = (a - b) & _M; a = (a - c) & _M; a ^= c >> 3
    b = (b - c) & _M; b = (b - a) & _M; b = (b ^ (a << 10)) & _M
    c = (c - a) & _M; c = (c - b) & _M; c ^= b >> 15
    return a, b, c


def crush_hash32(a: int) -> int:
    a &= _M
    h = (CRUSH_HASH_SEED ^ a) & _M
    b, x, y = a, 231232, 1232
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def crush_hash32_2(a: int, b: int) -> int:
    a &= _M; b &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b) & _M
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def crush_hash32_3(a: int, b: int, c: int) -> int:
    a &= _M; b &= _M; c &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c) & _M
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def crush_hash32_4(a: int, b: int, c: int, d: int) -> int:
    a &= _M; b &= _M; c &= _M; d &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d) & _M
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def crush_hash32_5(a: int, b: int, c: int, d: int, e: int) -> int:
    a &= _M; b &= _M; c &= _M; d &= _M; e &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e) & _M
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


# -- numpy vectorized versions (arrays of uint32) ---------------------------

def _mix_np(a, b, c):
    a = (a - b); a = (a - c); a ^= c >> np.uint32(13)
    b = (b - c); b = (b - a); b ^= a << np.uint32(8)
    c = (c - a); c = (c - b); c ^= b >> np.uint32(13)
    a = (a - b); a = (a - c); a ^= c >> np.uint32(12)
    b = (b - c); b = (b - a); b ^= a << np.uint32(16)
    c = (c - a); c = (c - b); c ^= b >> np.uint32(5)
    a = (a - b); a = (a - c); a ^= c >> np.uint32(3)
    b = (b - c); b = (b - a); b ^= a << np.uint32(10)
    c = (c - a); c = (c - b); c ^= b >> np.uint32(15)
    return a, b, c


def crush_hash32_2_np(a, b):
    a = np.asarray(a, dtype=np.uint32)
    b = np.asarray(b, dtype=np.uint32)
    h = np.uint32(CRUSH_HASH_SEED) ^ a ^ b
    x = np.full_like(a, 231232, dtype=np.uint32)
    y = np.full_like(a, 1232, dtype=np.uint32)
    a, b, h = _mix_np(a, b, h)
    x, a, h = _mix_np(x, a, h)
    b, y, h = _mix_np(b, y, h)
    return h


def crush_hash32_3_np(a, b, c):
    a = np.asarray(a, dtype=np.uint32)
    b = np.asarray(b, dtype=np.uint32)
    c = np.asarray(c, dtype=np.uint32)
    a, b, c = np.broadcast_arrays(a, b, c)
    h = np.uint32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = np.full_like(h, 231232, dtype=np.uint32)
    y = np.full_like(h, 1232, dtype=np.uint32)
    a = a.copy(); b = b.copy(); c = c.copy()
    a, b, h = _mix_np(a, b, h)
    c, x, h = _mix_np(c, x, h)
    y, a, h = _mix_np(y, a, h)
    b, x, h = _mix_np(b, x, h)
    y, c, h = _mix_np(y, c, h)
    return h


def ceph_str_hash_rjenkins(data: bytes) -> int:
    """String hash used for object-name -> placement seed."""
    a = 0x9E3779B9
    b = a
    c = 0
    length = len(data)
    i = 0
    rem = length
    while rem >= 12:
        k = data[i:i + 12]
        a = (a + (k[0] | k[1] << 8 | k[2] << 16 | k[3] << 24)) & _M
        b = (b + (k[4] | k[5] << 8 | k[6] << 16 | k[7] << 24)) & _M
        c = (c + (k[8] | k[9] << 8 | k[10] << 16 | k[11] << 24)) & _M
        a, b, c = _mix(a, b, c)
        i += 12
        rem -= 12
    c = (c + length) & _M
    k = data[i:]
    if rem >= 11: c = (c + (k[10] << 24)) & _M
    if rem >= 10: c = (c + (k[9] << 16)) & _M
    if rem >= 9:  c = (c + (k[8] << 8)) & _M
    if rem >= 8:  b = (b + (k[7] << 24)) & _M
    if rem >= 7:  b = (b + (k[6] << 16)) & _M
    if rem >= 6:  b = (b + (k[5] << 8)) & _M
    if rem >= 5:  b = (b + k[4]) & _M
    if rem >= 4:  a = (a + (k[3] << 24)) & _M
    if rem >= 3:  a = (a + (k[2] << 16)) & _M
    if rem >= 2:  a = (a + (k[1] << 8)) & _M
    if rem >= 1:  a = (a + k[0]) & _M
    a, b, c = _mix(a, b, c)
    return c
