"""CRUSH: deterministic pseudo-random placement.

Scalar (host) mapper mirrors the reference's pure-C core
(src/crush/mapper.c) decision-for-decision; the vectorized JAX mapper
(ceph_tpu/crush/vectorized.py) computes bulk PG->OSD mappings on TPU --
the job the reference parallelizes on thread pools via ParallelPGMapper
(src/osd/OSDMapMapping.h:18).
"""

from .hashes import (  # noqa: F401
    crush_hash32,
    crush_hash32_2,
    crush_hash32_3,
    crush_hash32_4,
    crush_hash32_5,
    ceph_str_hash_rjenkins,
)
from .ln import crush_ln  # noqa: F401
from .types import (  # noqa: F401
    CrushMap,
    Bucket,
    Rule,
    RuleStep,
    Tunables,
    CRUSH_BUCKET_UNIFORM,
    CRUSH_BUCKET_LIST,
    CRUSH_BUCKET_TREE,
    CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
)
from .mapper import crush_do_rule  # noqa: F401
from .builder import build_flat_map, build_two_level_map  # noqa: F401
