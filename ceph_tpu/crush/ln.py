"""Fixed-point 2^44 * log2(x+1) used by straw2 draws.

Tables: RH_LH[2k] ~= 2^48/(1+k/128), RH_LH[2k+1] ~= 2^48*log2(1+k/128),
LL[k] ~= 2^48*log2(1+k/2^15) -- kept as binary data
(crush_ln_tables.npz) because the historical values embed the original
generator's double rounding, which exact arithmetic cannot reproduce and
which placement compatibility requires bit-for-bit (semantics:
src/crush/mapper.c:229-269, tables src/crush/crush_ln_table.h).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

_data = np.load(Path(__file__).parent / "crush_ln_tables.npz")
RH_LH_TBL = _data["rh_lh"].astype(np.int64)   # 258 entries
LL_TBL = _data["ll"].astype(np.int64)         # 256 entries

S64_MIN = -(1 << 63)


def crush_ln(xin: int) -> int:
    """2^44 * log2(x+1) for x in [0, 0xffff], as mapper.c:229 computes it."""
    x = (xin + 1) & 0xFFFFFFFF
    iexpon = 15
    if not (x & 0x18000):
        # clz(x & 0x1FFFF) - 16: normalize so bit 15 is the top set bit
        bits = 16 - (x & 0x1FFFF).bit_length()
        x <<= bits
        iexpon = 15 - bits
    index1 = (x >> 8) << 1
    rh = int(RH_LH_TBL[index1 - 256])
    lh = int(RH_LH_TBL[index1 + 1 - 256])
    xl64 = (x * rh) >> 48
    result = iexpon << 44
    index2 = xl64 & 0xFF
    ll = int(LL_TBL[index2])
    lh = lh + ll
    lh >>= (48 - 12 - 32)
    return result + lh


def _normalize_np(x):
    """Vectorized normalization: returns (x_shifted, iexpon)."""
    x = x.astype(np.int64)
    need = (x & 0x18000) == 0
    masked = x & 0x1FFFF
    # bit_length via log2 on nonzero values (x>=1 always, since x = u+1)
    bl = np.zeros_like(x)
    nz = masked > 0
    bl[nz] = np.floor(np.log2(masked[nz])).astype(np.int64) + 1
    bits = np.where(need, 16 - bl, 0)
    x = x << bits
    iexpon = 15 - bits
    return x, iexpon


def crush_ln_np(xin) -> np.ndarray:
    """Vectorized crush_ln over uint16-ranged inputs."""
    u = np.asarray(xin, dtype=np.int64)
    x = u + 1
    x, iexpon = _normalize_np(x)
    index1 = (x >> 8) << 1
    rh = RH_LH_TBL[index1 - 256]
    lh = RH_LH_TBL[index1 + 1 - 256]
    xl64 = (x * rh) >> 48
    index2 = xl64 & 0xFF
    ll = LL_TBL[index2]
    return (iexpon << 44) + ((lh + ll) >> 4)
