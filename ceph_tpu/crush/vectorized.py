"""Vectorized CRUSH on TPU: bulk PG->OSD mapping as one XLA launch.

The reference recomputes full-cluster mappings on host thread pools
(ParallelPGMapper, src/osd/OSDMapMapping.h:18; used by the balancer and
OSDMonitor's PrimeTempJob).  Here the whole job is one data-parallel
program over the PG axis: straw2 draws become gathers into the fixed-point
log tables plus an argmax, and the firstn/indep retry loops become bounded
`lax.while_loop`s with per-lane masks -- decision-identical to the scalar
mapper (ceph_tpu/crush/mapper.py), which is itself pinned to mapper.c.

Supported map shape for the fused path: straw2 hierarchies of depth 1
(root->osds) or 2 (root->hosts->osds) with the standard replicated
(chooseleaf firstn) / erasure (chooseleaf indep) rules and jewel tunables.
Anything else falls back to the scalar engine.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax

# straw2 draws are 64-bit fixed-point; everything here uses explicit dtypes
# so the global x64 switch is safe for the rest of the package
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from .ln import RH_LH_TBL, LL_TBL  # noqa: E402
from .types import (
    CrushMap,
    CRUSH_BUCKET_STRAW2,
    CRUSH_ITEM_NONE,
    CRUSH_RULE_TAKE,
    CRUSH_RULE_EMIT,
    CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP,
    CRUSH_RULE_CHOOSELEAF_FIRSTN,
    CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES,
)

S64_MIN = jnp.int64(-(2**63))
CRUSH_HASH_SEED = np.uint32(1315423911)


def _u32(v):
    return jnp.asarray(v, dtype=jnp.uint32)


def _mix(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> 13)
    b = b - c; b = b - a; b = b ^ (a << 8)
    c = c - a; c = c - b; c = c ^ (b >> 13)
    a = a - b; a = a - c; a = a ^ (c >> 12)
    b = b - c; b = b - a; b = b ^ (a << 16)
    c = c - a; c = c - b; c = c ^ (b >> 5)
    a = a - b; a = a - c; a = a ^ (c >> 3)
    b = b - c; b = b - a; b = b ^ (a << 10)
    c = c - a; c = c - b; c = c ^ (b >> 15)
    return a, b, c


def hash32_2_jnp(a, b):
    a, b = _u32(a), _u32(b)
    h = _u32(CRUSH_HASH_SEED) ^ a ^ b
    x = jnp.full_like(h, 231232)
    y = jnp.full_like(h, 1232)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def hash32_3_jnp(a, b, c):
    a, b, c = _u32(a), _u32(b), _u32(c)
    a, b, c = jnp.broadcast_arrays(a, b, c)
    h = _u32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = jnp.full_like(h, 231232)
    y = jnp.full_like(h, 1232)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


_RH_LH = jnp.asarray(RH_LH_TBL)   # int64 (258,)
_LL = jnp.asarray(LL_TBL)         # int64 (256,)


def crush_ln_jnp(u):
    """Vector crush_ln over int32 u in [0, 0xffff] -> int64."""
    x = u.astype(jnp.int64) + 1
    need = (x & 0x18000) == 0
    masked = (x & 0x1FFFF).astype(jnp.int32)
    # bit_length via 31 - clz
    bl = 32 - jax.lax.clz(masked)
    bits = jnp.where(need, 16 - bl, 0).astype(jnp.int64)
    x = x << bits
    iexpon = (15 - bits).astype(jnp.int64)
    index1 = ((x >> 8) << 1).astype(jnp.int32)
    rh = _RH_LH[index1 - 256]
    lh = _RH_LH[index1 + 1 - 256]
    xl64 = (x * rh) >> 48
    index2 = (xl64 & 0xFF).astype(jnp.int32)
    ll = _LL[index2]
    return (iexpon << 44) + ((lh + ll) >> 4)


def straw2_draws(x, item_ids, r, weights):
    """Draw values for one bucket: shapes broadcast over (..., n_items).

    x: (...,) int32 lanes; item_ids/weights: (..., n) int32.
    Returns (..., n) int64 draws (S64_MIN where weight==0).
    """
    u = (hash32_3_jnp(x[..., None], item_ids, r[..., None])
         & np.uint32(0xFFFF)).astype(jnp.int32)
    ln = crush_ln_jnp(u) - jnp.int64(0x1000000000000)
    w = weights.astype(jnp.int64)
    draws = jax.lax.div(ln, jnp.maximum(w, 1))
    return jnp.where(w > 0, draws, S64_MIN)


def is_out_jnp(osd_weights, item, x):
    """Vector is_out (mapper.c:419-433): weight is 16.16 reweight."""
    w = osd_weights[item]
    h = hash32_2_jnp(x, item.astype(jnp.uint32)) & np.uint32(0xFFFF)
    probably_out = h.astype(jnp.int32) >= w
    return jnp.where(w >= 0x10000, False,
                     jnp.where(w == 0, True, probably_out))


@dataclass
class CompiledMap:
    """Flattened straw2 hierarchy for the fused path."""

    depth: int                      # 1 or 2
    host_ids: np.ndarray            # (H,) int32 bucket ids (depth2) / osd ids
    host_weights: np.ndarray        # (H,) int32 16.16
    leaf_items: np.ndarray | None   # (H, max_per_host) int32, -pad
    leaf_weights: np.ndarray | None
    max_devices: int

    @classmethod
    def from_map(cls, crush_map: CrushMap, root_id: int) -> "CompiledMap":
        root = crush_map.buckets[root_id]
        if root.alg != CRUSH_BUCKET_STRAW2:
            raise ValueError("fused path requires straw2 buckets")
        children = [crush_map.buckets.get(i) for i in root.items]
        if all(c is None for c in children):
            return cls(1, np.asarray(root.items, np.int32),
                       np.asarray(root.item_weights, np.int32),
                       None, None, crush_map.max_devices)
        if any(c is None for c in children):
            raise ValueError("mixed osd/bucket children unsupported")
        for c in children:
            if c.alg != CRUSH_BUCKET_STRAW2:
                raise ValueError("fused path requires straw2 buckets")
            if any(i < 0 for i in c.items):
                raise ValueError("fused path supports depth <= 2")
        maxn = max(c.size for c in children)
        li = np.zeros((len(children), maxn), np.int32)
        lw = np.zeros((len(children), maxn), np.int32)
        for j, c in enumerate(children):
            li[j, :c.size] = c.items
            li[j, c.size:] = c.items[0] if c.items else 0
            lw[j, :c.size] = c.item_weights
        return cls(2, np.asarray(root.items, np.int32),
                   np.asarray(root.item_weights, np.int32),
                   li, lw, crush_map.max_devices)


def _rule_shape(crush_map: CrushMap, ruleno: int):
    """Parse a rule into (root_id, firstn, leaf, choose_tries, leaf_tries)."""
    rule = crush_map.rules[ruleno]
    t = crush_map.tunables
    choose_tries = t.choose_total_tries + 1
    leaf_tries = 0
    root_id = None
    mode = None
    for step in rule.steps:
        if step.op == CRUSH_RULE_SET_CHOOSE_TRIES:
            choose_tries = step.arg1
        elif step.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            leaf_tries = step.arg1
        elif step.op == CRUSH_RULE_TAKE:
            root_id = step.arg1
        elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                         CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_CHOOSELEAF_INDEP):
            mode = step.op
        elif step.op == CRUSH_RULE_EMIT:
            pass
    firstn = mode in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSELEAF_FIRSTN)
    leaf = mode in (CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP)
    return root_id, firstn, leaf, choose_tries, leaf_tries


class VectorCrush:
    """Bulk mapper for one (map, rule) pair."""

    def __init__(self, crush_map: CrushMap, ruleno: int) -> None:
        root_id, firstn, leaf, choose_tries, leaf_tries = _rule_shape(
            crush_map, ruleno)
        self.cm = CompiledMap.from_map(crush_map, root_id)
        if leaf and self.cm.depth != 2:
            raise ValueError("chooseleaf rule needs a depth-2 map")
        if not leaf and self.cm.depth != 1:
            raise ValueError("plain choose rule needs a depth-1 map")
        t = crush_map.tunables
        self.firstn = firstn
        self.choose_tries = choose_tries
        self.leaf_tries = leaf_tries
        self.vary_r = t.chooseleaf_vary_r
        self.stable = t.chooseleaf_stable
        self.descend_once = t.chooseleaf_descend_once
        if firstn:
            self.recurse_tries = (leaf_tries if leaf_tries
                                  else (1 if self.descend_once
                                        else choose_tries))
        else:
            self.recurse_tries = leaf_tries if leaf_tries else 1
        if not self.stable or self.vary_r != 1:
            # scalar fallback covers other tunable profiles
            raise ValueError("fused path implements jewel tunables")

    # -- firstn -------------------------------------------------------------
    @partial(jax.jit, static_argnames=("self", "numrep"))
    def map_firstn(self, xs: jnp.ndarray, numrep: int,
                   osd_weights: jnp.ndarray) -> jnp.ndarray:
        """xs: (L,) int32 placement seeds -> (L, numrep) osd ids (or NONE)."""
        cm = self.cm
        L = xs.shape[0]
        host_ids = jnp.asarray(cm.host_ids)
        host_w = jnp.asarray(cm.host_weights)
        out = jnp.full((L, numrep), CRUSH_ITEM_NONE, jnp.int32)
        out_hosts = jnp.full((L, numrep), jnp.int32(2**31 - 1), jnp.int32)

        def pick_leaf(x, host_idx, r):
            if cm.depth == 1:
                osd = host_ids[host_idx]
                return osd
            litems = jnp.asarray(cm.leaf_items)[host_idx]
            lw = jnp.asarray(cm.leaf_weights)[host_idx]
            draws = straw2_draws(x, litems, r, lw)
            return litems[jnp.arange(L), jnp.argmax(draws, axis=-1)]

        for rep in range(numrep):
            # per-lane retry loop: state = (ftotal, done, host_idx, osd)
            def cond(state):
                ftotal, done, _, _ = state
                return jnp.any(~done & (ftotal < self.choose_tries))

            def body(state):
                ftotal, done, host_idx, osd = state
                r = (rep + ftotal).astype(jnp.int32)
                draws = straw2_draws(
                    xs, jnp.broadcast_to(host_ids, (L, host_ids.shape[0])),
                    r, jnp.broadcast_to(host_w, (L, host_w.shape[0])))
                cand_idx = jnp.argmax(draws, axis=-1).astype(jnp.int32)
                # collision vs previously placed hosts in this take block
                collide = jnp.zeros((L,), bool)
                for j in range(rep):
                    collide |= out_hosts[:, j] == cand_idx
                # descend to leaf: sub_r = r >> (vary_r - 1) = r
                cand_osd = pick_leaf(xs, cand_idx, r)
                reject = is_out_jnp(osd_weights, cand_osd, xs)
                if cm.depth == 2:
                    for j in range(rep):
                        reject |= out[:, j] == cand_osd
                ok = ~done & ~collide & ~reject
                host_idx = jnp.where(ok, cand_idx, host_idx)
                osd = jnp.where(ok, cand_osd, osd)
                newdone = done | ok
                ftotal = jnp.where(~newdone, ftotal + 1, ftotal)
                return ftotal, newdone, host_idx, osd

            init = (jnp.zeros((L,), jnp.int32), jnp.zeros((L,), bool),
                    jnp.full((L,), 2**31 - 1, jnp.int32),
                    jnp.full((L,), CRUSH_ITEM_NONE, jnp.int32))
            ftotal, done, host_idx, osd = jax.lax.while_loop(cond, body, init)
            out = out.at[:, rep].set(jnp.where(done, osd, CRUSH_ITEM_NONE))
            out_hosts = out_hosts.at[:, rep].set(
                jnp.where(done, host_idx, 2**31 - 1))
        return out

    # -- indep --------------------------------------------------------------
    @partial(jax.jit, static_argnames=("self", "numrep"))
    def map_indep(self, xs: jnp.ndarray, numrep: int,
                  osd_weights: jnp.ndarray) -> jnp.ndarray:
        cm = self.cm
        L = xs.shape[0]
        host_ids = jnp.asarray(cm.host_ids)
        host_w = jnp.asarray(cm.host_weights)
        UNDEF = jnp.int32(0x7FFFFFFE)

        def leaf_try(x, host_idx, parent_r, rep):
            """indep recursion: up to recurse_tries rounds for one slot."""
            litems = jnp.asarray(cm.leaf_items)[host_idx]
            lw = jnp.asarray(cm.leaf_weights)[host_idx]
            osd = jnp.full((L,), CRUSH_ITEM_NONE, jnp.int32)
            found = jnp.zeros((L,), bool)
            for ft in range(self.recurse_tries):
                r_leaf = (rep + parent_r + numrep * ft).astype(jnp.int32)
                draws = straw2_draws(x, litems, r_leaf, lw)
                cand = litems[jnp.arange(L), jnp.argmax(draws, axis=-1)]
                ok = ~found & ~is_out_jnp(osd_weights, cand, x)
                osd = jnp.where(ok, cand, osd)
                found |= ok
            return osd, found

        def cond(state):
            ftotal, out_h, out_o = state
            return (ftotal < self.choose_tries) & jnp.any(out_h == UNDEF)

        def body(state):
            ftotal, out_h, out_o = state
            for rep in range(numrep):
                slot_undef = out_h[:, rep] == UNDEF
                r = (rep + numrep * ftotal).astype(jnp.int32)
                draws = straw2_draws(
                    xs, jnp.broadcast_to(host_ids, (L, host_ids.shape[0])),
                    r, jnp.broadcast_to(host_w, (L, host_w.shape[0])))
                cand_idx = jnp.argmax(draws, axis=-1).astype(jnp.int32)
                if cm.depth == 1:
                    # flat: slots hold osd ids; compare apples to apples
                    cand_idx = host_ids[cand_idx]
                collide = jnp.zeros((L,), bool)
                for j in range(numrep):
                    collide |= out_h[:, j] == cand_idx
                if cm.depth == 2:
                    osd, found = leaf_try(xs, cand_idx, r, rep)
                else:
                    osd = cand_idx
                    found = ~is_out_jnp(osd_weights, osd, xs)
                ok = slot_undef & ~collide & found
                out_h = out_h.at[:, rep].set(
                    jnp.where(ok, cand_idx, out_h[:, rep]))
                out_o = out_o.at[:, rep].set(
                    jnp.where(ok, osd, out_o[:, rep]))
            return ftotal + 1, out_h, out_o

        init = (jnp.int32(0),
                jnp.full((L, numrep), UNDEF, jnp.int32),
                jnp.full((L, numrep), UNDEF, jnp.int32))
        _, out_h, out_o = jax.lax.while_loop(cond, body, init)
        return jnp.where(out_o == UNDEF, CRUSH_ITEM_NONE, out_o)

    def map_pgs(self, xs, numrep: int, osd_weights) -> np.ndarray:
        xs = jnp.asarray(xs, jnp.int32)
        w = jnp.asarray(osd_weights, jnp.int32)
        if self.firstn:
            return np.asarray(self.map_firstn(xs, numrep, w))
        return np.asarray(self.map_indep(xs, numrep, w))
